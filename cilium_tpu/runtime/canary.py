"""Shadow/canary policy rollout with a verdict-diff gate (ISSUE 20).

A bad CNP rollout at fleet scale is a mass outage with a one-line
root cause. This module makes generation N+1 EARN its commit: the
loader stages N+1 alongside the serving generation N
(:meth:`~cilium_tpu.runtime.loader.Loader.stage_canary` — the shadow
is the CPU oracle over the N+1 snapshot, bit-equal to the compiled
engine by the repo's core invariant, so a diff measures the POLICY
change, never a backend artifact), the serve loop double-dispatches a
configured sample fraction of ring traffic through BOTH generations
in the same pack cycle, and :class:`CanaryController` keeps the
verdict-diff ledger. Commit is REFUSED — serving generation N
untouched, zero bad verdicts served — when the diff fraction exceeds
the declared budget or the sample floor wasn't reached.

Sample selection is a pure counter walk (``floor(c·f) ≠
floor((c-1)·f)``), deterministic under any PYTHONHASHSEED and across
hosts (tests/dst/test_boundaries.py pins it) — never an RNG, never an
id hash.

The ``canary.dispatch`` fault point fires on every shadow dispatch: a
fired fault ABORTS the canary safely (counted, reported, staged
generation dropped) while generation N keeps serving untouched —
shadow evaluation is advisory until the moment of commit.

``python -m cilium_tpu.runtime.canary`` is the ``make canary`` lane:
it plants a genuinely bad N+1 (allow entries flipped to deny) behind
real ring traffic, proves the gate refuses it with ZERO bad verdicts
served, then commits a clean N+1 through the same gate, and stamps
the double-dispatch overhead against the pack-cycle wall budget.
"""

from __future__ import annotations

import argparse
import hashlib
import json
import os
import sys
import threading
from typing import Dict, List, Optional, Sequence

from cilium_tpu.runtime import faults
from cilium_tpu.runtime.logging import get_logger
from cilium_tpu.runtime.metrics import (
    CANARY_COMMITS,
    CANARY_DIFF_FRACTION,
    CANARY_SAMPLES,
    METRICS,
)

LOG = get_logger("canary")

#: fires on every shadow (N+1) dispatch of a sampled chunk: a fired
#: fault models the shadow evaluation path failing and must ABORT the
#: canary — counted, staged generation dropped — while the serving
#: generation N is untouched (tests/test_faults.py pins it)
CANARY_DISPATCH_POINT = faults.register_point(
    "canary.dispatch", "shadow verdict dispatch in CanaryController")

#: controller states; terminal ones keep their final report readable
STATE_IDLE = "idle"
STATE_SAMPLING = "sampling"
STATE_COMMITTED = "committed"
STATE_REFUSED = "refused"
STATE_ABORTED = "aborted"


class CanaryRefused(RuntimeError):
    """The verdict-diff gate refused the commit: the diff fraction
    exceeded the declared budget (or the sample floor wasn't met with
    a non-zero diff). Serving generation N is untouched."""

    def __init__(self, report: Dict):
        super().__init__(
            f"canary refused: diff_fraction="
            f"{report.get('diff_fraction')} over budget="
            f"{report.get('diff_budget')} after "
            f"{report.get('samples')} samples")
        self.report = report


class CanaryController:
    """The verdict-diff ledger of one staged rollout.

    One per service/loader. ``stage()`` installs generation N+1 as
    the loader's shadow; the serve loop calls ``should_sample`` on
    its chunk counter and ``observe_chunk`` with each sampled chunk's
    (flows, served verdicts); ``try_commit`` is the gate. Thread-safe:
    observes land from the pack thread while status/commit come from
    the API thread."""

    def __init__(self, loader, sample_fraction: float = 0.25,
                 diff_budget: float = 0.0, min_samples: int = 64):
        self.loader = loader
        self.sample_fraction = float(sample_fraction)
        self.diff_budget = float(diff_budget)
        self.min_samples = max(1, int(min_samples))
        self._lock = threading.Lock()
        self.state = STATE_IDLE
        self.revision = 0
        self.samples = 0       # sampled flow verdicts compared
        self.diffs = 0         # ... that disagreed across generations
        self.chunks = 0        # sampled chunks double-dispatched
        self.reason = ""       # terminal detail (abort cause, ...)

    @classmethod
    def from_config(cls, loader, cfg=None) -> "CanaryController":
        ccfg = cfg if cfg is not None else loader.config.canary
        return cls(loader,
                   sample_fraction=ccfg.sample_fraction,
                   diff_budget=ccfg.diff_budget,
                   min_samples=ccfg.min_samples)

    # -- rollout lifecycle ------------------------------------------------
    def stage(self, per_identity, revision: int = 0) -> None:
        """Stage generation N+1 and start sampling. Restaging while a
        rollout is live replaces it (the old ledger resets — a new
        generation earns its own samples)."""
        self.loader.stage_canary(per_identity, revision=revision)
        with self._lock:
            self.state = STATE_SAMPLING
            self.revision = int(revision)
            self.samples = 0
            self.diffs = 0
            self.chunks = 0
            self.reason = ""
        LOG.info("canary staged", extra={"fields": {
            "revision": revision,
            "sample_fraction": self.sample_fraction,
            "diff_budget": self.diff_budget}})

    def active(self) -> bool:
        with self._lock:
            return self.state == STATE_SAMPLING

    def should_sample(self, counter: int) -> bool:
        """Deterministic counter-walk sample selection: chunk ``c``
        (1-based) is sampled iff ``floor(c·f) != floor((c-1)·f)`` —
        exactly a fraction ``f`` of chunks, the SAME chunks on every
        host and under every PYTHONHASHSEED (pinned by the DST
        boundary suite)."""
        f = self.sample_fraction
        if f <= 0.0:
            return False
        c = int(counter)
        return int(c * f) != int((c - 1) * f)

    # -- the double-dispatch observe path ---------------------------------
    def observe_chunk(self, flows, served_verdicts) -> bool:
        """Dispatch one sampled chunk's flows through the SHADOW
        generation and diff against the verdicts generation N served.
        Returns False when the canary is not sampling (or just
        aborted) — the caller simply stops sampling; serving is never
        affected either way."""
        with self._lock:
            if self.state != STATE_SAMPLING:
                return False
        shadow = self.loader.canary_engine
        if shadow is None:
            return False
        try:
            faults.maybe_fail(CANARY_DISPATCH_POINT)
            shadow_verdicts = shadow.verdict_flows(flows)["verdict"]
        except Exception as e:  # noqa: BLE001 — ANY shadow-dispatch
            # failure (armed fault or real) aborts the canary safely:
            # the staged generation is advisory until commit, so the
            # only safe degradation is to stop the rollout — never to
            # guess a diff, never to touch generation N
            self.abort(f"dispatch-failed: {type(e).__name__}: {e}")
            return False
        matches = 0
        diffs = 0
        for served, shadowed in zip(served_verdicts, shadow_verdicts):
            if int(served) == int(shadowed):
                matches += 1
            else:
                diffs += 1
        with self._lock:
            self.samples += matches + diffs
            self.diffs += diffs
            self.chunks += 1
            frac = self.diffs / max(1, self.samples)
        if matches:
            METRICS.inc(CANARY_SAMPLES, matches,
                        labels={"result": "match"})
        if diffs:
            METRICS.inc(CANARY_SAMPLES, diffs,
                        labels={"result": "diff"})
        METRICS.set_gauge(CANARY_DIFF_FRACTION, frac)
        return True

    # -- terminal transitions ---------------------------------------------
    def abort(self, reason: str) -> None:
        """Stop the rollout without committing: staged generation
        dropped, ledger kept for the report, serving generation N
        untouched by construction."""
        with self._lock:
            if self.state not in (STATE_SAMPLING, STATE_IDLE):
                return
            self.state = STATE_ABORTED
            self.reason = str(reason)
        self.loader.clear_canary()
        METRICS.inc(CANARY_COMMITS, labels={"result": "aborted"})
        LOG.warning("canary aborted", extra={"fields": {
            "revision": self.revision, "reason": reason}})

    def diff_fraction(self) -> float:
        with self._lock:
            return self.diffs / max(1, self.samples)

    def try_commit(self):
        """The verdict-diff gate. Passes only when the sample floor
        was reached AND the diff fraction is within the declared
        budget; then — and only then — the staged snapshot promotes
        through the loader's normal regenerate. A refusal drops the
        staged generation and raises :class:`CanaryRefused`; the
        serving generation N never moves."""
        with self._lock:
            if self.state != STATE_SAMPLING:
                raise RuntimeError(
                    f"no canary sampling (state={self.state})")
            samples = self.samples
            frac = self.diffs / max(1, self.samples)
        ok = samples >= self.min_samples and frac <= self.diff_budget
        if not ok:
            report = self.report()
            with self._lock:
                self.state = STATE_REFUSED
                self.reason = (
                    f"diff_fraction {round(frac, 6)} > budget "
                    f"{self.diff_budget}" if frac > self.diff_budget
                    else f"samples {samples} < floor "
                         f"{self.min_samples}")
                report["reason"] = self.reason
            self.loader.clear_canary()
            METRICS.inc(CANARY_COMMITS, labels={"result": "refused"})
            LOG.error("canary REFUSED", extra={"fields": {
                "revision": self.revision,
                "diff_fraction": round(frac, 6),
                "budget": self.diff_budget, "samples": samples}})
            raise CanaryRefused(report)
        engine = self.loader.commit_canary()
        with self._lock:
            self.state = STATE_COMMITTED
        METRICS.inc(CANARY_COMMITS, labels={"result": "committed"})
        LOG.info("canary committed", extra={"fields": {
            "revision": self.revision, "samples": samples,
            "diff_fraction": round(frac, 6)}})
        return engine

    # -- introspection ----------------------------------------------------
    def report(self) -> Dict:
        """The verdict-diff report (`GET /v1/canary`, `cilium-tpu
        canary`)."""
        with self._lock:
            return {
                "state": self.state,
                "revision": self.revision,
                "sample_fraction": self.sample_fraction,
                "diff_budget": self.diff_budget,
                "min_samples": self.min_samples,
                "chunks": self.chunks,
                "samples": self.samples,
                "diffs": self.diffs,
                "diff_fraction": round(
                    self.diffs / max(1, self.samples), 6),
                "reason": self.reason,
            }


# -- the `make canary` lane ---------------------------------------------------


def _build_world(n_rules: int, chunk_flows: int, pool_chunks: int,
                 seed: int, sample_fraction: float,
                 min_samples: int):
    """Synth policy → TPU loader (CPU backend) → serve loop with the
    canary controller wired, plus a chunk pool with generation-N
    ground truth."""
    import random

    from cilium_tpu.core.config import Config
    from cilium_tpu.ingest import synth
    from cilium_tpu.ingest.binary import (
        capture_from_bytes,
        capture_to_bytes,
    )
    from cilium_tpu.runtime.loader import Loader
    from cilium_tpu.runtime.serveloop import ServeLoop

    sc = synth.scenario_by_name("http", n_rules,
                                max(512, chunk_flows * 8))
    per_identity, sc = synth.realize_scenario(sc)
    cfg = Config()
    cfg.enable_tpu_offload = True
    cfg.canary.enabled = True
    cfg.canary.sample_fraction = sample_fraction
    cfg.canary.min_samples = min_samples
    loader = Loader(cfg)
    loader.regenerate(per_identity, revision=1)
    engine = loader.engine
    rng = random.Random(seed ^ 0xCA7A)
    pool = []
    flows = list(sc.flows)
    for _ in range(pool_chunks):
        batch = [flows[rng.randrange(len(flows))]
                 for _ in range(chunk_flows)]
        sections = capture_from_bytes(capture_to_bytes(batch))
        truth = [int(v) for v in
                 engine.verdict_flows(batch)["verdict"]]
        pool.append((sections, truth))
    canary = CanaryController.from_config(loader)
    loop = ServeLoop(loader, capacity=64, lease_ttl_s=300.0,
                     pack_interval_s=0.001, canary=canary)
    return cfg, loader, per_identity, pool, canary, loop


def _bad_snapshot(per_identity):
    """The planted bad rollout: every ALLOW entry flipped to deny —
    the one-line CNP mistake that mass-denies a fleet. Deep-copied so
    the serving snapshot is untouched."""
    import copy

    bad = copy.deepcopy(per_identity)
    for ms in bad.values():
        for entry in ms.entries.values():
            entry.is_deny = True
    return bad


def _drive(loop, pool, chunks: int) -> Dict:
    """Push ``chunks`` chunks through the ring (inline pack cycles)
    and return {served_chunks, bad_verdicts} — a bad verdict is any
    SERVED verdict disagreeing with the generation-N ground truth, the
    'zero bad verdicts served' ledger of the lane."""
    from cilium_tpu.runtime.serveloop import LeaseExpired, ShedError

    lease = loop.connect("canary-lane", resume=True)
    served = 0
    bad = 0
    outstanding: List = []
    for i in range(chunks):
        sections, truth = pool[i % len(pool)]
        try:
            ticket = loop.submit(lease, *sections)
        except (ShedError, LeaseExpired):
            lease = loop.connect("canary-lane", resume=True)
            continue
        outstanding.append((ticket, truth))
        loop.step()
        done = []
        for ticket, t in outstanding:
            if ticket.done and ticket.error is None:
                served += 1
                for got, want in zip(ticket.verdicts, t):
                    if int(got) != int(want):
                        bad += 1
                done.append((ticket, t))
        for pair in done:
            outstanding.remove(pair)
    # bounded inline flush (drain() would wedge the loop for the
    # next rollout phase — it stops admitting permanently)
    for _ in range(8):
        if all(t.done for t, _ in outstanding):
            break
        loop.step()
    for ticket, t in outstanding:
        if ticket.done and ticket.error is None and \
                ticket.verdicts is not None:
            served += 1
            for got, want in zip(ticket.verdicts, t):
                if int(got) != int(want):
                    bad += 1
    return {"served_chunks": served, "bad_verdicts": bad}


def main(argv: Optional[Sequence[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        description="canary rollout lane: planted bad-policy commit "
                    "must be refused by the verdict-diff gate with "
                    "zero bad verdicts served")
    ap.add_argument("--rules", type=int, default=40)
    ap.add_argument("--chunk-flows", type=int, default=16)
    ap.add_argument("--pool-chunks", type=int, default=24)
    ap.add_argument("--chunks", type=int, default=96,
                    help="ring chunks driven per rollout phase")
    ap.add_argument("--sample-fraction", type=float, default=0.25)
    ap.add_argument("--min-samples", type=int, default=16)
    ap.add_argument("--seed", type=int,
                    default=int(os.environ.get("CILIUM_TPU_DST_SEED",
                                               "0") or 0))
    ap.add_argument("--budget-pct", type=float, default=5.0,
                    help="double-dispatch overhead ceiling, %% of "
                         "pack-cycle wall")
    ap.add_argument("--out", default="BENCH_CANARY_r09.jsonl")
    args = ap.parse_args(argv)

    cfg, loader, per_identity, pool, canary, loop = _build_world(
        args.rules, args.chunk_flows, args.pool_chunks, args.seed,
        args.sample_fraction, args.min_samples)

    # phase 1: the PLANTED BAD rollout — stage, sample, expect REFUSED
    canary.stage(_bad_snapshot(per_identity), revision=2)
    bad_phase = _drive(loop, pool, args.chunks)
    refused = False
    try:
        canary.try_commit()
    except CanaryRefused as e:
        refused = True
        refusal = e.report
    serving_rev_after_bad = loader.revision
    bad_report = canary.report()

    # phase 2: a CLEAN rollout of the same policy through the same
    # gate — zero diffs, commit passes, revision advances
    canary2 = CanaryController.from_config(loader)
    loop.canary = canary2
    canary2.stage(dict(per_identity), revision=3)
    clean_phase = _drive(loop, pool, args.chunks)
    committed = False
    try:
        canary2.try_commit()
        committed = True
    except CanaryRefused:
        pass
    clean_report = canary2.report()

    pack_s = max(loop.pack_seconds, 1e-9)
    overhead_pct = 100.0 * loop.canary_seconds / pack_s
    gates = {
        "diff_caught": refused,
        "serving_untouched": serving_rev_after_bad == 1
        and bad_phase["bad_verdicts"] == 0,
        "clean_committed": committed and loader.revision == 3
        and clean_report["diffs"] == 0,
        "clean_verdicts": clean_phase["bad_verdicts"] == 0,
        "sampled": bad_report["samples"] >= args.min_samples,
        "overhead": overhead_pct <= args.budget_pct,
    }

    from cilium_tpu.runtime.provenance import stamp

    os.environ["CILIUM_TPU_DST_SEED"] = str(args.seed)
    os.environ["CILIUM_TPU_DST_DIGEST"] = hashlib.sha256(
        json.dumps({"rules": args.rules, "chunks": args.chunks,
                    "seed": args.seed,
                    "sample_fraction": args.sample_fraction},
                   sort_keys=True).encode()).hexdigest()[:16]
    line = stamp({
        "metric": "canary_overhead_pct",
        "value": round(overhead_pct, 4),
        "unit": "% of pack-cycle wall spent double-dispatching",
        "lane": "canary",
        "canary_overhead_pct": round(overhead_pct, 4),
        "canary_budget_pct": args.budget_pct,
        "canary_samples": bad_report["samples"],
        "canary_diffs": bad_report["diffs"],
        "diff_caught": refused,
        "diff_fraction": bad_report["diff_fraction"],
        "sample_fraction": args.sample_fraction,
        "bad_verdicts_served": bad_phase["bad_verdicts"],
        "clean_samples": clean_report["samples"],
        "clean_diffs": clean_report["diffs"],
        "served_chunks": bad_phase["served_chunks"]
        + clean_phase["served_chunks"],
        "seed": args.seed,
        "gates": {k: bool(v) for k, v in gates.items()},
    })
    with open(args.out, "a") as fp:
        fp.write(json.dumps(line) + "\n")

    ok = all(gates.values())
    print(f"[canary] bad rollout: "
          f"{'REFUSED' if refused else 'NOT refused'} at "
          f"diff_fraction {bad_report['diff_fraction']} "
          f"({bad_report['diffs']}/{bad_report['samples']} sampled "
          f"verdicts), {bad_phase['bad_verdicts']} bad verdicts "
          f"served, serving revision {serving_rev_after_bad}; "
          f"clean rollout: "
          f"{'COMMITTED' if committed else 'refused'} at revision "
          f"{loader.revision} ({clean_report['samples']} samples, "
          f"{clean_report['diffs']} diffs); double-dispatch overhead "
          f"{overhead_pct:.2f}% of pack wall "
          f"(budget {args.budget_pct}%); gates "
          f"{'OK' if ok else 'FAILED ' + str(gates)}", flush=True)
    if refused:
        print(f"[canary] refusal: {refusal.get('reason', '')}",
              flush=True)
    loop.stop()
    loader.close()
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
