"""Structured logging: JSONL records with subsystem fields.

Reference: ``pkg/logging`` (SURVEY.md §5.5) — logrus with a
``subsys`` field on every logger, level from config, structured
key/value fields. Ours layers the same shape over stdlib ``logging``:
``get_logger("loader")`` returns a logger whose records carry
``subsys``; the JSONL handler emits one JSON object per line
(`ts`, `level`, `subsys`, `msg`, plus any ``extra`` fields), which is
what log collectors ingest and what `bugtool` bundles.

When a flight-recorder trace is active (``runtime/tracing.py``
contextvar), every record emitted under it carries ``trace_id`` — logs
join traces and Hubble flow records on one id with zero per-call-site
changes.

Usage::

    log = get_logger("loader")
    log.info("staged", extra={"fields": {"revision": 3, "banks": 4}})

Call :func:`setup` once (the agent does) to install the JSONL handler;
until then records propagate to whatever the host process configured —
library-friendly, like the reference's default logger.
"""

from __future__ import annotations

import json
import logging
import sys
from typing import Optional

from cilium_tpu.runtime import simclock

ROOT = "cilium_tpu"


def _current_trace_id() -> str:
    # lazy import: logging is the package's lowest layer; pulling the
    # tracer in at call time keeps import order unconstrained
    from cilium_tpu.runtime.tracing import TRACER

    return TRACER.current_trace_id()

_LEVELS = {"debug": logging.DEBUG, "info": logging.INFO,
           "warning": logging.WARNING, "warn": logging.WARNING,
           "error": logging.ERROR, "critical": logging.CRITICAL,
           "fatal": logging.CRITICAL}


class JSONLFormatter(logging.Formatter):
    """One JSON object per record; ``extra={"fields": {...}}`` merges in."""

    def format(self, record: logging.LogRecord) -> str:
        out = {
            "ts": round(record.created, 6),
            "level": record.levelname.lower(),
            "subsys": getattr(record, "subsys",
                              record.name.rsplit(".", 1)[-1]),
            "msg": record.getMessage(),
        }
        # correlate with the flight recorder: a record emitted under an
        # active trace context carries the trace id (contextvar read —
        # formatters run synchronously on the emitting thread)
        tid = _current_trace_id()
        if tid:
            out["trace_id"] = tid
        fields = getattr(record, "fields", None)
        if fields:
            for k, v in fields.items():
                if k not in out:
                    out[k] = v
        if record.exc_info and record.exc_info[0] is not None:
            out["error"] = self.formatException(record.exc_info)
        return json.dumps(out, default=str)


class _SubsysAdapter(logging.LoggerAdapter):
    """Stamps ``subsys`` on every record and accepts bare keyword
    fields: ``log.info("msg", extra={"fields": {...}})``."""

    def process(self, msg, kwargs):
        extra = kwargs.setdefault("extra", {})
        extra.setdefault("subsys", self.extra["subsys"])
        return msg, kwargs


def get_logger(subsys: str) -> logging.LoggerAdapter:
    """Per-subsystem structured logger (``subsys`` field on every
    record), mirroring ``logging.DefaultLogger.WithField(logfields.
    LogSubsys, ...)`` in the reference."""
    return _SubsysAdapter(logging.getLogger(f"{ROOT}.{subsys}"),
                          {"subsys": subsys})


def setup(level: str = "info", stream=None,
          path: Optional[str] = None) -> logging.Logger:
    """Install the JSONL handler on the package root logger.

    ``path`` appends to a file instead of (not in addition to) the
    stream — one sink, like the reference's single logrus output.
    Idempotent: repeated calls reconfigure rather than stack handlers.
    """
    root = logging.getLogger(ROOT)
    resolved = _LEVELS.get(level.lower())
    root.setLevel(logging.INFO if resolved is None else resolved)
    for h in list(root.handlers):
        root.removeHandler(h)
        h.close()
    if path is not None:
        handler: logging.Handler = logging.FileHandler(path)
    else:
        handler = logging.StreamHandler(stream or sys.stderr)
    handler.setFormatter(JSONLFormatter())
    root.addHandler(handler)
    root.propagate = False
    if resolved is None:
        # a typo'd level must not silently change verbosity unannounced
        root.warning("unknown log level %r, using info", level,
                     extra={"subsys": "logging"})
    return root


def span(log: logging.LoggerAdapter, msg: str, **fields):
    """Context manager logging ``msg`` with a ``duration_s`` field on
    exit — the logging face of spanstat (metrics keeps the histogram)."""

    class _Span:
        def __enter__(self):
            self.t0 = simclock.now()
            return self

        def __exit__(self, exc_type, exc, tb):
            dur = round(simclock.now() - self.t0, 6)
            all_fields = dict(fields, duration_s=dur)
            if exc is not None:
                all_fields["failed"] = f"{type(exc).__name__}: {exc}"
                log.error(msg, extra={"fields": all_fields})
            else:
                log.info(msg, extra={"fields": all_fields})
            return False

    return _Span()
