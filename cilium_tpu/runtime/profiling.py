"""On-demand profiling for long-running processes — the ``pkg/pprof``
analog (SURVEY.md §5.1): the reference serves CPU/heap profiles from a
flag-gated HTTP endpoint on a LIVE agent; ours captures either a
jax.profiler device trace or a sampled host-stack profile from the
running process, behind the REST API (``PUT /v1/profile``) and the
verdict service (``{"op": "profile"}``).

Host mode is a dependency-free sampling profiler: ``sys._current_frames``
polled at ``hz`` for ``seconds``, aggregated into collapsed-stack lines
(``frame;frame;frame count``) — the flamegraph input format, readable
with any pprof/speedscope tooling. Device mode wraps
``jax.profiler.start_trace``/``stop_trace`` (Perfetto/XPlane output),
the same trace ``bench.py --profile`` captures, but attachable to a
serving process on demand.
"""

from __future__ import annotations

import os
import sys
import threading
import time
from collections import Counter
from typing import Dict, Optional


class ProfileBusy(RuntimeError):
    pass


class Profiler:
    """One capture at a time per process (both backends are global)."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._active: Optional[str] = None

    def capture(self, out_dir: str, seconds: float = 2.0,
                mode: str = "host", hz: int = 97) -> Dict[str, object]:
        # bounded: this BLOCKS the calling handler. The cap stays
        # under common client socket timeouts (APIClient defaults to
        # 30s) — a capture the client can't wait out would leave it
        # with neither the path nor a retry (ProfileBusy until done)
        seconds = min(max(seconds, 0.1), 20.0)
        with self._lock:
            if self._active is not None:
                raise ProfileBusy(f"{self._active} capture in progress")
            self._active = mode
        try:
            os.makedirs(out_dir, exist_ok=True)
            if mode == "device":
                return self._capture_device(out_dir, seconds)
            if mode == "host":
                return self._capture_host(out_dir, seconds, hz)
            raise ValueError(f"unknown profile mode {mode!r}")
        finally:
            with self._lock:
                self._active = None

    def _capture_device(self, out_dir: str, seconds: float) -> Dict:
        import jax

        jax.profiler.start_trace(out_dir)
        try:
            # ctlint: disable=wall-clock  # the device trace window is real seconds of real execution by definition
            time.sleep(seconds)
        finally:
            jax.profiler.stop_trace()
        return {"mode": "device", "path": out_dir,
                "seconds": seconds,
                "hint": "open with Perfetto / tensorboard profile"}

    def _capture_host(self, out_dir: str, seconds: float,
                      hz: int) -> Dict:
        me = threading.get_ident()
        stacks: Counter = Counter()
        samples = 0
        interval = 1.0 / hz
        # ctlint: disable=wall-clock  # sampling profiler: the capture window measures real execution, never simulated time
        deadline = time.monotonic() + seconds
        # ctlint: disable=wall-clock  # see above — real capture window
        while time.monotonic() < deadline:
            for tid, frame in sys._current_frames().items():
                if tid == me:
                    continue  # don't profile the profiler
                parts = []
                while frame is not None:
                    code = frame.f_code
                    parts.append(
                        f"{code.co_name} "
                        f"({os.path.basename(code.co_filename)}:"
                        f"{frame.f_lineno})")
                    frame = frame.f_back
                stacks[";".join(reversed(parts))] += 1
            samples += 1
            # ctlint: disable=wall-clock  # real sampling cadence (hz is a real-time rate)
            time.sleep(interval)
        # ns resolution: two quick captures in one wall-clock second
        # must not overwrite each other
        path = os.path.join(
            out_dir,
            # ctlint: disable=wall-clock  # filename uniqueness stamp
            f"host_profile_{time.time_ns()}.collapsed")
        with open(path, "w") as fp:
            for stack, count in stacks.most_common():
                fp.write(f"{stack} {count}\n")
        return {"mode": "host", "path": path, "seconds": seconds,
                "samples": samples, "distinct_stacks": len(stacks),
                "hint": "collapsed-stack format (flamegraph.pl / "
                        "speedscope)"}


#: process-wide instance (both the REST API and the verdict service
#: route here; the reference's pprof server is process-global too)
PROFILER = Profiler()
