"""Controllers: named retry loops with backoff.

Reference: ``pkg/controller`` (SURVEY.md §2.4) — "the agent's universal
async primitive": a named function re-run on an interval, with
exponential backoff on failure, individually stoppable, all registered
in a manager for introspection (``cilium-dbg status --all-controllers``).
"""

from __future__ import annotations

import threading
from typing import Callable, Dict, Optional

from cilium_tpu.runtime import simclock
from cilium_tpu.runtime.logging import get_logger
from cilium_tpu.runtime.metrics import METRICS

LOG = get_logger("controller")


class Controller:
    def __init__(self, name: str, fn: Callable[[], None],
                 interval: float = 10.0, max_backoff: float = 300.0):
        self.name = name
        self.fn = fn
        self.interval = interval
        self.max_backoff = max_backoff
        self.failures = 0
        self.success_count = 0
        self.last_error: Optional[str] = None
        self._stop = threading.Event()
        self._wake = threading.Event()
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name=f"ctrl-{name}")

    def start(self) -> "Controller":
        self._thread.start()
        return self

    def trigger(self) -> None:
        """Run now (used instead of waiting out the interval)."""
        self._wake.set()

    def stop(self) -> None:
        self._stop.set()
        self._wake.set()
        # Join so no in-flight fn() run survives stop(): an unjoined
        # first-run checkpoint writing stale state after the caller's
        # final synchronous checkpoint corrupts restore.
        if (self._thread.is_alive()
                and threading.current_thread() is not self._thread):
            self._thread.join(timeout=30.0)

    def _loop(self) -> None:
        while not self._stop.is_set():
            try:
                self.fn()
                self.success_count += 1
                self.failures = 0
                self.last_error = None
                METRICS.inc("cilium_tpu_controller_runs_total",
                            labels={"controller": self.name,
                                    "status": "success"})
                delay = self.interval
            except Exception as e:
                self.failures += 1
                self.last_error = f"{type(e).__name__}: {e}"
                LOG.error("controller run failed",
                          extra={"fields": {"controller": self.name,
                                            "failures": self.failures,
                                            "error": self.last_error}})
                METRICS.inc("cilium_tpu_controller_runs_total",
                            labels={"controller": self.name,
                                    "status": "failure"})
                delay = min(self.max_backoff,
                            self.interval * (2 ** min(self.failures, 8)))
            # the interval/backoff wait rides the process clock: under
            # a VirtualClock the next run is one advance() away, so
            # heartbeat/reconcile controllers simulate hours in ms
            simclock.wait_on(self._wake, delay)
            self._wake.clear()


class ControllerManager:
    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._controllers: Dict[str, Controller] = {}
        # Per-name locks serialize update/remove for one controller
        # name without making one name's slow stop() (thread join)
        # block every other name — while stop()/status() stay on the
        # cheap manager lock.
        self._name_locks: Dict[str, threading.Lock] = {}
        self._closed = False
        # bumped at the START of every stop_all: an update() that began
        # before the bump (and so may have been missed by stop_all's
        # snapshot) sees the change and stops its own controller
        self._gen = 0

    def _name_lock(self, name: str) -> threading.Lock:
        with self._lock:
            lk = self._name_locks.get(name)
            if lk is None:
                lk = self._name_locks[name] = threading.Lock()
            return lk

    def update(self, name: str, fn: Callable[[], None],
               interval: float = 10.0) -> Controller:
        with self._name_lock(name):
            with self._lock:
                gen = self._gen
                old = self._controllers.pop(name, None)
            if old is not None:
                old.stop()  # joins the thread; only this name waits
            c = Controller(name, fn, interval=interval).start()
            with self._lock:
                if not self._closed and self._gen == gen:
                    self._controllers[name] = c
                    return c
        # stop_all() started or ran while we were in flight: our pop
        # may have hidden the old controller from its snapshot, so
        # honor the stop ourselves instead of leaking a running thread
        c.stop()
        return c

    def remove(self, name: str) -> None:
        with self._name_lock(name):
            with self._lock:
                c = self._controllers.pop(name, None)
            if c is not None:
                c.stop()

    def trigger(self, name: str) -> None:
        with self._lock:
            c = self._controllers.get(name)
        if c is not None:
            c.trigger()

    def status(self) -> Dict[str, Dict]:
        with self._lock:
            return {
                name: {
                    "success-count": c.success_count,
                    "failure-count": c.failures,
                    "last-error": c.last_error,
                }
                for name, c in self._controllers.items()
            }

    def stop_all(self) -> None:
        """Stop every controller. An update() racing this call has its
        controller stopped instead of leaking an unstoppable thread;
        updates after stop_all() returns register normally (the agent
        is restartable)."""
        with self._lock:
            self._closed = True
            self._gen += 1
            controllers = list(self._controllers.values())
            self._controllers.clear()
        try:
            for c in controllers:  # join outside the lock: a slow
                c.stop()           # in-flight fn must not block status()
        finally:
            with self._lock:
                self._closed = False

