"""Lease-backed service advertisement in the kvstore.

The recurring pattern behind peer discovery (hubble observers, health
endpoints; the reference publishes the analogous per-node state as
CiliumNode/peer entries): publish a key under a TTL lease, heartbeat
it, and let the lease age the entry out if the publisher dies. The
heartbeat is authoritative on KEY PRESENCE, not the lease object — the
in-process store's keepalive never fails, and a >TTL stall must
re-publish rather than silently extend a lease whose key was GC'd.
"""

from __future__ import annotations


class Advertisement:
    """Publish ``key = value`` under a TTL lease; heartbeat keeps it
    alive, re-publishing after any lapse; ``withdraw()`` removes it
    immediately (clean departure)."""

    def __init__(self, store, key: str, value: str, ttl: float = 60.0):
        self.store = store
        self.key = key
        self.value = value
        self.ttl = ttl
        self._lease = None
        self.publish()

    def publish(self) -> None:
        self._lease = self.store.lease(self.ttl)
        self.store.set(self.key, self.value, lease=self._lease)

    def heartbeat(self) -> None:
        if self._lease.expired() or self.store.get(self.key) is None:
            self.publish()
            return
        try:
            self._lease.keepalive()
        except KeyError:  # remote store: server-side expiry is an error
            self.publish()
            return
        if self.store.get(self.key) is None:  # lapsed in the window
            self.publish()

    def withdraw(self) -> None:
        try:
            self.store.delete(self.key)
            if self._lease is not None:
                self.store.revoke(self._lease)
        # ctlint: disable=swallowed-exception  # withdraw is best-effort
        except Exception:
            pass  # store gone first: the lease ages the entry out
