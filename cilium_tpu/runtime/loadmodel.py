"""DST-driven load model for the serving loop: production scale
without production hardware.

``make serve-soak``'s question is the ROADMAP's million-stream one:
does the continuously-batched serving plane (runtime/serveloop.py +
engine/ring.py) hold its latency and shed discipline with ≥100k
CONCURRENT streams? Real traffic at that scale can't run on a CI
host — but its *statistics* can, as virtual streams under the
simulation clock (runtime/simclock.py):

* **Heavy-tailed emission.** Per-stream chunk cadence is Pareto — a
  few chatty streams, a long quiet tail — which is what makes
  continuous batching the right shape: any single pack cycle sees a
  small, changing subset of streams.
* **Diurnal swing.** The emission rate swells and ebbs over one
  compressed virtual "day", so the loop crosses load levels instead
  of sitting at one operating point.
* **Reconnect storms.** Burst reconnect-with-resume over a seeded
  sample of streams — live leases must be RENEWED (never granted, so
  never double-counted), expired ones re-granted, and the at-least-
  once chunk replay must stay verdict-deterministic.
* **Seeded faults.** ``serve.lease`` / ``serve.ring_slot`` fire per
  the plan; every fired fault is an explicit counted shed, never a
  hang or a wrong verdict.

Invariants, checked after EVERY driver event (a violation names the
event index): lease accounting exact (grants − expiries − releases ==
occupancy ≤ capacity), sampled verdict correctness (resolved tickets
bit-equal to the engine's direct verdicts for the chunk's flows),
memo-accounting honesty, and no silent losses (every submission
resolves, sheds, or errors — nothing vanishes). End-of-run gates:
zero violations, concurrency peak ≥ target, p99 ≤ ``p99-factor`` ×
the unloaded baseline, shed rate ≤ bound.

Two clock modes: ``thread`` (default — the PRODUCTION pack thread
under an autojumping VirtualClock, `make soak`'s discipline) and
``driven`` (inline ``ServeLoop.step``, byte-deterministic; what the
DST schedule arm uses). The lane writes one provenance-stamped line
to ``BENCH_SERVE_r07.jsonl`` (perf-report consumes it; the dst rider
carries the seed).
"""

from __future__ import annotations

import argparse
import hashlib
import heapq
import json
import math
import os
import random
import sys
from typing import Dict, List, Optional, Sequence, Tuple

from cilium_tpu.runtime import faults, simclock
from cilium_tpu.runtime.serveloop import (
    LeaseExpired,
    ServeLoop,
    ShedError,
)

#: event kinds, processed in virtual-time order
_ARRIVE, _EMIT, _STORM = 0, 1, 2


class _Chunk:
    """One pooled chunk: parsed capture sections + the engine's
    ground-truth verdicts (the sampled-correctness oracle)."""

    __slots__ = ("sections", "truth", "n")

    def __init__(self, sections, truth):
        self.sections = sections
        self.truth = truth
        self.n = len(truth)


class Violation(AssertionError):
    def __init__(self, index: int, name: str, detail: str):
        super().__init__(f"event {index}: [{name}] {detail}")
        self.index = index
        self.invariant = name
        self.detail = detail


def _build_policy(n_rules: int, chunk_flows: int,
                  protocol_mix: float = 0.0):
    """The policy half of the world: synth scenario(s) → realized
    per-identity rule sets + the flow pools chunks draw from. Split
    out of :func:`_build_world` so the serving FLEET
    (runtime/fleetserve.py) can regenerate the SAME policy on every
    replica loader — identical rules per host is the precondition for
    cross-host handoff serving identical verdicts, and for the
    bank-artifact store satisfying every host after the first
    without a recompile."""
    from cilium_tpu.ingest import synth

    n_flows = max(1024, chunk_flows * 8)
    sc_http = synth.scenario_by_name("http", n_rules, n_flows)
    proto_flows: List = []
    if protocol_mix > 0:
        sc_proto = synth.scenario_by_name(
            "protocols", max(12, n_rules // 2), n_flows)
        merged = synth.SynthScenario(
            name="servemix",
            rules=sc_http.rules + sc_proto.rules,
            endpoints={**sc_http.endpoints, **sc_proto.endpoints},
            flows=[])
        per_identity, merged = synth.realize_scenario(merged)
        ids = merged.ids
        for f in sc_http.flows:
            f.src_identity, f.dst_identity = (ids["client"],
                                              ids["server"])
        for f in sc_proto.flows:
            f.src_identity, f.dst_identity = (ids["client"],
                                              ids["polysvc"])
        proto_flows = list(sc_proto.flows)
        scenario_flows = list(sc_http.flows)
    else:
        per_identity, sc_http = synth.realize_scenario(sc_http)
        scenario_flows = list(sc_http.flows)
    return per_identity, scenario_flows, proto_flows


def _build_world(seed: int, n_rules: int, pool_chunks: int,
                 chunk_flows: int, protocol_mix: float = 0.0):
    """A real compiled serving slice: synth policy → TPU loader →
    chunk pool with engine ground truth. ``protocol_mix`` > 0 blends
    protocol-frontend traffic (cassandra/memcache/r2d2, ISSUE 15)
    into the pool at that chunk fraction: ONE loader serves a merged
    policy (http + frontend rule sets), so mixed-family packs ride
    one fused dispatch exactly like production."""
    from cilium_tpu.core.config import Config
    from cilium_tpu.ingest.binary import (
        capture_from_bytes,
        capture_to_bytes,
    )
    from cilium_tpu.runtime.loader import Loader

    per_identity, scenario_flows, proto_flows = _build_policy(
        n_rules, chunk_flows, protocol_mix=protocol_mix)
    cfg = Config()
    cfg.enable_tpu_offload = True
    loader = Loader(cfg)
    loader.regenerate(per_identity, revision=1)
    engine = loader.engine
    rng = random.Random(seed ^ 0x5EED)
    pool: List[_Chunk] = []
    for _ in range(pool_chunks):
        src = (proto_flows if proto_flows
               and rng.random() < protocol_mix else scenario_flows)
        flows = [src[rng.randrange(len(src))]
                 for _ in range(chunk_flows)]
        sections = capture_from_bytes(capture_to_bytes(flows))
        truth = [int(v) for v in
                 engine.verdict_flows(flows)["verdict"]]
        pool.append(_Chunk(sections, truth))
    return loader, pool


class LoadModel:
    """The 100k-stream soak. ``run()`` returns the result dict the
    lane stamps; ``violations`` carries any invariant failures."""

    def __init__(self, seed: int = 0, streams: int = 100_000,
                 virtual_s: float = 120.0, ramp_s: float = 30.0,
                 capacity: Optional[int] = None,
                 pack_interval_ms: float = 50.0,
                 lease_ttl_s: float = 300.0,
                 chunk_flows: int = 8, pool_chunks: int = 64,
                 n_rules: int = 60, storms: int = 3,
                 storm_size: int = 2000,
                 pareto_xm_s: float = 30.0, pareto_alpha: float = 1.3,
                 fault_rules: Optional[Sequence] = None,
                 sample_every: int = 64, mode: str = "thread",
                 protocol_mix: float = 0.0):
        self.seed = seed
        self.streams = int(streams)
        self.virtual_s = float(virtual_s)
        self.ramp_s = float(ramp_s)
        self.capacity = (int(capacity) if capacity
                         else max(1024, 1 << (self.streams - 1)
                                  .bit_length()))
        self.pack_interval_s = pack_interval_ms / 1e3
        self.lease_ttl_s = float(lease_ttl_s)
        self.chunk_flows = int(chunk_flows)
        self.pool_chunks = int(pool_chunks)
        self.n_rules = int(n_rules)
        self.storms = int(storms)
        self.storm_size = int(storm_size)
        self.pareto_xm_s = float(pareto_xm_s)
        self.pareto_alpha = float(pareto_alpha)
        self.fault_rules = list(fault_rules or ())
        self.sample_every = max(1, int(sample_every))
        self.mode = mode
        #: fraction of pool chunks carrying protocol-frontend traffic
        #: (cassandra/memcache/r2d2) instead of http — the ISSUE-15
        #: protocol-mix knob; the lane default is 0.2
        self.protocol_mix = float(protocol_mix)
        self.rng = random.Random(seed)
        self.violations: List[Dict] = []
        self.latencies: List[float] = []
        self.submissions = 0
        self.resolved = 0
        self.shed_submits = 0
        self.shed_connects = 0
        self.retries = 0
        self.concurrency_peak = 0
        self.sampled_checks = 0

    # -- schedule construction -------------------------------------------
    def _diurnal(self, t: float) -> float:
        """Emission-rate multiplier: one compressed virtual day over
        the run, ±60% swing."""
        return 1.0 + 0.6 * math.sin(2.0 * math.pi * t / self.virtual_s)

    def _next_interval(self, t: float) -> float:
        """Heavy-tailed (Pareto) inter-chunk gap, diurnally scaled."""
        u = max(1e-9, 1.0 - self.rng.random())
        gap = self.pareto_xm_s / (u ** (1.0 / self.pareto_alpha))
        return min(gap, self.virtual_s) / self._diurnal(t)

    def _build_events(self) -> List[Tuple[float, int, int, int]]:
        """(t, seq, kind, stream) heap — seeded, self-contained."""
        events: List[Tuple[float, int, int, int]] = []
        seq = 0
        for i in range(self.streams):
            t = self.rng.random() * self.ramp_s
            events.append((t, seq, _ARRIVE, i))
            seq += 1
            # first emission shortly after arrival, then Pareto gaps
            # (scheduled lazily as each emission fires)
            t_emit = t + self.rng.random() * self.pareto_xm_s
            events.append((t_emit, seq, _EMIT, i))
            seq += 1
        for k in range(self.storms):
            t = self.ramp_s + (k + 1) * (
                (self.virtual_s - self.ramp_s) / (self.storms + 1))
            events.append((t, seq, _STORM, k))
            seq += 1
        heapq.heapify(events)
        self._seq = seq
        return events

    # -- invariants -------------------------------------------------------
    def _check(self, loop: ServeLoop, index: int) -> None:
        st = loop.status()
        occ = st["occupancy"]
        self.concurrency_peak = max(self.concurrency_peak, occ)
        if occ > loop.ring.capacity:
            raise Violation(index, "ring-occupancy",
                            f"{occ} leased > capacity "
                            f"{loop.ring.capacity}")
        books = st["grants"] - st["expiries"] - st["releases"]
        if books != occ:
            raise Violation(
                index, "lease-accounting",
                f"grants {st['grants']} - expiries {st['expiries']} "
                f"- releases {st['releases']} = {books} != occupancy "
                f"{occ}")
        memo = st["memo"]
        if memo and (memo["hits"] < 0 or memo["misses"] < 0
                     or memo["hits"] + memo["misses"] < 0):
            raise Violation(index, "memo-accounting", str(memo))

    def _sweep(self, outstanding: List, index: int) -> None:
        """Collect resolved tickets: latencies, sampled correctness
        AND sampled explanation decode, retry bookkeeping. Nothing
        may vanish."""
        keep = []
        for ticket, chunk, stream in outstanding:
            if not ticket.done:
                keep.append((ticket, chunk, stream))
                continue
            self.resolved += 1
            if ticket.error is not None:
                # session-reset / lease-expired: a retryable loss the
                # stream re-submits; counted, never silent
                self.retries += 1
                continue
            lat = ticket.latency
            if lat is not None:
                self.latencies.append(lat)
            if self.resolved % self.sample_every == 0:
                self.sampled_checks += 1
                got = [int(v) for v in ticket.verdicts]
                if got != chunk.truth:
                    raise Violation(
                        index, "verdict-correctness",
                        f"stream {stream}: ring verdicts diverged "
                        f"from the engine's direct verdicts")
                self._check_explainable(ticket, chunk, stream, index)
        outstanding[:] = keep

    def _check_explainable(self, ticket, chunk, stream,
                           index: int) -> None:
        """Sampled explanation decode: a served chunk's provenance
        must be present, its L7 winners must resolve through the
        policy's AttributionMap, and cited generations must be sane
        (in (0, current])."""
        import numpy as np

        from cilium_tpu.engine.memo import policy_generation

        prov = ticket.prov
        if prov is None:
            raise Violation(index, "explain-coverage",
                            f"stream {stream}: served chunk carried "
                            f"no provenance bundle")
        amap = self._loop._amap_for(self._loop.ring.session.engine)
        l7m = np.asarray(prov.l7_match)
        gens = np.asarray(prov.gens)
        l7t = np.asarray(chunk.sections[0]["l7_type"])
        gen = chunk.sections[4]
        if gen is not None:
            # protocol-frontend records carry the canonical GENERIC
            # code in the capture; the engine verdicts them on their
            # FAMILY lane — decode the attribution code in that space
            # (the same normalization every featurize path applies)
            from cilium_tpu.engine.verdict import _gen_l7g_cols

            fam, _uniq, _row = _gen_l7g_cols(
                gen, chunk.sections[2], chunk.sections[3])
            l7t = np.where(fam > 0, fam, l7t)
        gen_now = policy_generation()
        for r in range(min(len(l7m), len(l7t))):
            code = int(l7m[r])
            if code >= 0 and (amap is None
                              or amap.resolve(int(l7t[r]),
                                              code) is None):
                raise Violation(
                    index, "explain-undecodable",
                    f"stream {stream} row {r}: l7_match={code} does "
                    f"not resolve to a live rule")
            if not (0 < int(gens[r]) <= gen_now):
                raise Violation(
                    index, "explain-undecodable",
                    f"stream {stream} row {r}: cited generation "
                    f"{int(gens[r])} outside (0, {gen_now}]")

    # -- the run ----------------------------------------------------------
    def run(self) -> Dict:
        loader, pool = _build_world(self.seed, self.n_rules,
                                    self.pool_chunks, self.chunk_flows,
                                    protocol_mix=self.protocol_mix)
        autojump = self.mode == "thread"
        clock = simclock.VirtualClock(
            autojump=0.001 if autojump else None, poll=0.001)
        plan = faults.FaultPlan(rules=self.fault_rules, seed=self.seed)
        result: Dict = {}
        with simclock.use(clock):
            loop = ServeLoop(loader, capacity=self.capacity,
                             lease_ttl_s=self.lease_ttl_s,
                             pack_interval_s=self.pack_interval_s,
                             max_slot_pending=8)
            self._loop = loop
            # -- unloaded baseline: one stream, quiet ring -------------
            base = self._baseline(loop, pool, clock, autojump)
            with faults.inject(plan):
                if autojump:
                    loop.start()
                try:
                    self._drive(loop, pool, clock, autojump)
                except Violation as v:
                    self.violations.append({
                        "index": v.index, "invariant": v.invariant,
                        "detail": v.detail})
            # drain flushes whatever the tail left pending
            loop.drain()
            loop.stop()
            st = loop.status()
            result = self._result(loop, st, base, clock)
        return result

    def _baseline(self, loop: ServeLoop, pool, clock,
                  autojump: bool) -> float:
        """Unloaded p99: one stream, one chunk per pack cycle. Driven
        inline — the production thread isn't running yet, so the
        driver advances (or virtually sleeps) one interval per chunk."""
        lease = loop.connect("baseline")
        lats: List[float] = []
        for k in range(20):
            chunk = pool[k % len(pool)]
            ticket = loop.submit(lease, *chunk.sections)
            if autojump:
                simclock.sleep(self.pack_interval_s)
            else:
                clock.advance(self.pack_interval_s)
            loop.step()
            if ticket.done and ticket.latency is not None:
                lats.append(ticket.latency)
        loop.disconnect(lease)
        lats.sort()
        return lats[min(len(lats) - 1, int(0.99 * len(lats)))] \
            if lats else self.pack_interval_s

    def _drive(self, loop: ServeLoop, pool, clock, autojump: bool
               ) -> None:
        events = self._build_events()
        leases: Dict[int, object] = {}
        outstanding: List = []
        if autojump:
            index = self._drive_thread(loop, pool, clock, events,
                                       leases, outstanding)
            simclock.sleep(2 * self.pack_interval_s)
        else:
            index = self._drive_driven(loop, pool, clock, events,
                                       leases, outstanding)
            clock.advance(2 * self.pack_interval_s)
            loop.step()
        self._sweep(outstanding, index)

    def _run_event(self, loop, pool, events, leases, outstanding,
                   kind, arg, index) -> None:
        if kind == _ARRIVE:
            self._arrive(loop, leases, arg, events)
        elif kind == _EMIT:
            self._emit(loop, leases, pool, outstanding, arg,
                       events, index)
        elif kind == _STORM:
            self._storm(loop, leases, pool, outstanding, index)
        self._check(loop, index)

    def _drive_thread(self, loop, pool, clock, events, leases,
                      outstanding) -> int:
        """Autojump mode: the PRODUCTION pack thread dispatches; the
        driver wakes once per pack interval and replays that bucket's
        events — 100k streams cost one wake per cycle, not one per
        event."""
        index = 0
        while events:
            bucket_end = events[0][0] + self.pack_interval_s
            batch = []
            while events and events[0][0] <= bucket_end:
                batch.append(heapq.heappop(events))
            target = max(bucket_end, batch[-1][0])
            now = clock.now()
            if target > now:
                simclock.sleep(target - now)
            # the driver is CPU-busy, not idle, while it replays the
            # bucket: hold the autojump so host work doesn't read as
            # quiet and race virtual time ahead of the submissions
            with simclock.hold():
                for _t, _seq, kind, arg in batch:
                    index += 1
                    self._run_event(loop, pool, events, leases,
                                    outstanding, kind, arg, index)
                self._sweep(outstanding, index)
        return index

    def _drive_driven(self, loop, pool, clock, events, leases,
                      outstanding) -> int:
        """Driven mode (deterministic, the DST arm's face): pack
        ticks are first-class — the clock advances event-by-event and
        the loop steps exactly every pack interval, so latency is a
        pure function of the schedule."""
        index = 0
        next_step = clock.now() + self.pack_interval_s
        while events:
            if events[0][0] <= next_step:
                t, _seq, kind, arg = heapq.heappop(events)
                clock.advance_to(t)
                index += 1
                self._run_event(loop, pool, events, leases,
                                outstanding, kind, arg, index)
            else:
                clock.advance_to(next_step)
                loop.step()
                next_step += self.pack_interval_s
                self._sweep(outstanding, index)
        return index

    def _arrive(self, loop, leases, i, events) -> None:
        try:
            leases[i] = loop.connect(f"vs{i}")
        except ShedError:
            self.shed_connects += 1
            # retry once, later — the model's clients back off
            heapq.heappush(events, (simclock.now() + 1.0,
                                    self._bump(), _ARRIVE, i))

    def _emit(self, loop, leases, pool, outstanding, i, events,
              index) -> None:
        lease = leases.get(i)
        if lease is None:
            return  # never admitted (shed twice): stays departed
        chunk = pool[(i * 2654435761 + index) % len(pool)]
        try:
            ticket = loop.submit(lease, *chunk.sections)
            outstanding.append((ticket, chunk, i))
            self.submissions += 1
        except LeaseExpired:
            # idle past TTL: reconnect-with-resume grants a fresh
            # slot, then the chunk re-sends
            leases.pop(i, None)
            try:
                leases[i] = loop.connect(f"vs{i}", resume=True)
                ticket = loop.submit(leases[i], *chunk.sections)
                outstanding.append((ticket, chunk, i))
                self.submissions += 1
                self.retries += 1
            except (ShedError, LeaseExpired):
                self.shed_connects += 1
        except ShedError:
            self.shed_submits += 1
        # schedule the stream's next emission (heavy-tailed)
        t_next = simclock.now() + self._next_interval(simclock.now())
        if t_next < self.virtual_s:
            heapq.heappush(events, (t_next, self._bump(), _EMIT, i))

    def _storm(self, loop, leases, pool, outstanding, index) -> None:
        """Reconnect storm: a seeded burst of streams drops and
        re-dials with resume. Live leases renew WITHOUT a second
        grant; expired ones re-grant; each resumed stream replays one
        chunk (at-least-once — verdicts are deterministic)."""
        ids = [self.rng.randrange(self.streams)
               for _ in range(min(self.storm_size, self.streams))]
        for i in ids:
            old = leases.get(i)
            grants_before = loop.grants
            try:
                lease = loop.connect(f"vs{i}", resume=True)
            except ShedError:
                self.shed_connects += 1
                leases.pop(i, None)
                continue
            # the never-double-counted property, exactly: a resume
            # that found its lease alive returns the SAME lease and
            # must not have granted (only this driver thread ever
            # connects, so the grants counter is race-free here)
            if lease is old and loop.grants != grants_before:
                raise Violation(
                    index, "lease-double-grant",
                    f"stream {i}: reconnect-with-resume renewed a "
                    f"live lease AND counted a grant")
            leases[i] = lease
            chunk = pool[i % len(pool)]
            try:
                ticket = loop.submit(lease, *chunk.sections)
                outstanding.append((ticket, chunk, i))
                self.submissions += 1
            except (ShedError, LeaseExpired):
                self.shed_submits += 1

    def _bump(self) -> int:
        self._seq += 1
        return self._seq

    def _result(self, loop, st, base_p99, clock) -> Dict:
        lats = sorted(self.latencies)

        def pct(q):
            return (lats[min(len(lats) - 1, int(q * len(lats)))]
                    if lats else 0.0)

        shed_total = self.shed_submits + self.shed_connects
        denom = max(1, self.submissions + shed_total)
        prov = st.get("provenance", {})
        slo = st.get("slo", {})
        burn = slo.get("burn_rates", {})
        # gate on the LONGEST window: it covers the whole virtual run
        long_w = (f"{int(max(loop.slo.windows_s))}s"
                  if loop.slo is not None else "")
        return {
            "explain_coverage": prov.get("explain_coverage", 0.0),
            "records_explained": prov.get("records_explained", 0),
            "records_unexplained": prov.get("records_unexplained", 0),
            "slo_burn": burn,
            "slo_burn_p99": burn.get("serve-p99", {}).get(long_w, 0.0),
            "slo_burn_shed": burn.get("serve-shed", {}).get(long_w,
                                                            0.0),
            "seed": self.seed,
            "mode": self.mode,
            "streams": self.streams,
            "concurrency_peak": self.concurrency_peak,
            "virtual_s": self.virtual_s,
            "simulated_s": round(clock.simulated, 3),
            "submissions": self.submissions,
            "resolved": self.resolved,
            "served_records": st["served_records"],
            "packs": st["packs"],
            "records_packed": st["records_packed"],
            "grants": st["grants"],
            "expiries": st["expiries"],
            "releases": st["releases"],
            "sheds": shed_total,
            "shed_rate": round(shed_total / denom, 6),
            "retries": self.retries,
            "chunk_errors": st["chunk_errors"],
            "bytes_saved": st["bytes_saved"],
            "bytes_shipped": st["bytes_shipped"],
            "memo": st["memo"],
            "sampled_checks": self.sampled_checks,
            "p50_ms": round(pct(0.50) * 1e3, 3),
            "p99_ms": round(pct(0.99) * 1e3, 3),
            "p99_unloaded_ms": round(base_p99 * 1e3, 3),
            "p99_ratio": round(pct(0.99) / max(base_p99, 1e-9), 3),
            "violations": list(self.violations),
        }


# -- the `make serve-soak` lane ----------------------------------------------


def main(argv: Optional[Sequence[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        description="100k-virtual-stream serving-loop soak (DST load "
                    "model over the verdict ring)")
    ap.add_argument("--streams", type=int, default=100_000)
    ap.add_argument("--seed", type=int,
                    default=int(os.environ.get("CILIUM_TPU_DST_SEED",
                                               "0") or 0))
    ap.add_argument("--virtual-s", type=float, default=120.0)
    ap.add_argument("--pack-interval-ms", type=float, default=50.0)
    ap.add_argument("--lease-ttl-s", type=float, default=300.0)
    ap.add_argument("--mode", choices=("thread", "driven"),
                    default="thread")
    ap.add_argument("--storms", type=int, default=3)
    ap.add_argument("--storm-size", type=int, default=2000)
    ap.add_argument("--faults", type=int, default=12,
                    help="serve.lease/serve.ring_slot fires to arm "
                         "(seeded; 0 disables)")
    ap.add_argument("--p99-factor", type=float, default=2.0)
    ap.add_argument("--max-shed-rate", type=float, default=0.02)
    ap.add_argument("--min-explain-coverage", type=float,
                    default=0.999,
                    help="served verdicts carrying decodable "
                         "provenance, as a fraction")
    ap.add_argument("--max-burn", type=float, default=1.0,
                    help="whole-run SLO burn-rate ceiling "
                         "(1.0 = exactly the declared budget)")
    ap.add_argument("--protocol-mix", type=float, default=0.2,
                    help="fraction of traffic chunks carrying "
                         "protocol-frontend records (ISSUE 15)")
    ap.add_argument("--target-concurrency", type=int, default=0,
                    help="gate floor (default: 95%% of --streams)")
    ap.add_argument("--out", default="BENCH_SERVE_r07.jsonl")
    args = ap.parse_args(argv)

    rules = []
    if args.faults > 0:
        rules = [
            faults.FaultRule("serve.lease", prob=0.0005,
                             times=args.faults),
            faults.FaultRule("serve.ring_slot", prob=0.0005,
                             times=args.faults),
        ]
    t0 = simclock.perf()
    model = LoadModel(seed=args.seed, streams=args.streams,
                      virtual_s=args.virtual_s,
                      pack_interval_ms=args.pack_interval_ms,
                      lease_ttl_s=args.lease_ttl_s,
                      storms=args.storms, storm_size=args.storm_size,
                      fault_rules=rules, mode=args.mode,
                      protocol_mix=args.protocol_mix)
    result = model.run()
    wall_s = simclock.perf() - t0
    result["wall_s"] = round(wall_s, 3)
    result["speedup_vs_real_time"] = round(
        result["simulated_s"] / max(wall_s, 1e-9), 1)

    target = args.target_concurrency or int(0.95 * args.streams)
    gates = {
        "violations": len(result["violations"]) == 0,
        "concurrency": result["concurrency_peak"] >= target,
        "p99": result["p99_ratio"] <= args.p99_factor,
        "shed_rate": result["shed_rate"] <= args.max_shed_rate,
        "bytes_saved": result["bytes_saved"] > 0,
        # ISSUE-14 provenance gates: ≥99.9% of served verdicts carry
        # a decodable provenance bundle, and the declared-SLO burn
        # rates over the whole-run window stay within budget
        "explain_coverage":
            result["explain_coverage"] >= args.min_explain_coverage,
        "burn_rate": (result["slo_burn_p99"] <= args.max_burn
                      and result["slo_burn_shed"] <= args.max_burn),
    }
    result["gates"] = {k: bool(v) for k, v in gates.items()}

    from cilium_tpu.runtime.provenance import stamp

    os.environ["CILIUM_TPU_DST_SEED"] = str(args.seed)
    os.environ["CILIUM_TPU_DST_DIGEST"] = hashlib.sha256(
        json.dumps({"streams": args.streams, "seed": args.seed,
                    "virtual_s": args.virtual_s, "mode": args.mode},
                   sort_keys=True).encode()).hexdigest()[:16]
    line = stamp({
        "metric": "serve_soak_p99_ms",
        "value": result["p99_ms"],
        "unit": "ms submit->verdict p99 (virtual)",
        "lane": "serve-soak",
        **{k: v for k, v in result.items() if k != "violations"},
        "violations": len(result["violations"]),
    })
    with open(args.out, "a") as fp:
        fp.write(json.dumps(line) + "\n")

    ok = all(gates.values())
    print(f"[serve-soak] {result['concurrency_peak']} concurrent "
          f"virtual streams (target {target}), "
          f"{result['submissions']} chunks / "
          f"{result['served_records']} records over "
          f"{result['packs']} packs; p99 {result['p99_ms']}ms "
          f"({result['p99_ratio']}x unloaded), shed rate "
          f"{result['shed_rate']}, {result['bytes_saved']} H2D bytes "
          f"saved by memo bypass; explain coverage "
          f"{result['explain_coverage']}, burn p99/shed "
          f"{result['slo_burn_p99']}/{result['slo_burn_shed']}; "
          f"simulated "
          f"{result['simulated_s']:.0f}s in {wall_s:.1f}s wall "
          f"({result['speedup_vs_real_time']}x); gates "
          f"{'OK' if ok else 'FAILED ' + str(result['gates'])}",
          flush=True)
    if result["violations"]:
        print(f"[serve-soak] violations: {result['violations']}",
              flush=True)
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
