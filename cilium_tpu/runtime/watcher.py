"""Policy directory watcher — the k8s CNP watcher analog.

Reference: ``pkg/k8s`` resource watchers feed CNP add/update/delete
events into the policy repository (SURVEY.md §3.2); the k8s apiserver
is the source of truth and the agent reconciles. Here the source of
truth is a directory of CNP YAML files (one or more CNPs per file):

* new file / changed mtime → parse; **upsert** each CNP (delete rules
  carrying the CNP's provenance labels, then add — the same
  replace-on-update the reference performs);
* removed file → delete the rules of every CNP it last contained;
* parse errors leave the previously-applied state intact (a bad CNP
  must not wipe enforcement) and are surfaced via metrics.

Runs as a named controller (runtime/controller.py retry loop), matching
how watchers live inside the reference agent.
"""

from __future__ import annotations

import glob
import os
from typing import Dict, List, Tuple

from cilium_tpu.policy.api.cnp import load_cnp_yaml
from cilium_tpu.runtime.metrics import METRICS


class PolicyDirWatcher:
    """Reconcile ``*.yaml`` under ``directory`` into the agent's repo."""

    def __init__(self, agent, directory: str):
        self.agent = agent
        self.directory = directory
        # path → (mtime, [cnp labels tuples])
        self._seen: Dict[str, Tuple[float, List[Tuple[str, ...]]]] = {}

    def scan_once(self) -> int:
        """One reconcile pass; returns the number of apply/delete ops."""
        with self.agent.write_lock:
            return self._scan_locked()

    def _scan_locked(self) -> int:
        ops = 0
        present = {}
        for path in sorted(glob.glob(
                os.path.join(self.directory, "**", "*.yaml"),
                recursive=True)):
            try:
                present[path] = os.stat(path).st_mtime
            except OSError:
                continue  # raced with deletion

        # deletions first: a rename (delete+create) must not end with
        # the old provenance labels still installed
        for path in list(self._seen):
            if path not in present:
                _, label_sets = self._seen.pop(path)
                for labels in label_sets:
                    self.agent.policy_delete(list(labels), wait=False)
                    ops += 1

        for path, mtime in present.items():
            old = self._seen.get(path)
            if old is not None and old[0] == mtime:
                continue
            try:
                cnps = load_cnp_yaml(path)
            except Exception:
                METRICS.inc("cilium_tpu_policy_watch_parse_errors_total", 1)
                # keep previously-applied rules, but record the mtime so
                # the bad file is not re-parsed until it changes again
                self._seen[path] = (mtime, old[1] if old else [])
                continue
            new_label_sets = [tuple(c.labels) for c in cnps]
            if old is not None:  # update: drop CNPs no longer in the file
                for labels in old[1]:
                    if labels not in new_label_sets:
                        self.agent.policy_delete(list(labels), wait=False)
                        ops += 1
            for cnp in cnps:
                self.agent.policy_delete(list(cnp.labels), wait=False)
                self.agent.policy_add(cnp, wait=False)
                ops += 1
            self._seen[path] = (mtime, new_label_sets)

        if ops:
            self.agent.endpoint_manager.regenerate_all(wait=False)
            METRICS.inc("cilium_tpu_policy_watch_ops_total", ops)
        return ops

    def register(self, controllers, interval: float = 2.0) -> None:
        """Install as a named retry-loop controller."""
        controllers.update("policy-dir-watcher", self.scan_once,
                           interval=interval)
