"""Sharded, byte-bounded identity-fingerprint store (ISSUE 13).

The loader derives every commit's artifact key and bank-scoped
invalidation delta from per-identity fingerprints
(``runtime/loader.identity_fingerprints`` /
``identity_family_fingerprints``). At churn-soak scale (12 identities)
recomputing them per regeneration is noise; at BASELINE configs[4]
scale (10k identities × 5k CNP) the full walk — pickle + sha over
every identity's entry set, twice — dominates the update path and
grows with policy size, not with the change.

This store makes the walk O(Δ): fingerprints are cached per identity,
keyed by the **object identity** of the resolved MapState. The
contract is the one in-tree resolvers already satisfy: a MapState is
immutable once handed to the loader — every resolver builds fresh
objects per resolve, so a caller that mutates state gets fresh
objects and therefore fresh fingerprints, while a fleet-scale caller
that reuses unchanged MapState objects across updates (10k identities
sharing ~hundreds of service-class states) pays only for the
identities it actually touched. The entry pins a strong reference to
the MapState, so its ``id()`` can never be recycled while the cache
entry lives — the identity check is sound, not heuristic.

Shards are byte-bounded LRUs (``[compile] fp_cache_max_bytes``
total). Eviction is pure cost, never correctness: an evicted bundle
recomputes on next use and fingerprints are pure functions of
content."""

from __future__ import annotations

import collections
import threading
from typing import Callable, Dict

from cilium_tpu.runtime.metrics import (
    FP_CACHE_EVICTIONS,
    METRICS,
)

#: shard count: fixed (identity id mod N) — the store is in-process,
#: so sharding buys lock granularity and eviction isolation, not
#: placement; 8 matches the registry default
N_SHARDS = 8


class _FPShard:
    __slots__ = ("lock", "entries", "bytes")

    def __init__(self):
        self.lock = threading.Lock()
        #: identity → (mapstate ref, fingerprint bundle, nbytes),
        #: LRU order
        self.entries: "collections.OrderedDict[int, Tuple[object, object, int]]" = \
            collections.OrderedDict()
        self.bytes = 0


def _bundle_bytes(bundle) -> int:
    """Rough, stable byte estimate of one (fp, family→port→fp)
    bundle — enough for the LRU bound; exactness buys nothing."""
    fp, fams = bundle
    n = len(fp) + 64
    for fam, ports in fams.items():
        n += len(fam) + 16
        if isinstance(ports, dict):
            for _, pfp in ports.items():
                n += len(pfp) + 24
        else:
            n += len(ports) + 8
    return n


class FingerprintStore:
    """``bundle(per_identity, compute)`` → ``{ep: (fp, family_fps)}``
    with per-object caching. ``compute(ms)`` produces the bundle for
    one MapState; identities sharing one MapState object share one
    computation per call AND one cache entry's content."""

    def __init__(self, max_bytes: int = 64 << 20):
        self.max_bytes = max(0, int(max_bytes))
        self._shards = [_FPShard() for _ in range(N_SHARDS)]
        self.hits = 0
        self.misses = 0
        self.evictions = 0

    def _shard(self, ep: int) -> _FPShard:
        return self._shards[int(ep) % N_SHARDS]

    def bundle(self, per_identity: Dict[int, object],
               compute: Callable[[object], object]
               ) -> Dict[int, object]:
        out: Dict[int, object] = {}
        #: per-call memo keyed by the MapState object id — identities
        #: sharing one resolved state compute once (safe: the dict
        #: values keep every ms alive for the call's duration)
        by_obj: Dict[int, object] = {}
        for ep, ms in per_identity.items():
            sh = self._shard(ep)
            with sh.lock:
                ent = sh.entries.get(ep)
                if ent is not None and ent[0] is ms:
                    sh.entries.move_to_end(ep)
                    out[ep] = ent[1]
                    self.hits += 1
                    continue
            bundle = by_obj.get(id(ms))
            if bundle is None:
                bundle = compute(ms)
                by_obj[id(ms)] = bundle
            self.misses += 1
            out[ep] = bundle
            nbytes = _bundle_bytes(bundle)
            evicted = 0
            with sh.lock:
                old = sh.entries.pop(ep, None)
                if old is not None:
                    sh.bytes -= old[2]
                sh.entries[ep] = (ms, bundle, nbytes)
                sh.bytes += nbytes
                if self.max_bytes:
                    cap = max(1, self.max_bytes // N_SHARDS)
                    while sh.entries and sh.bytes > cap:
                        _, (_, _, nb) = sh.entries.popitem(last=False)
                        sh.bytes -= nb
                        evicted += 1
            if evicted:
                self.evictions += evicted
                METRICS.inc(FP_CACHE_EVICTIONS, evicted)
        return out

    def status(self) -> Dict[str, int]:
        return {
            "entries": sum(len(s.entries) for s in self._shards),
            "bytes": sum(s.bytes for s in self._shards),
            "hits": self.hits,
            "misses": self.misses,
            "evictions": self.evictions,
        }
