"""Loader: compile → stage → hot-swap, behind the feature gate.

The analog of ``pkg/datapath/loader`` (SURVEY.md §2.3): where the
reference compiles/templates BPF ELF per endpoint and attaches it under
a revision counter, we compile rule sets to tensors, stage them on
device, and atomically swap the active engine. The
``enable_tpu_offload`` gate selects TPU engine vs CPU oracle — the
default stays "reference behavior" (oracle), mirroring how eBPF/Envoy
remain the reference's default datapath.
"""

from __future__ import annotations

import threading
from typing import Dict, Optional

from cilium_tpu.core.config import Config
from cilium_tpu.core.labels import LabelSet
from cilium_tpu.policy.mapstate import MapState, PolicyResolver
from cilium_tpu.policy.oracle import OracleVerdictEngine
from cilium_tpu.policy.repository import Repository
from cilium_tpu.policy.selectorcache import SelectorCache
from cilium_tpu.runtime.checkpoint import ArtifactCache, ruleset_fingerprint
from cilium_tpu.runtime import faults
from cilium_tpu.runtime.logging import get_logger, span as _log_span
from cilium_tpu.runtime.metrics import (
    BANK_HOTSWAPS,
    LOADER_ROLLBACKS,
    METRICS,
    SpanStat,
    WARM_RESTORES,
)
from cilium_tpu.runtime.tracing import PHASE_HOST, TRACER

LOG = get_logger("loader")

#: fires between stage and commit: a crash here must leave the
#: PREVIOUS revision serving (tests/test_faults.py pins it)
SWAP_POINT = faults.register_point(
    "loader.swap", "revision swap in Loader.regenerate")

#: artifact-cache key of the warm-restart snapshot (graceful drain
#: writes it; a restarted loader restores from it). Versioned like
#: the policy fingerprint epochs — bump on layout change so stale
#: snapshots read as a clean miss, never as a misparse.
WARM_STATE_KEY = "warm-state-v1"


def _identity_entry_tuple(ms) -> tuple:
    """The verdict-relevant content of one identity's MapState — every
    key/entry field that can change a verdict must appear here, or two
    policies differing only in that field would share a fingerprint."""
    return (
        tuple(sorted(
            (k.identity, k.dport, k.proto, k.direction, k.port_plen,
             e.is_deny, e.l7_wildcard, e.auth_required,
             tuple(sorted(repr(lr) for lr in e.l7_rules)))
            for k, e in ms.entries.items()
        )),
        ms.ingress_enforced,
        ms.egress_enforced,
        getattr(ms, "audit", False),
    )


def identity_fingerprints(per_identity: Dict[int, "MapState"]
                          ) -> Dict[int, str]:
    """Per-identity content fingerprints — the unit of the bank-scoped
    invalidation delta. Cross-process-stable (pickle+sha, like every
    checkpoint fingerprint): a CNP add/delete changes exactly the
    fingerprints of the identities it selects, so a committed revision
    can tell memo owners WHICH rows may have moved."""
    return {ep: ruleset_fingerprint(_identity_entry_tuple(ms))
            for ep, ms in per_identity.items()}


#: rule-family accessors of one L7Rules object — the split behind the
#: family-granular (bank-reference) invalidation delta. The generic
#: accessor splits further at runtime: ``l7proto`` rules whose proto
#: has a registered engine frontend fingerprint under that frontend's
#: family name (cassandra/memcache/r2d2), so a cassandra-rule change
#: refills only cassandra memo rows.
_L7_FAMILIES = (("http", "http"), ("kafka", "kafka"), ("dns", "dns"),
                ("generic", "l7"))


def _l7_family_names() -> tuple:
    """Every family name the split can produce: the static four plus
    the registered frontend families (policy/compiler/frontends)."""
    from cilium_tpu.policy.compiler import frontends as _fe

    return tuple(name for name, _ in _L7_FAMILIES) + tuple(
        sorted(set(_fe.family_names().values())))


def _family_port_of(key) -> int:
    """The bank-reference port bucket of one MapState entry key:
    its exact dport for an exact-port entry, PORT_ALL for wildcard/
    range entries (a row on ANY port may route through them)."""
    from cilium_tpu.engine.memo import PORT_ALL

    plen = getattr(key, "port_plen", None)
    if plen is None:
        plen = 0 if key.dport == 0 else 16
    if key.dport == 0 or plen != 16:
        return PORT_ALL
    return int(key.dport)


def _identity_family_tuples(ms) -> Dict[str, object]:
    """One identity's MapState, split into the independently-
    fingerprintable pieces a verdict reads: ``struct`` (keys, deny/
    auth/wildcard bits, enforcement flags, which entries carry L7
    rules at all — what EVERY row of the identity reads through the
    mapstate gather) plus, per rule family, a PER-PORT split of the
    entries carrying that family's rules (what only rows of that L7
    type AND that destination port read — a row reads a bank only
    through its own entry's ruleset). A path-bank swap on port 8080
    moves only the ``http``/8080 tuple, so the identity's DNS/kafka
    rows — and its port-80 HTTP rows — keep serving."""
    from cilium_tpu.policy.compiler import frontends as _fe

    struct = []
    fam: Dict[str, Dict[int, list]] = {name: {}
                                       for name in _l7_family_names()}
    for k, e in sorted(ms.entries.items(),
                       key=lambda kv: repr(kv[0])):
        key = (k.identity, k.dport, k.proto, k.direction, k.port_plen)
        struct.append((key, e.is_deny, e.l7_wildcard, e.auth_required,
                       bool(e.l7_rules)))
        port = _family_port_of(k)
        for name, attr in _L7_FAMILIES[:3]:
            rules = tuple(sorted(
                repr(r) for lr in e.l7_rules
                for r in getattr(lr, attr)))
            if rules:
                fam[name].setdefault(port, []).append((key, rules))
        # generic/frontend split: each l7proto rule set fingerprints
        # under its FRONTEND family when one is registered, so a
        # cassandra-only change never refills generic (or memcache)
        # rows — the frontend half of the bank-reference granularity
        by_fam: Dict[str, list] = {}
        for lr in e.l7_rules:
            if not lr.l7proto:
                continue
            name = _fe.family_name_of(lr.l7proto) or "generic"
            by_fam.setdefault(name, []).append(
                (lr.l7proto, tuple(sorted(repr(r) for r in lr.l7))))
        for name, rules in by_fam.items():
            fam[name].setdefault(port, []).append(
                (key, tuple(sorted(rules))))
    out: Dict[str, object] = {
        "struct": (tuple(struct), ms.ingress_enforced,
                   ms.egress_enforced, getattr(ms, "audit", False))}
    out.update({name: {port: tuple(v) for port, v in ports.items()}
                for name, ports in fam.items()})
    return out


def identity_family_fingerprints(per_identity: Dict[int, "MapState"]
                                 ) -> Dict[int, Dict[str, object]]:
    """Per-identity per-family-per-port fingerprints: ``{identity:
    {"struct": fp, "http": {port: fp, ...}, "kafka": {...}, "dns":
    {...}, "generic": {...}}}`` — the inputs of the bank-reference
    :class:`PolicyDelta` narrowing (engine/memo.py). A commit whose
    only difference is one family's rules on one port produces a
    delta that refills ONLY that family's rows on that port, counted
    honestly as misses. Port :data:`~cilium_tpu.engine.memo.PORT_ALL`
    buckets wildcard/range entries."""
    return {ep: _family_fps_of_tuples(_identity_family_tuples(ms))
            for ep, ms in per_identity.items()}


def _family_fps_of_tuples(tuples: Dict[str, object]
                          ) -> Dict[str, object]:
    out: Dict[str, object] = {}
    for name, t in tuples.items():
        if name == "struct":
            out[name] = ruleset_fingerprint(t)
        else:
            out[name] = {port: ruleset_fingerprint(v)
                         for port, v in t.items()}
    return out


def _identity_bundle(ms) -> tuple:
    """(whole-identity fp, family/port fps) of one MapState in ONE
    entry walk — the unit the sharded FingerprintStore caches so a
    10k-identity regeneration fingerprints only the identities whose
    resolved state actually changed."""
    return (ruleset_fingerprint(_identity_entry_tuple(ms)),
            _family_fps_of_tuples(_identity_family_tuples(ms)))


def _referenced_secret_values(per_identity, secrets) -> tuple:
    """(namespace, name, value) for every secret referenced by a
    header match in the snapshot — the slice of the secret store that
    affects compiled requirements."""
    refs = set()
    for ms in per_identity.values():
        for entry in ms.entries.values():
            for lr in entry.l7_rules:
                for h in lr.http:
                    for hm in h.header_matches:
                        if hm.secret is not None:
                            refs.add(hm.secret)
    if not refs or secrets is None:
        return ()
    return tuple(sorted(
        (ns, name, secrets.lookup(ns, name) or "") for ns, name in refs))


class Loader:
    """Owns the active engine; single-writer regeneration (the
    reference's endpoint-regeneration queue is serialized per endpoint;
    our unit of regeneration is the whole policy snapshot)."""

    def __init__(self, config: Optional[Config] = None, device=None,
                 secrets=None):
        self.config = config or Config()
        self.device = device
        if self.config.enable_tpu_offload:
            # every engine shape is bucketed to repeat; a persistent
            # XLA cache makes them repeat ACROSS processes (a daemon
            # restart or a fresh bench process otherwise pays 10-20s
            # per shape through the tunneled TPU)
            from cilium_tpu.runtime.xla_cache import (
                enable_persistent_cache,
            )

            enable_persistent_cache()
        #: optional SecretStore: secret-backed header-match values
        #: resolve against it at compile (both engines see the same
        #: snapshot; its fingerprint enters the artifact key so secret
        #: rotation recompiles)
        self.secrets = secrets
        self._lock = threading.Lock()
        self._engine = None
        self._revision = 0
        #: declared tenant partition (None = tenant-blind): built once
        #: from [tenant] config — the bank namer, the compile queue's
        #: fair-share weights, and the admission plane all read it
        if self.config.tenant.enabled:
            from cilium_tpu.runtime.tenant import TenantMap

            self.tenant_map: Optional[TenantMap] = \
                TenantMap.from_config(self.config)
        else:
            self.tenant_map = None
        #: staged generation N+1 (shadow engine + snapshot) while a
        #: canary rollout samples — NEVER the serving engine until
        #: commit_canary() promotes it through the normal regenerate
        self._canary_engine = None
        self._canary_snapshot: Optional[Dict[int, MapState]] = None
        self._canary_revision = 0
        #: the staged snapshot (identity → MapState); the proxy bridge
        #: walks it host-side for per-request header-rewrite ops (the
        #: winning entry's HTTP rules carry the mismatch actions)
        self.per_identity: Dict[int, MapState] = {}
        self._cache = ArtifactCache(
            self.config.loader.cache_dir,
            self.config.loader.enable_cache,
            max_bytes=self.config.loader.artifact_cache_max_bytes)
        self._cache.set_protected({WARM_STATE_KEY})
        # per-loader DFA bank cache: incremental rule updates recompile
        # only the banks whose pattern group changed (SURVEY §7 hard
        # part #4 — the reference stays O(Δ) via SelectorCache; our
        # compile stays O(Δ banks) via this)
        from cilium_tpu.policy.compiler.dfa import BankCache

        self.bank_cache = BankCache()
        #: sharded per-identity fingerprint store: the 10k-identity
        #: fingerprint walk is O(Δ) when the caller reuses unchanged
        #: MapState objects (runtime/fingerprints.py)
        from cilium_tpu.runtime.fingerprints import FingerprintStore

        self._fp_store = FingerprintStore(
            max_bytes=self.config.compile.fp_cache_max_bytes)
        # content-addressed bank registry (policy/compiler/bankplan):
        # the churn-proof compile path — content-defined partition, per-
        # bank quarantine, O(Δ) rebuilds. Supersedes bank_cache when on.
        # The fleet-scale plane rides it: a parallel compile queue
        # ([compile] workers > 0), byte-bounded registry shards, and
        # distributable checksum-verified bank artifacts.
        if self.config.loader.bank_isolation:
            from cilium_tpu.policy.compiler.bankplan import BankRegistry
            from cilium_tpu.policy.compiler.compilequeue import (
                CompileQueue,
            )
            from cilium_tpu.runtime.checkpoint import BankArtifactStore

            ccfg = self.config.compile
            queue = None
            if ccfg.workers > 0:
                # tenant-aware fair queueing: weights + the per-tenant
                # occupancy bound come from the declared partition, so
                # one tenant's compile storm queues against itself
                weight_of = (self.tenant_map.weight_of
                             if self.tenant_map is not None else None)
                tenant_share = (self.config.tenant.max_share
                                if self.tenant_map is not None else 1.0)
                queue = CompileQueue(
                    workers=ccfg.workers,
                    deadline_s=ccfg.deadline_s,
                    max_retries=ccfg.max_retries,
                    backoff_base_s=ccfg.backoff_base_s,
                    backoff_max_s=ccfg.backoff_max_s,
                    max_pending=ccfg.max_pending,
                    weight_of=weight_of,
                    tenant_max_share=tenant_share)
            artifacts = None
            if ccfg.bank_artifacts and self.config.loader.enable_cache:
                artifacts = BankArtifactStore(self._cache)
            self.bank_registry = BankRegistry(
                quarantine_ttl_s=self.config.loader.bank_quarantine_ttl_s,
                max_bytes=ccfg.registry_max_bytes,
                shards=ccfg.registry_shards,
                queue=queue, artifacts=artifacts)
        else:
            self.bank_registry = None
        #: per-identity fingerprints + bank plan of the SERVING policy
        #: (None/empty until the first TPU commit): the inputs of the
        #: bank-scoped PolicyDelta a commit hands to memo owners
        self._identity_fps: Optional[Dict[int, str]] = None
        #: per-identity per-family fingerprints of the serving policy
        #: (identity_family_fingerprints) — the family-granular half
        #: of the delta; None whenever _identity_fps is
        self._identity_family_fps: Optional[
            Dict[int, Dict[str, str]]] = None
        self._globals_fp: Optional[str] = None
        self._bank_plan: Dict[str, tuple] = {}
        #: True while the serving policy contains quarantined banks —
        #: degraded builds are never cached, never warm-snapshotted,
        #: and always commit a FULL delta
        self._degraded = False
        self._warned_oracle_scale = False
        # lazily-built CPU oracle over the ACTIVE snapshot: the circuit
        # breaker's fallback lane (runtime/service.py). Cached per
        # revision; invalidated by _commit.
        self._fallback = None
        self._fallback_revision = -1
        #: artifact-cache key of the ACTIVE compiled policy (None on
        #: the oracle backend) — what the warm-restart snapshot points
        #: at so a restarted loader skips fingerprint + compile
        self._last_artifact_key: Optional[str] = None

    @property
    def revision(self) -> int:
        return self._revision

    @property
    def engine(self):
        with self._lock:
            return self._engine

    @property
    def fallback_engine(self):
        """CPU oracle over the currently-serving snapshot — the
        circuit breaker's degraded lane. When the active engine IS the
        oracle (gate off) it is returned directly; otherwise an
        OracleVerdictEngine is built lazily and cached until the next
        revision commit. Always correct, never fast."""
        with self._lock:
            engine = self._engine
            revision = self._revision
            per_identity = self.per_identity
            if engine is None or isinstance(engine, OracleVerdictEngine):
                return engine
            if self._fallback is not None \
                    and self._fallback_revision == revision:
                return self._fallback
        secret_lookup = (self.secrets.lookup
                         if self.secrets is not None else None)
        fallback = OracleVerdictEngine(
            per_identity, secret_lookup=secret_lookup,
            audit=self.config.policy_audit_mode)
        with self._lock:
            # only install if no newer revision committed meanwhile
            if self._revision == revision:
                self._fallback = fallback
                self._fallback_revision = revision
        return fallback

    def _commit(self, engine, revision: int,
                per_identity: Dict[int, MapState], backend: str,
                delta=None):
        """The revision swap — ONE critical section, so a reader sees
        either the old (engine, revision, snapshot) triple or the new
        one, never a mix. The loader.swap injection point fires just
        before: a fault here models a crash mid-swap, and regenerate's
        rollback guarantees the previous table keeps serving.

        ``delta`` (engine.memo.PolicyDelta, default FULL) tells memo
        owners what this commit actually changed: a bank-scoped delta
        lets sessions drop only the rows touching a changed bank, and
        a no-change delta (same artifact key) drops nothing."""
        faults.maybe_fail(SWAP_POINT)
        with self._lock:
            self._engine = engine
            self._revision = revision
            self.per_identity = per_identity
            self._fallback = None
            self._fallback_revision = -1
        # every committed revision — regenerate, warm restore, oracle
        # alike — bumps the process-global policy generation so
        # device-resident verdict memos (engine/memo.py) can never
        # serve a verdict computed under a previous revision. The
        # import stays lazy: memo.py is jax-free at module level, and
        # the oracle-only loader path must remain so too.
        from cilium_tpu.engine.memo import POLICY_GENERATION

        POLICY_GENERATION.bump(delta)
        METRICS.inc("cilium_tpu_regenerations_total",
                    labels={"backend": backend})
        return engine

    def regenerate(self, per_identity: Dict[int, MapState],
                   revision: int = 0):
        """Compile + stage a policy snapshot; atomic swap on success
        (old engine keeps serving until then — the reference's datapath
        likewise keeps enforcing during regeneration). Any failure
        before or during the swap ROLLS BACK: the previous
        (engine, revision, snapshot) triple is restored verbatim and
        keeps serving, the rollback is counted, and the error
        propagates to the caller."""
        with self._lock:
            prev = (self._engine, self._revision, self.per_identity,
                    self._last_artifact_key, self._identity_fps,
                    self._globals_fp, self._bank_plan, self._degraded,
                    self._identity_family_fps)
        # regeneration is its own ingress: a root trace per attempt, so
        # compile/stage cost and rollbacks are attributable like any
        # request (and the staged-revision log line carries the id)
        with TRACER.trace("loader.regenerate", revision=revision):
            try:
                return self._regenerate(per_identity, revision)
            except Exception as e:
                with self._lock:
                    # ctlint: disable=thread-safety  # rollback restores the pre-attempt snapshot verbatim under the lock; regenerate() is the only writer between read and restore and it is the frame raising here
                    self._engine, self._revision, self.per_identity = \
                        prev[:3]
                    # the artifact pointer rolls back WITH the triple:
                    # a compile that succeeded before the failed swap
                    # already moved it, and a later snapshot_warm /
                    # restore_warm would otherwise restage the ABORTED
                    # revision's policy under the serving revision's
                    # name (found by the ISSUE-7 memo staleness suite).
                    # The DST mutation re-plants exactly that bug so
                    # the schedule search can prove it catches it.
                    if not faults.mutation_active("rollback-artifact-key"):
                        self._last_artifact_key = prev[3]
                        self._update_protected()
                    # ...and so do the delta inputs: fingerprints/plan
                    # of the ABORTED build must not seed the next
                    # commit's bank-scoped invalidation
                    self._identity_fps = prev[4]
                    self._globals_fp = prev[5]
                    # ctlint: disable=thread-safety  # same rollback window as above: the snapshot is restored wholesale, racing writers rolled back with it
                    self._bank_plan = prev[6]
                    self._degraded = prev[7]
                    self._identity_family_fps = prev[8]
                    self._fallback = None
                    self._fallback_revision = -1
                # a rollback is a serving-state change too: memos
                # filled against the aborted revision's partial state
                # (the swap point fires between stage and commit)
                # must drop, exactly like a successful commit
                from cilium_tpu.engine.memo import POLICY_GENERATION

                POLICY_GENERATION.bump()
                METRICS.inc(LOADER_ROLLBACKS)
                TRACER.event("loader.rollback", revision=revision,
                             serving_revision=prev[1],
                             error=f"{type(e).__name__}: {e}")
                LOG.error("regeneration rolled back",
                          extra={"fields": {
                              "revision": revision,
                              "serving_revision": prev[1],
                              "error": f"{type(e).__name__}: {e}"}})
                raise

    def _regenerate(self, per_identity: Dict[int, MapState],
                    revision: int = 0):
        secret_lookup = (self.secrets.lookup
                         if self.secrets is not None else None)
        if not self.config.enable_tpu_offload:
            # the oracle is a correctness reference, not a fast path:
            # at headline scale (1k-rule policies) its per-request
            # regex scan has seconds-scale batch latency. Warn ONCE
            # per loader instead of letting a production-sized policy
            # silently crawl (VERDICT r3 weak #3).
            n_l7 = 0 if self._warned_oracle_scale else sum(
                len(lr.http) + len(lr.kafka) + len(lr.dns) + len(lr.l7)
                for ms in per_identity.values()
                for e in ms.entries.values() for lr in e.l7_rules)
            if n_l7 >= 200:
                self._warned_oracle_scale = True
                LOG.warning(
                    "oracle backend with %d L7 rules: the CPU matcher "
                    "is the correctness reference, not a fast path — "
                    "expect seconds-scale batch latency; enable the "
                    "TPU engine (enable_tpu_offload) for production "
                    "rule counts", n_l7)
            engine = OracleVerdictEngine(
                per_identity, secret_lookup=secret_lookup,
                audit=self.config.policy_audit_mode)
            # delta inputs move under the loader lock: bank_status /
            # _delta_for read them from other threads mid-regeneration
            with self._lock:
                self._last_artifact_key = None
                self._identity_fps = None
                self._identity_family_fps = None
                self._globals_fp = None
                self._bank_plan = {}
                self._degraded = False
            return self._commit(engine, revision, per_identity, "oracle")

        from cilium_tpu.engine.memo import PolicyDelta
        from cilium_tpu.engine.verdict import CompiledPolicy, VerdictEngine

        # "policy-v11": v2 gained the ms_auth array; v3 port-range prefix
        # keys (ms_plens + the w2 repack); v4 the audit_mode scalar; v5
        # the per-endpoint audit bit (enf_flags grew a column); v6 the
        # distillery template dedup (ms_tmpl_ids; key_w0 holds template
        # ids); v7 the content-addressed bank partition (lane layout
        # differs from the positional grouping); v8 the megakernel
        # resolve plan (rp_* group arrays + resolve_meta on the
        # artifact); v9 kafka/generic predicate groups joined the plan
        # (rp_k_*/rp_gen_*); v10 the attribution lane's rule→group
        # maps (rp_rule_group/rp_k_rule_group/rp_gen_rule_group +
        # group-member meta); v11 the protocol-frontend compiler plane
        # (fe rule tables + l7g automaton stack + rp_fe_* groups +
        # frontend enum predicates in the gen pair interns, l7-type
        # lanes normalized to frontend families) — each bump
        # invalidates older cached artifacts.
        # The key is now derived from the per-identity fingerprints +
        # a globals fingerprint, so the SAME inputs also seed the
        # bank-scoped invalidation delta. Both fingerprint views come
        # from ONE walk through the sharded store: identities whose
        # resolved MapState object is unchanged since the last
        # regeneration don't re-fingerprint (O(Δ) at 10k identities).
        bundles = self._fp_store.bundle(per_identity, _identity_bundle)
        fps = {ep: b[0] for ep, b in bundles.items()}
        fam_fps_all = {ep: b[1] for ep, b in bundles.items()}
        globals_fp = ruleset_fingerprint(
            self.config.policy_audit_mode,
            repr(self.config.engine),
            bool(self.config.loader.bank_isolation),
            # the tenant partition shapes the bank order (and thus the
            # compiled lane layout): flipping/redeclaring it must read
            # as a different policy, never as a stale-artifact hit
            (self.config.tenant.enabled, self.config.tenant.ranges,
             self.config.tenant.default_tenant),
            # only secrets actually REFERENCED by this snapshot's
            # header matches enter the key: rotating an unrelated
            # secret must not invalidate every cached artifact
            _referenced_secret_values(per_identity, self.secrets),
        )
        key = ruleset_fingerprint(
            "policy-v11", globals_fp, tuple(sorted(fps.items())))
        with self._lock:
            serving_engine = self._engine
            serving_key = self._last_artifact_key
            serving_degraded = self._degraded
        if (key == serving_key and not serving_degraded
                and isinstance(serving_engine, VerdictEngine)):
            # byte-identical policy re-committed (identity churn that
            # netted out, a redundant update): keep the serving engine,
            # advance the revision, and tell memo owners NOTHING
            # changed — the add-then-delete case of the churn plane
            with self._lock:
                self._identity_fps = fps
                self._identity_family_fps = fam_fps_all
            return self._commit(serving_engine, revision, per_identity,
                                "tpu", delta=PolicyDelta.none())
        policy = self._cache.get(key)
        cached = policy is not None
        if policy is None:
            if self.bank_registry is not None:
                # install THIS snapshot's pattern → namespace map
                # before compiling: the partition splits by namespace
                # first, so tenant A's churn can only perturb banks
                # inside A's namespace (or the shared one)
                self.bank_registry.namer = \
                    self._tenant_namer(per_identity)
            with SpanStat("policy_compile") as span, \
                    TRACER.span("policy.compile", phase=PHASE_HOST,
                                identities=len(per_identity)):
                policy = CompiledPolicy.build(
                    per_identity, self.config.engine, revision=revision,
                    secret_lookup=secret_lookup,
                    bank_cache=self.bank_cache,
                    bank_registry=self.bank_registry,
                    audit=self.config.policy_audit_mode)
            quarantined = tuple(getattr(policy, "bank_quarantined",
                                        ()) or ())
            if not quarantined:
                # degraded builds (quarantined banks serving stale
                # covers) are never cached: the clean key must keep
                # reading as a miss so the TTL retry recompiles
                self._cache.put(key, policy)
            METRICS.observe("cilium_tpu_compile_seconds", span.seconds)
        else:
            quarantined = tuple(getattr(policy, "bank_quarantined",
                                        ()) or ())
        with _log_span(LOG, "policy staged", revision=revision,
                       identities=len(per_identity), cache_hit=cached):
            with SpanStat("policy_stage"), \
                    TRACER.span("policy.stage", cache_hit=cached):
                engine = VerdictEngine(policy, device=self.device,
                                       cfg=self.config.engine)
        self._record_kernel_plan(policy, engine)
        # serving frontend-rule counts per proto (the ISSUE-15 family
        # surface; zeroed protos simply stop being reported)
        fe_counts: Dict[str, int] = {}
        for proto, _pairs in getattr(policy, "fe_rules", ()) or ():
            fe_counts[proto] = fe_counts.get(proto, 0) + 1
        for proto, n in fe_counts.items():
            METRICS.set_gauge("cilium_tpu_frontend_rules", n,
                              labels={"proto": proto})
        new_plan = dict(getattr(policy, "bank_plan", {}) or {})
        fam_fps = fam_fps_all
        delta = self._delta_for(fps, globals_fp, new_plan,
                                bool(quarantined), fam_fps)
        with self._lock:
            self._last_artifact_key = key if not quarantined else None
            self._identity_fps = fps
            self._identity_family_fps = fam_fps
            self._globals_fp = globals_fp
            self._bank_plan = new_plan
            self._degraded = bool(quarantined)
        # the cache has its own lock — keep it out of ours so the
        # loader lock never nests into the artifact-cache lock
        self._update_protected()
        return self._commit(engine, revision, per_identity, "tpu",
                            delta=delta)

    def _delta_for(self, fps: Dict[int, str], globals_fp: str,
                   new_plan: Dict[str, tuple], degraded: bool,
                   fam_fps: Optional[Dict[int, Dict[str, str]]] = None):
        """Bank-scoped PolicyDelta of this commit vs the serving
        state; conservative FULL whenever the serving state can't
        vouch for unchanged rows (first commit, globals change,
        quarantine involved on either side). With family fingerprints
        on both sides the delta narrows to true bank-REFERENCE
        granularity: per changed identity, the (identity, family)
        pairs whose rule family actually moved — FAMILY_ALL when the
        structural MapState did — and, per moved family, the exact
        ports whose entry rule sets changed (PORT_ALL for wildcard/
        range entries)."""
        from cilium_tpu.engine.memo import FAMILY_ALL, PolicyDelta

        # one coherent snapshot of the serving-side delta inputs: a
        # concurrent commit/rollback must not swap them out between
        # the bank diff and the fingerprint diff below
        with self._lock:
            old_plan = dict(self._bank_plan)
            prev_fps = self._identity_fps
            prev_globals_fp = self._globals_fp
            prev_degraded = self._degraded
            prev_fams = self._identity_family_fps
        changed_banks = set()
        for field in set(old_plan) | set(new_plan):
            old_keys = set(old_plan.get(field, ()))
            new_keys = set(new_plan.get(field, ()))
            changed_banks |= old_keys ^ new_keys
            swapped_in = len(new_keys - old_keys)
            if swapped_in:
                METRICS.inc(BANK_HOTSWAPS, swapped_in,
                            labels={"field": field})
        if (prev_fps is None or prev_globals_fp != globals_fp
                or degraded or prev_degraded):
            return PolicyDelta(full=True)
        changed_ids = {ep for ep in set(prev_fps) | set(fps)
                       if prev_fps.get(ep) != fps.get(ep)}
        families: set = set()
        family_ports: set = set()
        if prev_fams is not None and fam_fps is not None:
            for ep in changed_ids:
                old_f = prev_fams.get(ep)
                new_f = fam_fps.get(ep)
                if old_f is None or new_f is None or \
                        old_f.get("struct") != new_f.get("struct"):
                    # appeared/vanished/structural: everything moved
                    families.add((ep, FAMILY_ALL))
                    continue
                moved = [name for name in new_f
                         if name != "struct"
                         and old_f.get(name) != new_f.get(name)]
                if moved:
                    for name in moved:
                        families.add((ep, name))
                        # bank-reference narrowing: the exact entry
                        # ports whose rule sets moved (symmetric diff
                        # of the per-port fingerprints — non-empty by
                        # construction when the family dict differs)
                        oldp = old_f.get(name) or {}
                        newp = new_f.get(name) or {}
                        for port in set(oldp) | set(newp):
                            if oldp.get(port) != newp.get(port):
                                family_ports.add((ep, name, port))
                else:
                    # whole-identity fp moved but neither struct nor
                    # any family tuple did (fingerprint formulation
                    # drift): never narrow past what we can prove
                    families.add((ep, FAMILY_ALL))
        return PolicyDelta.banks(changed_ids, changed_banks,
                                 identity_families=families,
                                 identity_family_ports=family_ports)

    def _record_kernel_plan(self, policy, engine) -> None:
        """Push the staged engine's per-bank kernel picks into the
        bank registry (content-addressed banks carry their kernel
        choice across regenerations) and onto the serving plan the
        `status` op exposes."""
        picks = dict(getattr(engine, "impl_plan", {}) or {})
        self._kernel_plan = picks
        if self.bank_registry is None or not picks:
            return
        field_of_prefix = {"path": "path", "method": "method",
                           "host": "host", "hdr": "hdr", "dns": "dns"}
        for prefix, impl in picks.items():
            field = field_of_prefix.get(prefix, prefix)
            for key in getattr(policy, "bank_plan", {}).get(field, ()):
                self.bank_registry.kernel_picks[key] = impl

    def _update_protected(self) -> None:
        """Keep the byte-bounded artifact cache's eviction-exempt set
        pointing at what we actually serve: the active compiled
        policy's artifact + the warm-restart snapshot."""
        self._cache.set_protected(
            {self._last_artifact_key, WARM_STATE_KEY})

    def kick_expired_bank_rebuilds(self) -> int:
        """Proactively re-submit expired-quarantine banks at
        BACKGROUND priority through the compile queue (the repair
        compiles between regenerations, off the serving critical
        path). Returns the number submitted; 0 when the fleet compile
        plane is off."""
        if self.bank_registry is None:
            return 0
        return self.bank_registry.kick_expired_rebuilds()

    def close(self) -> None:
        """Tear down the owned compile plane (worker threads). The
        loader stays queryable — only background compiles stop; tests
        and the DST harness call this when replacing a loader so
        abandoned workers never outlive their world."""
        if self.bank_registry is not None:
            self.bank_registry.close()

    def bank_status(self) -> Dict[str, object]:
        """Bank registry + serving-plan snapshot (the service `status`
        op's churn-plane face)."""
        if self.bank_registry is None:
            return {"enabled": False}
        with self._lock:
            degraded = self._degraded
            plan = {f: len(k) for f, k in self._bank_plan.items()}
        out: Dict[str, object] = {"enabled": True, "degraded": degraded}
        out.update(self.bank_registry.status())
        out["plan"] = plan
        out["kernel_plan"] = dict(getattr(self, "_kernel_plan", {}))
        out["fp_store"] = self._fp_store.status()
        return out

    # -- tenant namespaces (ISSUE 20) -------------------------------------
    def _tenant_namer(self, per_identity: Dict[int, MapState]):
        """Pattern → tenant namespace for THIS snapshot, or None when
        tenancy is off. Walks the snapshot exactly the way the compiler
        extracts pattern text (h.path / h.method / h.host, header
        requirement regexes, DNS matchpattern regexes), claiming each
        pattern for the tenant of the identity carrying it. A pattern
        claimed by two tenants — or one the walk can't attribute
        (kafka/generic/frontend predicates) — lands in the SHARED
        namespace: its banks are common infrastructure, attributable
        to every claimant, and recompiling them isolates no one."""
        if self.tenant_map is None:
            return None
        from cilium_tpu.engine.verdict import header_requirement_regex
        from cilium_tpu.policy.compiler import matchpattern
        from cilium_tpu.runtime.tenant import SHARED_NAMESPACE
        from cilium_tpu.secrets import resolve_header_value

        secret_lookup = (self.secrets.lookup
                         if self.secrets is not None else None)
        claims: Dict[str, str] = {}

        def claim(pat: str, tenant: str) -> None:
            if not pat:
                return
            prev = claims.get(pat)
            if prev is None:
                claims[pat] = tenant
            elif prev != tenant:
                claims[pat] = SHARED_NAMESPACE

        for ep, ms in per_identity.items():
            tenant = self.tenant_map.tenant_of(ep)
            for entry in ms.entries.values():
                for lr in entry.l7_rules:
                    for h in lr.http:
                        claim(h.path, tenant)
                        claim(h.method, tenant)
                        claim(h.host, tenant)
                        for hdr in h.headers:
                            if ":" in hdr:
                                name, value = hdr.split(":", 1)
                            else:
                                name, value = hdr, ""
                            claim(header_requirement_regex(name, value),
                                  tenant)
                        for hm in h.header_matches:
                            value = resolve_header_value(hm,
                                                         secret_lookup)
                            if value is not None:
                                claim(header_requirement_regex(
                                    hm.name, value), tenant)
                    for d in lr.dns:
                        if d.match_name:
                            claim(matchpattern.name_to_regex(
                                d.match_name), tenant)
                        else:
                            claim(matchpattern.to_regex(
                                d.match_pattern), tenant)

        def namer(pattern: str) -> str:
            return claims.get(pattern, SHARED_NAMESPACE)

        return namer

    # -- shadow/canary staging (ISSUE 20) ---------------------------------
    def stage_canary(self, per_identity: Dict[int, MapState],
                     revision: int = 0):
        """Stage generation N+1 ALONGSIDE the serving generation N.

        The shadow is the CPU oracle over the N+1 snapshot — bit-equal
        to the compiled engine by the repo's core invariant (the
        oracle IS the correctness reference the engine is pinned
        against), so a verdict diff between serving and shadow
        measures the POLICY change, never a backend artifact. The
        expensive compile happens once, at :meth:`commit_canary`,
        after the verdict-diff gate passed — a refused canary costs
        zero compile work and never touches the serving triple."""
        secret_lookup = (self.secrets.lookup
                         if self.secrets is not None else None)
        shadow = OracleVerdictEngine(
            per_identity, secret_lookup=secret_lookup,
            audit=self.config.policy_audit_mode)
        with self._lock:
            self._canary_engine = shadow
            self._canary_snapshot = per_identity
            self._canary_revision = revision
        return shadow

    @property
    def canary_engine(self):
        """The staged shadow engine, or None when no canary is live."""
        with self._lock:
            return self._canary_engine

    @property
    def canary_revision(self) -> int:
        with self._lock:
            return self._canary_revision

    def clear_canary(self) -> None:
        """Drop the staged generation (abort/refuse path): the serving
        triple is untouched by construction — the shadow never entered
        it."""
        with self._lock:
            self._canary_engine = None
            self._canary_snapshot = None
            self._canary_revision = 0

    def commit_canary(self):
        """Promote the staged snapshot to the serving generation via
        the normal :meth:`regenerate` (compile → stage → atomic swap,
        rollback on failure). Only the verdict-diff gate
        (runtime/canary.py) calls this, and only after it passed."""
        with self._lock:
            snap = self._canary_snapshot
            revision = self._canary_revision
        if snap is None:
            raise RuntimeError("no canary generation staged")
        engine = self.regenerate(snap, revision=revision)
        self.clear_canary()
        return engine

    # -- warm restart -----------------------------------------------------
    def snapshot_warm(self) -> bool:
        """Persist the serving state — revision, the compiled policy's
        artifact key, and the resolved snapshot (from which the oracle
        fallback rebuilds) — through the artifact cache. The graceful
        drain calls this last, so a restarted service can
        :meth:`restore_warm` and answer its first request
        verdict-identically without recompilation (the reference's
        pinned-map restart discipline, SURVEY §5.3/§5.4, applied to
        compiled tensors instead of BPF maps)."""
        with self._lock:
            engine = self._engine
            revision = self._revision
            per_identity = self.per_identity
            key = self._last_artifact_key
        if engine is None or not self._cache.enable:
            return False
        from cilium_tpu.engine.megakernel import (
            autotune_cache_snapshot,
        )

        self._cache.put(WARM_STATE_KEY, {
            "format": 1,
            "revision": revision,
            "artifact_key": key,
            "per_identity": per_identity,
            "offload": bool(self.config.enable_tpu_offload),
            "audit": bool(self.config.policy_audit_mode),
            # per-bank-shape kernel picks survive the restart: the
            # restaged engine re-plans against a warm autotune cache
            # instead of re-benching every shape
            "kernel_autotune": autotune_cache_snapshot(),
        })
        return True

    def restore_warm(self) -> bool:
        """Rebuild the serving state from the last drain's snapshot.
        Fast path (gate unchanged, compiled artifact still cached):
        stage the cached policy directly — no fingerprint walk, no
        compile. Degraded path (artifact evicted/corrupt, or the
        feature gate flipped since the snapshot): full
        :meth:`regenerate` from the snapshot's resolved policy — still
        no caller-side policy replay needed. Returns False on a clean
        miss (no/stale snapshot); the caller then boots cold."""
        state = self._cache.get(WARM_STATE_KEY)
        if not isinstance(state, dict) or state.get("format") != 1:
            return False
        from cilium_tpu.engine.megakernel import autotune_cache_adopt

        autotune_cache_adopt(state.get("kernel_autotune"))
        try:
            revision = int(state["revision"])
            per_identity = state["per_identity"]
            key = state.get("artifact_key")
            offload = bool(state.get("offload"))
        except (KeyError, TypeError, ValueError):
            return False
        if self.config.enable_tpu_offload and offload and key:
            from cilium_tpu.engine.memo import PolicyDelta
            from cilium_tpu.engine.verdict import VerdictEngine

            with self._lock:
                serving_engine = self._engine
                serving_key = self._last_artifact_key
                serving_degraded = self._degraded
            if (key == serving_key and not serving_degraded
                    and isinstance(serving_engine, VerdictEngine)):
                # the snapshot IS the serving policy (drain → restore
                # without an intervening change): keep the staged
                # engine, commit the snapshot's revision, and drop
                # NOTHING — replay memos and unique-row buffers stay
                # hot across the warm restart (ISSUE-8 satellite; the
                # old unconditional drop cost the whole memo hit
                # ratio on every restart)
                fps = identity_fingerprints(per_identity)
                fam = identity_family_fingerprints(per_identity)
                with self._lock:
                    self._identity_fps = fps
                    self._identity_family_fps = fam
                self._commit(serving_engine, revision, per_identity,
                             "warm", delta=PolicyDelta.none())
                METRICS.inc(WARM_RESTORES)
                return True
            policy = self._cache.get(key)
            if policy is not None:
                with _log_span(LOG, "warm restore", revision=revision,
                               identities=len(per_identity)):
                    with SpanStat("policy_stage"), \
                            TRACER.span("policy.stage",
                                        cache_hit=True, warm=True):
                        engine = VerdictEngine(
                            policy, device=self.device,
                            cfg=self.config.engine)
                self._record_kernel_plan(policy, engine)
                # a real fingerprint change (or an unknown serving
                # state): hand memo owners the identity-scoped delta
                # when the serving fingerprints can vouch for it
                fps = identity_fingerprints(per_identity)
                fam_fps = identity_family_fingerprints(per_identity)
                new_plan = dict(getattr(policy, "bank_plan", {}) or {})
                with self._lock:
                    globals_fp = self._globals_fp
                delta = self._delta_for(fps, globals_fp or "",
                                        new_plan, False, fam_fps) \
                    if globals_fp is not None \
                    else PolicyDelta(full=True)
                with self._lock:
                    self._last_artifact_key = key
                    self._identity_fps = fps
                    self._identity_family_fps = fam_fps
                    self._bank_plan = new_plan
                    self._degraded = False
                self._update_protected()
                self._commit(engine, revision, per_identity, "warm",
                             delta=delta)
                METRICS.inc(WARM_RESTORES)
                return True
        if not self.config.enable_tpu_offload and not offload:
            secret_lookup = (self.secrets.lookup
                             if self.secrets is not None else None)
            engine = OracleVerdictEngine(
                per_identity, secret_lookup=secret_lookup,
                audit=self.config.policy_audit_mode)
            with self._lock:
                self._last_artifact_key = None
            self._commit(engine, revision, per_identity, "warm")
            METRICS.inc(WARM_RESTORES)
            return True
        # artifact evicted or the gate flipped since the snapshot:
        # regenerate from the snapshot's resolved policy (may compile,
        # but the caller still needn't replay policy sources)
        self.regenerate(per_identity, revision=revision)
        METRICS.inc(WARM_RESTORES)
        return True

    def regenerate_from_repo(self, repo: Repository, cache: SelectorCache,
                             endpoint_labels: Dict[int, LabelSet]):
        """Resolve + regenerate for a set of endpoint identities
        (§3.2's regeneration fan-out, collapsed to one snapshot)."""
        resolver = PolicyResolver(repo, cache)
        per_identity = {
            ep: resolver.resolve(lbls)
            for ep, lbls in endpoint_labels.items()
        }
        return self.regenerate(per_identity, revision=repo.revision)
