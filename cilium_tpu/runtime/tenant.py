"""Tenant namespaces for the control plane (ISSUE 20).

"Millions of users" means multi-tenant: one tenant's CNP churn storm
or verdict burst must not starve every other tenant's control ops and
p99, and a tenant's bank-compile failure or compile-deadline lapse
must never invalidate another tenant's banks. This module is the
shared vocabulary of that partition:

- :class:`TenantMap` — the declared identity-range → tenant mapping
  (``[tenant].ranges``) plus per-tenant fair-queueing weights. Pure
  and deterministic: the same config maps the same identity to the
  same tenant on every host of a fleet.
- :class:`TenantQuotas` — the per-tenant share store with TTL'd
  entries. A share not refreshed within its TTL lapses to the
  conservative default, and a LOST read (the ``tenant.quota`` fault
  point) fails to the same conservative default — a tenant whose
  quota record vanished is bounded, never unbounded.
- :class:`FairShareWindow` — the weighted-fair admission window on
  the installed clock: per-tenant admitted counts over a rotating
  quantum. Rotation happens at EXACTLY ``window_start + quantum_s``
  (closed boundary, pinned by tests/dst/test_boundaries.py), so the
  fairness decision is an exact virtual tick, never sleep-shaped.

The namespace partition of the BANK plane (pattern → tenant
namespace folded into content-addressed bank keys) is built by the
loader from this map — see ``Loader._tenant_namer`` and
``policy/compiler/bankplan.partition_patterns``.
"""

from __future__ import annotations

import threading
from typing import Dict, Optional, Sequence, Tuple

from cilium_tpu.runtime import faults, simclock
from cilium_tpu.runtime.metrics import METRICS, TENANT_QUOTA_READS

#: the namespace of identities matching no declared range, and of
#: requests that declare no tenant
DEFAULT_TENANT = "default"

#: patterns claimed by two or more tenants (or by none) land in the
#: shared namespace: their banks are common infrastructure, and a
#: shared bank recompile is attributable to every claimant
SHARED_NAMESPACE = "shared"

#: fires on every per-tenant quota-store read: a fired fault models
#: the quota record being lost/unreadable and MUST fail to the
#: conservative default share — bounded, never unbounded
#: (tests/test_faults.py pins it)
TENANT_QUOTA_POINT = faults.register_point(
    "tenant.quota", "per-tenant quota-store read in TenantQuotas")


def parse_ranges(specs: Sequence[str]
                 ) -> Tuple[Tuple[str, int, int], ...]:
    """``"name:lo-hi"`` declarations → ((name, lo, hi), ...) with
    inclusive bounds; malformed entries raise at config time, not at
    admission time."""
    out = []
    for spec in specs:
        name, _, span = spec.partition(":")
        lo, _, hi = span.partition("-")
        if not (name and lo and hi):
            raise ValueError(f"bad tenant range {spec!r} "
                             f"(want 'name:lo-hi')")
        out.append((name, int(lo), int(hi)))
    return tuple(out)


def parse_weights(specs: Sequence[str]) -> Dict[str, float]:
    """``"name:weight"`` declarations → {name: weight}; weights must
    be positive (a zero-weight tenant could never drain its queue)."""
    out: Dict[str, float] = {}
    for spec in specs:
        name, _, w = spec.partition(":")
        if not (name and w):
            raise ValueError(f"bad tenant weight {spec!r} "
                             f"(want 'name:weight')")
        weight = float(w)
        if weight <= 0.0:
            raise ValueError(f"tenant weight must be > 0: {spec!r}")
        out[name] = weight
    return out


class TenantMap:
    """The declared tenant partition: identity ranges + weights.

    Immutable after construction and safe to share across threads —
    every lookup is a pure read."""

    def __init__(self, ranges: Sequence[str] = (),
                 weights: Sequence[str] = (),
                 default_tenant: str = DEFAULT_TENANT):
        self.ranges = parse_ranges(ranges)
        self.weights = parse_weights(weights)
        self.default_tenant = default_tenant or DEFAULT_TENANT

    @classmethod
    def from_config(cls, cfg) -> "TenantMap":
        return cls(ranges=cfg.tenant.ranges,
                   weights=cfg.tenant.weights,
                   default_tenant=cfg.tenant.default_tenant)

    def tenant_of(self, identity: int) -> str:
        """First declared range containing ``identity`` wins; no
        match → the default tenant."""
        nid = int(identity)
        for name, lo, hi in self.ranges:
            if lo <= nid <= hi:
                return name
        return self.default_tenant

    def weight_of(self, tenant: str) -> float:
        return self.weights.get(tenant, 1.0)

    def tenants(self) -> Tuple[str, ...]:
        """Every declared tenant name, deterministic order."""
        seen = []
        for name, _, _ in self.ranges:
            if name not in seen:
                seen.append(name)
        return tuple(seen)


class _QuotaEntry:
    __slots__ = ("share", "expires_at")

    def __init__(self, share: float, expires_at: float):
        self.share = share
        self.expires_at = expires_at


class TenantQuotas:
    """TTL'd per-tenant share store with a conservative default.

    ``share_of`` is the ONE read path, and it is where the
    ``tenant.quota`` fault point fires: a lost read returns the
    conservative default share (bounded), counted ``fault-default``.
    An entry whose TTL lapsed — ``expires_at <= now``, the closed
    boundary the DST boundary suite pins — reads as the default too,
    counted ``lapsed``; a live entry counts ``live``."""

    def __init__(self, default_share: float = 0.5,
                 ttl_s: float = 60.0, clock=None):
        self.default_share = float(default_share)
        self.ttl_s = float(ttl_s)
        self.clock = clock if clock is not None else simclock.now
        self._lock = threading.Lock()
        self._entries: Dict[str, _QuotaEntry] = {}

    @classmethod
    def from_config(cls, cfg, clock=None) -> "TenantQuotas":
        return cls(default_share=cfg.tenant.max_share,
                   ttl_s=cfg.tenant.quota_ttl_s, clock=clock)

    def set_share(self, tenant: str, share: float,
                  ttl_s: Optional[float] = None) -> None:
        ttl = self.ttl_s if ttl_s is None else float(ttl_s)
        entry = _QuotaEntry(float(share), self.clock() + ttl)
        with self._lock:
            self._entries[tenant] = entry

    def share_of(self, tenant: str) -> float:
        try:
            faults.maybe_fail(TENANT_QUOTA_POINT)
        except faults.FaultInjected:
            # the quota record is unreadable: the tenant is bounded
            # by the conservative default, never unbounded
            METRICS.inc(TENANT_QUOTA_READS,
                        labels={"result": "fault-default"})
            return self.default_share
        now = self.clock()
        with self._lock:
            entry = self._entries.get(tenant)
            if entry is not None and entry.expires_at <= now:
                # lapsed AT the tick (closed boundary): drop it so a
                # later refresh starts a fresh TTL
                del self._entries[tenant]
                entry = None
        if entry is None:
            METRICS.inc(TENANT_QUOTA_READS,
                        labels={"result": "lapsed"})
            return self.default_share
        METRICS.inc(TENANT_QUOTA_READS, labels={"result": "live"})
        return entry.share

    def status(self) -> Dict:
        now = self.clock()
        with self._lock:
            return {
                "tenants": sorted(self._entries),
                "live": sum(1 for e in self._entries.values()
                            if e.expires_at > now),
                "default_share": self.default_share,
            }


class FairShareWindow:
    """Per-tenant admitted counts over a rotating virtual-time
    quantum — the AdmissionGate's weighted-fairness memory.

    The window rotates at EXACTLY ``window_start + quantum_s`` (``now
    >= start + quantum``, closed boundary): the counts reset and the
    storming tenant gets a fresh fair chance every quantum. A tenant
    is over-share only when BOTH hold — its CURRENT share of the
    window is past the hard ``max_share`` ceiling AND past its
    weighted fair share among the tenants seen this window. Judging
    the current share (never the would-be-next fraction) means a
    tenant sitting exactly AT its fair share still admits — two equal
    tenants alternate instead of mutually shedding at equilibrium —
    and a lone tenant (fair share 1.0) is never penalized."""

    def __init__(self, quantum_s: float = 1.0, max_share: float = 0.5,
                 weight_of=None, clock=None):
        self.quantum_s = float(quantum_s)
        self.max_share = float(max_share)
        self.weight_of = weight_of or (lambda tenant: 1.0)
        self.clock = clock if clock is not None else simclock.now
        self._lock = threading.Lock()
        self._start = self.clock()
        self._counts: Dict[str, int] = {}
        self._total = 0

    def _rotate_locked(self, now: float) -> None:
        if now >= self._start + self.quantum_s:
            # land the new window's start ON the quantum grid so a
            # long idle gap doesn't skew the next rotation tick
            lapsed = int((now - self._start) // self.quantum_s)
            self._start += lapsed * self.quantum_s
            self._counts.clear()
            self._total = 0

    def note(self, tenant: str) -> None:
        """Record one admission for ``tenant`` in the current window."""
        now = self.clock()
        with self._lock:
            self._rotate_locked(now)
            self._counts[tenant] = self._counts.get(tenant, 0) + 1
            self._total += 1

    def over_share(self, tenant: str,
                   share_cap: Optional[float] = None) -> bool:
        """Is ``tenant`` past its fair share of the current window?

        ``share_cap`` overrides the window's ``max_share`` ceiling
        (the per-tenant quota read feeds it)."""
        cap = self.max_share if share_cap is None else float(share_cap)
        now = self.clock()
        with self._lock:
            self._rotate_locked(now)
            total = self._total
            if total <= 0:
                return False
            frac = self._counts.get(tenant, 0) / total
            if frac <= cap:
                return False
            weights = {t: self.weight_of(t) for t in self._counts}
            weights.setdefault(tenant, self.weight_of(tenant))
            wsum = sum(weights.values())
            fair = weights[tenant] / wsum if wsum > 0 else 1.0
            return frac > fair

    def counts(self) -> Dict[str, int]:
        with self._lock:
            return dict(self._counts)

    def window_start(self) -> float:
        with self._lock:
            return self._start
