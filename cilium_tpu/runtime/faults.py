"""Deterministic, seeded fault injection.

The round-5 sweeps met real transient failures — a tunnel drop during
a forced recompile, a stream stall escaping as a raw traceback, a shim
serving a stale table (docs/PLATFORM.md outage log, ADVICE.md) — but
none were reproducible on demand. This module makes failure a test
input: named **injection points** sit at the seams where production
failures actually happen (device dispatch, frame delivery, revision
swap, kvstore sessions, the DNS proxy), and a :class:`FaultPlan`
decides, deterministically, which hits of which point raise what.

Design constraints, in order:

* **Zero cost when idle.** ``maybe_fail`` is a module-global ``None``
  check when no plan is installed — the seams stay in production code
  paths, so the disarmed probe must be free.
* **Replayable.** Every decision is drawn from a per-point RNG seeded
  by ``(plan seed, point name)`` and consumed in per-point hit order,
  so the decision sequence at a point is a pure function of the plan —
  independent of thread interleaving ACROSS points. The recorded
  :meth:`FaultPlan.trace` of two runs with the same plan and the same
  per-point hit counts is identical; chaos tests assert exactly that.
* **Plans choose the exception.** A stream-drop plan raises
  ``ConnectionError`` so the reconnect path (not a generic handler)
  absorbs it; a device fault raises :class:`FaultInjected`.

Usage::

    plan = FaultPlan(seed=7, rules=[
        FaultRule("engine.dispatch", times=3),          # first 3 hits
        FaultRule("stream.frame.client", prob=0.1,
                  exc=ConnectionError),                  # 10% of frames
    ])
    with inject(plan):
        ... run the workload ...
    plan.trace()   # {"engine.dispatch": [(0, True), (1, True), ...]}

Known injection points (registered by the modules owning the seam):

=========================  ==================================================
``engine.dispatch``        device dispatch in ``engine/verdict.py``
                           (``verdict_batch_arrays`` / blob step)
``loader.swap``            between stage and commit in ``runtime/loader.py``
``loader.bank_compile``    per-bank DFA compile in
                           ``policy/compiler/bankplan.BankRegistry`` (a
                           fired fault quarantines ONLY that bank; the
                           regeneration proceeds on the old cover)
``kvstore.churn_storm``    per identity-churn event delivery in
                           ``identity_kvstore.ClusterIdentityAllocator``
                           (a fired fault loses that delivery —
                           modelling burst add/delete churn)
``stream.frame.server``    per-chunk dispatch in ``StreamSession``
``stream.frame.client``    per-frame receive in ``StreamClient``
``stream.credit``          credit-grant send in ``StreamSession`` (a
                           fired fault LOSES the grant)
``service.admit``          admission decision in ``runtime/admission.py``
                           (a fired fault forces an explicit shed)
``serve.lease``            slot-lease decision in
                           ``runtime/serveloop.ServeLoop.connect`` (a
                           fired fault is an explicit shed)
``serve.ring_slot``        chunk submit into a ring slot in
                           ``ServeLoop.submit`` (a fired fault fails
                           only that chunk)
``service.drain``          between stop-admitting and the pending
                           flush in ``VerdictService.drain``
``kvstore.watch``          per-watch event delivery in ``kvstore.py``
``clustermesh.session``    remote-cluster event ingest in ``clustermesh.py``
``clustermesh.heartbeat``  local-state publisher heartbeat
``dnsproxy.query``         banked-DFA batch path in ``fqdn/dnsproxy.py``
``fleet.heartbeat``        per-host heartbeat in ``runtime/fleetserve.py``
                           (a fired fault LOSES the beat — enough lost
                           beats push the host through suspicion into
                           fail-closed death)
``fleet.handoff``          per-stream lease migration in the fleet
                           router's host-death handoff (a fired fault
                           interrupts the transfer mid-batch; the
                           unmigrated remainder re-grants through the
                           client resume path, never on two live hosts)
``artifact.fetch``         compiled-bank artifact fetch in
                           ``runtime/checkpoint.BankArtifactStore``
``canary.dispatch``        shadow (N+1) verdict dispatch in
                           ``runtime/canary.CanaryController`` (a fired
                           fault ABORTS the canary safely — staged
                           generation dropped, serving generation N
                           untouched)
``tenant.quota``           per-tenant quota-store read in
                           ``runtime/tenant.TenantQuotas`` (a fired
                           fault falls back to the conservative
                           configured default share)
=========================  ==================================================
"""

from __future__ import annotations

import contextlib
import os
import random
import threading
import zlib
from typing import Dict, List, Optional, Sequence, Tuple

from cilium_tpu.runtime.metrics import FAULTS_INJECTED, METRICS


class FaultInjected(Exception):
    """Default exception raised at an armed injection point."""


class FaultRule:
    """One point's failure policy.

    ``prob``  — per-hit fire probability (1.0 = every eligible hit).
    ``times`` — max fires (None = unbounded); after that the point is
                permanently healthy, which is how chaos tests model
                "the outage ends".
    ``after`` — skip the first N hits (fault appears mid-run).
    ``exc``   — exception *class* to raise (``FaultInjected`` default);
                instantiated with ``message`` per fire so tracebacks
                carry the point name.
    """

    def __init__(self, point: str, prob: float = 1.0,
                 times: Optional[int] = None, after: int = 0,
                 exc: type = FaultInjected,
                 message: Optional[str] = None):
        if not 0.0 <= prob <= 1.0:
            raise ValueError(f"prob must be in [0, 1], got {prob}")
        self.point = point
        self.prob = prob
        self.times = times
        self.after = after
        self.exc = exc
        self.message = message or f"injected fault at {point}"


class _PointState:
    """Per-point mutable state: its own RNG, counters, and trace."""

    __slots__ = ("rule", "rng", "hits", "fires", "trace", "lock")

    def __init__(self, rule: FaultRule, seed: int):
        self.rule = rule
        # crc32 folds the point name into the seed so two points under
        # one plan draw independent, order-free decision streams
        self.rng = random.Random(
            (seed << 32) ^ zlib.crc32(rule.point.encode()))
        self.hits = 0
        self.fires = 0
        self.trace: List[Tuple[int, bool]] = []
        self.lock = threading.Lock()

    def decide(self) -> Optional[Exception]:
        with self.lock:
            idx = self.hits
            self.hits += 1
            # the RNG is consumed on EVERY hit (fired or not) so the
            # decision at hit k never depends on times/after gating
            draw = self.rng.random()
            fire = (idx >= self.rule.after
                    and (self.rule.times is None
                         or self.fires < self.rule.times)
                    and draw < self.rule.prob)
            if fire:
                self.fires += 1
            self.trace.append((idx, fire))
        if not fire:
            return None
        return self.rule.exc(f"{self.rule.message} (hit {idx})")


class FaultPlan:
    """A seeded set of :class:`FaultRule`\\ s plus the recorded trace."""

    def __init__(self, rules: Sequence[FaultRule] = (), seed: int = 0):
        self.seed = seed
        self._points: Dict[str, _PointState] = {}
        for r in rules:
            if r.point in self._points:
                raise ValueError(f"duplicate rule for point {r.point!r}")
            self._points[r.point] = _PointState(r, seed)

    def check(self, point: str) -> Optional[Exception]:
        st = self._points.get(point)
        return st.decide() if st is not None else None

    def trace(self) -> Dict[str, List[Tuple[int, bool]]]:
        """point → [(hit index, fired)] — the replayable event trace."""
        return {p: list(st.trace) for p, st in self._points.items()}

    def counts(self, point: str) -> Tuple[int, int]:
        """(hits, fires) for one point (0, 0 if never hit/ruled)."""
        st = self._points.get(point)
        return (st.hits, st.fires) if st is not None else (0, 0)


#: the armed plan; ``None`` (the default, and the production state)
#: makes every ``maybe_fail`` a single global read
_PLAN: Optional[FaultPlan] = None
_PLAN_LOCK = threading.Lock()

#: advisory registry of seams that call ``maybe_fail`` (introspection /
#: docs; unknown points still work — the registry is not a gate)
_POINTS: Dict[str, str] = {}


def register_point(name: str, doc: str = "") -> str:
    """Declare an injection point (module import time). Returns the
    name so seams can do ``POINT = register_point(...)``."""
    # ctlint: disable=unbounded-registry  # import-time registration, bounded by module count
    _POINTS.setdefault(name, doc)
    return name


def registered_points() -> Dict[str, str]:
    return dict(_POINTS)


def install(plan: FaultPlan) -> None:
    global _PLAN
    with _PLAN_LOCK:
        if _PLAN is not None:
            raise RuntimeError("a FaultPlan is already installed")
        _PLAN = plan


def clear() -> None:
    global _PLAN
    with _PLAN_LOCK:
        _PLAN = None


def active() -> Optional[FaultPlan]:
    return _PLAN


@contextlib.contextmanager
def inject(plan: FaultPlan):
    """``with inject(plan): ...`` — install for the block, always
    cleared on exit (a leaked plan would fail unrelated tests)."""
    install(plan)
    try:
        yield plan
    finally:
        clear()


#: DST mutation testing (runtime/dst.py): ``CILIUM_TPU_DST_MUTATION``
#: names a known FIXED bug to re-introduce, so the schedule search can
#: prove it would have caught the bug. Off (empty) in production; the
#: env var is read per call so tests toggle it with monkeypatch.
MUTATION_ENV = "CILIUM_TPU_DST_MUTATION"

#: mutation name → where the planted bug lives (introspection/docs)
MUTATIONS: Dict[str, str] = {
    "rollback-artifact-key":
        "Loader.regenerate rollback keeps _last_artifact_key at the "
        "aborted revision (the PR-7 warm-snapshot staleness bug)",
    "positional-banks":
        "bankplan.partition_patterns groups positionally — one delete "
        "shifts every later bank (the pre-PR-8 O(policy) compile bug)",
}


def mutation_active(name: str) -> bool:
    """True when the named planted bug is armed. The seams guard their
    buggy variant with this, so shipped behavior is untouched unless
    the DST validation lane arms the mutation explicitly."""
    return os.environ.get(MUTATION_ENV, "") == name


def maybe_fail(point: str) -> None:
    """The seam probe. Raises the plan's exception when the armed plan
    says this hit of ``point`` fails; otherwise (or with no plan) does
    nothing. Seams call this unconditionally — disarmed cost is one
    global read."""
    plan = _PLAN
    if plan is None:
        return
    exc = plan.check(point)
    if exc is not None:
        METRICS.inc(FAULTS_INJECTED, labels={"point": point})
        # a fired fault under an active flight-recorder trace becomes
        # a span event — the trace shows WHICH request the fault hit
        # (import here: the disarmed path must stay one global read)
        from cilium_tpu.runtime.tracing import TRACER

        TRACER.event("fault.injected", point=point,
                     exc=type(exc).__name__)
        raise exc
