"""Continuously-batched device-resident serving loop: stream slot
leases over the persistent verdict ring.

The serving-plane shape this module replaces is request/response: a
host-side MicroBatcher forms a batch per request wave, answers it,
and forgets everything. The serve loop is the opposite — a PERSISTENT
loop over device-resident state (engine/ring.py):

* **Slot leases.** A stream is admitted ONCE, through the same
  AdmissionGate/credit discipline as every other ingress (PRs 5/10),
  into a ring slot lease with a TTL. Chunks then ride the lease —
  no per-chunk admission, no per-wave barrier. A lease renews on
  activity and EXPIRES when idle past its TTL, returning the slot; a
  reconnect-with-resume that finds its lease alive reuses it without
  a second grant (``cilium_tpu_serve_lease_grants_total`` counts
  streams, not dial attempts).
* **Continuous batching.** The pack cycle (``pack_interval``) drains
  whatever slots have pending encoded chunks into ONE fused
  megakernel dispatch + one on-device memo gather. Latency under
  light load ≈ pack interval + dispatch; under heavy load the pack
  amortizes one dispatch over hundreds of streams.
* **Explicit shed, never queue-forever.** Ring at capacity →
  ``ring-full``; per-slot pending at bound → ``queue-full``;
  draining → ``draining``; armed ``serve.lease`` fault → ``fault``.
  All counted on the shared admission series, surface ``serve``.
* **Hot-swap safe.** The ring's shared session consumes committed
  PolicyDeltas (PR 8): a bank-scoped commit refills only the memo
  rows whose identity+family read the swapped bank; slots and leases
  notice nothing.
* **Canary double-dispatch.** With a :class:`~cilium_tpu.runtime.
  canary.CanaryController` wired and sampling, a deterministic
  fraction of chunks evaluates through the STAGED generation N+1 as
  well — in the same pack cycle, off the already-resolved verdicts —
  feeding the verdict-diff gate. Shadow work is advisory: its wall is
  metered (``canary_seconds`` vs ``pack_seconds``) and a shadow
  failure aborts the canary, never the chunk (ISSUE 20).
* **Tenant attribution.** Streams connect WITH a tenant; the tenant
  rides the lease and every chunk ticket, so sheds, SLO windows, and
  explain entries attribute to the tenant that caused them (ISSUE 20
  satellite).

Two driving modes, mirroring the simulation clock's: ``start()``
spawns the production pack thread (``simclock.sleep`` paced, so an
autojumping VirtualClock converts the loop to virtual time
unrestructured); ``step()`` is the inline pack cycle the DST runner
and the 100k-stream load model (runtime/loadmodel.py) drive
deterministically.

Fault points: ``serve.lease`` fires at every lease decision (a fired
fault is an explicit shed); ``serve.ring_slot`` fires at every chunk
submit (a fired fault fails THAT chunk — per-chunk degradation, the
stream transport's contract).
"""

from __future__ import annotations

import heapq
import threading
from typing import Dict, Optional

import numpy as np

from cilium_tpu.engine.ring import (
    RingFull,
    RingSlot,
    SlotNotResident,
    VerdictRing,
)
from cilium_tpu.runtime import admission, faults, simclock
from cilium_tpu.runtime.logging import get_logger
from cilium_tpu.runtime.metrics import (
    METRICS,
    SERVE_LATENCY,
    SERVE_LEASE_EXPIRIES,
    SERVE_LEASE_GRANTS,
    SERVE_LEASE_RELEASES,
    SERVE_PACK_DISPATCH_SECONDS,
    SERVE_PACK_OCCUPANCY,
    SERVE_RING_OCCUPANCY,
)

LOG = get_logger("serveloop")

#: fires at every lease decision in ServeLoop.connect — an injected
#: fault forces an explicit shed (reason "fault"), never a half-grant
LEASE_POINT = faults.register_point(
    "serve.lease", "slot-lease decision in ServeLoop.connect")
#: fires at every chunk submit into a ring slot — an injected fault
#: fails ONLY that chunk (the per-chunk degradation contract)
RING_SLOT_POINT = faults.register_point(
    "serve.ring_slot", "chunk submit into a ring slot in "
                       "ServeLoop.submit")


class ShedError(RuntimeError):
    """An explicit, counted shed: the stream/chunk was refused with a
    reason, never silently queued."""

    def __init__(self, reason: str):
        super().__init__(f"shed: {reason}")
        self.reason = reason


class LeaseExpired(RuntimeError):
    """The stream's slot lease lapsed (idle past TTL): the caller
    re-connects (reconnect-with-resume grants a fresh slot)."""


class SlotLease:
    """One stream's ring residency grant. Renewed by activity;
    expired by the pack cycle when idle past ``ttl_s``."""

    __slots__ = ("stream_id", "slot", "ttl_s", "granted_at",
                 "expires_at", "active", "tenant")

    def __init__(self, stream_id: str, slot: RingSlot, ttl_s: float,
                 now: float, tenant: str = ""):
        self.stream_id = stream_id
        self.slot = slot
        self.ttl_s = float(ttl_s)
        self.granted_at = now
        self.expires_at = now + self.ttl_s
        self.active = True
        #: the stream's tenant — rides every chunk this lease submits
        #: so sheds/SLO/explain attribute to the tenant that caused
        #: them; "" is the pre-tenant (unattributed) contract
        self.tenant = str(tenant)

    def renew(self, now: float) -> None:
        self.expires_at = now + self.ttl_s

    def expired(self, now: float) -> bool:
        # the exact tick expires: expires_at <= now, the same closed
        # boundary as admission deadlines (zero budget = lapsed)
        return self.expires_at <= now


class ChunkTicket:
    """Completion token for one submitted chunk: the submitter parks
    on a clock-integrated event; the pack cycle resolves it with host
    verdicts or an error string. ``trace_id`` is the submitting
    stream's flight-recorder context — stamped at submit so the pack
    thread (which has no contextvar) can still attribute its work and
    the explain plane can key on it; ``prov`` is the chunk's
    :class:`~cilium_tpu.engine.attribution.ServedPack` slice when the
    ring serves with provenance on."""

    __slots__ = ("ev", "n", "t_submit", "t_done", "verdicts", "error",
                 "trace_id", "prov", "sample_flows", "epoch",
                 "tenant", "canary")

    def __init__(self, n: int, trace_id: str = "", epoch: int = 0):
        self.ev = simclock.event()
        self.n = n
        self.t_submit = simclock.now()
        self.t_done: Optional[float] = None
        self.verdicts: Optional[np.ndarray] = None
        self.error: Optional[str] = None
        self.trace_id = trace_id
        #: the trace's causal epoch at submit (bumped per handoff) —
        #: rides the ticket so the pack thread's span sorts AFTER the
        #: dead host's spans in the stitched timeline
        self.epoch = int(epoch)
        self.prov = None
        self.sample_flows = None
        #: tenant attribution (from the lease) for SLO/explain
        self.tenant = ""
        #: True when this chunk was canary-sampled: its sampled flows
        #: double-dispatch through the staged generation at resolve
        self.canary = False

    def resolve(self, verdicts: Optional[np.ndarray],
                error: Optional[str] = None, prov=None) -> None:
        self.verdicts = verdicts
        self.error = error
        self.prov = prov
        self.t_done = simclock.now()
        self.ev.set()

    @property
    def latency(self) -> Optional[float]:
        return (None if self.t_done is None
                else max(0.0, self.t_done - self.t_submit))

    @property
    def done(self) -> bool:
        return self.ev.is_set()

    def wait(self, timeout: float = 30.0) -> np.ndarray:
        if not simclock.wait_on(self.ev, timeout):
            raise TimeoutError("no verdict from the serve loop")
        if self.error is not None:
            raise ShedError(self.error)
        return self.verdicts


class ServeLoop:
    """The serving loop. One instance per service; owns the ring and
    every lease. Thread-safe: connects/submits land from connection
    threads while the single pack thread (or the DST runner's inline
    ``step()``) cycles."""

    def __init__(self, loader, capacity: int = 1024,
                 lease_ttl_s: float = 30.0,
                 pack_interval_s: float = 0.002,
                 max_slot_pending: int = 64,
                 gate: Optional[admission.AdmissionGate] = None,
                 authed_pairs_fn=None,
                 widths: Optional[Dict[str, int]] = None,
                 memo: bool = True,
                 provenance: Optional[bool] = None,
                 slo=None,
                 explain_store=None,
                 host_id: str = "",
                 canary=None):
        from cilium_tpu.runtime.explain import EXPLAIN
        from cilium_tpu.runtime.slo import SLOTracker

        engine = loader.engine
        if engine is None or not hasattr(engine, "_blob_step"):
            raise RuntimeError(
                "the serve loop needs the device engine "
                "(enable_tpu_offload) — the oracle has no ring to "
                "be resident in")
        self.loader = loader
        root_cfg = getattr(loader, "config", None)
        prov_cfg = getattr(root_cfg, "provenance", None)
        if provenance is None:
            provenance = bool(getattr(prov_cfg, "enabled", True))
        self.provenance = bool(provenance)
        self.explain_sample = int(getattr(prov_cfg, "sample_per_chunk",
                                          8) or 0)
        #: which host this loop serves AS (fleet replicas pass their
        #: identity; a standalone loop is anonymous) — rides every
        #: explain entry so a pack cycle is scoped (host, cycle)
        self.host_id = str(host_id)
        #: serve-plane metric labels: host-scoped for fleet replicas
        #: so N in-process loops land on DISTINCT series instead of
        #: colliding on one unlabeled family (ISSUE 17 satellite);
        #: standalone loops keep the pre-fleet unlabeled series
        self._host_labels = ({"host": self.host_id}
                             if self.host_id else None)
        #: fleet replicas pass a per-replica store so a trace resolves
        #: against the replica that served it; standalone loops share
        #: the process-global EXPLAIN (the pre-fleet contract)
        self.explain = explain_store if explain_store is not None \
            else EXPLAIN
        if prov_cfg is not None:
            self.explain.configure(
                capacity=getattr(prov_cfg, "explain_capacity", None))
        self.slo = (SLOTracker.from_config(slo) if slo is not None
                    else SLOTracker.from_config(
                        getattr(root_cfg, "slo", None)))
        if self.slo is not None and self.host_id:
            self.slo.host = self.host_id
        from cilium_tpu.hubble.flowagg import FlowAggregator

        #: continuous Hubble flow export (ISSUE 17): per-host bounded
        #: aggregation fed from the resolve path — ids on the hot
        #: path, sampled flows reused from the explain feed
        self.flows = FlowAggregator(host=self.host_id)
        self.ring = VerdictRing(engine, capacity, loader=loader,
                                widths=widths, memo=memo,
                                provenance=self.provenance,
                                host=self.host_id)
        self.lease_ttl_s = float(lease_ttl_s)
        self.pack_interval_s = float(pack_interval_s)
        #: per-slot pending-chunk bound: a producer outrunning the
        #: pack cycle sheds (queue-full) instead of buffering forever
        self.max_slot_pending = max(1, int(max_slot_pending))
        self.gate = gate
        self.authed_pairs_fn = authed_pairs_fn
        self._lock = threading.Lock()
        #: serializes pack cycles: step() may be driven inline (DST)
        #: AND by the production thread, and drain() packs too — the
        #: shared session's device tables are single-writer
        self._pack_lock = threading.Lock()
        self._leases: Dict[str, SlotLease] = {}
        #: lazy expiry heap of (expires_at-at-push, stream_id): a
        #: renewed lease's stale entries re-push at pop time, so
        #: expiry sweeps are O(lapsed log n), never O(all leases) —
        #: the difference between 100k idle streams costing nothing
        #: and costing every pack cycle
        self._expiry_heap: list = []
        self._draining = False
        self._stop = False
        self._thread: Optional[threading.Thread] = None
        #: leaf lock for the lifetime counters below: they are bumped
        #: from client threads AND the pack thread, sometimes while
        #: self._lock is held and sometimes not (`_shed`), so they
        #: get their own guard — nothing is called while holding it
        self._stats_lock = threading.Lock()
        #: lifetime counters (the load model's invariant face)
        self.grants = 0
        self.expiries = 0
        self.releases = 0
        self.sheds = 0
        self.served_records = 0
        self.chunk_errors = 0
        self.pack_failures = 0
        #: explanation-coverage counters: served records that carried
        #: a provenance bundle vs not (the ≥0.999 serve-soak gate)
        self.records_explained = 0
        self.records_unexplained = 0
        #: wall seconds spent on observability bookkeeping (flow
        #: aggregation, trace spans, explain sampling) — the fleet
        #: lane's ≤2% obs-budget numerator
        self.obs_seconds = 0.0
        #: shadow/canary rollout (ISSUE 20): a CanaryController whose
        #: sampling window double-dispatches a deterministic fraction
        #: of chunks through the staged generation N+1
        self.canary = canary
        #: monotone chunk counter driving the canary's deterministic
        #: counter-walk sample selection (never an RNG/id hash)
        self._canary_counter = 0
        #: wall seconds spent double-dispatching sampled chunks — the
        #: canary lane's ≤5%-of-pack-wall overhead numerator ...
        self.canary_seconds = 0.0
        #: ... and the pack-cycle wall it is measured against
        self.pack_seconds = 0.0

    @classmethod
    def from_config(cls, loader, cfg, gate=None,
                    authed_pairs_fn=None) -> "ServeLoop":
        """Build from ``Config.serve`` (tolerates absence so embedders
        with older configs keep working). Provenance and SLO knobs
        come off the loader's ROOT config (``[provenance]``/``[slo]``)
        inside ``__init__``."""
        return cls(
            loader,
            capacity=getattr(cfg, "slot_capacity", 1024),
            lease_ttl_s=getattr(cfg, "lease_ttl_s", 30.0),
            pack_interval_s=getattr(cfg, "pack_interval_ms", 2.0) / 1e3,
            max_slot_pending=getattr(cfg, "max_slot_pending", 64),
            gate=gate, authed_pairs_fn=authed_pairs_fn)

    # -- leases -----------------------------------------------------------
    def _shed(self, reason: str, tenant: str = "") -> None:
        with self._stats_lock:
            self.sheds += 1
        admission.count_shed("serve", admission.CLASS_DATA, reason,
                             tenant=tenant)
        if self.slo is not None:
            self.slo.observe_request(shed=True, tenant=tenant)

    def connect(self, stream_id: str, resume: bool = False,
                tenant: str = "") -> SlotLease:
        """Admit one stream into a slot lease. ``resume=True`` is
        reconnect-with-resume: a still-live lease for the stream is
        RENEWED and returned — never granted (counted) twice; an
        expired/absent one falls through to a fresh grant. ``tenant``
        attributes the stream (sheds, SLO, explain) and rides its
        lease. Raises :class:`ShedError` (reason ``fault`` /
        ``draining`` / ``ring-full`` / gate reason — including
        ``tenant-quota`` when the gate's fairness window sheds this
        tenant) instead of queueing."""
        try:
            faults.maybe_fail(LEASE_POINT)
        except Exception:  # noqa: BLE001 — plan-chosen exception
            self._shed(admission.SHED_FAULT, tenant=tenant)
            raise ShedError(admission.SHED_FAULT)
        now = simclock.now()
        with self._lock:
            if self._draining:
                self._shed(admission.SHED_DRAINING, tenant=tenant)
                raise ShedError(admission.SHED_DRAINING)
            if resume:
                lease = self._leases.get(stream_id)
                if lease is not None and lease.active:
                    if not lease.expired(now):
                        lease.renew(now)
                        return lease
                    # expired but not yet swept: release the slot NOW
                    # (counted as an expiry) before re-granting — or
                    # the overwrite below would leak the old slot
                    # until the ring filled up
                    self._release_locked(lease, "expired")
            elif stream_id in self._leases:
                # duplicate connect without resume: one stream, one
                # lease — the old one is released first (its pending
                # work resolves as error)
                self._release_locked(self._leases[stream_id],
                                     "superseded")
        if self.gate is not None:
            ok, reason = self.gate.admit(admission.CLASS_DATA,
                                         tenant=tenant)
            if not ok:
                with self._stats_lock:
                    self.sheds += 1  # counted by the gate already
                raise ShedError(reason)
        now = simclock.now()
        with self._lock:
            if self._draining:
                self._shed(admission.SHED_DRAINING, tenant=tenant)
                raise ShedError(admission.SHED_DRAINING)
            # the lock was dropped around gate.admit: a concurrent
            # connect for the SAME stream may have granted meanwhile.
            # Overwriting its lease would orphan the old slot (the
            # expiry heap resolves stream_id to the NEW lease) and
            # leak it until the ring filled — so reuse or release
            # the racer's lease first, one stream = one live slot
            racer = self._leases.get(stream_id)
            if racer is not None and racer.active:
                if resume and not racer.expired(now):
                    racer.renew(now)
                    return racer
                self._release_locked(
                    racer, "expired" if racer.expired(now)
                    else "superseded")
            try:
                slot = self.ring.acquire(stream_id)
            except RingFull:
                self._shed(admission.SHED_RING_FULL, tenant=tenant)
                raise ShedError(admission.SHED_RING_FULL)
            lease = SlotLease(stream_id, slot, self.lease_ttl_s, now,
                              tenant=tenant)
            self._leases[stream_id] = lease
            heapq.heappush(self._expiry_heap,
                           (lease.expires_at, stream_id))
            self.grants += 1
            METRICS.inc(SERVE_LEASE_GRANTS, labels=self._host_labels)
            METRICS.set_gauge(SERVE_RING_OCCUPANCY,
                              float(len(self._leases)),
                              labels=self._host_labels)
            return lease

    def _release_locked(self, lease: SlotLease, how: str) -> None:
        """Caller holds self._lock. Resolves the slot's pending
        chunks as errors, returns the slot, counts by ``how``."""
        if not lease.active:
            return
        lease.active = False
        # release pops the slot's pending under the RING lock, so a
        # chunk resolves through exactly one of (pack → verdicts,
        # release → error) — never both
        dropped = self.ring.release(lease.slot)
        if self._leases.get(lease.stream_id) is lease:
            self._leases.pop(lease.stream_id, None)
        for _idx, done, _epoch in dropped:
            if done is not None:
                done.resolve(None, error=f"lease-{how}")
                tid = getattr(done, "trace_id", "")
                if tid:
                    # the dropped chunk's host-A attribution: the
                    # abandon marker is what the stitched timeline
                    # shows between the dead host's last span and
                    # the survivor's replay (ISSUE 17)
                    from cilium_tpu.runtime.tracing import TRACER

                    TRACER.event_remote(
                        tid, "serve.abandon", host=self.host_id,
                        epoch=getattr(done, "epoch", 0),
                        error=f"lease-{how}")
        if how == "expired":
            self.expiries += 1
            METRICS.inc(SERVE_LEASE_EXPIRIES,
                        labels=self._host_labels)
        else:
            self.releases += 1
            METRICS.inc(SERVE_LEASE_RELEASES,
                        labels=self._host_labels)
        METRICS.set_gauge(SERVE_RING_OCCUPANCY,
                          float(len(self._leases)),
                          labels=self._host_labels)

    def disconnect(self, lease: SlotLease) -> None:
        """Clean stream end: release the slot (pending unpacked
        chunks resolve as ``lease-closed`` errors — callers flush
        with a final ``step()``/pack before disconnecting)."""
        with self._lock:
            self._release_locked(lease, "closed")

    # -- data path --------------------------------------------------------
    def submit(self, lease: SlotLease, rec, l7, offsets, blob,
               gen=None) -> ChunkTicket:
        """Encode one chunk into the stream's slot (host work only)
        and return its completion ticket; the next pack cycle serves
        it. Raises :class:`LeaseExpired` when the lease lapsed
        (reconnect first) and :class:`ShedError` on backpressure
        (``queue-full``) or an armed ``serve.ring_slot`` fault."""
        try:
            faults.maybe_fail(RING_SLOT_POINT)
        except Exception:  # noqa: BLE001 — plan-chosen exception
            with self._stats_lock:
                self.chunk_errors += 1
            self._shed(admission.SHED_FAULT, tenant=lease.tenant)
            raise ShedError(admission.SHED_FAULT)
        now = simclock.now()
        with self._lock:
            if not lease.active or lease.expired(now):
                if lease.active:
                    self._release_locked(lease, "expired")
                raise LeaseExpired(
                    f"lease for {lease.stream_id} lapsed")
            if len(lease.slot.pending) >= self.max_slot_pending:
                self._shed(admission.SHED_QUEUE_FULL,
                           tenant=lease.tenant)
                raise ShedError(admission.SHED_QUEUE_FULL)
            lease.renew(now)
        # the stream's trace context rides the TICKET: the pack thread
        # has no contextvar, so this is where ring-path verdicts keep
        # their trace id (flows/log lines/explain entries join on it)
        from cilium_tpu.runtime.tracing import TRACER

        ctx = TRACER.current()
        ticket = ChunkTicket(
            len(rec),
            trace_id=ctx.trace_id if ctx is not None else "",
            epoch=getattr(ctx, "epoch", 0) if ctx is not None else 0)
        ticket.tenant = lease.tenant
        # canary sample selection (ISSUE 20): a monotone chunk counter
        # walked through the controller's deterministic fraction —
        # the SAME chunks sample on every host and PYTHONHASHSEED
        if self.canary is not None and self.canary.active():
            with self._stats_lock:
                self._canary_counter += 1
                c = self._canary_counter
            ticket.canary = self.canary.should_sample(c)
        want_explain = (ticket.trace_id and self.provenance
                        and self.explain_sample > 0)
        if want_explain or ticket.canary:
            # sampled flows for the explain plane (traced chunks) and
            # the canary's shadow dispatch — both pay the same
            # bounded host reconstruction, built once
            t_obs = simclock.perf()
            try:
                from cilium_tpu.ingest.binary import records_to_flows_l7

                k = min(self.explain_sample or 8, len(rec))
                ticket.sample_flows = records_to_flows_l7(
                    rec[:k], l7[:k], offsets, blob,
                    gen=(gen[:k] if gen is not None else None))
            except Exception:  # noqa: BLE001 — explain/canary are
                ticket.sample_flows = None  # advisory; never fail
                ticket.canary = False       # the chunk
            with self._stats_lock:
                self.obs_seconds += max(0.0, simclock.perf() - t_obs)
        # ring.submit takes its own lock; encoding outside ours keeps
        # lease ops responsive while a big chunk featurizes
        try:
            self.ring.submit(lease.slot, rec, l7, offsets, blob,
                             gen=gen, done=ticket)
        except SlotNotResident:
            # the pack thread expired the lease (or a concurrent
            # disconnect released it) between our lease check and the
            # ring call: surface it as the lease-lapsed contract so
            # callers hit the reconnect-with-resume path, not a
            # connection-fatal error
            with self._lock:
                if lease.active:
                    self._release_locked(lease, "closed")
            raise LeaseExpired(
                f"lease for {lease.stream_id} lost its ring slot")
        return ticket

    # -- the pack cycle ---------------------------------------------------
    def _expire_leases(self, now: float) -> int:
        lapsed = 0
        with self._lock:
            heap = self._expiry_heap
            while heap and heap[0][0] <= now:
                _, stream_id = heapq.heappop(heap)
                lease = self._leases.get(stream_id)
                if lease is None or not lease.active:
                    continue          # released/superseded: stale entry
                if lease.expired(now):
                    self._release_locked(lease, "expired")
                    lapsed += 1
                else:
                    # renewed since this entry was pushed: re-arm at
                    # the lease's REAL deadline
                    heapq.heappush(heap, (lease.expires_at, stream_id))
        return lapsed

    def _amap_for(self, engine):
        """AttributionMap for the serving engine, rebuilt on swap."""
        if getattr(self, "_amap_engine", None) is not engine:
            from cilium_tpu.engine.attribution import AttributionMap

            try:
                self._amap = AttributionMap.from_policy(engine.policy)
            except Exception:  # noqa: BLE001 — attribution is
                self._amap = None  # advisory; never fail serving
            self._amap_engine = engine
        return self._amap

    def _resolve_ticket(self, ticket: ChunkTicket, n: int, dev
                        ) -> int:
        """Resolve one packed chunk's ticket (verdicts + provenance),
        feed the SLO trackers, and record explain entries for traced
        chunks. Returns records served."""
        prov = None
        if hasattr(dev, "slice"):        # ServedPack (provenance on)
            prov = dev.host()
            verdicts = np.asarray(prov.verdict)[:n].astype(np.int32)
        else:
            verdicts = np.asarray(dev)[:n].astype(np.int32)
        ticket.resolve(verdicts, prov=prov)
        lat = max(0.0, simclock.now() - ticket.t_submit)
        METRICS.observe(SERVE_LATENCY, lat, labels=self._host_labels)
        if self.slo is not None:
            self.slo.observe_latency(lat, tenant=ticket.tenant)
            self.slo.observe_request(shed=False,
                                     tenant=ticket.tenant)
        if ticket.canary and self.canary is not None \
                and ticket.sample_flows:
            # the double dispatch: the sampled flows re-evaluate
            # through the STAGED generation, diffed against what N
            # just served — in this pack cycle, metered against it
            t_can = simclock.perf()
            self.canary.observe_chunk(
                ticket.sample_flows,
                verdicts[:len(ticket.sample_flows)])
            with self._stats_lock:
                self.canary_seconds += max(
                    0.0, simclock.perf() - t_can)
        with self._stats_lock:
            if prov is not None:
                self.records_explained += n
            else:
                self.records_unexplained += n
        self.flows.note_served(n)
        if ticket.trace_id:
            # the serving host's span, appended BY id: the pack
            # thread holds no contextvar for the submitter's trace,
            # and after a handoff THIS host is not the one that
            # started the trace — host + epoch are what the stitched
            # timeline orders by (ISSUE 17)
            from cilium_tpu.runtime.tracing import TRACER

            TRACER.record_remote(
                ticket.trace_id, "serve.chunk", phase="device-dispatch",
                t0=ticket.t_submit, dur=lat, host=self.host_id,
                epoch=ticket.epoch, records=n)
        if ticket.trace_id and ticket.sample_flows and prov is not None:
            from cilium_tpu.runtime.explain import build_entries

            amap = self._amap_for(self.ring.session.engine)
            entries = build_entries(
                ticket.trace_id, "serve", ticket.sample_flows,
                prov.verdict, prov.l7_match, amap,
                gens=prov.gens, memo_hit=prov.memo_hit,
                match_spec=prov.match_spec, kernel=prov.kernel,
                pack_cycle=prov.pack_cycle,
                generation=prov.generation,
                host_id=self.host_id,
                sample=len(ticket.sample_flows),
                tenant=ticket.tenant)
            self.explain.record(ticket.trace_id, entries)
            self.flows.observe_entries(entries)
            LOG.debug("serve chunk explained", extra={"fields": {
                "trace_id": ticket.trace_id, "records": n,
                "sampled": len(entries)}})
        return n

    def step(self) -> int:
        """One pack cycle: expire idle leases, pack + dispatch
        pending chunks, resolve tickets. Returns records served.
        The inline face the DST runner / load model drives; the
        production thread calls it on the pack interval."""
        now = simclock.now()
        self._expire_leases(now)
        pairs = (self.authed_pairs_fn()
                 if self.authed_pairs_fn is not None else None)
        served = 0
        t0 = simclock.perf()
        with self._pack_lock:
            results = self.ring.pack(authed_pairs=pairs)
        if results:
            # per-pack-cycle SLO telemetry: dispatch wall, pack size
            # (SERVE_PACK_RECORDS rides ring.pack), slot occupancy
            METRICS.observe(SERVE_PACK_DISPATCH_SECONDS,
                            max(0.0, simclock.perf() - t0),
                            labels=self._host_labels)
            with self._lock:
                occ = float(len(self._leases))
            METRICS.observe(SERVE_PACK_OCCUPANCY, occ,
                            labels=self._host_labels)
        for _slot, n, ticket, dev in results:
            if ticket is None:
                continue
            if dev is None:
                # encoded ids predate a session reset — the payload
                # is gone; the stream retries the chunk
                with self._stats_lock:
                    self.chunk_errors += 1
                ticket.resolve(None, error="session-reset")
                continue
            served += self._resolve_ticket(ticket, n, dev)
        with self._stats_lock:
            self.served_records += served
            if results:
                # pack-cycle wall (dispatch + resolution, shadow
                # included) — the canary overhead's denominator
                self.pack_seconds += max(0.0, simclock.perf() - t0)
        if results and self.slo is not None:
            self.slo.publish()
        return served

    def _run(self) -> None:
        while True:
            with self._lock:
                if self._stop:
                    return
            # hold the (possibly autojumping) virtual clock while the
            # pack's REAL compute runs: a dispatch/compile must not
            # read as idle time, or simulated latencies would inflate
            # by wall compute (see simclock.hold)
            with simclock.hold():
                try:
                    self.step()
                except Exception as e:  # noqa: BLE001 — degrade,
                    # never die: the ring put the batch back, the
                    # next cycle retries (transient faults recover)
                    with self._stats_lock:
                        self.pack_failures += 1
                    LOG.warning("pack cycle failed; retrying next "
                                "interval", extra={"fields": {
                                    "error": f"{type(e).__name__}: "
                                             f"{e}"}})
            simclock.sleep(self.pack_interval_s)

    def start(self) -> "ServeLoop":
        """Spawn the production pack thread (virtual-time ready: the
        interval is a ``simclock.sleep``)."""
        if self._thread is None:
            self._thread = threading.Thread(target=self._run,
                                            daemon=True,
                                            name="serve-pack-loop")
            self._thread.start()
        return self

    # -- drain ------------------------------------------------------------
    def drain(self, max_cycles: int = 64) -> int:
        """Stop admitting new leases, pack out every pending chunk
        (bounded cycles — a wedged engine must not wedge the drain),
        then release every lease. Returns records flushed. A lease
        that expires at exactly the drain tick still gets its pending
        chunks FLUSHED — drain packs before releasing, so expiry vs
        drain is a who-counts race, never a lost verdict."""
        with self._lock:
            self._draining = True
        flushed = 0
        for _ in range(max_cycles):
            # NOTE: no lease expiry here — pending work of an
            # already-expired lease was resolved at expiry; work
            # still pending on live leases flushes even if their TTL
            # lapses mid-drain
            pairs = (self.authed_pairs_fn()
                     if self.authed_pairs_fn is not None else None)
            t0 = simclock.perf()
            with self._pack_lock:
                results = self.ring.pack(authed_pairs=pairs)
            if not results:
                break
            for _slot, n, ticket, dev in results:
                if ticket is None:
                    continue
                if dev is None:
                    with self._stats_lock:
                        self.chunk_errors += 1
                    ticket.resolve(None, error="session-reset")
                    continue
                flushed += self._resolve_ticket(ticket, n, dev)
            with self._stats_lock:
                self.pack_seconds += max(0.0, simclock.perf() - t0)
        with self._stats_lock:
            self.served_records += flushed
        with self._lock:
            for lease in list(self._leases.values()):
                self._release_locked(lease, "drained")
        return flushed

    def abandon(self, how: str = "closed") -> int:
        """Host-death face (runtime/fleetserve.py): release EVERY
        lease without a final pack — nothing else is served; pending
        chunks resolve as ``lease-{how}`` errors, which is exactly
        what a client sees when its host dies mid-chunk (connection
        reset → the reconnect-with-resume replay path). Contrast
        :meth:`drain` (graceful: pending chunks FLUSH). Returns the
        number of leases dropped. The books stay exact — every
        abandoned lease counts as a release — so a dead host's loop
        still balances in the fleet-wide accounting."""
        with self._lock:
            self._draining = True
            dropped = 0
            for lease in list(self._leases.values()):
                self._release_locked(lease, how)
                dropped += 1
        return dropped

    def lease_ids(self) -> list:
        """Stream ids currently holding a live lease here — the fleet
        router's lease-conservation invariant reads this per host to
        prove no stream is leased on two live hosts at once."""
        with self._lock:
            return [sid for sid, lease in self._leases.items()
                    if lease.active]

    def stop(self) -> None:
        with self._lock:
            self._stop = True
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None

    # -- introspection ----------------------------------------------------
    def status(self) -> Dict[str, object]:
        with self._lock:
            occupancy = len(self._leases)
        served = max(1, self.records_explained
                     + self.records_unexplained)
        out = {
            "occupancy": occupancy,
            "capacity": self.ring.capacity,
            "grants": self.grants,
            "expiries": self.expiries,
            "releases": self.releases,
            "sheds": self.sheds,
            "packs": self.ring.packs,
            "records_packed": self.ring.records_packed,
            "served_records": self.served_records,
            "chunk_errors": self.chunk_errors,
            "pack_failures": self.pack_failures,
            "bytes_saved": self.ring.bytes_saved,
            "bytes_shipped": self.ring.bytes_shipped,
            "memo": self.ring.memo_stats(),
            "draining": self._draining,
            "provenance": {
                "enabled": self.provenance,
                "records_explained": self.records_explained,
                "records_unexplained": self.records_unexplained,
                "explain_coverage": round(
                    self.records_explained / served, 6),
                "explain_entries": len(self.explain),
            },
            "flows": {
                "records": self.flows.records,
                "aggregated": self.flows.aggregated,
                "overflow": self.flows.overflow,
                "keys": self.flows.key_count(),
            },
        }
        if self.slo is not None:
            out["slo"] = self.slo.status()
        if self.canary is not None:
            report = self.canary.report()
            report["canary_seconds"] = round(self.canary_seconds, 6)
            report["pack_seconds"] = round(self.pack_seconds, 6)
            out["canary"] = report
        return out
