"""Persistent XLA compilation cache setup (shared, idempotent).

A TPU compile through the tunneled transport costs 10-20s per shape
(docs/PLATFORM.md); the engine's shapes are deliberately bucketed
(pow2 batch buckets in the service path, pow2 string/unique-row tables
in capture replay) precisely so they repeat — but without a persistent
cache every fresh PROCESS recompiles all of them, which turned whole
bench_service measurement windows into compile storms (round-4 first
TPU sweep) and costs every daemon restart the same. One call, before
or after jax import, points every process at one on-disk cache.

Reference analog: compiled-datapath reuse across agent restarts
(``pkg/datapath/loader``'s object cache keyed by template hash); the
artifact cache in ``runtime/loader.py`` plays that role for staged
POLICY tensors, this one for XLA executables.
"""

from __future__ import annotations

import os
import sys

_done = False


def enable_persistent_cache() -> None:
    """Point jax at the shared on-disk compilation cache; failure to
    set up (read-only HOME, exotic jax build) degrades to no-cache.
    Override the location with ``CILIUM_TPU_XLA_CACHE``; set it empty
    to disable."""
    global _done
    if _done:
        return
    _done = True
    try:
        import jax

        cache_dir = os.environ.get(
            "CILIUM_TPU_XLA_CACHE",
            os.path.expanduser("~/.cache/cilium_tpu/xla"))
        if not cache_dir:
            return
        os.makedirs(cache_dir, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        # 0.1s, not the 0.5s default: the capture-staging programs
        # (fused table scan, memo gather) compile in 0.1-0.5s on CPU
        # and sat just under the old bar — every fresh bench process
        # recompiled all of them, which WAS the dominant stage_ms
        # phase of the tier-1 CPU config. Sub-0.1s programs stay
        # uncached (disk round-trip wouldn't pay).
        jax.config.update(
            "jax_persistent_cache_min_compile_time_secs", 0.1)
    except Exception as e:  # noqa: BLE001 — cache is an optimization
        print(f"xla persistent cache disabled: {e}", file=sys.stderr)
