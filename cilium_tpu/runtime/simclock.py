"""Injectable time: the clock seam under the whole serving plane.

Every time-driven state machine in this tree — circuit-breaker
recovery, bank-quarantine TTLs, admission deadlines, reconnect
backoff, credit waits, kvstore leases, clustermesh heartbeats, DNS
cache expiry — used to read the wall clock directly, so `make chaos`
and `make soak` could only exercise the handful of schedules they had
the patience to *sleep* through. This module makes time a test input,
the FoundationDB deterministic-simulation discipline: production code
routes every behavioral clock read and every timed wait through the
installed :class:`Clock`; tests install a :class:`VirtualClock` and
drive (or auto-advance) virtual time, so hours of TTL/backoff/deadline
behavior run in milliseconds and a seeded fault schedule replays
byte-identically (``runtime/dst.py`` builds the schedule search on
top).

Contract:

* **Behavioral time** (``now``/``wall``/``sleep`` and the timed
  waits) is virtualizable. ``now()`` is monotonic seconds (the
  ``time.monotonic`` role: deadlines, TTLs, backoff); ``wall()`` is
  epoch seconds (the ``time.time`` role: stamps on flows, traces,
  cache entries).
* **Measurement time** (``perf()``) is real by default even under the
  virtual clock's driven mode — an engine batch still takes real CPU
  seconds and benchmarks must say so. ``VirtualClock`` flips it to
  virtual so simulated service times (a ``sleep`` inside a synthetic
  engine) are measured in the same currency they were spent in.
* The module-level functions (:func:`now`, :func:`sleep`, ...) read
  the installed clock at **call time**, so objects constructed before
  a test installs its virtual clock still follow it; constructors may
  also take an explicit ``clock`` for per-instance injection (the
  chaos suite's manually-advanced breaker clock predates this module
  and keeps working).

The ctlint ``wall-clock`` rule (analysis/wallclock.py) enforces the
seam: direct ``time.time/monotonic/sleep`` in runtime/engine/policy
modules is a finding unless justified (provenance/bench stamping and
profiler sampling measure the real world by definition).
"""

from __future__ import annotations

import contextlib
import heapq
import threading
import time
from typing import Callable, List, Optional

__all__ = [
    "Clock", "RealClock", "VirtualClock", "ClockEvent",
    "get", "install", "reset", "use",
    "now", "wall", "perf", "sleep", "event", "hold",
    "wait_on", "wait_for", "wait_cond",
]

#: fixed virtual epoch (2020-09-13T12:26:40Z): wall stamps under a
#: VirtualClock must be a pure function of virtual time, never of the
#: host's clock, or DST traces would differ byte-wise across runs
VIRTUAL_EPOCH = 1_600_000_000.0


class Clock:
    """The protocol. ``RealClock`` is the production implementation;
    ``VirtualClock`` the simulation one. Methods mirror the stdlib
    call sites they replace so the refactor stays mechanical."""

    def now(self) -> float:            # pragma: no cover - interface
        raise NotImplementedError

    def wall(self) -> float:           # pragma: no cover - interface
        raise NotImplementedError

    def perf(self) -> float:           # pragma: no cover - interface
        raise NotImplementedError

    def sleep(self, seconds: float) -> float:  # pragma: no cover
        """Block for ``seconds`` on this clock; returns the wake
        instant in this clock's ``now()`` timeline."""
        raise NotImplementedError

    def event(self) -> threading.Event:
        """An Event whose timed wait integrates with this clock (pair
        with :meth:`wait_on`)."""
        return threading.Event()

    def wait_on(self, ev, timeout: Optional[float] = None) -> bool:
        """``ev.wait(timeout)`` with the timeout measured on THIS
        clock. Returns True when the event fired."""
        raise NotImplementedError      # pragma: no cover - interface

    def wait_for(self, cond: threading.Condition,
                 predicate: Callable[[], bool],
                 timeout: Optional[float] = None) -> bool:
        """``cond.wait_for(predicate, timeout)`` with the timeout on
        THIS clock. Caller holds ``cond``."""
        raise NotImplementedError      # pragma: no cover - interface

    def wait_cond(self, cond: threading.Condition,
                  timeout: Optional[float] = None) -> bool:
        """``cond.wait(timeout)`` with the timeout on THIS clock.
        Returns False once the (virtual) deadline has passed; True on
        any earlier wake-up. Like the stdlib primitive it may wake
        spuriously — call sites re-check their predicate in a loop."""
        raise NotImplementedError      # pragma: no cover - interface


class RealClock(Clock):
    """Production time: thin delegation to the stdlib."""

    # the one module allowed to touch time.* directly is this one —
    # it IS the seam the wall-clock rule points everyone else at

    def now(self) -> float:
        return time.monotonic()

    def wall(self) -> float:
        return time.time()

    def perf(self) -> float:
        return time.perf_counter()

    def sleep(self, seconds: float) -> float:
        time.sleep(seconds)
        return self.now()

    def wait_on(self, ev, timeout: Optional[float] = None) -> bool:
        return ev.wait(timeout)

    def wait_for(self, cond, predicate, timeout=None) -> bool:
        return cond.wait_for(predicate, timeout)

    def wait_cond(self, cond, timeout=None) -> bool:
        woke = cond.wait(timeout)
        return True if timeout is None else woke


class ClockEvent:
    """A ``threading.Event`` that notifies its VirtualClock on
    ``set()``, so a virtual ``wait_on`` wakes promptly instead of on
    its safety poll. Transparent on the real clock (never built)."""

    __slots__ = ("_ev", "_clock")

    def __init__(self, clock: "VirtualClock"):
        self._ev = threading.Event()
        self._clock = clock

    def set(self) -> None:
        self._ev.set()
        self._clock.kick()

    def clear(self) -> None:
        self._ev.clear()

    def is_set(self) -> bool:
        return self._ev.is_set()

    def wait(self, timeout: Optional[float] = None) -> bool:
        # a bare .wait on a ClockEvent measures on the virtual clock
        # too — callers that hold one got it from VirtualClock.event()
        return self._clock.wait_on(self, timeout)


class _Waiter:
    """One parked virtual wait: its deadline, the condition to notify
    at expiry (None = parked on the clock's own condvar), and the
    fired flag advance() flips."""

    __slots__ = ("deadline", "seq", "cond", "fired")

    def __init__(self, deadline: float, seq: int,
                 cond: Optional[threading.Condition]):
        self.deadline = deadline
        self.seq = seq
        self.cond = cond
        self.fired = False


class VirtualClock(Clock):
    """Deterministic simulated time.

    Two driving modes:

    * **Driven** (default): time moves only when the test/DST runner
      calls :meth:`advance` / :meth:`advance_to` — sleepers park on an
      event heap and wake exactly at their deadline. This is the mode
      the schedule-search runner uses: the whole event sequence is a
      pure function of the schedule.
    * **Autojump** (``autojump=seconds``): when the clock has parked
      waiters and sees no clock activity for that many REAL seconds
      (every thread that participates in time is blocked), it jumps to
      the earliest deadline — trio's MockClock discipline adapted to
      OS threads. This converts sleep-bound multi-threaded lanes
      (`make soak`'s synthetic service times) to virtual time without
      restructuring them.

    ``perf()`` is virtual here: simulated work (a virtual sleep inside
    a synthetic engine) must be measured in the currency it was spent
    in, or EWMA service-rate estimates would divide real microseconds
    into virtual records.
    """

    def __init__(self, start: float = 0.0, wall0: float = VIRTUAL_EPOCH,
                 autojump: Optional[float] = None, poll: float = 0.002,
                 max_real_block: float = 120.0):
        self._cv = threading.Condition()
        self._now = float(start)
        self._wall0 = float(wall0)
        self._heap: List[tuple] = []   # (deadline, seq) → waiter
        self._by_seq = {}
        self._seq = 0
        self._activity = 0
        self._busy = 0
        self._poll = float(poll)
        self._autojump = autojump
        self._max_real_block = float(max_real_block)
        self._jumper: Optional[threading.Thread] = None
        self._closed = False
        #: total virtual seconds advanced — the lane-output speedup
        #: report divides this by real elapsed seconds
        self.simulated = 0.0

    # -- reads ------------------------------------------------------------
    def now(self) -> float:
        return self._now          # float read is atomic under the GIL

    def wall(self) -> float:
        return self._wall0 + self._now

    def perf(self) -> float:
        return self._now

    # -- waiter bookkeeping ----------------------------------------------
    def _register(self, deadline: float,
                  cond: Optional[threading.Condition]) -> _Waiter:
        # registering (= a thread going to sleep) is deliberately NOT
        # activity: a waiter re-arming a short poll must not hold the
        # autojump off forever. Activity is the real wake signals —
        # events firing, kicks, advances.
        with self._cv:
            self._seq += 1
            w = _Waiter(deadline, self._seq, cond)
            heapq.heappush(self._heap, (deadline, w.seq))
            self._by_seq[w.seq] = w
            self._ensure_jumper()
            return w

    def _unregister(self, w: _Waiter) -> None:
        with self._cv:
            self._by_seq.pop(w.seq, None)   # heap entry lazily dropped
            self._cv.notify_all()

    def kick(self) -> None:
        """External wake signal (a ClockEvent fired, work arrived):
        bump activity so autojump holds off, and wake parked
        waiters so they re-check their events."""
        with self._cv:
            self._activity += 1
            self._cv.notify_all()

    @contextlib.contextmanager
    def hold(self):
        """Mark the calling thread BUSY for the block: autojump will
        not advance virtual time while any thread holds. An unparked
        thread doing real compute (an engine dispatch, a compile) is
        invisible to the parked-waiter heuristic — without a hold the
        jumper reads its silence as quiet and races virtual time past
        work that is still happening, which inflates every simulated
        latency by REAL compute time. Driven mode and RealClock are
        unaffected (the jumper is the only reader)."""
        with self._cv:
            self._busy += 1
            self._activity += 1
        try:
            yield
        finally:
            with self._cv:
                self._busy -= 1
                self._activity += 1
                self._cv.notify_all()

    # -- advancing --------------------------------------------------------
    def advance(self, dt: float) -> float:
        """Move virtual time forward by ``dt``; fires every waiter
        whose deadline falls inside, in deadline order, waking each at
        exactly its own instant. Returns the new now()."""
        return self.advance_to(self._now + max(0.0, float(dt)))

    def advance_to(self, target: float) -> float:
        while True:
            notify_conds = []
            with self._cv:
                target = max(target, self._now)
                due = None
                while self._heap:
                    deadline, seq = self._heap[0]
                    w = self._by_seq.get(seq)
                    if w is None:            # stale heap entry
                        heapq.heappop(self._heap)
                        continue
                    if deadline > target:
                        break
                    heapq.heappop(self._heap)
                    due = w
                    break
                if due is None:
                    self.simulated += target - self._now
                    self._now = target
                    self._activity += 1
                    self._cv.notify_all()
                    return self._now
                # step to THIS deadline only: a woken sleeper may
                # register new, earlier work before later waiters fire
                self.simulated += max(0.0, due.deadline - self._now)
                self._now = max(self._now, due.deadline)
                due.fired = True
                self._by_seq.pop(due.seq, None)
                self._activity += 1
                self._cv.notify_all()
                if due.cond is not None:
                    notify_conds.append(due.cond)
            # notify foreign condvars OUTSIDE self._cv: a waiter holds
            # its cond then takes _cv to register — acquiring in the
            # opposite order here would deadlock the pair
            for cond in notify_conds:
                with cond:
                    cond.notify_all()

    def advance_to_next(self) -> Optional[float]:
        """Jump to the earliest parked deadline (None when idle)."""
        with self._cv:
            while self._heap and self._heap[0][1] not in self._by_seq:
                heapq.heappop(self._heap)
            if not self._heap:
                return None
            target = self._heap[0][0]
        return self.advance_to(target)

    # -- autojump ---------------------------------------------------------
    def _ensure_jumper(self) -> None:
        # caller holds _cv
        if self._autojump is None or self._jumper is not None:
            return
        t = threading.Thread(target=self._jump_loop, daemon=True,
                             name="simclock-autojump")
        self._jumper = t
        t.start()

    def _jump_loop(self) -> None:
        last = -1
        while not self._closed:
            time.sleep(self._autojump)
            with self._cv:
                if self._closed:
                    return
                live = [s for _, s in self._heap if s in self._by_seq]
                if not live or self._busy > 0 \
                        or self._activity != last:
                    last = self._activity
                    continue
                target = min(self._by_seq[s].deadline for s in live)
                if target <= self._now:
                    continue
            self.advance_to(target)

    def close(self) -> None:
        with self._cv:
            self._closed = True
            self._cv.notify_all()

    # -- waits ------------------------------------------------------------
    def sleep(self, seconds: float) -> float:
        """Park until virtual time reaches now+seconds. Returns the
        virtual WAKE instant (the waiter's own deadline) — the only
        race-free way for a woken thread to know when it ran: by the
        time it reads ``now()`` a driver may have advanced further."""
        w = self._register(self._now + max(0.0, float(seconds)), None)
        deadline_real = time.monotonic() + self._max_real_block
        try:
            with self._cv:
                while not w.fired:
                    if time.monotonic() >= deadline_real:
                        raise RuntimeError(
                            "virtual sleep blocked for "
                            f"{self._max_real_block}s real time — "
                            "nothing is advancing the VirtualClock")
                    self._cv.wait(self._poll if self._autojump is None
                                  else 1.0)
            return w.deadline
        finally:
            self._unregister(w)

    def event(self):
        return ClockEvent(self)

    def wait_on(self, ev, timeout: Optional[float] = None) -> bool:
        real = getattr(ev, "_ev", ev)   # unwrap ClockEvent
        if timeout is None:
            return real.wait()
        # ClockEvent.set() kicks our condvar, so the poll slice is a
        # safety net only; a plain threading.Event set by a thread
        # that doesn't know the clock is caught by the poll
        slice_s = 0.25 if isinstance(ev, ClockEvent) else self._poll
        w = self._register(self._now + max(0.0, float(timeout)), None)
        deadline_real = time.monotonic() + self._max_real_block
        try:
            with self._cv:
                while True:
                    if real.is_set():
                        return True
                    if w.fired or self._now >= w.deadline:
                        return real.is_set()
                    if time.monotonic() >= deadline_real:
                        raise RuntimeError(
                            "virtual wait_on blocked for "
                            f"{self._max_real_block}s real time — "
                            "nothing is advancing the VirtualClock")
                    self._cv.wait(slice_s)
        finally:
            self._unregister(w)

    def wait_for(self, cond, predicate, timeout=None) -> bool:
        if timeout is None:
            # timeless wait: plain condition semantics, no heap entry
            while not predicate():
                cond.wait(self._poll)
            return True
        w = self._register(self._now + max(0.0, float(timeout)), cond)
        deadline_real = time.monotonic() + self._max_real_block
        try:
            while True:
                if predicate():
                    return True
                if w.fired or self._now >= w.deadline:
                    return predicate()
                if time.monotonic() >= deadline_real:
                    raise RuntimeError(
                        "virtual wait_for blocked for "
                        f"{self._max_real_block}s real time — "
                        "nothing is advancing the VirtualClock")
                cond.wait(self._poll)
        finally:
            self._unregister(w)

    def wait_cond(self, cond, timeout=None) -> bool:
        if timeout is None:
            cond.wait()
            return True
        w = self._register(self._now + max(0.0, float(timeout)), cond)
        try:
            cond.wait(self._poll)
            return not (w.fired or self._now >= w.deadline)
        finally:
            self._unregister(w)


# -- the installed clock ----------------------------------------------------

_REAL = RealClock()
_CLOCK: Clock = _REAL
_INSTALL_LOCK = threading.Lock()


def get() -> Clock:
    return _CLOCK


def install(clock: Clock) -> None:
    """Install ``clock`` process-wide. Tests prefer :func:`use`."""
    global _CLOCK
    with _INSTALL_LOCK:
        _CLOCK = clock


def reset() -> None:
    global _CLOCK
    with _INSTALL_LOCK:
        _CLOCK = _REAL


@contextlib.contextmanager
def use(clock: Clock):
    """``with use(VirtualClock()) as clk: ...`` — install for the
    block, always restored (a leaked virtual clock would wedge every
    later test's timeouts)."""
    prev = _CLOCK
    install(clock)
    try:
        yield clock
    finally:
        install(prev)
        if isinstance(clock, VirtualClock):
            clock.close()


# -- call-time delegation: late-bound so objects built before a test
#    installs its clock still follow it ------------------------------------

def now() -> float:
    return _CLOCK.now()


def wall() -> float:
    return _CLOCK.wall()


def perf() -> float:
    return _CLOCK.perf()


def sleep(seconds: float) -> float:
    return _CLOCK.sleep(seconds)


def event() -> threading.Event:
    return _CLOCK.event()


def hold():
    """``with simclock.hold(): <real compute>`` — marks the calling
    thread busy so an autojumping VirtualClock will not advance
    virtual time past work that is still physically happening. A
    no-op context under RealClock (and harmless under driven virtual
    clocks — only the autojump loop reads the flag)."""
    clock = _CLOCK
    if isinstance(clock, VirtualClock):
        return clock.hold()
    return contextlib.nullcontext()


def wait_on(ev, timeout: Optional[float] = None) -> bool:
    return _CLOCK.wait_on(ev, timeout)


def wait_for(cond: threading.Condition, predicate,
             timeout: Optional[float] = None) -> bool:
    return _CLOCK.wait_for(cond, predicate, timeout)


def wait_cond(cond: threading.Condition,
              timeout: Optional[float] = None) -> bool:
    return _CLOCK.wait_cond(cond, timeout)
