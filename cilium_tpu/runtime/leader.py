"""Leader election over the kvstore (operator HA).

Reference: cilium-operator runs replicas behind leader election (a
k8s Lease; ``operator/cmd`` leaderelection) so exactly one instance
reconciles while standbys wait to take over. Same contract here on the
kvstore's primitives: the lock is a create-only key under a TTL lease —
holding it means leading, losing the lease (crash, partition, clean
resign) frees the lock for a standby within the TTL.

Split-brain guard: a leader that can no longer confirm it holds the
key (keepalive fails, or the key no longer carries its identity)
demotes itself FIRST (``on_stopped_leading``) and only then
re-campaigns — the reference's leaderelection does the same
release-before-retry dance so two reconcilers never run concurrently.
"""

from __future__ import annotations

import threading
from typing import Callable, Optional

from cilium_tpu.runtime.logging import get_logger
from cilium_tpu.runtime import simclock
from cilium_tpu.runtime.metrics import METRICS

LOG = get_logger("leader")

LEADER_PREFIX = "cilium/leader/"


class LeaderElector:
    """Campaign for ``cilium/leader/<name>``; drive the caller's
    started/stopped callbacks as leadership comes and goes."""

    def __init__(self, store, name: str, identity: str,
                 on_started_leading: Callable[[], None],
                 on_stopped_leading: Callable[[], None],
                 ttl: float = 15.0):
        self.store = store
        self.key = LEADER_PREFIX + name
        self.identity = identity
        self.on_started_leading = on_started_leading
        self.on_stopped_leading = on_stopped_leading
        self.ttl = ttl
        self.is_leader = False
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- campaign loop ----------------------------------------------------
    def start(self) -> "LeaderElector":
        self._thread = threading.Thread(
            target=self._run, daemon=True,
            name=f"leader-{self.key.rsplit('/', 1)[-1]}")
        self._thread.start()
        return self

    def _run(self) -> None:
        interval = max(0.05, self.ttl / 3.0)
        while not self._stop.is_set():
            # EVERY store call in the campaign cycle is an RPC that
            # can fail transiently; none may kill this thread — a dead
            # campaign thread is a standby that silently never takes
            # over (2-replica HA degraded to 1 with no error)
            try:
                lease = self.store.lease(self.ttl)
            except Exception:  # store briefly unreachable: retry
                if simclock.wait_on(self._stop, interval):
                    return
                continue
            try:
                won = self.store.create(self.key, self.identity,
                                        lease=lease)
            except Exception:
                won = False
            if not won:
                try:
                    self.store.revoke(lease)
                # ctlint: disable=swallowed-exception  # best-effort revoke of a lost campaign; the lease ages out
                except Exception:  # noqa: BLE001
                    pass
                if simclock.wait_on(self._stop, interval):
                    return
                continue
            self._lead(lease, interval)
            if self._stop.is_set():
                # resign path: drop our key via lease revocation — the
                # key is attached to OUR lease, so this can never
                # delete a lock a standby re-acquired in the meantime
                # (the unconditional get-then-delete could)
                try:
                    self.store.revoke(lease)
                # ctlint: disable=swallowed-exception  # resign is best-effort; the lease ages the key out
                except Exception:  # noqa: BLE001 — lease ages out
                    pass
                return
            try:  # leadership lost mid-stint: release our leftovers
                self.store.revoke(lease)
            # ctlint: disable=swallowed-exception  # best-effort cleanup; the lease ages the key out
            except Exception:  # noqa: BLE001
                pass

    def _lead(self, lease, interval: float) -> None:
        """One leadership stint: callbacks, keepalive, demotion."""
        self.is_leader = True
        METRICS.set_gauge("cilium_tpu_leader", 1.0,
                          labels={"name": self.key})
        LOG.info("started leading",
                 extra={"fields": {"key": self.key,
                                   "identity": self.identity}})
        # the startup callback (e.g. Operator adopting persisted
        # assignments over a slow remote store) can outlast the TTL:
        # a ticker keeps the lease alive while it runs, or a standby
        # would win the lock mid-startup and reconcile concurrently
        ka_stop = threading.Event()

        def ticker() -> None:
            while not simclock.wait_on(ka_stop, interval):
                try:
                    lease.keepalive()
                except Exception:  # lost anyway; main loop detects
                    return

        t = threading.Thread(target=ticker, daemon=True,
                             name="leader-keepalive")
        t.start()
        try:
            try:
                self.on_started_leading()
            finally:
                ka_stop.set()
                t.join(timeout=5.0)
            while not simclock.wait_on(self._stop, interval):
                try:
                    lease.keepalive()
                    if self.store.get(self.key) != self.identity:
                        raise KeyError("lock lost")
                except Exception:  # expired / lost / unreachable
                    LOG.warning("leadership lost",
                                extra={"fields": {
                                    "key": self.key,
                                    "identity": self.identity}})
                    break
        except Exception:  # noqa: BLE001 — startup failed: demote,
            LOG.exception("leadership stint failed")  # then re-campaign
        finally:
            # demote BEFORE any re-campaign: no window where two
            # instances both believe they lead
            self.is_leader = False
            METRICS.set_gauge("cilium_tpu_leader", 0.0,
                              labels={"name": self.key})
            try:
                self.on_stopped_leading()
            except Exception:  # noqa: BLE001 — must keep cycling
                LOG.exception("on_stopped_leading failed")

    def stop(self) -> None:
        """Resign: stop campaigning, release the lock if held (clean
        handover — standbys take over immediately instead of waiting
        out the TTL)."""
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=max(5.0, self.ttl))
            if self._thread.is_alive():
                # teardown still in flight (a reconcile stuck on a
                # slow RPC): do NOT hand the lock to a standby while
                # this instance may still be acting on it — the lease
                # ages the key out once the straggler stops
                # keepaliving, which is the safe, slower handover
                LOG.warning("resign timed out; leaving lock to lapse",
                            extra={"fields": {"key": self.key}})
                return
            self._thread = None
