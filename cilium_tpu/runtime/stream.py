"""Streaming binary verdict transport: the serving-path counterpart of
the offline capture replay.

Reference role: the per-request JSON protocol in ``runtime/service.py``
models the agent↔proxy control channel, but the reference's DATA paths
all stream — Envoy verdicts in-filter with no agent round-trip, access
logs ride a one-way socket (SURVEY §2.2, §2.7). On a tunneled TPU the
request/response shape is fatal for throughput: every verdict batch
pays a full H2D+readback RTT (~120 ms observed, docs/PLATFORM.md), so
the in-flight window equals the connection count and the online path
saturated at ~438 rps in round 4 while the offline path did 207M/s.

This module closes that gap with a CHUNKED BINARY STREAM on the same
Unix socket:

* the client sends length-prefixed frames whose payload is a
  self-contained v2/v3 capture image (``ingest.binary
  .sections_to_bytes``) — no JSON, no base64, no per-record parsing;
* the server runs a decoupled three-stage pipeline: a reader thread
  (socket → frame queue), a worker thread (parse → featurize →
  single-blob H2D dispatch), and a writer thread (device readback →
  verdict frame). JAX dispatch is asynchronous, so while chunk k's
  readback is in flight over the tunnel, chunks k+1..k+D are already
  staged/executing on device — the RTT is amortized over the pipeline
  depth instead of paid per chunk;
* verdicts return as raw u8 arrays keyed by the client's sequence
  number, on the same socket, decoupled from sends (the client can
  have many chunks outstanding).

Chunk shapes are padded to power-of-two record counts and the string
widths are fixed for the whole session (handshake), so the engine sees
a handful of compiled shapes no matter what traffic streams.

Protocol (after a ``{"op": "stream_start", ...}`` JSON handshake on
the verdict socket; see ``VerdictService``):

  frame   := <u32 payload_len> <u32 seq> <u8 kind> payload
  c→s     := kind 0: capture image | kind 1: end-of-stream (empty)
           | kind 3: capture image prefixed by a 16-hex trace id
             (only to servers that advertised ``"trace": true``)
  s→c     := kind 0: u8 verdict array (one byte per record, in the
             chunk's record order)
           | kind 1: end-ack (all pending verdicts flushed)
           | kind 2: per-chunk error (utf-8 message; stream continues)
           | kind 4: credit grant (u32 additional chunk credits; only
             to clients that sent ``"credit": true`` in the hello)

Credit flow control: clients that opt in receive a window in the
stream_start ack (``"credit": N`` — ``Config.admission
.stream_credit_window``); each chunk send consumes a credit, each
answered chunk grants one back, and the client HALTS sends at zero —
a slow consumer backpressures the producer instead of ballooning the
server's queues. Credits survive reconnect-with-resume (fresh window
minus the re-sent unacked chunks). Peers that don't opt in see
neither the field nor the frames.

A poisoned frame (bad magic, truncated image) fails ONLY its sequence
number — the serving path must degrade per-chunk, not per-connection.
"""

from __future__ import annotations

import queue
import random
import socket
import struct
import threading
from typing import Dict, Iterator, List, Optional, Sequence, Tuple

import numpy as np

from cilium_tpu.ingest.binary import (
    CaptureError,
    capture_from_bytes,
    capture_to_bytes,
)
from cilium_tpu.runtime import faults, simclock
from cilium_tpu.runtime.metrics import (
    METRICS,
    STREAM_CREDIT_WAITS,
    STREAM_CREDITS_GRANTED,
    STREAM_RECONNECTS,
)
from cilium_tpu.runtime.tracing import (
    PHASE_DEVICE,
    PHASE_FALLBACK,
    PHASE_HOST,
    PHASE_QUEUE,
    TRACE_ID_CHARS,
    TRACER,
)

#: fires at the server's per-chunk dispatch (a fault fails ONE seq —
#: the per-chunk degradation contract)
FRAME_SERVER_POINT = faults.register_point(
    "stream.frame.server", "per-chunk dispatch in StreamSession")
#: fires at the client's per-frame receive; plans typically raise
#: ConnectionError here to exercise reconnect-with-resume
FRAME_CLIENT_POINT = faults.register_point(
    "stream.frame.client", "per-frame receive in StreamClient")
#: fires at the server's credit-grant send: an injected fault LOSES
#: the grant (the client's window shrinks by one) — the chaos suite
#: proves a lost credit degrades throughput, never correctness
CREDIT_POINT = faults.register_point(
    "stream.credit", "credit grant send in StreamSession")

FRAME_HEADER = struct.Struct("<IIB")

KIND_CHUNK = 0
KIND_END = 1
KIND_ERROR = 2
#: a capture chunk whose payload is prefixed by a 16-hex-char trace id
#: (runtime/tracing.py): the flight-recorder context crossing the wire.
#: OPTIONAL both ways — servers advertise ``"trace": true`` in the
#: stream_start ack and clients only send this kind to peers that do,
#: so old clients and old servers interoperate unchanged.
# client-to-server only: the server adopts the id and always replies
# with plain KIND_CHUNK frames, so the client dispatch never sees this
# kind (unknown kinds there are dropped and counted, not misparsed).
# ctlint: disable=frame-kind  # one-directional kind, see above
KIND_CHUNK_TRACED = 3
#: credit grant: payload is a little-endian u32 of additional chunk
#: credits. Server-to-client only — the writer grants one per
#: answered chunk; clients that opted in (``"credit": true`` in the
#: stream_start hello) halt sends at zero credit, so a slow consumer
#: backpressures the producer instead of ballooning server queues.
#: Old clients never opt in and old servers never grant — unchanged
#: interop both ways.
# ctlint: disable=frame-kind  # server-to-client only, see above
KIND_CREDIT = 4

#: hard cap on one frame's payload — a corrupt length prefix must not
#: make the server try to buffer gigabytes
MAX_FRAME = 256 << 20

#: default bound on dispatched-but-unread device computations: deep
#: enough to hide several tunnel RTTs, shallow enough that per-chunk
#: latency stays ~(depth/throughput) under saturation
PIPELINE_DEPTH = 16

#: the largest record count one chunk may carry (pow2-padded shapes
#: above this would blow compile-shape variety and device memory)
CHUNK_MAX = 1 << 17


def send_frame(sock: socket.socket, seq: int, kind: int,
               payload: bytes = b"") -> None:
    sock.sendall(FRAME_HEADER.pack(len(payload), seq, kind) + payload)


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    parts: List[bytes] = []
    got = 0
    while got < n:
        chunk = sock.recv(n - got)
        if not chunk:
            raise ConnectionError("peer closed mid-frame")
        parts.append(chunk)
        got += len(chunk)
    return b"".join(parts)


def recv_frame(sock: socket.socket) -> Tuple[int, int, bytes]:
    n, seq, kind = FRAME_HEADER.unpack(
        _recv_exact(sock, FRAME_HEADER.size))
    if n > MAX_FRAME:
        raise ConnectionError(f"frame too large ({n} bytes)")
    return seq, kind, _recv_exact(sock, n) if n else b""


class StreamSession:
    """Server side of one stream connection (runs on the service's
    handler thread until end-of-stream or disconnect)."""

    def __init__(self, loader, sock: socket.socket,
                 widths: Optional[Dict[str, int]] = None,
                 authed_pairs_fn=None,
                 pipeline_depth: int = PIPELINE_DEPTH,
                 verdictor=None, credit_window: int = 0,
                 serveloop=None):
        from cilium_tpu.core.config import EngineConfig

        self.loader = loader
        self.sock = sock
        self.authed_pairs_fn = authed_pairs_fn
        #: optional continuously-batched serving loop
        #: (runtime/serveloop.py): when set, device chunks dispatch
        #: through a ring slot lease — cross-stream dedup/memo, one
        #: fused launch per pack cycle — instead of this session's
        #: private IncrementalSession. Verdict-bit-equal either way;
        #: a ring-full shed at lease time falls back to the private
        #: path for this session.
        self.serveloop = serveloop
        self._lease = None
        self._stream_id = f"stream-{id(self):x}"
        #: chunk credits advertised to this session's client in the
        #: stream_start ack; 0 = the client didn't opt in, grant
        #: nothing (old-peer interop)
        self.credit_window = max(0, int(credit_window))
        #: optional ResilientVerdictor (runtime/service.py): shares the
        #: service-wide circuit breaker so a sick device degrades
        #: stream chunks to the oracle instead of erroring every seq
        self.verdictor = verdictor
        cfg = EngineConfig()
        # session-fixed string widths: the client promises its strings
        # fit (longer ones clip exactly like the engine's config caps);
        # fixed widths mean one compiled step per pow2 record bucket
        caps = {"path": max(cfg.http_path_buckets),
                "method": cfg.http_method_len,
                "host": cfg.http_host_len,
                "headers": 1024, "qname": cfg.dns_name_len}
        self.widths = dict(caps)
        for k, v in (widths or {}).items():
            if k in caps:
                self.widths[k] = max(1, min(int(v), caps[k]))
        self._in: "queue.Queue" = queue.Queue(maxsize=32)
        self._out: "queue.Queue" = queue.Queue(
            maxsize=max(1, int(pipeline_depth)))
        self._send_lock = threading.Lock()
        #: incremental dedup session, rebuilt on engine swap (policy
        #: revision bump) — see engine/session.py
        self._inc = None
        self._inc_engine = None

    # -- pipeline stages ---------------------------------------------------
    def run(self) -> None:
        worker = threading.Thread(target=self._work, daemon=True,
                                  name="stream-worker")
        writer = threading.Thread(target=self._write, daemon=True,
                                  name="stream-writer")
        worker.start()
        writer.start()
        try:
            while True:
                try:
                    seq, kind, payload = recv_frame(self.sock)
                except (ConnectionError, OSError):
                    break
                # receive stamp: the worker attributes reader-queue
                # dwell as the chunk's queue-wait phase
                self._in.put((seq, kind, payload, simclock.now()))
                if kind == KIND_END:
                    break
        finally:
            self._in.put(None)
            worker.join()
            writer.join()
            if self._lease is not None and self.serveloop is not None:
                # end-of-stream: the slot returns to the ring (the
                # worker drained, so no pending chunk is lost)
                self.serveloop.disconnect(self._lease)
                self._lease = None

    def _dispatch_chunk(self, payload: bytes):
        """Parse + incremental-dedup featurize + async device dispatch.
        Returns (n_records, device verdict array) — readback happens on
        the writer thread so the tunnel RTT overlaps the next chunks'
        host work and device execution.

        The transport math that dictates the design (measured,
        docs/PLATFORM.md round 5): the tunneled TPU moves ~10–30 MB/s
        H2D and a synchronous readback is a ~120 ms RTT. Streaming the
        raw featurized blob (244 B/flow) capped the stream at ~60k
        verdicts/s; the incremental dedup session
        (engine/session.py) ships 4 B/flow steady-state, and the
        ``copy_to_host_async`` below keeps several readbacks in
        flight (130 ms/chunk serialized → ~25 ms/chunk measured with
        5 in flight)."""
        faults.maybe_fail(FRAME_SERVER_POINT)
        with TRACER.span("stream.parse", phase=PHASE_HOST,
                         bytes=len(payload)):
            rec, l7, offsets, blob, gen = capture_from_bytes(payload)
        n = len(rec)
        if n == 0:
            return 0, None
        if n > CHUNK_MAX:
            raise CaptureError(
                f"chunk of {n} records exceeds max {CHUNK_MAX}")
        engine = self.loader.engine
        if engine is None:
            raise RuntimeError("no policy loaded")
        pairs = (self.authed_pairs_fn()
                 if self.authed_pairs_fn is not None else None)
        if not hasattr(engine, "_blob_step"):
            # oracle backend (enable_tpu_offload off): no device, no
            # pipelining to win — reconstruct and verdict host-side so
            # stream clients work identically under either gate
            from cilium_tpu.ingest.binary import records_to_flows_l7

            with TRACER.span("oracle.verdict", phase=PHASE_FALLBACK,
                             records=n):
                flows = records_to_flows_l7(rec, l7, offsets, blob,
                                            gen=gen)
                out = engine.verdict_flows(flows, authed_pairs=pairs)
                return n, np.asarray(out["verdict"])
        vd = self.verdictor
        if vd is not None and not vd.allow_device(engine):
            # breaker open: the whole service is in degraded mode —
            # this chunk rides the oracle like every other path
            return n, self._oracle_chunk(rec, l7, offsets, blob, gen,
                                         pairs)
        if self.serveloop is not None:
            out = self._ring_chunk(rec, l7, offsets, blob, gen)
            if out is not None:
                if vd is not None:
                    vd.on_device_success()
                return n, out
            # ring-full at lease time: this session fell back to its
            # private dispatch path (serveloop cleared below)
        try:
            if self._inc is None:
                # loader-wired session (ISSUE 8): a policy committed
                # mid-stream is consumed as a bank-scoped delta — the
                # session rescans only what changed and keeps its
                # interned rows + memo instead of rebuilding from
                # scratch on every hot-swap (the old behavior, which
                # cost the whole dedup state per CNP update)
                from cilium_tpu.engine.session import IncrementalSession

                self._inc = IncrementalSession(engine,
                                               widths=self.widths,
                                               loader=self.loader)
                self._inc_engine = engine
            n, verdict = self._inc.verdict_chunk(
                rec, l7, offsets, blob, gen=gen, authed_pairs=pairs)
            self._inc_engine = self._inc.engine
        except Exception as e:  # noqa: BLE001 — degrade, don't error
            if vd is None:
                raise
            vd.on_device_failure(e)
            # the session may hold state staged against the failed
            # dispatch — rebuild it on the next device chunk
            self._inc = None
            return n, self._oracle_chunk(rec, l7, offsets, blob, gen,
                                         pairs)
        if vd is not None:
            vd.on_device_success()
        # issue the D2H NOW, not at the writer's np.asarray: readbacks
        # only overlap if ISSUED while earlier ones are in flight
        if hasattr(verdict, "copy_to_host_async"):
            verdict.copy_to_host_async()
        return n, verdict

    def _ring_chunk(self, rec, l7, offsets, blob, gen):
        """One chunk through the verdict ring: lease on first use
        (reconnect-with-resume on expiry), submit, wait for the pack
        cycle. Returns host verdicts, or None when the ring shed the
        LEASE (ring-full/draining) — the session then falls back to
        its private dispatch for good. Chunk-level sheds (queue-full,
        armed serve.ring_slot faults) raise and fail only their seq,
        the per-chunk degradation contract."""
        from cilium_tpu.runtime.serveloop import (
            LeaseExpired,
            ShedError,
        )

        loop = self.serveloop
        try:
            if self._lease is None:
                self._lease = loop.connect(self._stream_id)
        except ShedError:
            self.serveloop = None
            return None
        with TRACER.span("stream.ring", phase=PHASE_DEVICE,
                         records=len(rec)):
            try:
                ticket = loop.submit(self._lease, rec, l7, offsets,
                                     blob, gen=gen)
            except LeaseExpired:
                self._lease = loop.connect(self._stream_id,
                                           resume=True)
                ticket = loop.submit(self._lease, rec, l7, offsets,
                                     blob, gen=gen)
            return ticket.wait(timeout=30.0)

    def _oracle_chunk(self, rec, l7, offsets, blob, gen, pairs):
        """One chunk through the CPU oracle (the breaker's degraded
        lane) — correct verdicts, no device involved."""
        from cilium_tpu.ingest.binary import records_to_flows_l7

        flows = records_to_flows_l7(rec, l7, offsets, blob, gen=gen)
        out = self.verdictor.fallback_outputs(flows, authed_pairs=pairs,
                                              outputs=("verdict",))
        return np.asarray(out["verdict"])

    def _work(self) -> None:
        while True:
            item = self._in.get()
            if item is None:
                self._out.put(None)
                return
            seq, kind, payload, t_recv = item
            if kind == KIND_END:
                if self._lease is not None and self.serveloop is not None:
                    # release BEFORE queueing the END ack: every prior
                    # chunk already resolved (ring waits are
                    # synchronous on this thread), and a client whose
                    # finish() saw the ack must observe the slot
                    # returned — not race the handler's cleanup
                    self.serveloop.disconnect(self._lease)
                    self._lease = None
                self._out.put((seq, KIND_END, 0, None, None))
                self._out.put(None)
                return
            ctx = None
            if kind == KIND_CHUNK_TRACED:
                # adopt the client's trace id (the CLIENT sampled;
                # adoption bypasses the local sampler) and split the
                # id prefix off the capture image
                tid = payload[:TRACE_ID_CHARS].decode("ascii", "replace")
                payload = payload[TRACE_ID_CHARS:]
                ctx = TRACER.start("stream.chunk", trace_id=tid,
                                   seq=seq)
                kind = KIND_CHUNK
            if kind != KIND_CHUNK:
                self._out.put((seq, KIND_ERROR, 0,
                               f"unknown frame kind {kind}", None))
                continue
            if ctx is not None:
                waited = simclock.now() - t_recv
                TRACER.add_span(ctx, "stream.queue", PHASE_QUEUE,
                                simclock.wall() - waited, waited)
            try:
                with TRACER.activate(ctx):
                    n, dev = self._dispatch_chunk(payload)
            except Exception as e:  # noqa: BLE001 — fail the SEQ only
                TRACER.event("stream.chunk_error", ctx=ctx,
                             error=f"{type(e).__name__}: {e}")
                TRACER.finish(ctx)
                self._out.put((seq, KIND_ERROR, 0,
                               f"{type(e).__name__}: {e}", None))
                continue
            self._out.put((seq, KIND_CHUNK, n, dev, ctx))

    def _grant_credit(self, seq: int) -> None:
        """One credit back to the producer for one answered chunk. An
        injected ``stream.credit`` fault LOSES the grant — the client
        window shrinks; reconnect-with-resume restores it — so the
        chaos suite can prove credit loss degrades pacing, never
        verdicts."""
        if not self.credit_window:
            return
        try:
            faults.maybe_fail(CREDIT_POINT)
        except Exception:  # noqa: BLE001 — plan-chosen exception
            return  # the grant is lost; FAULTS_INJECTED counted it
        with self._send_lock:
            send_frame(self.sock, seq, KIND_CREDIT,
                       struct.pack("<I", 1))
        METRICS.inc(STREAM_CREDITS_GRANTED)

    def _write(self) -> None:
        while True:
            item = self._out.get()
            if item is None:
                return
            seq, kind, n, dev, ctx = item
            try:
                if kind == KIND_END:
                    with self._send_lock:
                        send_frame(self.sock, seq, KIND_END)
                    continue
                if kind == KIND_ERROR:
                    with self._send_lock:
                        send_frame(self.sock, seq, KIND_ERROR,
                                   str(dev).encode())
                    self._grant_credit(seq)
                    continue
                if n == 0:
                    with self._send_lock:
                        send_frame(self.sock, seq, KIND_CHUNK)
                    self._grant_credit(seq)
                    continue
                # the blocking wait for an async dispatch is genuine
                # device time — attributed where it is PAID (here),
                # not where the dispatch was issued
                with TRACER.span("stream.readback", phase=PHASE_DEVICE,
                                 ctx=ctx, records=n):
                    verdicts = np.asarray(dev)[:n].astype(np.uint8)
                METRICS.inc("cilium_tpu_stream_verdicts_total", n)
                with self._send_lock:
                    send_frame(self.sock, seq, KIND_CHUNK,
                               verdicts.tobytes())
                # grant AFTER the verdict frame: the window counts
                # unanswered chunks, so the producer's next send is
                # paced by consumption, not by raw socket capacity
                self._grant_credit(seq)
            except (OSError, BrokenPipeError):
                # client went away: drain silently so the worker can
                # finish and the session unwinds
                continue
            finally:
                TRACER.finish(ctx)


class StreamClient:
    """Client for the stream protocol (what a proxy data plane would
    speak in C; Python here for tests/bench).

    ``send_flows``/``send_image`` are non-blocking up to the socket
    buffer; verdicts arrive on a background thread and are retrieved
    with ``result(seq)`` (blocking) or ``results()`` (drain in
    completion order). ``finish()`` sends end-of-stream and blocks for
    the end-ack, guaranteeing every outstanding verdict has landed.

    ``reconnect=True`` adds RECONNECT-WITH-RESUME: every sent chunk is
    retained until its verdict (or per-chunk error) lands; on a
    connection drop the client re-dials with exponential backoff +
    jitter (the ``controller.py`` retry discipline), re-handshakes,
    and re-sends every unacked chunk in sequence order — resuming from
    the last acked cursor. Server verdicts are deterministic, so the
    at-least-once replay of an in-flight chunk is idempotent."""

    def __init__(self, socket_path: str, widths: Optional[Dict] = None,
                 timeout: float = 120.0,
                 pipeline_depth: Optional[int] = None,
                 reconnect: bool = False, max_reconnects: int = 5,
                 backoff_base: float = 0.05, backoff_max: float = 2.0,
                 reconnect_seed: int = 0):
        self.socket_path = socket_path
        self.timeout = timeout
        self._widths = widths or {}
        self._pipeline_depth = pipeline_depth
        self.reconnect = reconnect
        self.max_reconnects = max_reconnects
        self.backoff_base = backoff_base
        self.backoff_max = backoff_max
        #: seeded jitter so chaos runs with one plan replay identically
        self._jitter = random.Random(reconnect_seed)
        self._seq = 0
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._send_lock = threading.Lock()
        self._results: Dict[int, object] = {}
        #: seq → (trace_id, chunk image), retained until acked
        #: (reconnect mode) — the trace id rides the resume so a chunk
        #: re-sent across a drop keeps its identity end to end
        self._unacked: Dict[int, Tuple[str, bytes]] = {}
        #: did the server's stream_start ack advertise trace support?
        self._trace_peer = False
        #: credit flow control: None = peer didn't advertise a window
        #: (old server) → unenforced; else the remaining chunk credits
        #: — sends halt at zero until the server grants more
        self._credits: Optional[int] = None
        self._credit_window = 0
        self._finish_seq: Optional[int] = None
        self._done = False
        self._connect()
        self._recv_thread = threading.Thread(target=self._recv_loop,
                                             daemon=True)
        self._recv_thread.start()

    def _connect(self) -> None:
        from cilium_tpu.runtime.service import recv_msg, send_msg

        sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        sock.connect(self.socket_path)
        hello = {"op": "stream_start", "widths": self._widths,
                 "credit": True}
        if self._pipeline_depth:
            hello["pipeline_depth"] = int(self._pipeline_depth)
        send_msg(sock, hello)
        ack = recv_msg(sock)
        if not ack.get("ok"):
            sock.close()
            raise RuntimeError(f"stream_start refused: {ack}")
        self.revision = ack.get("revision")
        # only send traced frames to servers that understand them —
        # absent on old peers, so the field degrades to plain chunks
        self._trace_peer = bool(ack.get("trace"))
        # a fresh window per (re)connect: old servers advertise none →
        # credits stay unenforced
        window = int(ack.get("credit") or 0)
        with self._cond:
            self._credit_window = window
            self._credits = window if window > 0 else None
            self._cond.notify_all()
        self.sock = sock

    def _try_reconnect(self) -> bool:
        """Re-dial + re-handshake + re-send unacked chunks. Backoff is
        the controller.py discipline: base * 2^attempt capped, plus
        seeded jitter so simultaneous clients don't re-dial in sync."""
        try:
            self.sock.close()
        except OSError:
            pass
        for attempt in range(self.max_reconnects):
            delay = min(self.backoff_base * (2 ** attempt),
                        self.backoff_max)
            simclock.sleep(delay * (1.0 + 0.25 * self._jitter.random()))
            try:
                self._connect()
            except (OSError, RuntimeError):
                continue
            with self._lock:
                pending = sorted(self._unacked.items())
                finish_seq = self._finish_seq
            try:
                with self._send_lock:
                    for seq, (tid, image) in pending:
                        send_frame(self.sock, seq, *self._chunk_frame(
                            tid, image))
                    if finish_seq is not None:
                        # finish() already ran: re-send end-of-stream
                        # so the resumed session still end-acks
                        send_frame(self.sock, finish_seq, KIND_END)
            except (OSError, ConnectionError):
                continue
            with self._cond:
                # credits survive the reconnect: the fresh window from
                # the re-handshake, minus the unacked chunks just
                # re-sent (each consumes a credit; their grants come
                # back as the resumed session answers them)
                if self._credits is not None:
                    self._credits = max(
                        0, self._credit_window - len(pending))
                self._cond.notify_all()
            METRICS.inc(STREAM_RECONNECTS)
            return True
        return False

    def _recv_loop(self) -> None:
        while True:
            try:
                seq, kind, payload = recv_frame(self.sock)
                # injected drops model the tunnel dying mid-frame: the
                # received frame is DISCARDED (its seq stays unacked
                # and is re-sent after resume)
                faults.maybe_fail(FRAME_CLIENT_POINT)
            except (ConnectionError, OSError):
                if self.reconnect and not self._done \
                        and self._try_reconnect():
                    continue
                with self._cond:
                    self._done = True
                    self._cond.notify_all()
                return
            with self._cond:
                if kind == KIND_CREDIT:
                    # replenished window: wake any send blocked at
                    # zero. MUST precede the resume-dedup branch — a
                    # grant's seq echoes an already-acked chunk and
                    # would be swallowed as a duplicate there.
                    grant = (struct.unpack("<I", payload[:4])[0]
                             if len(payload) >= 4 else 1)
                    if self._credits is not None:
                        self._credits += grant
                    self._cond.notify_all()
                    continue
                if kind == KIND_END:
                    self._done = True
                elif (self.reconnect and seq not in self._unacked
                      and seq not in self._results):
                    # at-least-once resume: a chunk double-sent across
                    # the drop can answer twice — the second delivery
                    # of an already-consumed seq is dropped, or the
                    # count-consuming drain would overcount
                    pass
                elif kind == KIND_ERROR:
                    self._unacked.pop(seq, None)
                    self._results[seq] = RuntimeError(
                        payload.decode("utf-8", "replace"))
                elif kind == KIND_CHUNK:
                    self._unacked.pop(seq, None)
                    self._results[seq] = np.frombuffer(
                        payload, dtype=np.uint8)
                else:
                    # a kind this client does not speak (ctlint
                    # frame-kind found the old catch-all here):
                    # dropping and counting the frame beats misparsing
                    # its payload as a verdict array — the seq stays
                    # pending and surfaces as a timeout or a resume
                    # re-send, never as wrong verdicts
                    METRICS.inc(
                        "cilium_tpu_stream_unknown_frames_total")
                self._cond.notify_all()
                if kind == KIND_END:
                    return

    def _chunk_frame(self, trace_id: str,
                     image: bytes) -> Tuple[int, bytes]:
        """(kind, payload) for one chunk: traced when the peer
        advertised support and a well-formed id is present."""
        if self._trace_peer and trace_id \
                and len(trace_id) == TRACE_ID_CHARS:
            return KIND_CHUNK_TRACED, trace_id.encode("ascii") + image
        return KIND_CHUNK, image

    def _acquire_credit(self) -> None:
        """Halt at zero credit until the server grants (backpressure:
        the producer paces to the consumer). No-op when the peer
        advertised no window. Raises TimeoutError if no grant lands
        within ``timeout`` — a wedged consumer must surface, not
        buffer."""
        with self._cond:
            if self._credits is None:
                return
            if self._credits <= 0:
                METRICS.inc(STREAM_CREDIT_WAITS)
                ok = simclock.wait_for(
                    self._cond,
                    lambda: (self._credits is None
                             or self._credits > 0 or self._done),
                    timeout=self.timeout)
                if self._credits is None or self._done:
                    return  # window gone / stream over: let send fail
                if not ok:
                    raise TimeoutError(
                        "no stream credit: server window exhausted "
                        "and no grant arrived")
            self._credits -= 1

    def send_image(self, image: bytes,
                   trace_id: Optional[str] = None) -> int:
        """``trace_id=None`` picks up the ambient flight-recorder
        context (if any); pass ``""`` to force an untraced frame."""
        if trace_id is None:
            trace_id = TRACER.current_trace_id()
        self._acquire_credit()
        with self._lock:
            seq = self._seq
            self._seq += 1
            if self.reconnect:
                self._unacked[seq] = (trace_id, image)
        try:
            kind, payload = self._chunk_frame(trace_id, image)
            with self._send_lock:
                send_frame(self.sock, seq, kind, payload)
        except (OSError, ConnectionError):
            if not self.reconnect:
                raise
            # the chunk stays in _unacked; the recv thread's reconnect
            # re-sends it once the session is back
        return seq

    def send_flows(self, flows: Sequence,
                   trace_id: Optional[str] = None) -> int:
        return self.send_image(capture_to_bytes(flows),
                               trace_id=trace_id)

    def result(self, seq: int) -> np.ndarray:
        """Block for one chunk's verdicts (raises if the server failed
        that chunk)."""
        with self._cond:
            ok = simclock.wait_for(
                self._cond,
                lambda: seq in self._results or self._done,
                timeout=self.timeout)
            if seq not in self._results:
                raise TimeoutError(
                    f"no verdict for seq {seq}"
                    + (" (stream closed)" if self._done else ""))
            assert ok
            r = self._results.pop(seq)
        if isinstance(r, Exception):
            raise r
        return r

    def results(self) -> Iterator[Tuple[int, object]]:
        """Drain results as they land, until the stream ends and all
        are consumed. Yields ``(seq, ndarray)`` for verdicts and
        ``(seq, Exception)`` for per-chunk failures — the protocol
        degrades per CHUNK, so a failed seq must not terminate the
        drain (raising from a generator closes it for good)."""
        while True:
            with self._cond:
                simclock.wait_for(
                    self._cond,
                    lambda: self._results or self._done,
                    timeout=self.timeout)
                if not self._results:
                    if self._done:
                        return
                    raise TimeoutError("stream stalled")
                seq = next(iter(self._results))
                r = self._results.pop(seq)
            yield seq, r

    def finish(self) -> None:
        with self._lock:
            seq = self._seq
            self._seq += 1
            if self.reconnect:
                self._finish_seq = seq
        try:
            with self._send_lock:
                send_frame(self.sock, seq, KIND_END)
        except (OSError, ConnectionError):
            if not self.reconnect:
                raise  # the recv thread's resume re-sends END
        with self._cond:
            if not simclock.wait_for(self._cond, lambda: self._done,
                                     timeout=self.timeout):
                raise TimeoutError("no end-ack")

    def close(self) -> None:
        try:
            self.sock.close()
        except OSError:
            pass
