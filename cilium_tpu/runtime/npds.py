"""NPDS-style policy push-down: compiled L3/L4 MapState → proxy shim.

Reference: the agent pushes per-endpoint NetworkPolicy into Envoy over
NPDS (``pkg/envoy`` xDS server + the ``cilium.network`` filter, SURVEY
§2.2/§3.4), so flows with no L7 component verdict IN-PROXY with zero
agent round-trips. Round 4 inverted that (every verdict crossed the
service socket), which was fine for bulk replay but put a tunnel RTT
under every online verdict. This module is the other half: the
compiled L3/L4 table serialized into a flat blob the C++ shim
(``shim/cilium_shim.cpp``) loads and probes locally — only flows whose
WINNING entry demands L7 inspection or mutual auth still cross the
socket, exactly the split the reference runs.

Blob layout (little-endian; version bumps MAGIC):

  header  := <u32 magic 'NPD1'> <u32 revision> <u32 n_endpoints>
  per ep  := <u32 ep_identity> <u32 n_entries> <u8 ep_flags> <u8 x3 pad>
             then n_entries × entry
  entry   := <u32 peer_identity> <u16 dport> <u8 port_plen> <u8 proto>
             <u8 direction> <u8 entry_flags> <u16 pad>     (12 bytes)

  ep_flags:    bit0 ingress_enforced, bit1 egress_enforced, bit2 audit
               (per-endpoint audit OR the global policy_audit_mode —
               baked in so the shim needs no config channel)
  entry_flags: bit0 deny, bit1 redirect (winning ⇒ L7 path),
               bit2 auth_required (winning ⇒ auth path)

The probe semantics the shim implements are the golden model's
(``policy.mapstate.MapState.lookup``): covering = direction + peer ∈
{0, wildcard} + masked-port + proto ∈ {0, exact}, ICMP types carry the
1<<15 marker bit and never match proto-ANY port entries; any covering
deny denies; else the max-specificity allow wins; else default by the
direction's enforcement flag. Pinned by a randomized differential test
(tests/test_npds_shim.py) against the golden model.
"""

from __future__ import annotations

import struct
from typing import Dict

MAGIC = 0x4E504431  # 'NPD1'

EP_INGRESS_ENFORCED = 1
EP_EGRESS_ENFORCED = 2
EP_AUDIT = 4

E_DENY = 1
E_REDIRECT = 2
E_AUTH = 4

_HDR = struct.Struct("<III")
_EP = struct.Struct("<IIB3x")
_ENTRY = struct.Struct("<IHBBBBH")


def serialize_mapstates(per_identity: Dict, revision: int,
                        audit_global: bool = False) -> bytes:
    """The staged snapshot (identity → MapState) as one NPDS blob."""
    parts = [_HDR.pack(MAGIC, revision & 0xFFFFFFFF, len(per_identity))]
    for ep_id in sorted(per_identity):
        ms = per_identity[ep_id]
        ep_flags = (
            (EP_INGRESS_ENFORCED if ms.ingress_enforced else 0)
            | (EP_EGRESS_ENFORCED if ms.egress_enforced else 0)
            | (EP_AUDIT if (audit_global or getattr(ms, "audit", False))
               else 0))
        parts.append(_EP.pack(int(ep_id), len(ms.entries), ep_flags))
        for key, entry in ms.entries.items():
            eflags = ((E_DENY if entry.is_deny else 0)
                      | (E_REDIRECT if entry.is_redirect else 0)
                      | (E_AUTH if entry.auth_required else 0))
            parts.append(_ENTRY.pack(
                int(key.identity), int(key.dport) & 0xFFFF,
                int(key.port_plen), int(key.proto),
                int(key.direction), eflags, 0))
    return b"".join(parts)
