"""The explain plane: verdict → (rule id, bank, generation), queryable.

Hubble answers "what happened to this flow"; this module answers
"WHY, and can the answer be trusted": every sampled verdict records a
bounded explain entry keyed by its flight-recorder trace id — the
decoded attribution (rule ids + content via
``engine/attribution.AttributionMap``, the content-addressed bank the
match was read from, the ``POLICY_GENERATION`` the verdict was
computed under, memo-hit vs computed, pack cycle, kernel impl) plus
enough of the flow itself to RE-RESOLVE it. ``GET /v1/explain`` and
``cilium-tpu explain`` then replay each recorded flow through the CPU
oracle at the CURRENT committed revision and report served-vs-fresh
agreement — the live face of the DST explanation-honesty invariant.

Entries live in one process-global bounded store (:data:`EXPLAIN`,
like the flight recorder's span ring): constant memory, eviction
counted, and the record side costs nothing for untraced traffic —
only chunks that drew a trace id (the deterministic sampler) record.
"""

from __future__ import annotations

import threading
from collections import OrderedDict
from typing import Dict, List, Optional, Sequence

import numpy as np

from cilium_tpu.runtime import simclock
from cilium_tpu.runtime.metrics import (
    EXPLAIN_QUERIES,
    METRICS,
    PROVENANCE_RECORDS,
)

#: default bounded capacity (trace ids retained) and per-chunk record
#: sample — overridden by ``Config.provenance`` via configure()
DEFAULT_CAPACITY = 1024
DEFAULT_SAMPLE = 8


class ExplainStore:
    """Bounded trace-id → explain-entry store (LRU on insert)."""

    def __init__(self, capacity: int = DEFAULT_CAPACITY):
        self.capacity = max(1, int(capacity))
        self._lock = threading.Lock()
        self._entries: "OrderedDict[str, List[Dict]]" = OrderedDict()
        self.evictions = 0

    def configure(self, capacity: Optional[int] = None) -> None:
        with self._lock:
            if capacity is not None:
                self.capacity = max(1, int(capacity))

    def record(self, trace_id: str, entries: Sequence[Dict]) -> None:
        if not trace_id or not entries:
            return
        with self._lock:
            bucket = self._entries.get(trace_id)
            if bucket is None:
                bucket = self._entries[trace_id] = []
                while len(self._entries) > self.capacity:
                    self._entries.popitem(last=False)
                    self.evictions += 1
            bucket.extend(entries)

    def get(self, trace_id: str) -> List[Dict]:
        with self._lock:
            return list(self._entries.get(trace_id, ()))

    def trace_ids(self) -> List[str]:
        with self._lock:
            return list(self._entries.keys())

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)

    def clear(self) -> None:
        with self._lock:
            self._entries.clear()


#: the process-global store (configure() from Config.provenance)
EXPLAIN = ExplainStore()


def build_entries(trace_id: str, surface: str, flows: Sequence,
                  verdicts, l7_match, amap,
                  gens=None, memo_hit=None, match_spec=None,
                  kernel: str = "", pack_cycle: int = -1,
                  generation: int = -1, host_id: str = "",
                  sample: int = DEFAULT_SAMPLE,
                  tenant: str = "") -> List[Dict]:
    """Explain entries for (up to ``sample``) flows of one served
    chunk. Alignment contract: ``flows[i]`` ↔ row i of every array.
    Counts explained/unexplained on the provenance series — a verdict
    is *explainable* when its attribution decodes (an L7 winner that
    resolves to live rules, or an honest L3/L4-only attribution via
    ``match_spec``).

    ``host_id`` widens the packed word's pack-cycle scope to the FLEET:
    pack cycles are per-ring counters, so once several replica rings
    serve concurrently (runtime/fleetserve.py) cycle 17 exists on every
    host — the ``host`` field is the disambiguating half of the
    (host, cycle) pair and the join key a router-forwarded explain
    query uses to attribute a trace to the replica that served it.

    ``tenant`` attributes the entry to the tenant whose stream it was
    served on (ISSUE 20 satellite) — "" keeps the pre-tenant shape."""
    from cilium_tpu.core.flow import Verdict
    from cilium_tpu.engine.attribution import flow_family, pack_word
    from cilium_tpu.ingest.hubble import flow_to_dict

    verdicts = np.asarray(verdicts)
    l7m = (np.asarray(l7_match) if l7_match is not None
           else np.full(len(verdicts), -1, dtype=np.int64))
    specs = (np.asarray(match_spec) if match_spec is not None
             else np.full(len(verdicts), -1, dtype=np.int64))
    n = min(len(flows), len(verdicts), max(0, int(sample)))
    out: List[Dict] = []
    for i in range(n):
        f = flows[i]
        code = int(l7m[i]) if i < len(l7m) else -1
        gen = int(gens[i]) if gens is not None and i < len(gens) \
            else int(generation)
        hit = bool(memo_hit[i]) if memo_hit is not None \
            and i < len(memo_hit) else False
        # frontend records carry l7 == GENERIC on the flow object
        # but verdict on their family lane (engine normalization)
        fam = flow_family(f)
        res = amap.resolve(fam, code) if amap is not None \
            else None
        spec = int(specs[i]) if i < len(specs) else -1
        explained = res is not None or (code < 0 and spec >= 0) \
            or (code < 0 and int(verdicts[i]) == int(Verdict.DROPPED))
        METRICS.inc(PROVENANCE_RECORDS,
                    labels={"result": "explained" if explained
                            else "unexplained"})
        prov: Dict[str, object] = {
            "word": pack_word(code, fam, hit, gen, pack_cycle,
                              kernel),
            "generation": gen,
            "memo_hit": hit,
            "kernel": kernel,
            "pack_cycle": pack_cycle,
            "match_spec": spec,
            "explained": bool(explained),
            "host": host_id,
        }
        if res is not None:
            prov.update(res)
            if res.get("bank_key"):
                from cilium_tpu.engine.memo import POLICY_GENERATION

                prov["bank_epoch"] = POLICY_GENERATION.bank_epoch(
                    str(res["bank_key"]))
        entry = {
            "trace_id": trace_id,
            "surface": surface,
            "t": simclock.wall(),
            "index": i,
            "verdict": int(verdicts[i]),
            "verdict_name": Verdict(int(verdicts[i])).name,
            "flow": flow_to_dict(f),
            "provenance": prov,
        }
        if tenant:
            entry["tenant"] = tenant
        out.append(entry)
    return out


def resolve_explain(loader, trace_id: str,
                    store: Optional[ExplainStore] = None) -> Dict:
    """The query side: recorded entries for ``trace_id``, each
    re-resolved through the CPU oracle at the CURRENT committed
    revision → served-vs-fresh agreement. A disagreement on a
    non-degraded plane is the staleness class the DST invariant
    hunts; here it is surfaced to the operator instead."""
    from cilium_tpu.core.flow import Verdict
    from cilium_tpu.ingest.hubble import flow_from_dict

    store = store if store is not None else EXPLAIN
    entries = store.get(trace_id)
    METRICS.inc(EXPLAIN_QUERIES,
                labels={"result": "hit" if entries else "miss"})
    if not entries:
        return {"trace_id": trace_id, "found": False, "records": []}
    oracle = loader.fallback_engine if loader is not None else None
    records: List[Dict] = []
    flows = [flow_from_dict(e["flow"]) for e in entries]
    fresh: Optional[List[int]] = None
    if oracle is not None:
        try:
            fresh = [int(v) for v in
                     oracle.verdict_flows(flows)["verdict"]]
        except Exception:  # noqa: BLE001 — a sick oracle degrades the
            fresh = None   # comparison, never the query
    degraded = bool(loader.bank_status().get("degraded")) \
        if loader is not None else False
    agree_all = True
    for k, e in enumerate(entries):
        rec = dict(e)
        if fresh is not None:
            rec["fresh_verdict"] = fresh[k]
            rec["fresh_verdict_name"] = Verdict(fresh[k]).name
            rec["agreement"] = fresh[k] == e["verdict"]
            agree_all &= rec["agreement"]
        records.append(rec)
    out = {"trace_id": trace_id, "found": True,
           "records": records, "degraded": degraded}
    if fresh is not None:
        out["served_equals_fresh"] = agree_all
    if loader is not None:
        out["revision"] = loader.revision
        from cilium_tpu.engine.memo import policy_generation

        out["generation_now"] = policy_generation()
    # link to the stitched cross-host timeline (ISSUE 17): a verdict
    # served after a handoff explains on host B while its trace spans
    # hosts A and B — the summary joins the two planes on the id
    from cilium_tpu.runtime.tracing import TRACER

    stitched = TRACER.stitch(trace_id)
    if stitched["records"]:
        out["trace"] = {"hosts": stitched["hosts"],
                        "epochs": stitched["epochs"],
                        "spans": len(stitched["records"]),
                        "stitched": stitched["stitched"]}
    return out
