"""Compiled-artifact cache.

The reference survives agent restarts because compiled state outlives
the process (pinned BPF maps, endpoint state JSON — SURVEY.md §5.3/§5.4).
Ours: compiled policies are content-addressed by a fingerprint of the
rule set + engine config; the cache lets a restarted verdict service
(and bench.py) skip automaton compilation entirely.

Two fleet-scale additions (ISSUE 13):

* the cache is **byte-bounded**: past ``max_bytes`` the least-recently-
  used entries are evicted (counted), so sustained churn can no longer
  grow the artifact dir without limit. The currently-serving policy's
  artifact and the warm-restart snapshot are *protected* — evicting
  the thing being served would turn the next restart into a recompile
  exactly when the plane is busiest.
* :class:`BankArtifactStore` makes compiled bank GROUPS distributable
  artifacts: content-addressed by their bank key, wrapped with a
  sha256 checksum, fetched on registry miss. A corrupt, truncated, or
  lost artifact (the ``artifact.fetch`` injection point) degrades to
  a counted recompile — never a crash, never a silently wrong bank.
"""

from __future__ import annotations

import collections
import hashlib
import os
import pickle
import threading
from typing import Any, Dict, Iterable, Optional

from cilium_tpu.runtime import faults
from cilium_tpu.runtime.metrics import (
    ARTIFACT_CACHE_CORRUPT,
    ARTIFACT_CACHE_EVICTIONS,
    BANK_ARTIFACT_FETCHES,
    METRICS,
)

#: everything a poisoned/stale pickle can legitimately raise: I/O
#: failures, truncation, garbage bytes, and artifacts referencing
#: classes that moved or vanished across versions. Deliberately NOT
#: ``Exception`` — a MemoryError or KeyboardInterrupt mid-load must
#: propagate, not silently turn into "cache miss, recompile"
_CORRUPT_ERRORS = (OSError, EOFError, pickle.UnpicklingError,
                   AttributeError, ImportError)

#: fires on every compiled-bank artifact fetch: a fired fault models a
#: lost/corrupt distributed artifact — the fetch degrades to a counted
#: recompile, never a crash or a silently wrong bank
ARTIFACT_FETCH_POINT = faults.register_point(
    "artifact.fetch",
    "compiled-bank artifact fetch in runtime/checkpoint."
    "BankArtifactStore (a fired fault = lost/corrupt artifact; "
    "degrade to recompile, counted)")


def ruleset_fingerprint(*parts: Any) -> str:
    """Stable hash over arbitrary picklable rule-set descriptors."""
    h = hashlib.sha256()
    for p in parts:
        h.update(pickle.dumps(p, protocol=4))
    return h.hexdigest()[:24]


class ArtifactCache:
    """On-disk pickle cache with an in-process byte-bounded LRU.

    ``max_bytes=0`` disables the bound (the pre-ISSUE-13 behavior).
    LRU order is tracked in-process (gets/puts move to MRU) and seeded
    from file mtimes on first touch, so a restarted process evicts the
    artifacts the PREVIOUS incarnation used least recently rather than
    arbitrary ones. Keys in the protected set are never evicted."""

    def __init__(self, cache_dir: str, enable: bool = True,
                 max_bytes: int = 0):
        self.cache_dir = cache_dir
        self.enable = enable
        self.max_bytes = max(0, int(max_bytes))
        self.evictions = 0
        self._lock = threading.Lock()
        self._protected: frozenset = frozenset()
        #: key → file size, in LRU order (oldest first); lazily seeded
        self._sizes: "collections.OrderedDict[str, int]" = \
            collections.OrderedDict()
        self._scanned = False
        if enable:
            os.makedirs(cache_dir, exist_ok=True)

    def _path(self, key: str) -> str:
        return os.path.join(self.cache_dir, f"{key}.pkl")

    # -- byte-bound bookkeeping -------------------------------------------
    def _scan_locked(self) -> None:
        """Seed the size/LRU index from the dir (once): mtime order
        approximates the previous incarnation's recency."""
        if self._scanned:
            return
        self._scanned = True
        entries = []
        try:
            names = os.listdir(self.cache_dir)
        except OSError:
            return
        for name in names:
            if not name.endswith(".pkl"):
                continue
            path = os.path.join(self.cache_dir, name)
            try:
                st = os.stat(path)
            except OSError:
                continue
            entries.append((st.st_mtime, name[:-4], int(st.st_size)))
        for _, key, size in sorted(entries):
            self._sizes[key] = size

    def _touch_locked(self, key: str, size: Optional[int] = None
                      ) -> None:
        self._scan_locked()
        if size is not None:
            self._sizes[key] = size
        if key in self._sizes:
            self._sizes.move_to_end(key)

    def _evict_locked(self) -> None:
        if not self.max_bytes:
            return
        total = sum(self._sizes.values())
        if total <= self.max_bytes:
            return
        for key in list(self._sizes):
            if total <= self.max_bytes:
                break
            if key in self._protected:
                continue
            size = self._sizes.pop(key)
            total -= size
            try:
                os.remove(self._path(key))
            except OSError:
                pass  # already gone — the byte goal is what matters
            self.evictions += 1
            METRICS.inc(ARTIFACT_CACHE_EVICTIONS)

    def set_protected(self, keys: Iterable[str]) -> None:
        """Replace the eviction-exempt key set (the loader keeps the
        serving artifact + warm snapshot here). Never evicting the
        serving key is a correctness property of the warm-restart
        path, not an optimization."""
        with self._lock:
            self._protected = frozenset(k for k in keys if k)

    def total_bytes(self) -> int:
        with self._lock:
            self._scan_locked()
            return sum(self._sizes.values())

    # -- read/write -------------------------------------------------------
    def get(self, key: str) -> Optional[Any]:
        if not self.enable:
            return None
        path = self._path(key)
        if not os.path.exists(path):
            return None
        try:
            with open(path, "rb") as f:
                value = pickle.load(f)
        except _CORRUPT_ERRORS:
            # corrupt entry → recompile; DELETE it so every later get
            # of this key is a clean miss instead of a re-parse of the
            # same poison, and count it so a recurring corruption
            # (bad disk, version skew) is visible to operators
            METRICS.inc(ARTIFACT_CACHE_CORRUPT)
            try:
                os.remove(path)
            except OSError:
                pass  # already gone, or unremovable — miss either way
            with self._lock:
                self._sizes.pop(key, None)
            return None
        with self._lock:
            self._touch_locked(key)
        return value

    def put(self, key: str, value: Any) -> None:
        if not self.enable:
            return
        # unique tmp per writer: concurrent puts of the same key are
        # benign (content-addressed) but must not race on one tmp file
        tmp = self._path(key) + f".{os.getpid()}.{threading.get_ident()}.tmp"
        with open(tmp, "wb") as f:
            pickle.dump(value, f, protocol=4)
        size = os.path.getsize(tmp)
        os.replace(tmp, self._path(key))
        with self._lock:
            self._touch_locked(key, size)
            self._evict_locked()


class BankArtifactStore:
    """Compiled bank groups as distributable, checksummed artifacts.

    Content-addressed bank keys (policy/compiler/bankplan.py) make a
    compiled group location-transparent: any host that compiled it can
    publish it here, any host that needs it can fetch instead of
    compiling. The payload is pickled separately and wrapped with a
    sha256 so a torn write, bit rot, or a wrong-content artifact under
    the right name is DETECTED — the fetch returns None (counted
    ``corrupt``) and the caller recompiles. Fail closed on integrity,
    open on availability."""

    FORMAT = "bank-art-v1"
    _PREFIX = "bankart-"

    def __init__(self, cache: ArtifactCache):
        self.cache = cache

    def put(self, key: str, group: Any) -> None:
        if not self.cache.enable:
            return
        payload = pickle.dumps(group, protocol=4)
        self.cache.put(self._PREFIX + key, {
            "format": self.FORMAT,
            "sha": hashlib.sha256(payload).hexdigest(),
            "payload": payload,
        })

    def fetch(self, key: str) -> Optional[Any]:
        """The distributed-fetch seam. Returns the compiled group, or
        None on miss/corruption/fault — the caller's recompile path is
        the degradation for every failure mode."""
        if not self.cache.enable:
            return None
        try:
            faults.maybe_fail(ARTIFACT_FETCH_POINT)
            entry = self.cache.get(self._PREFIX + key)
        except faults.FaultInjected:
            # a lost artifact (network partition, GC'd blob store):
            # indistinguishable from a miss to the caller
            METRICS.inc(BANK_ARTIFACT_FETCHES,
                        labels={"result": "corrupt"})
            return None
        if entry is None:
            METRICS.inc(BANK_ARTIFACT_FETCHES,
                        labels={"result": "miss"})
            return None
        try:
            if (not isinstance(entry, dict)
                    or entry.get("format") != self.FORMAT):
                raise ValueError("unknown bank-artifact format")
            payload = entry["payload"]
            if hashlib.sha256(payload).hexdigest() != entry["sha"]:
                raise ValueError("bank-artifact checksum mismatch")
            group = pickle.loads(payload)
        except _CORRUPT_ERRORS + (KeyError, TypeError, ValueError):
            # verified-corrupt: delete the poison so later fetches are
            # clean misses, count it, recompile
            METRICS.inc(BANK_ARTIFACT_FETCHES,
                        labels={"result": "corrupt"})
            try:
                os.remove(self.cache._path(self._PREFIX + key))
            except OSError:
                pass
            return None
        METRICS.inc(BANK_ARTIFACT_FETCHES, labels={"result": "hit"})
        return group

    #: corruption metrics split: BANK_ARTIFACT_FETCHES{result} is the
    #: fetch-side ledger; ARTIFACT_CACHE_CORRUPT still counts pickle-
    #: level poison the underlying cache deleted


def artifact_sizes(store: BankArtifactStore) -> Dict[str, int]:
    """Debug/introspection helper: bank-artifact keys → payload bytes
    currently in the underlying cache (best-effort, scans the dir)."""
    out: Dict[str, int] = {}
    cache = store.cache
    if not cache.enable:
        return out
    try:
        names = os.listdir(cache.cache_dir)
    except OSError:
        return out
    for name in names:
        if name.startswith(store._PREFIX) and name.endswith(".pkl"):
            key = name[len(store._PREFIX):-4]
            try:
                out[key] = os.path.getsize(
                    os.path.join(cache.cache_dir, name))
            except OSError:
                continue
    return out
