"""Compiled-artifact cache.

The reference survives agent restarts because compiled state outlives
the process (pinned BPF maps, endpoint state JSON — SURVEY.md §5.3/§5.4).
Ours: compiled policies are content-addressed by a fingerprint of the
rule set + engine config; the cache lets a restarted verdict service
(and bench.py) skip automaton compilation entirely.
"""

from __future__ import annotations

import hashlib
import os
import pickle
import threading
from typing import Any, Optional

from cilium_tpu.runtime.metrics import ARTIFACT_CACHE_CORRUPT, METRICS

#: everything a poisoned/stale pickle can legitimately raise: I/O
#: failures, truncation, garbage bytes, and artifacts referencing
#: classes that moved or vanished across versions. Deliberately NOT
#: ``Exception`` — a MemoryError or KeyboardInterrupt mid-load must
#: propagate, not silently turn into "cache miss, recompile"
_CORRUPT_ERRORS = (OSError, EOFError, pickle.UnpicklingError,
                   AttributeError, ImportError)


def ruleset_fingerprint(*parts: Any) -> str:
    """Stable hash over arbitrary picklable rule-set descriptors."""
    h = hashlib.sha256()
    for p in parts:
        h.update(pickle.dumps(p, protocol=4))
    return h.hexdigest()[:24]


class ArtifactCache:
    def __init__(self, cache_dir: str, enable: bool = True):
        self.cache_dir = cache_dir
        self.enable = enable
        if enable:
            os.makedirs(cache_dir, exist_ok=True)

    def _path(self, key: str) -> str:
        return os.path.join(self.cache_dir, f"{key}.pkl")

    def get(self, key: str) -> Optional[Any]:
        if not self.enable:
            return None
        path = self._path(key)
        if not os.path.exists(path):
            return None
        try:
            with open(path, "rb") as f:
                return pickle.load(f)
        except _CORRUPT_ERRORS:
            # corrupt entry → recompile; DELETE it so every later get
            # of this key is a clean miss instead of a re-parse of the
            # same poison, and count it so a recurring corruption
            # (bad disk, version skew) is visible to operators
            METRICS.inc(ARTIFACT_CACHE_CORRUPT)
            try:
                os.remove(path)
            except OSError:
                pass  # already gone, or unremovable — miss either way
            return None

    def put(self, key: str, value: Any) -> None:
        if not self.enable:
            return
        # unique tmp per writer: concurrent puts of the same key are
        # benign (content-addressed) but must not race on one tmp file
        tmp = self._path(key) + f".{os.getpid()}.{threading.get_ident()}.tmp"
        with open(tmp, "wb") as f:
            pickle.dump(value, f, protocol=4)
        os.replace(tmp, self._path(key))
