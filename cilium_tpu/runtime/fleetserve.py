"""The horizontal serving FLEET: stream-affinity routing over N agent
replicas, host-death failover with zero stale verdicts, and
fleet-coherent shedding.

One ServeLoop (runtime/serveloop.py) holds ~100k virtual streams on
one host (`make serve-soak`). The ROADMAP's million-stream question is
the next order of magnitude, and it is not a bigger ring — it is a
FLEET: N replicas, each owning a real ServeLoop + VerdictRing +
IncrementalSession, behind a router that keeps every stream's chunks
landing on the replica whose session already knows the stream's rows.
Three properties carry the whole design:

* **Stream affinity by rendezvous.** Placement is highest-random-
  weight (HRW) hashing of (stream, host) over the LIVE host set — no
  central placement table to rebuild, and a host death moves ONLY the
  dead host's streams (every survivor's placement is unchanged by
  construction). A pinned placement survives reconnect-with-resume:
  the stream re-dials, the router routes it home, the live lease
  RENEWS (never a second grant).
* **Host death drains nothing.** A replica's death (hard kill, or
  heartbeats lost past the suspicion TTL) abandons its leases — the
  in-flight chunks resolve as typed errors, which is what the client's
  connection reset looks like, and the chunks REPLAY through the same
  reconnect-with-resume protocol a lease expiry already exercises.
  The router re-grants the dead host's streams on survivors
  (``cilium_tpu_fleet_handoffs_total``); survivors fetch nothing and
  compile nothing — every replica loaded the same policy through the
  content-addressed BankArtifactStore (PR 13), so the swap path is
  zero-recompile by construction, and the warm rejoin of the dead
  host restores from the same artifacts. No verdict is ever served
  stale: every served verdict cites its generation (PR 14) and
  re-resolves at that citation on whichever replica served it.
* **Shedding is fleet-coherent.** Admission pressure is exchanged as
  per-host occupancy digests on the heartbeat: a saturated host sheds
  explicitly with reason ``host-overloaded`` only when NO live host
  has spill headroom; otherwise the router spills the new stream to
  the next host in its rendezvous order
  (``cilium_tpu_fleet_spilled_streams_total``). A draining host
  refuses new streams with ``host-draining`` (retryable — the router
  re-places on retry). A PARTITIONED host — one that can no longer
  reach the heartbeat plane — fails CLOSED: it refuses to serve
  possibly-stale policy with reason ``partitioned`` rather than
  answer from a world it can no longer verify.

The cross-host handoff also ships a Libra-style residency manifest:
the dead ring exports the content hashes of its session-resident rows
(``VerdictRing.resident_keys``) and each survivor reports how much of
that residency it ALREADY holds (``handoff_overlap``) — the measured
bytes a selective row-id copy avoids re-shipping host-to-device.

Fault points: ``fleet.heartbeat`` fires at every per-host beat (a
fired fault LOSES the beat; enough lost beats push the host through
suspicion into fail-closed death); ``fleet.handoff`` fires at every
per-stream lease migration (a fired fault interrupts the transfer
mid-batch; the unmigrated remainder re-grants through the client
resume path — never on two live hosts, which is the fleet's
lease-conservation invariant).

``make serve-fleet`` drives the ≥1M-concurrent-stream lane across ≥4
simulated hosts under the virtual clock, kills a host mid-storm,
partitions another, drains a third, warm-rejoins them all, and writes
one provenance-stamped line to ``BENCH_FLEET_SERVE_r08.jsonl``.
"""

from __future__ import annotations

import argparse
import hashlib
import heapq
import json
import os
import random
import sys
import tempfile
import threading
import zlib
from typing import Dict, List, Optional, Sequence, Tuple

from cilium_tpu.parallel.multihost import host_id
from cilium_tpu.runtime import admission, faults, simclock
from cilium_tpu.runtime.explain import ExplainStore, resolve_explain
from cilium_tpu.runtime.loadmodel import (
    Violation,
    _build_policy,
    _Chunk,
)
from cilium_tpu.runtime.logging import get_logger
from cilium_tpu.runtime.metrics import (
    FLEET_FAILOVER_SECONDS,
    FLEET_HANDOFFS,
    FLEET_HOST_DEATHS,
    FLEET_HOST_OCCUPANCY,
    FLEET_JOURNAL_EVENTS,
    FLEET_REJOINS,
    FLEET_SLO_BURN_RATE,
    FLEET_SPILLED_STREAMS,
    FLEET_TRACE_STITCHES,
    METRICS,
)
from cilium_tpu.runtime.serveloop import (
    LeaseExpired,
    ServeLoop,
    ShedError,
)
from cilium_tpu.runtime.tracing import TRACER, TraceContext

LOG = get_logger("fleetserve")

#: fires at every per-host heartbeat in FleetRouter.beat — a fired
#: fault LOSES that beat; beats lost past the suspicion TTL push the
#: host through suspicion into fail-closed death
HEARTBEAT_POINT = faults.register_point(
    "fleet.heartbeat", "per-host heartbeat in FleetRouter.beat (a "
                       "fired fault loses the beat)")
#: fires at every per-stream lease migration during a host-death
#: handoff — a fired fault interrupts the transfer mid-batch; the
#: unmigrated remainder re-grants through the client resume path
HANDOFF_POINT = faults.register_point(
    "fleet.handoff", "per-stream lease migration in "
                     "FleetRouter._handoff (a fired fault interrupts "
                     "the transfer mid-batch)")


#: the fleet event-journal catalog (ISSUE 17): every membership /
#: suspicion / handoff / drain / rejoin transition the router makes,
#: as an exactly-tick-stamped, causally-ordered journal entry. The
#: catalog is machine-checked against OBSERVABILITY.md by ctlint's
#: obs-doc-parity rule — adding a kind here without a documented row
#: (or leaving a stale row behind) is a lint finding.
JOURNAL_KINDS = (
    "host-join",
    "beat-lost",
    "host-death",
    "handoff",
    "handoff-interrupted",
    "host-partitioned",
    "drain-begin",
    "host-restart",
    "host-rejoin",
)


class FleetJournal:
    """The fleet's membership timeline: bounded, append-only, stamped
    with the installed clock's EXACT tick and a monotone sequence
    number taken under one lock — so two events at the same virtual
    tick (a suspicion death and its handoff) still order causally.
    The DST fleet arm holds the journal consistent with the router's
    exact books after every membership change."""

    def __init__(self, capacity: int = 65536):
        self._lock = threading.Lock()
        self._events: List[Dict] = []
        self.capacity = max(1, int(capacity))
        self._seq = 0
        #: events dropped at the bound (consistency folding refuses
        #: to pretend it saw a truncated history)
        self.dropped = 0

    def record(self, kind: str, host: str = "", **detail) -> None:
        if kind not in JOURNAL_KINDS:
            raise ValueError(f"unknown journal event kind: {kind!r}")
        now = simclock.now()
        with self._lock:
            self._seq += 1
            if len(self._events) >= self.capacity:
                self.dropped += 1
            else:
                self._events.append({
                    "seq": self._seq, "t": round(now, 9),
                    "kind": kind, "host": host,
                    **({"detail": detail} if detail else {})})
        METRICS.inc(FLEET_JOURNAL_EVENTS, labels={"kind": kind})

    def events(self) -> List[Dict]:
        with self._lock:
            return list(self._events)

    def __len__(self) -> int:
        with self._lock:
            return self._seq

    def counts(self) -> Dict[str, int]:
        out: Dict[str, int] = {}
        for e in self.events():
            out[e["kind"]] = out.get(e["kind"], 0) + 1
        return out


class HostDead(RuntimeError):
    """The stream's host died between admit and submit (or its
    placement was dropped by an interrupted handoff). TYPED so the
    client treats it exactly like a lease lapse — reconnect with
    resume and replay the chunk — never as a stream-fatal error."""

    def __init__(self, host: str, detail: str = ""):
        super().__init__(
            f"host {host or '<unplaced>'} is dead{': ' if detail else ''}"
            f"{detail}")
        self.host = host


class HostReplica:
    """One simulated fleet host: a stable identity
    (``parallel/multihost.host_id``), its own ServeLoop (ring +
    incremental session) and its own bounded ExplainStore. The store
    OUTLIVES the loop across death/rejoin — a trace served before the
    host died still resolves after its warm restore, which is what
    keeps ``GET /v1/explain`` regression-pinned across a handoff."""

    def __init__(self, index: int, loader, capacity: int = 1024,
                 lease_ttl_s: float = 300.0,
                 pack_interval_s: float = 0.05,
                 max_slot_pending: int = 8):
        self.index = int(index)
        self.name = host_id(index)
        self.loader = loader
        self.capacity = int(capacity)
        self.lease_ttl_s = float(lease_ttl_s)
        self.pack_interval_s = float(pack_interval_s)
        self.max_slot_pending = int(max_slot_pending)
        #: per-replica explain store (persists across death/rejoin)
        self.explain = ExplainStore()
        self.alive = True
        #: partitioned from the heartbeat plane: the host itself
        #: fails CLOSED (sheds ``partitioned``) while the router's
        #: suspicion clock runs it down
        self.cut = False
        #: planned drain toward a restart: existing leases keep
        #: serving, NEW streams shed ``host-draining``
        self.draining = False
        self.last_beat = simclock.now()
        self.deaths = 0
        self.loop = self._make_loop()

    def _make_loop(self) -> ServeLoop:
        return ServeLoop(self.loader, capacity=self.capacity,
                         lease_ttl_s=self.lease_ttl_s,
                         pack_interval_s=self.pack_interval_s,
                         max_slot_pending=self.max_slot_pending,
                         explain_store=self.explain,
                         host_id=self.name)

    def guard(self, new_stream: bool = False) -> None:
        """The host's own fail-closed gate, checked before any lease
        or chunk touches the loop. Dead → :class:`HostDead` (typed;
        the client resumes elsewhere). Partitioned → shed
        ``partitioned`` (the host refuses possibly-stale service).
        Draining refuses only NEW streams (``host-draining``)."""
        if not self.alive:
            raise HostDead(self.name)
        if self.cut:
            admission.count_shed("fleet", admission.CLASS_DATA,
                                 admission.SHED_PARTITIONED)
            raise ShedError(admission.SHED_PARTITIONED)
        if new_stream and self.draining:
            admission.count_shed("fleet", admission.CLASS_DATA,
                                 admission.SHED_HOST_DRAINING)
            raise ShedError(admission.SHED_HOST_DRAINING)

    def revive(self, loader=None) -> None:
        """Warm restore: a FRESH loop (empty ring — the dead ring's
        residency is gone with the device) over a loader rebuilt from
        the shared bank artifacts; the explain store persists."""
        if loader is not None:
            self.loader = loader
        self.alive = True
        self.cut = False
        self.draining = False
        self.last_beat = simclock.now()
        self.loop = self._make_loop()


class FleetRouter:
    """Stream-affinity router + health plane over the replicas.

    One lock serializes placement mutation (connect / handoff /
    rejoin), which is what makes the lease-conservation invariant —
    no stream holds leases on two LIVE hosts — checkable as a simple
    sweep rather than a protocol. Heartbeats ride the installed
    simulation clock; suspicion is the closed boundary the lease TTL
    already uses (age ≥ TTL = lapsed)."""

    def __init__(self, replicas: Sequence[HostReplica],
                 heartbeat_interval_s: float = 1.0,
                 suspicion_ttl_s: float = 5.0,
                 spill_headroom: float = 0.1):
        self.replicas = list(replicas)
        self._by_name: Dict[str, HostReplica] = {
            r.name: r for r in self.replicas}
        self.heartbeat_interval_s = float(heartbeat_interval_s)
        self.suspicion_ttl_s = float(suspicion_ttl_s)
        self.spill_headroom = float(spill_headroom)
        self._lock = threading.Lock()
        #: stream id → host name (the affinity table; absent =
        #: unplaced, the next connect re-places by rendezvous)
        self.placements: Dict[str, str] = {}
        #: the exchanged occupancy digest (refreshed per beat, bumped
        #: locally per grant so a burst between beats doesn't
        #: overshoot) — spill/shed decisions read THIS, never a
        #: remote host's instantaneous state
        self._digest: Dict[str, int] = {r.name: 0 for r in self.replicas}
        self.handoffs = 0
        self.host_deaths = 0
        self.rejoins = 0
        self.spilled = 0
        #: handoffs interrupted mid-batch by a ``fleet.handoff`` fault
        #: (the remainder re-granted through client resume)
        self.partial_handoffs = 0
        #: Libra-style selective-copy ledger: dead-ring resident rows
        #: already resident on survivors, and the H2D bytes that
        #: residency avoids re-shipping
        self.handoff_rows_resident = 0
        self.handoff_bytes_avoided = 0
        #: the fleet event journal (ISSUE 17): every membership
        #: transition, exactly tick-stamped and causally ordered
        self.journal = FleetJournal()
        #: stream id → {"tid", "epoch"}: the stitch context that rides
        #: the lease handoff — a traced stream's replayed chunks adopt
        #: the SAME trace id with a bumped causal epoch, so the kill →
        #: abandon → re-grant → replay sequence is ONE timeline.
        #: Bounded: only traced streams get entries
        self._trace_ctx: Dict[str, Dict] = {}
        self._trace_ctx_cap = 8192
        #: stream id → failover stamps ({"death", "regrant"}): the
        #: death-declared → re-grant → first-verdict-after-replay
        #: latency ledger, bounded per death (histograms need volume,
        #: not totality)
        self._failover: Dict[str, Dict] = {}
        self._failover_cap = 4096
        self.failover_samples: List[float] = []
        #: wall seconds spent on observability bookkeeping (journal,
        #: stitch plumbing, roll-ups) — the ≤2% budget's numerator
        self.obs_seconds = 0.0
        #: last fleet burn-rate roll-up ({slo: {window: {view: rate}}})
        self._fleet_burn: Dict = {}
        for r in self.replicas:
            self.journal.record("host-join", host=r.name,
                                index=r.index)

    # -- placement --------------------------------------------------------
    @staticmethod
    def _score(name: str, stream_id: str) -> int:
        return zlib.crc32(f"{name}|{stream_id}".encode())

    def _rank(self, stream_id: str,
              hosts: Sequence[HostReplica]) -> List[HostReplica]:
        return sorted(hosts, key=lambda r: self._score(r.name,
                                                       stream_id),
                      reverse=True)

    def _headroom_ok(self, r: HostReplica) -> bool:
        cap = r.loop.ring.capacity
        return self._digest.get(r.name, 0) < cap * (
            1.0 - self.spill_headroom)

    def connect(self, stream_id: str, resume: bool = False
                ) -> Tuple[str, object]:
        """Place + admit one stream; returns ``(host name, lease)``.
        A live pinned placement routes home (resume renews, never a
        second grant). A pinned host that DIED unpins and re-places by
        rendezvous over live hosts, spilling past saturated ones;
        every live host past its spill headroom is the fleet-coherent
        shed (``host-overloaded``). A pinned host that is suspected
        but not yet declared (partitioned: cut, still alive) fences
        the stream instead — the host may still think it owns the
        lease and the router cannot reach it to release, so re-placing
        NOW would put the stream live on two hosts; the client sheds
        ``partitioned`` (retryable) until suspicion declares the death
        and the handoff re-grants on a survivor."""
        with self._lock:
            target: Optional[HostReplica] = None
            placed = self.placements.get(stream_id)
            if placed is not None:
                r = self._by_name.get(placed)
                if r is not None and r.alive and r.cut:
                    admission.count_shed("fleet", admission.CLASS_DATA,
                                         admission.SHED_PARTITIONED)
                    raise ShedError(admission.SHED_PARTITIONED)
                if r is not None and r.alive and not r.cut:
                    if r.draining:
                        # pinned to a draining host: refuse
                        # (retryable) and unpin so the retry lands on
                        # a serving host
                        self.placements.pop(stream_id, None)
                        admission.count_shed(
                            "fleet", admission.CLASS_DATA,
                            admission.SHED_HOST_DRAINING)
                        raise ShedError(admission.SHED_HOST_DRAINING)
                    target = r
                else:
                    self.placements.pop(stream_id, None)
            fresh = target is None
            if fresh:
                live = [r for r in self.replicas
                        if r.alive and not r.cut and not r.draining]
                ranked = self._rank(stream_id, live)
                for cand in ranked:
                    if self._headroom_ok(cand):
                        target = cand
                        break
                if target is None:
                    # every live host is past its spill headroom (or
                    # none is live): coherent, explicit shed
                    admission.count_shed(
                        "fleet", admission.CLASS_DATA,
                        admission.SHED_HOST_OVERLOADED)
                    raise ShedError(admission.SHED_HOST_OVERLOADED)
                if ranked and target is not ranked[0]:
                    self.spilled += 1
                    METRICS.inc(FLEET_SPILLED_STREAMS)
            target.guard(new_stream=fresh)
            lease = target.loop.connect(stream_id, resume=resume)
            self.placements[stream_id] = target.name
            self._digest[target.name] = \
                self._digest.get(target.name, 0) + 1
        # a doomed stream re-placing through lazy client resume (the
        # fault-interrupted handoff remainder) closes its death→
        # re-grant stage here instead of in the handoff loop
        self._note_regrant(stream_id)
        return target.name, lease

    def replica_of(self, stream_id: str) -> Optional[HostReplica]:
        with self._lock:
            name = self.placements.get(stream_id)
        return self._by_name.get(name) if name is not None else None

    def submit(self, stream_id: str, lease, sections):
        """Route one chunk (parsed capture sections, ``gen`` rides as
        the fifth section) to the stream's placed host. Raises
        :class:`HostDead` (typed) when the placement died or was
        dropped between admit and submit — the client's resume path,
        never a stream failure — and passes the loop's own
        :class:`LeaseExpired` / :class:`ShedError` through."""
        replica = self.replica_of(stream_id)
        if replica is None:
            raise HostDead("", f"stream {stream_id} has no live "
                               f"placement")
        replica.guard(new_stream=False)
        ctx = TRACER.current()
        if ctx is not None:
            # a client-side trace is active: remember its id so the
            # handoff can carry it to the survivor (ISSUE 17). Same
            # id → keep the stored entry (its epoch may already be
            # bumped past the client's stale context)
            with self._lock:
                entry = self._trace_ctx.get(stream_id)
                if (entry is None or entry["tid"] != ctx.trace_id) \
                        and len(self._trace_ctx) < self._trace_ctx_cap:
                    self._trace_ctx[stream_id] = {
                        "tid": ctx.trace_id,
                        "epoch": getattr(ctx, "epoch", 0)}
            return replica.loop.submit(lease, *sections)
        with self._lock:
            entry = self._trace_ctx.get(stream_id)
        if entry is not None and TRACER.enabled:
            # client replay with no active context (the reconnect-
            # with-resume path after a host death): the chunk rides
            # the stream's STITCHED trace — same id, bumped epoch —
            # so both hosts' spans land on one timeline
            resume_ctx = TraceContext(entry["tid"], "stream.resume",
                                      epoch=entry["epoch"])
            with TRACER.activate(resume_ctx):
                return replica.loop.submit(lease, *sections)
        return replica.loop.submit(lease, *sections)

    # -- health plane -----------------------------------------------------
    def beat(self) -> List[str]:
        """One heartbeat round on the installed clock: collect each
        live host's beat (an armed ``fleet.heartbeat`` fault LOSES
        it; a partitioned host's beats never arrive), refresh the
        exchanged occupancy digest, then run the suspicion sweep —
        any host whose last beat aged past the suspicion TTL is
        declared dead and handed off. Returns hosts declared dead
        this round."""
        now = simclock.now()
        for r in self.replicas:
            if not r.alive:
                continue
            lost = r.cut
            if not lost:
                try:
                    faults.maybe_fail(HEARTBEAT_POINT)
                except Exception:  # noqa: BLE001 — plan-chosen exc
                    lost = True
                if lost:
                    self.journal.record("beat-lost", host=r.name,
                                        reason="fault")
            if not lost:
                r.last_beat = now
            occ = int(r.loop.status()["occupancy"])
            with self._lock:
                self._digest[r.name] = occ
            METRICS.set_gauge(FLEET_HOST_OCCUPANCY, float(occ),
                              labels={"host": r.name})
        died: List[str] = []
        for r in self.replicas:
            if r.alive and now - r.last_beat >= self.suspicion_ttl_s:
                self._declare_dead(r, partitioned=True)
                died.append(r.name)
        t_obs = simclock.perf()
        self._publish_fleet_slo()
        with self._lock:
            self.obs_seconds += max(0.0, simclock.perf() - t_obs)
        return died

    def _publish_fleet_slo(self) -> Dict:
        """Fleet burn-rate roll-up over the per-replica SLO trackers
        (ISSUE 17): ``worst`` is the worst single host (the paging
        view — one burning host must not hide behind a quiet fleet),
        ``weighted`` is fleet-weighted by each host's request volume
        over the same window (the capacity view)."""
        per_slo: Dict[str, Dict[str, Dict[str, float]]] = {}
        acc: Dict = {}
        for r in self.replicas:
            if not r.alive:
                continue
            slo = r.loop.slo
            if slo is None:
                continue
            rates = slo.burn_rates()
            totals = slo.window_totals()
            for name, per_window in rates.items():
                for window, rate in per_window.items():
                    key = (name, window)
                    worst, wsum, tsum = acc.get(key, (0.0, 0.0, 0))
                    weight = totals.get(window, 0)
                    acc[key] = (max(worst, rate),
                                wsum + rate * weight, tsum + weight)
        for (name, window), (worst, wsum, tsum) in acc.items():
            weighted = round(wsum / tsum, 4) if tsum else 0.0
            per_slo.setdefault(name, {})[window] = {
                "worst": worst, "weighted": weighted}
            METRICS.set_gauge(FLEET_SLO_BURN_RATE, worst,
                              labels={"slo": name, "window": window,
                                      "view": "worst"})
            METRICS.set_gauge(FLEET_SLO_BURN_RATE, weighted,
                              labels={"slo": name, "window": window,
                                      "view": "weighted"})
        self._fleet_burn = per_slo
        return per_slo

    def partition(self, name: str) -> None:
        """Cut the host off the heartbeat plane: it fails CLOSED on
        its own (sheds ``partitioned``) while suspicion runs down."""
        self._by_name[name].cut = True
        self.journal.record("host-partitioned", host=name)

    def kill(self, name: str) -> int:
        """Hard host death (power loss): declare dead NOW and hand
        the leases off. Returns streams migrated."""
        return self._declare_dead(self._by_name[name],
                                  partitioned=False)

    def begin_drain(self, name: str) -> None:
        """Planned restart, phase 1: stop placing NEW streams on the
        host (they shed ``host-draining`` / re-place); existing
        leases keep serving until :meth:`restart_host`."""
        self._by_name[name].draining = True
        self.journal.record("drain-begin", host=name)

    def restart_host(self, name: str) -> int:
        """Planned restart, phase 2: graceful — pack out every
        pending chunk (nothing is lost), release every lease, leave
        the rotation. The host comes back via :meth:`rejoin`.
        Returns records flushed by the final drain."""
        r = self._by_name[name]
        flushed = r.loop.drain()
        r.alive = False
        with self._lock:
            for sid in [s for s, n in self.placements.items()
                        if n == name]:
                self.placements.pop(sid, None)
        self.journal.record("host-restart", host=name,
                            flushed=flushed)
        return flushed

    def _declare_dead(self, r: HostReplica, partitioned: bool) -> int:
        """Death + handoff, atomically from the fleet's view: the
        dead host's leases are abandoned (in-flight chunks resolve as
        typed errors → client replay) BEFORE any survivor re-grant,
        so no stream ever holds leases on two live hosts. Survivors'
        re-grants ride the normal resume path; an armed
        ``fleet.handoff`` fault interrupts the migration mid-batch
        and the remainder re-grants lazily through client resume."""
        r.alive = False
        r.cut = r.cut or partitioned
        r.deaths += 1
        with self._lock:
            self.host_deaths += 1
        METRICS.inc(FLEET_HOST_DEATHS)
        dropped = r.loop.abandon("closed")
        manifest = r.loop.ring.resident_keys()
        with self._lock:
            doomed = [s for s, n in self.placements.items()
                      if n == r.name]
            for s in doomed:
                self.placements.pop(s, None)
        t_obs = simclock.perf()
        t_death = simclock.now()
        self.journal.record("host-death", host=r.name,
                            partitioned=partitioned,
                            leases=len(doomed))
        with self._lock:
            for s in doomed:
                # the trace context rides the handoff: bump the
                # stream's causal epoch BEFORE any re-grant, so even
                # a fault-interrupted remainder (re-granted lazily
                # through client resume) replays onto the stitched
                # timeline — the fleet.handoff marker is the seam
                # the merged trace shows between the two hosts
                entry = self._trace_ctx.get(s)
                if entry is not None:
                    entry["epoch"] += 1
                    TRACER.event_remote(
                        entry["tid"], "fleet.handoff", host=r.name,
                        epoch=entry["epoch"], stream=s,
                        partitioned=partitioned)
                # failover latency ledger, bounded per death
                if len(self._failover) < self._failover_cap:
                    self._failover[s] = {"death": t_death}
            self.obs_seconds += max(0.0, simclock.perf() - t_obs)
        survivors = [x for x in self.replicas
                     if x.alive and not x.cut]
        for x in survivors:
            rows, avoided = x.loop.ring.handoff_overlap(manifest)
            with self._lock:
                self.handoff_rows_resident += rows
                self.handoff_bytes_avoided += avoided
        migrated = 0
        interrupted = False
        for s in doomed:
            if not survivors:
                break
            try:
                faults.maybe_fail(HANDOFF_POINT)
            except Exception:  # noqa: BLE001 — plan-chosen exception
                # mid-batch interruption: the unmigrated remainder is
                # simply UNPLACED — each stream re-grants through its
                # own reconnect-with-resume, never on two live hosts
                with self._lock:
                    self.partial_handoffs += 1
                interrupted = True
                break
            ranked = self._rank(s, survivors)
            with self._lock:
                target = next((c for c in ranked
                               if self._headroom_ok(c)), ranked[0])
            try:
                target.loop.connect(s, resume=True)
            except ShedError:
                continue  # stays unplaced; client resume retries
            with self._lock:
                # ctlint: disable=thread-safety  # lost race is self-healing: if a concurrent connect() placed this stream while the lock was dropped for the blocking connect above, the orphaned re-grant lease expires and the client's own placement wins on reconnect
                self.placements[s] = target.name
                self._digest[target.name] = \
                    self._digest.get(target.name, 0) + 1
                self.handoffs += 1
            migrated += 1
            METRICS.inc(FLEET_HANDOFFS)
            self._note_regrant(s)
        t_obs = simclock.perf()
        self.journal.record("handoff", host=r.name,
                            streams=migrated)
        if interrupted:
            self.journal.record("handoff-interrupted", host=r.name,
                                remainder=len(doomed) - migrated)
        with self._lock:
            self.obs_seconds += max(0.0, simclock.perf() - t_obs)
        LOG.warning("host death handled", extra={"fields": {
            "host": r.name, "partitioned": partitioned,
            "leases_dropped": dropped, "migrated": migrated,
            "resident_rows_on_survivors": self.handoff_rows_resident}})
        return migrated

    def _note_regrant(self, stream_id: str) -> None:
        """Stamp the death→re-grant stage of the failover latency
        ledger (called at the handoff re-grant AND at a lazy client
        resume that re-places a doomed stream). The ledger mutates
        under ``_lock`` — a racing ``note_failover_verdict`` pop
        would otherwise leave this stamp on an orphaned dict — and
        the metric is emitted after release (no lock-order edge into
        the metrics registry)."""
        now = simclock.now()
        with self._lock:
            fo = self._failover.get(stream_id)
            if fo is None or "regrant" in fo:
                return
            fo["regrant"] = now
            death = fo["death"]
        METRICS.observe(FLEET_FAILOVER_SECONDS,
                        max(0.0, now - death),
                        labels={"stage": "death-to-regrant"})

    def note_failover_verdict(self, stream_id: str) -> None:
        """Close a stream's failover ledger at its first verdict
        after replay: observes the regrant→verdict and end-to-end
        death→verdict latencies and frees the entry. The driving
        model calls this when a replayed ticket resolves cleanly."""
        now = simclock.now()
        with self._lock:
            fo = self._failover.pop(stream_id, None)
        if fo is None:
            return
        if "regrant" in fo:
            METRICS.observe(FLEET_FAILOVER_SECONDS,
                            max(0.0, now - fo["regrant"]),
                            labels={"stage": "regrant-to-verdict"})
        total = max(0.0, now - fo["death"])
        METRICS.observe(FLEET_FAILOVER_SECONDS, total,
                        labels={"stage": "death-to-verdict"})
        self.failover_samples.append(total)

    def rejoin(self, name: str, loader=None) -> None:
        """Warm restore the dead host back into rotation: fresh loop,
        loader rebuilt from the shared bank artifacts (zero
        recompile), explain store intact, rendezvous set regains the
        host — NEW streams start landing there immediately."""
        r = self._by_name[name]
        r.revive(loader)
        with self._lock:
            self._digest[name] = 0
            self.rejoins += 1
        METRICS.inc(FLEET_REJOINS)
        self.journal.record("host-rejoin", host=name)

    # -- fleet-wide invariants & introspection ----------------------------
    def books(self) -> Tuple[int, int]:
        """(grants − expiries − releases, occupancy) summed over the
        WHOLE fleet — dead hosts balance at zero because abandonment
        releases every lease, so the equality is exact at all
        times."""
        lhs = rhs = 0
        for r in self.replicas:
            st = r.loop.status()
            lhs += st["grants"] - st["expiries"] - st["releases"]
            rhs += st["occupancy"]
        return lhs, rhs

    def conservation_violation(self) -> Optional[Tuple[str, str, str]]:
        """The fleet's cardinal invariant: no stream holds leases on
        two LIVE hosts. Returns ``(stream, host_a, host_b)`` on
        violation, ``None`` when conserved."""
        seen: Dict[str, str] = {}
        for r in self.replicas:
            if not r.alive:
                continue
            for sid in r.loop.lease_ids():
                if sid in seen:
                    return sid, seen[sid], r.name
                seen[sid] = r.name
        return None

    def journal_consistent(self) -> Optional[str]:
        """The journal's DST invariant (ISSUE 17): folding the event
        journal forward must reproduce the router's EXACT fleet books
        — per-host liveness/cut/drain state and the death / rejoin /
        handoff / interruption counters. Returns a description of the
        first divergence, ``None`` when consistent. A truncated
        journal (events dropped at the bound) refuses to certify."""
        if self.journal.dropped:
            return (f"journal truncated: {self.journal.dropped} "
                    f"events dropped at the bound")
        folded: Dict[str, Dict[str, bool]] = {}
        deaths = rejoins = handoffs = interrupted = 0
        for e in self.journal.events():
            host, kind = e["host"], e["kind"]
            st = folded.setdefault(host, {
                "alive": False, "cut": False, "draining": False})
            if kind == "host-join":
                st.update(alive=True, cut=False, draining=False)
            elif kind == "host-partitioned":
                st["cut"] = True
            elif kind == "drain-begin":
                st["draining"] = True
            elif kind == "host-death":
                st["alive"] = False
                if (e.get("detail") or {}).get("partitioned"):
                    st["cut"] = True
                deaths += 1
            elif kind == "host-restart":
                st["alive"] = False
            elif kind == "host-rejoin":
                st.update(alive=True, cut=False, draining=False)
                rejoins += 1
            elif kind == "handoff":
                handoffs += int((e.get("detail") or {})
                                .get("streams", 0))
            elif kind == "handoff-interrupted":
                interrupted += 1
        for r in self.replicas:
            st = folded.get(r.name)
            if st is None:
                return f"host {r.name} never joined the journal"
            actual = {"alive": r.alive, "cut": r.cut,
                      "draining": r.draining}
            if st != actual:
                return (f"host {r.name}: journal folds to {st}, "
                        f"router books say {actual}")
        for label, got, want in (
                ("host-death", deaths, self.host_deaths),
                ("host-rejoin", rejoins, self.rejoins),
                ("handoff streams", handoffs, self.handoffs),
                ("handoff-interrupted", interrupted,
                 self.partial_handoffs)):
            if got != want:
                return (f"{label}: journal folds to {got}, router "
                        f"counters say {want}")
        return None

    def flows(self, limit: Optional[int] = None) -> Dict:
        """The fleet's continuous flow export: per-replica
        FlowAggregator snapshots merged by aggregation key with
        per-host attribution (``hubble/flowagg.merge_snapshots``)."""
        from cilium_tpu.hubble.flowagg import merge_snapshots

        return merge_snapshots(
            r.loop.flows.snapshot(limit=limit)
            for r in self.replicas)

    def trace(self, trace_id: str) -> Dict:
        """The stitched cross-host timeline for one trace id: spans
        merged across every replica that served the stream, ordered
        by (causal epoch, timestamp), host-attributed. In-process
        replicas share the flight recorder, so the fan-out/merge
        degenerates to one stitch over the shared ring."""
        return TRACER.stitch(trace_id)

    def step_all(self) -> int:
        """One pack cycle on every live replica (the driven face)."""
        served = 0
        for r in self.replicas:
            if r.alive:
                served += r.loop.step()
        return served

    def explain(self, trace_id: str) -> Dict:
        """Router-forwarded explain: resolve the trace against
        whichever replica served it — each replica records into its
        OWN store, so the router finds the owner first and re-resolves
        there (at the owner's loader, i.e. the policy the verdict was
        actually served under)."""
        for r in self.replicas:
            if r.explain.get(trace_id):
                out = resolve_explain(r.loader, trace_id,
                                      store=r.explain)
                out["host"] = r.name
                return out
        anchor = self.replicas[0] if self.replicas else None
        return resolve_explain(
            anchor.loader if anchor is not None else None, trace_id,
            store=anchor.explain if anchor is not None else None)

    def status(self) -> Dict[str, object]:
        with self._lock:
            digest = dict(self._digest)
            placements = len(self.placements)
        return {
            "hosts": [{
                "host": r.name, "alive": r.alive, "cut": r.cut,
                "draining": r.draining, "deaths": r.deaths,
                "occupancy_digest": digest.get(r.name, 0),
            } for r in self.replicas],
            "placements": placements,
            "handoffs": self.handoffs,
            "partial_handoffs": self.partial_handoffs,
            "host_deaths": self.host_deaths,
            "rejoins": self.rejoins,
            "spilled_streams": self.spilled,
            "handoff_rows_resident": self.handoff_rows_resident,
            "handoff_bytes_avoided": self.handoff_bytes_avoided,
            "journal": {
                "events": len(self.journal),
                "counts": self.journal.counts(),
                "consistent": self.journal_consistent() is None,
            },
            "fleet_burn_rates": self._fleet_burn,
            "failover_tracked": len(self.failover_samples),
        }


# -- the million-stream fleet load model -------------------------------------

#: event kinds, processed in virtual-time order
(_ARRIVE, _EMIT, _STORM, _BEAT, _KILL, _REJOIN, _PARTITION, _DRAIN,
 _RESTART) = range(9)


class FleetModel:
    """The ≥1M-stream fleet soak (driven mode — byte-deterministic,
    the DST ``fleet`` arm's face). Mirrors
    :class:`~cilium_tpu.runtime.loadmodel.LoadModel` one level up:
    virtual streams arrive through the ROUTER, a seeded active subset
    emits heavy-tailed chunk traffic, reconnect storms churn leases —
    and mid-storm one host is KILLED, another PARTITIONED, a third
    drained for a planned restart, each warm-rejoining later.

    Invariants, checked after every driver event: fleet books exact
    (Σ grants − expiries − releases == Σ occupancy), lease
    conservation after every membership change (no stream leased on
    two live hosts), sampled verdict correctness against the engine's
    ground truth, sampled explanation decode at the CITED generation,
    and no silent losses — every errored in-flight chunk REPLAYS
    through resume until served (bounded attempts, counted)."""

    def __init__(self, seed: int = 0, streams: int = 1_000_000,
                 hosts: int = 4, virtual_s: float = 120.0,
                 ramp_s: float = 30.0, capacity: Optional[int] = None,
                 pack_interval_ms: float = 50.0,
                 lease_ttl_s: float = 600.0,
                 chunk_flows: int = 8, pool_chunks: int = 64,
                 n_rules: int = 60, storms: int = 3,
                 storm_size: int = 2000,
                 active_fraction: float = 0.05,
                 heartbeat_interval_s: float = 1.0,
                 suspicion_ttl_s: float = 5.0,
                 spill_headroom: float = 0.1,
                 pareto_xm_s: float = 30.0, pareto_alpha: float = 1.3,
                 fault_rules: Optional[Sequence] = None,
                 sample_every: int = 64,
                 max_replays: int = 4,
                 trace_sample_every: int = 8):
        if hosts < 2:
            raise ValueError("a fleet needs >= 2 hosts")
        self.seed = seed
        self.streams = int(streams)
        self.hosts = int(hosts)
        self.virtual_s = float(virtual_s)
        self.ramp_s = float(ramp_s)
        per_host = max(64, int(self.streams / self.hosts * 2))
        self.capacity = (int(capacity) if capacity
                         else 1 << (per_host - 1).bit_length())
        self.pack_interval_s = pack_interval_ms / 1e3
        self.lease_ttl_s = float(lease_ttl_s)
        self.chunk_flows = int(chunk_flows)
        self.pool_chunks = int(pool_chunks)
        self.n_rules = int(n_rules)
        self.storms = int(storms)
        self.storm_size = int(storm_size)
        #: fraction of streams that EMIT chunks (the rest hold leases
        #: — concurrency is a property of residency, not chatter; at
        #: 1M streams the emitting subset keeps wall time sane while
        #: every lease still exercises placement/expiry/handoff)
        self.active_fraction = min(1.0, max(0.0,
                                            float(active_fraction)))
        self.heartbeat_interval_s = float(heartbeat_interval_s)
        self.suspicion_ttl_s = float(suspicion_ttl_s)
        self.spill_headroom = float(spill_headroom)
        self.pareto_xm_s = float(pareto_xm_s)
        self.pareto_alpha = float(pareto_alpha)
        self.fault_rules = list(fault_rules or ())
        self.sample_every = max(1, int(sample_every))
        self.max_replays = max(1, int(max_replays))
        #: every Nth EMITTING stream carries a trace context end to
        #: end (0 disables) — the stitched-coverage population
        self.trace_sample_every = max(0, int(trace_sample_every))
        self.rng = random.Random(seed)
        self.violations: List[Dict] = []
        self.latencies: List[float] = []
        self.submissions = 0
        self.resolved = 0
        self.shed_submits = 0
        self.shed_connects = 0
        self.retries = 0
        self.replays = 0
        self.unrecovered = 0
        self.concurrency_peak = 0
        self.sampled_checks = 0
        self.rejoin_compiles = 0
        self.rejoin_artifact_hits = 0
        #: rejoins whose loader came up with ZERO bank compiles — the
        #: whole compiled policy (or every bank of it) was satisfied
        #: from the shared artifact cache; a cold build of this
        #: policy registers compiles > 0, so zero is real evidence
        self.rejoin_warm_restores = 0
        self.survivor_recompiles = 0
        #: chunk submits made under an active trace context
        self.traced_chunks = 0
        #: replayed chunks whose original ticket died traced on a
        #: closing lease (the stitch-coverage denominator) and the
        #: subset whose replacement ticket carried the SAME trace id
        #: at a HIGHER causal epoch (the numerator)
        self.handoff_replays = 0
        self.stitched_replays = 0

    # -- world ------------------------------------------------------------
    def _build_fleet(self):
        """Shared policy + per-host loaders over ONE artifact cache
        dir: host 0 compiles, every later host (and every warm
        rejoin) is satisfied from the content-addressed
        BankArtifactStore — the zero-recompile swap path, measured."""
        from cilium_tpu.core.config import Config
        from cilium_tpu.ingest.binary import (
            capture_from_bytes,
            capture_to_bytes,
        )
        from cilium_tpu.runtime.loader import Loader

        per_identity, scenario_flows, _proto = _build_policy(
            self.n_rules, self.chunk_flows)
        self._per_identity = per_identity
        self._cache_dir = tempfile.mkdtemp(prefix="ct_fleet_")

        def mk_loader():
            cfg = Config()
            cfg.enable_tpu_offload = True
            cfg.loader.cache_dir = self._cache_dir
            loader = Loader(cfg)
            loader.regenerate(per_identity, revision=1)
            return loader

        self._mk_loader = mk_loader
        loaders = [mk_loader() for _ in range(self.hosts)]
        engine = loaders[0].engine
        rng = random.Random(self.seed ^ 0x5EED)
        pool: List[_Chunk] = []
        for _ in range(self.pool_chunks):
            flows = [scenario_flows[rng.randrange(len(scenario_flows))]
                     for _ in range(self.chunk_flows)]
            sections = capture_from_bytes(capture_to_bytes(flows))
            truth = [int(v) for v in
                     engine.verdict_flows(flows)["verdict"]]
            pool.append(_Chunk(sections, truth))
        replicas = [HostReplica(i, loaders[i], capacity=self.capacity,
                                lease_ttl_s=self.lease_ttl_s,
                                pack_interval_s=self.pack_interval_s)
                    for i in range(self.hosts)]
        router = FleetRouter(
            replicas, heartbeat_interval_s=self.heartbeat_interval_s,
            suspicion_ttl_s=self.suspicion_ttl_s,
            spill_headroom=self.spill_headroom)
        # compile counters AFTER the build: any later motion on a
        # survivor is a recompile the artifact store failed to avoid
        self._compiles_after_build = {
            r.name: r.loader.bank_status().get("compiles", 0)
            for r in replicas}
        return router, pool

    # -- schedule ---------------------------------------------------------
    def _diurnal(self, t: float) -> float:
        import math

        return 1.0 + 0.6 * math.sin(
            2.0 * math.pi * t / self.virtual_s)

    def _next_interval(self, t: float) -> float:
        u = max(1e-9, 1.0 - self.rng.random())
        gap = self.pareto_xm_s / (u ** (1.0 / self.pareto_alpha))
        return min(gap, self.virtual_s) / self._diurnal(t)

    def _build_events(self) -> List[Tuple[float, int, int, int]]:
        events: List[Tuple[float, int, int, int]] = []
        seq = 0
        stride = max(1, int(round(1.0 / self.active_fraction))) \
            if self.active_fraction > 0 else 0
        for i in range(self.streams):
            t = self.rng.random() * self.ramp_s
            events.append((t, seq, _ARRIVE, i))
            seq += 1
            if stride and i % stride == 0:
                t_emit = t + self.rng.random() * self.pareto_xm_s
                events.append((t_emit, seq, _EMIT, i))
                seq += 1
        span = self.virtual_s - self.ramp_s
        storm_ts = [self.ramp_s + (k + 1) * (span / (self.storms + 1))
                    for k in range(self.storms)]
        for k, t in enumerate(storm_ts):
            events.append((t, seq, _STORM, k))
            seq += 1
        t = self.heartbeat_interval_s
        while t < self.virtual_s:
            events.append((t, seq, _BEAT, 0))
            seq += 1
            t += self.heartbeat_interval_s
        # the failure schedule, pinned to the storm windows: host 1
        # dies mid-storm-1 (hard kill, in-flight chunks replay), host
        # 2 partitions mid-storm-2 (suspicion runs it down), host 3
        # drains for a planned restart after storm 3; all rejoin warm
        half_pack = self.pack_interval_s / 2.0
        if self.storms >= 1 and self.hosts >= 2:
            events.append((storm_ts[0] + half_pack, seq, _KILL, 1))
            seq += 1
            events.append((min(storm_ts[0] + span / 8.0,
                               self.virtual_s - 2.0), seq,
                           _REJOIN, 1))
            seq += 1
        if self.storms >= 2 and self.hosts >= 3:
            events.append((storm_ts[1] + half_pack, seq,
                           _PARTITION, 2))
            seq += 1
            events.append((min(storm_ts[1] + self.suspicion_ttl_s
                               + span / 8.0, self.virtual_s - 1.5),
                           seq, _REJOIN, 2))
            seq += 1
        if self.storms >= 3 and self.hosts >= 4:
            events.append((storm_ts[2] + half_pack, seq, _DRAIN, 3))
            seq += 1
            events.append((storm_ts[2] + half_pack + 2.0, seq,
                           _RESTART, 3))
            seq += 1
            events.append((min(storm_ts[2] + span / 8.0,
                               self.virtual_s - 1.0), seq,
                           _REJOIN, 3))
            seq += 1
        heapq.heapify(events)
        self._seq = seq
        return events

    def _bump(self) -> int:
        self._seq += 1
        return self._seq

    # -- invariants -------------------------------------------------------
    def _check(self, router: FleetRouter, index: int) -> None:
        lhs, rhs = router.books()
        occ = rhs
        self.concurrency_peak = max(self.concurrency_peak, occ)
        if lhs != rhs:
            raise Violation(
                index, "fleet-lease-accounting",
                f"Σ(grants-expiries-releases) {lhs} != Σ occupancy "
                f"{rhs}")

    def _check_conservation(self, router: FleetRouter,
                            index: int) -> None:
        bad = router.conservation_violation()
        if bad is not None:
            raise Violation(
                index, "lease-conservation",
                f"stream {bad[0]} leased on BOTH {bad[1]} and "
                f"{bad[2]}")

    def _sweep(self, router, pool, leases, outstanding,
               index: int) -> None:
        """Collect resolved tickets. An errored ticket (host death,
        lease lapse, drain) REPLAYS through reconnect-with-resume —
        at-least-once, bounded attempts, every loss counted."""
        keep = []
        for ticket, chunk, stream, attempt in outstanding:
            if not ticket.done:
                keep.append((ticket, chunk, stream, attempt))
                continue
            self.resolved += 1
            if ticket.error is not None:
                self.retries += 1
                if attempt + 1 >= self.max_replays:
                    self.unrecovered += 1
                    continue
                t2 = self._replay(router, leases, pool, chunk,
                                  stream)
                if t2 is not None:
                    # stitch coverage, measured STRUCTURALLY: a chunk
                    # that died traced on a closing lease must replay
                    # under the SAME trace id at a HIGHER causal
                    # epoch — one timeline across both hosts,
                    # independent of trace-ring retention
                    if ticket.error == "lease-closed" \
                            and ticket.trace_id:
                        self.handoff_replays += 1
                        if t2.trace_id == ticket.trace_id \
                                and t2.epoch > ticket.epoch:
                            self.stitched_replays += 1
                            METRICS.inc(FLEET_TRACE_STITCHES)
                    keep.append((t2, chunk, stream, attempt + 1))
                continue
            if attempt > 0:
                # first clean verdict after a replay closes the
                # stream's failover-latency ledger on the router
                router.note_failover_verdict(f"vs{stream}")
            lat = ticket.latency
            if lat is not None:
                self.latencies.append(lat)
            if self.resolved % self.sample_every == 0:
                self.sampled_checks += 1
                got = [int(v) for v in ticket.verdicts]
                if got != chunk.truth:
                    raise Violation(
                        index, "verdict-correctness",
                        f"stream {stream}: fleet verdicts diverged "
                        f"from the engine's direct verdicts")
                self._check_explainable(router, ticket, chunk,
                                        stream, index)
        outstanding[:] = keep

    def _replay(self, router, leases, pool, chunk, stream):
        """One resume-and-resubmit attempt for an errored chunk."""
        sid = f"vs{stream}"
        try:
            _, lease = router.connect(sid, resume=True)
            leases[stream] = lease
            ticket = router.submit(sid, lease, chunk.sections)
            self.replays += 1
            self.submissions += 1
            return ticket
        except (ShedError, LeaseExpired, HostDead):
            self.unrecovered += 1
            return None

    def _check_explainable(self, router, ticket, chunk, stream,
                           index: int) -> None:
        """Sampled explanation decode at the CITED generation — the
        fleet face of the PR-14 honesty invariant: no matter which
        replica served (or re-served, post-handoff) the chunk, its
        provenance must decode and its cited generations must be in
        (0, current]."""
        import numpy as np

        from cilium_tpu.engine.memo import policy_generation

        prov = ticket.prov
        if prov is None:
            raise Violation(index, "explain-coverage",
                            f"stream {stream}: served chunk carried "
                            f"no provenance bundle")
        gens = np.asarray(prov.gens)
        gen_now = policy_generation()
        for r in range(len(gens)):
            if not (0 < int(gens[r]) <= gen_now):
                raise Violation(
                    index, "explain-undecodable",
                    f"stream {stream} row {r}: cited generation "
                    f"{int(gens[r])} outside (0, {gen_now}]")

    # -- events -----------------------------------------------------------
    def _arrive(self, router, leases, i, events) -> None:
        try:
            _, leases[i] = router.connect(f"vs{i}")
        except (ShedError, HostDead):
            self.shed_connects += 1
            heapq.heappush(events, (simclock.now() + 1.0,
                                    self._bump(), _ARRIVE, i))

    def _emit(self, router, leases, pool, outstanding, i, events,
              index) -> None:
        lease = leases.get(i)
        if lease is None:
            return
        chunk = pool[(i * 2654435761 + index) % len(pool)]
        sid = f"vs{i}"
        traced = (self.trace_sample_every > 0
                  and i % self.trace_sample_every == 0)
        try:
            if traced:
                # deterministic stride: every Nth emitting stream
                # carries a trace context; the router pins it so
                # post-handoff replays resume the SAME timeline
                with TRACER.trace("stream.chunk", stream=sid):
                    ticket = router.submit(sid, lease,
                                           chunk.sections)
                self.traced_chunks += 1
            else:
                ticket = router.submit(sid, lease, chunk.sections)
            outstanding.append((ticket, chunk, i, 0))
            self.submissions += 1
        except (LeaseExpired, HostDead):
            # lease lapsed OR the host died under the stream: the
            # SAME client protocol recovers both — reconnect with
            # resume, replay the chunk
            leases.pop(i, None)
            try:
                _, leases[i] = router.connect(sid, resume=True)
                ticket = router.submit(sid, leases[i],
                                       chunk.sections)
                outstanding.append((ticket, chunk, i, 0))
                self.submissions += 1
                self.retries += 1
            except (ShedError, LeaseExpired, HostDead):
                self.shed_connects += 1
        except ShedError:
            self.shed_submits += 1
        t_next = simclock.now() + self._next_interval(simclock.now())
        if t_next < self.virtual_s:
            heapq.heappush(events, (t_next, self._bump(), _EMIT, i))

    def _storm(self, router, leases, pool, outstanding,
               index) -> None:
        """Reconnect storm through the ROUTER: live leases renew on
        their placed host without a second grant (affinity held);
        streams whose host died re-place on a survivor."""
        ids = [self.rng.randrange(self.streams)
               for _ in range(min(self.storm_size, self.streams))]
        for i in ids:
            old = leases.get(i)
            grants_before = sum(r.loop.grants
                                for r in router.replicas)
            try:
                _, lease = router.connect(f"vs{i}", resume=True)
            except (ShedError, HostDead):
                self.shed_connects += 1
                leases.pop(i, None)
                continue
            if lease is old and sum(
                    r.loop.grants
                    for r in router.replicas) != grants_before:
                raise Violation(
                    index, "lease-double-grant",
                    f"stream {i}: reconnect-with-resume renewed a "
                    f"live lease AND counted a grant")
            leases[i] = lease
            chunk = pool[i % len(pool)]
            try:
                ticket = router.submit(f"vs{i}", lease,
                                       chunk.sections)
                outstanding.append((ticket, chunk, i, 0))
                self.submissions += 1
            except (ShedError, LeaseExpired, HostDead):
                self.shed_submits += 1

    def _survivor_compile_delta(self, router) -> int:
        delta = 0
        for r in router.replicas:
            base = self._compiles_after_build.get(r.name)
            if base is None:
                continue
            delta += max(0, r.loader.bank_status().get("compiles", 0)
                         - base)
        return delta

    def _kill(self, router, index, host_idx) -> None:
        name = router.replicas[host_idx].name
        before = self._survivor_compile_delta(router)
        router.kill(name)
        self.survivor_recompiles += \
            self._survivor_compile_delta(router) - before
        self._check_conservation(router, index)

    def _rejoin(self, router, index, host_idx) -> None:
        name = router.replicas[host_idx].name
        if router.replicas[host_idx].alive:
            return  # suspicion never fired (no-op rejoin)
        loader = self._mk_loader()
        bs = loader.bank_status()
        self.rejoin_compiles += bs.get("compiles", 0)
        self.rejoin_artifact_hits += bs.get("artifact_hits", 0)
        if bs.get("compiles", 0) == 0:
            self.rejoin_warm_restores += 1
        router.rejoin(name, loader)
        # track the restored host's compile counter from here on
        self._compiles_after_build[name] = bs.get("compiles", 0)
        self._check_conservation(router, index)

    def _run_event(self, router, pool, events, leases, outstanding,
                   kind, arg, index) -> None:
        membership = kind in (_KILL, _REJOIN, _PARTITION, _DRAIN,
                              _RESTART)
        if kind == _ARRIVE:
            self._arrive(router, leases, arg, events)
        elif kind == _EMIT:
            self._emit(router, leases, pool, outstanding, arg,
                       events, index)
        elif kind == _STORM:
            self._storm(router, leases, pool, outstanding, index)
        elif kind == _BEAT:
            before = self._survivor_compile_delta(router)
            died = router.beat()
            if died:
                membership = True
                self.survivor_recompiles += \
                    self._survivor_compile_delta(router) - before
                self._check_conservation(router, index)
        elif kind == _KILL:
            self._kill(router, index, arg)
        elif kind == _REJOIN:
            self._rejoin(router, index, arg)
        elif kind == _PARTITION:
            router.partition(router.replicas[arg].name)
        elif kind == _DRAIN:
            router.begin_drain(router.replicas[arg].name)
        elif kind == _RESTART:
            router.restart_host(router.replicas[arg].name)
            self._check_conservation(router, index)
        if membership:
            # the journal's DST invariant: after EVERY membership
            # change, folding the event journal must reproduce the
            # router's exact fleet books
            msg = router.journal_consistent()
            if msg is not None:
                raise Violation(index, "fleet-journal-consistency",
                                msg)
        self._check(router, index)

    # -- the run ----------------------------------------------------------
    def run(self) -> Dict:
        clock = simclock.VirtualClock(poll=0.001)
        plan = faults.FaultPlan(rules=self.fault_rules,
                                seed=self.seed)
        result: Dict = {}
        # the model owns its trace sampling stride (every Nth
        # emitting stream), so the flight recorder itself runs
        # unsampled for the run; restored after — callers (tests,
        # the DST arm) keep their own tracer state
        prev_enabled, prev_rate = TRACER.enabled, TRACER.sample_rate
        TRACER.configure(enabled=True, sample_rate=1.0)
        try:
            result = self._run(clock, plan)
        finally:
            TRACER.configure(enabled=prev_enabled,
                             sample_rate=prev_rate)
        return result

    def _run(self, clock, plan) -> Dict:
        result: Dict = {}
        with simclock.use(clock):
            router, pool = self._build_fleet()
            self._router = router
            base = self._baseline(router, pool, clock)
            with faults.inject(plan):
                try:
                    index = self._drive(router, pool, clock)
                except Violation as v:
                    index = v.index
                    self.violations.append({
                        "index": v.index, "invariant": v.invariant,
                        "detail": v.detail})
            # graceful end: drain every live replica, then the final
            # invariant sweep over the whole fleet
            for r in router.replicas:
                if r.alive:
                    r.loop.drain()
            try:
                self._check(router, index + 1)
                self._check_conservation(router, index + 1)
            except Violation as v:
                self.violations.append({
                    "index": v.index, "invariant": v.invariant,
                    "detail": v.detail})
            result = self._result(router, base, clock)
        return result

    def _baseline(self, router: FleetRouter, pool, clock) -> float:
        """Unloaded p99 on one replica — the intra-run denominator
        (the cross-round single-host baseline comes from the
        serve-soak artifact in :func:`main`)."""
        r0 = router.replicas[0]
        lease = r0.loop.connect("baseline")
        lats: List[float] = []
        for k in range(20):
            chunk = pool[k % len(pool)]
            ticket = r0.loop.submit(lease, *chunk.sections)
            clock.advance(self.pack_interval_s)
            r0.loop.step()
            if ticket.done and ticket.latency is not None:
                lats.append(ticket.latency)
        r0.loop.disconnect(lease)
        lats.sort()
        return lats[min(len(lats) - 1, int(0.99 * len(lats)))] \
            if lats else self.pack_interval_s

    def _drive(self, router, pool, clock) -> int:
        events = self._build_events()
        leases: Dict[int, object] = {}
        outstanding: List = []
        index = 0
        next_step = clock.now() + self.pack_interval_s
        while events:
            if events[0][0] <= next_step:
                t, _seq, kind, arg = heapq.heappop(events)
                clock.advance_to(t)
                index += 1
                self._run_event(router, pool, events, leases,
                                outstanding, kind, arg, index)
            else:
                clock.advance_to(next_step)
                router.step_all()
                next_step += self.pack_interval_s
                self._sweep(router, pool, leases, outstanding, index)
        # settle the tail: packs + replays until quiet (bounded)
        for _ in range(self.max_replays * 2):
            clock.advance(self.pack_interval_s)
            router.step_all()
            self._sweep(router, pool, leases, outstanding, index)
            if not outstanding:
                break
        for _ticket, _chunk, _stream, _attempt in outstanding:
            self.unrecovered += 1
        return index

    def _result(self, router: FleetRouter, base_p99: float,
                clock) -> Dict:
        lats = sorted(self.latencies)

        def pct(q):
            return (lats[min(len(lats) - 1, int(q * len(lats)))]
                    if lats else 0.0)

        shed_total = self.shed_submits + self.shed_connects
        denom = max(1, self.submissions + shed_total)
        explained = unexplained = served = packs = 0
        flow_records = flows_aggregated = 0
        flow_keys = flow_overflow = 0
        obs_seconds = router.obs_seconds
        for r in router.replicas:
            st = r.loop.status()
            prov = st.get("provenance", {})
            explained += prov.get("records_explained", 0)
            unexplained += prov.get("records_unexplained", 0)
            served += st["served_records"]
            packs += st["packs"]
            fl = r.loop.flows
            flow_records += fl.records
            flows_aggregated += fl.aggregated
            flow_keys += fl.key_count()
            flow_overflow += fl.overflow
            obs_seconds += r.loop.obs_seconds
        fleet = router.status()
        p99_burn = (fleet.get("fleet_burn_rates") or {}).get(
            "serve-p99") or {}
        wkey = min(p99_burn, key=lambda w: int(w.rstrip("s"))) \
            if p99_burn else None
        fo = sorted(router.failover_samples)
        failover_p99 = fo[min(len(fo) - 1, int(0.99 * len(fo)))] \
            if fo else 0.0
        return {
            "seed": self.seed,
            "streams": self.streams,
            "hosts": self.hosts,
            "capacity_per_host": self.capacity,
            "concurrency_peak": self.concurrency_peak,
            "virtual_s": self.virtual_s,
            "simulated_s": round(clock.simulated, 3),
            "active_fraction": self.active_fraction,
            "submissions": self.submissions,
            "resolved": self.resolved,
            "served_records": served,
            "packs": packs,
            "sheds": shed_total,
            "shed_rate": round(shed_total / denom, 6),
            "retries": self.retries,
            "replays": self.replays,
            "unrecovered": self.unrecovered,
            "sampled_checks": self.sampled_checks,
            "handoffs": fleet["handoffs"],
            "partial_handoffs": fleet["partial_handoffs"],
            "host_deaths": fleet["host_deaths"],
            "rejoins": fleet["rejoins"],
            "spilled_streams": fleet["spilled_streams"],
            "handoff_rows_resident": fleet["handoff_rows_resident"],
            "handoff_bytes_avoided": fleet["handoff_bytes_avoided"],
            "survivor_recompiles": self.survivor_recompiles,
            "rejoin_compiles": self.rejoin_compiles,
            "rejoin_artifact_hits": self.rejoin_artifact_hits,
            "rejoin_warm_restores": self.rejoin_warm_restores,
            "records_explained": explained,
            "records_unexplained": unexplained,
            "explain_coverage": round(
                explained / max(1, explained + unexplained), 6),
            "traced_chunks": self.traced_chunks,
            "handoff_replays": self.handoff_replays,
            "stitched_replays": self.stitched_replays,
            "stitch_coverage": round(
                self.stitched_replays / self.handoff_replays, 6)
            if self.handoff_replays else 1.0,
            "flow_records": flow_records,
            "flows_aggregated": flows_aggregated,
            "flow_keys": flow_keys,
            "flow_overflow": flow_overflow,
            "journal_events": fleet["journal"]["events"],
            "journal_consistent": fleet["journal"]["consistent"],
            "burn_worst": p99_burn[wkey]["worst"] if wkey else 0.0,
            "burn_weighted": p99_burn[wkey]["weighted"]
            if wkey else 0.0,
            "failover_p99_ms": round(failover_p99 * 1e3, 3),
            "failover_tracked": len(router.failover_samples),
            "obs_seconds": round(obs_seconds, 6),
            "obs_budget_pct": 2.0,
            "p50_ms": round(pct(0.50) * 1e3, 3),
            "p99_ms": round(pct(0.99) * 1e3, 3),
            "p99_unloaded_ms": round(base_p99 * 1e3, 3),
            "p99_ratio": round(pct(0.99) / max(base_p99, 1e-9), 3),
            "violations": list(self.violations),
        }


# -- the `make serve-fleet` lane ---------------------------------------------


def _single_host_baseline_ms(root: str = ".") -> Optional[float]:
    """The ≤2×-single-host denominator: the MAX serve-soak p99 ever
    recorded in ``BENCH_SERVE_r07.jsonl`` (max, not latest — the gate
    is about fleet overhead, not run-to-run host noise)."""
    path = os.path.join(root, "BENCH_SERVE_r07.jsonl")
    best: Optional[float] = None
    try:
        with open(path) as fp:
            for raw in fp:
                raw = raw.strip()
                if not raw:
                    continue
                try:
                    row = json.loads(raw)
                except ValueError:
                    continue
                v = row.get("p99_ms")
                if isinstance(v, (int, float)) and v > 0:
                    best = max(best or 0.0, float(v))
    except OSError:
        return None
    return best


def main(argv: Optional[Sequence[str]] = None) -> int:
    from cilium_tpu.core.config import Config

    # the [fleet] config block (core/config.FleetConfig, env
    # CILIUM_TPU_FLEET_*) seeds the lane's topology/health defaults;
    # flags override per-run
    fcfg = Config.from_env().fleet
    ap = argparse.ArgumentParser(
        description="million-stream serving-fleet soak: stream-"
                    "affinity routing, host-death failover, "
                    "fleet-coherent shedding (DST driven)")
    ap.add_argument("--streams", type=int, default=1_050_000)
    ap.add_argument("--hosts", type=int, default=fcfg.replicas)
    ap.add_argument("--seed", type=int,
                    default=int(os.environ.get("CILIUM_TPU_DST_SEED",
                                               "0") or 0))
    ap.add_argument("--virtual-s", type=float, default=120.0)
    ap.add_argument("--pack-interval-ms", type=float, default=50.0)
    ap.add_argument("--lease-ttl-s", type=float, default=600.0)
    ap.add_argument("--active-fraction", type=float, default=0.02,
                    help="fraction of streams emitting chunk traffic "
                         "(every stream holds a lease)")
    ap.add_argument("--storms", type=int, default=3)
    ap.add_argument("--storm-size", type=int, default=2000)
    ap.add_argument("--heartbeat-interval-s", type=float,
                    default=fcfg.heartbeat_interval_s)
    ap.add_argument("--suspicion-ttl-s", type=float,
                    default=fcfg.suspicion_ttl_s)
    ap.add_argument("--spill-headroom", type=float,
                    default=fcfg.spill_headroom)
    ap.add_argument("--faults", type=int, default=8,
                    help="fleet.heartbeat/fleet.handoff fires to arm "
                         "(seeded; 0 disables)")
    ap.add_argument("--p99-factor", type=float, default=2.0,
                    help="aggregate p99 ceiling as a multiple of the "
                         "single-host serve-soak baseline")
    ap.add_argument("--max-shed-rate", type=float, default=0.02)
    ap.add_argument("--target-concurrency", type=int, default=0,
                    help="gate floor (default: 95%% of --streams)")
    ap.add_argument("--no-p99-gate", action="store_true",
                    help="smoke mode: skip the p99-vs-baseline gate "
                         "(tiny runs are all fixed overhead)")
    ap.add_argument("--min-handoffs", type=int, default=400,
                    help="gate floor on handed-off streams (the "
                         "stitch-coverage population; smoke runs "
                         "set 1)")
    ap.add_argument("--trace-sample-every", type=int, default=8,
                    help="every Nth emitting stream carries a trace "
                         "context end to end (0 disables)")
    ap.add_argument("--out", default="BENCH_FLEET_SERVE_r08.jsonl")
    args = ap.parse_args(argv)

    rules = []
    if args.faults > 0:
        rules = [
            faults.FaultRule("fleet.heartbeat", prob=0.002,
                             times=args.faults),
            faults.FaultRule("fleet.handoff", prob=0.01,
                             times=args.faults),
        ]
    t0 = simclock.perf()
    model = FleetModel(
        seed=args.seed, streams=args.streams, hosts=args.hosts,
        virtual_s=args.virtual_s,
        pack_interval_ms=args.pack_interval_ms,
        lease_ttl_s=args.lease_ttl_s,
        active_fraction=args.active_fraction,
        storms=args.storms, storm_size=args.storm_size,
        heartbeat_interval_s=args.heartbeat_interval_s,
        suspicion_ttl_s=args.suspicion_ttl_s,
        spill_headroom=args.spill_headroom,
        fault_rules=rules,
        trace_sample_every=args.trace_sample_every)
    result = model.run()
    wall_s = simclock.perf() - t0
    result["wall_s"] = round(wall_s, 3)
    result["speedup_vs_real_time"] = round(
        result["simulated_s"] / max(wall_s, 1e-9), 1)
    result["obs_overhead_pct"] = round(
        100.0 * result["obs_seconds"] / max(wall_s, 1e-9), 3)

    base_ms = _single_host_baseline_ms()
    result["single_host_p99_ms"] = base_ms
    target = args.target_concurrency or int(0.95 * args.streams)
    p99_ok = True
    if not args.no_p99_gate:
        if base_ms is not None:
            p99_ok = result["p99_ms"] <= args.p99_factor * base_ms
        else:
            p99_ok = result["p99_ratio"] <= args.p99_factor
    gates = {
        "violations": len(result["violations"]) == 0,
        "concurrency": result["concurrency_peak"] >= target,
        "hosts": args.hosts >= 4,
        "p99": p99_ok,
        "shed_rate": result["shed_rate"] <= args.max_shed_rate,
        "deaths": result["host_deaths"] >= 1,
        "rejoins": result["rejoins"] >= 1,
        "handoffs": result["handoffs"] >= max(1, args.min_handoffs),
        # fleet observability plane (ISSUE 17): handoff-replayed
        # chunks keep ONE causally-ordered trace (≥99%), flows export
        # continuously, the event journal folds to the router's exact
        # books, and the whole plane stays under its ≤2% wall budget
        "stitch_coverage": result["stitch_coverage"] >= 0.99,
        "flow_export": result["flows_aggregated"] > 0,
        "journal_consistent": bool(result["journal_consistent"]),
        "obs_overhead": (result["obs_overhead_pct"]
                         <= result["obs_budget_pct"]),
        # the zero-recompile swap path: survivors compiled nothing
        # during any handoff, and every warm rejoin came entirely
        # from the shared policy/bank artifact store (a cold build
        # of this policy registers compiles > 0)
        "zero_recompile": (result["survivor_recompiles"] == 0
                           and result["rejoin_compiles"] == 0
                           and result["rejoin_warm_restores"] >= 1),
        # zero stale / zero lost: every error replayed to a verdict
        "no_losses": result["unrecovered"] == 0,
    }
    result["gates"] = {k: bool(v) for k, v in gates.items()}

    from cilium_tpu.runtime.provenance import stamp

    os.environ["CILIUM_TPU_DST_SEED"] = str(args.seed)
    os.environ["CILIUM_TPU_DST_DIGEST"] = hashlib.sha256(
        json.dumps({"streams": args.streams, "hosts": args.hosts,
                    "seed": args.seed, "virtual_s": args.virtual_s},
                   sort_keys=True).encode()).hexdigest()[:16]
    line = stamp({
        "metric": "fleet_serve_p99_ms",
        "value": result["p99_ms"],
        "unit": "ms submit->verdict aggregate p99 (virtual)",
        "lane": "serve-fleet",
        **{k: v for k, v in result.items() if k != "violations"},
        "violations": len(result["violations"]),
    })
    with open(args.out, "a") as fp:
        fp.write(json.dumps(line) + "\n")

    ok = all(gates.values())
    print(f"[serve-fleet] {result['concurrency_peak']} concurrent "
          f"virtual streams (target {target}) across {args.hosts} "
          f"hosts; {result['host_deaths']} deaths / "
          f"{result['rejoins']} rejoins / {result['handoffs']} "
          f"handoffs ({result['partial_handoffs']} interrupted), "
          f"{result['spilled_streams']} spilled; "
          f"{result['submissions']} chunks / "
          f"{result['served_records']} records over "
          f"{result['packs']} packs; p99 {result['p99_ms']}ms "
          f"(single-host {base_ms}ms), shed rate "
          f"{result['shed_rate']}, replays {result['replays']}, "
          f"unrecovered {result['unrecovered']}; "
          f"{result['rejoin_warm_restores']} warm restores / "
          f"{result['rejoin_compiles']} rejoin compiles; stitch "
          f"coverage {result['stitch_coverage']} over "
          f"{result['handoff_replays']} handoff replays "
          f"({result['traced_chunks']} traced chunks), "
          f"{result['flows_aggregated']} flows aggregated into "
          f"{result['flow_keys']} keys "
          f"(overflow {result['flow_overflow']}), journal "
          f"{result['journal_events']} events "
          f"{'consistent' if result['journal_consistent'] else 'INCONSISTENT'}, "
          f"burn worst/weighted {result['burn_worst']}/"
          f"{result['burn_weighted']}, failover p99 "
          f"{result['failover_p99_ms']}ms "
          f"({result['failover_tracked']} tracked), obs overhead "
          f"{result['obs_overhead_pct']}%; simulated "
          f"{result['simulated_s']:.0f}s in {wall_s:.1f}s wall "
          f"({result['speedup_vs_real_time']}x); gates "
          f"{'OK' if ok else 'FAILED ' + str(result['gates'])}",
          flush=True)
    if result["violations"]:
        print(f"[serve-fleet] violations: {result['violations']}",
              flush=True)
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
