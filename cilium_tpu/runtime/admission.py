"""Bounded admission control for the serving path.

The reference agent survives traffic spikes because the datapath
bounds work per admitted packet and pushes back on producers instead
of buffering arbitrarily (the kernel-offload and selective-copy
arguments in PAPERS.md make the same point from the socket side).
Before this module our service plane had the opposite shape:
``MicroBatcher._pending`` grew without bound under overload, callers
that hit their timeout still consumed device batch slots, and p99
diverged instead of shedding.

This module is the front door every serving ingress now passes:

* **Bounded queue occupancy.** ``AdmissionGate.admit`` sheds when the
  verdict queue is at its configured bound
  (``Config.admission.max_pending``) — an explicit, counted shed
  response beats an unbounded queue and a timeout.
* **Two priority classes.** ``CLASS_CONTROL`` (policy updates, drain,
  health — the ops an operator needs DURING an overload) gets
  ``control_reserve`` headroom above the data-path bound, so control
  traffic never sheds behind data-path verdicts.
* **Deadline feasibility.** Requests carry deadlines (absolute
  monotonic seconds; ``deadline_from_ms`` builds them from the wire's
  ``deadline_ms``). A request whose deadline cannot be met given the
  current queue depth and the recent batch service rate is shed AT
  ADMISSION — serving it would waste a device batch slot on an answer
  nobody is waiting for.
* **Abandoned-request reaping.** The MicroBatcher carries each
  entry's deadline; entries whose caller timed out (abandoned) or
  whose deadline passed while queued are dropped before
  featurize/dispatch and counted (``cilium_tpu_admission_reaped_total``).
* **Drain mode.** ``begin_drain`` stops admitting data-path work
  (control still admitted — a draining service must answer status and
  the drain op itself) ahead of the flush + warm-snapshot sequence in
  ``VerdictService.drain``.

``RequestSlots`` is the same discipline for the REST API
(``runtime/api.py``): a bounded in-flight count with control-class
headroom, returning explicit 503 sheds instead of piling threads.

Shed decisions are visible three ways: counters
(``cilium_tpu_admission_{admitted,shed}_total``), the queue-depth
gauge, and a ``shed``-phase span on sampled traces
(``runtime/tracing.py``) so a shed request's trace says WHY it never
reached the engine.
"""

from __future__ import annotations

import threading
from typing import Callable, Optional, Tuple

from cilium_tpu.runtime import faults, simclock
from cilium_tpu.runtime.metrics import (
    ADMISSION_ADMITTED,
    ADMISSION_QUEUE_DEPTH,
    ADMISSION_REAPED,
    ADMISSION_SHED,
    METRICS,
)

#: priority classes: data-path verdict traffic sheds first; control
#: traffic (policy/config/drain/health) gets reserved headroom
CLASS_DATA = "data"
CLASS_CONTROL = "control"

#: shed reasons (the ``reason`` label on the shed counter)
SHED_QUEUE_FULL = "queue-full"
SHED_DEADLINE = "deadline"
SHED_DRAINING = "draining"
SHED_FAULT = "fault"
#: the verdict ring has no free slot for a new stream lease
#: (runtime/serveloop.py) — explicit, counted, retryable
SHED_RING_FULL = "ring-full"
#: fleet serving (runtime/fleetserve.py): every live host is past its
#: spill headroom — the router found no headroom anywhere, so the
#: saturated owner sheds explicitly instead of queueing
SHED_HOST_OVERLOADED = "host-overloaded"
#: the placed host is draining toward a restart/rejoin — new streams
#: belong elsewhere (retryable; the router re-places on retry)
SHED_HOST_DRAINING = "host-draining"
#: the host missed enough heartbeats to suspect a partition and FAILED
#: CLOSED: it refuses to serve possibly-stale policy rather than
#: answer from the wrong side of a split
SHED_PARTITIONED = "partitioned"
#: multi-tenant fairness (ISSUE 20): the requesting tenant is past its
#: weighted fair share of the admission window while the gate is
#: congested — THAT tenant sheds; everyone else keeps admitting. The
#: shed carries the tenant label, so per-tenant debugging works day
#: one
SHED_TENANT_QUOTA = "tenant-quota"

#: fires at every admission decision; an injected fault forces a shed
#: (reason "fault") — the chaos suite's handle on the gate
ADMIT_POINT = faults.register_point(
    "service.admit", "admission decision in AdmissionGate.admit")


def deadline_from_ms(deadline_ms, default_ms: float,
                     clock=None) -> float:
    """Absolute monotonic deadline from a wire-carried ``deadline_ms``.
    None/0/unparsable → the configured default; NEGATIVE passes
    through as already-expired (the caller declared it gave up — the
    gate sheds it with reason "deadline")."""
    try:
        ms = float(deadline_ms) if deadline_ms is not None else 0.0
    except (TypeError, ValueError):
        ms = 0.0
    if ms == 0.0:
        ms = float(default_ms)
    now = clock() if clock is not None else simclock.now()
    return now + ms / 1e3


def count_shed(surface: str, klass: str, reason: str,
               tenant: str = "") -> None:
    """One shed, on the shared counter — callers that shed outside the
    gate (the MicroBatcher's hard bound) stay on the same series. A
    non-empty ``tenant`` rides as an extra label (tenant-less callers
    keep the exact pre-tenant series)."""
    labels = {"surface": surface, "class": klass, "reason": reason}
    if tenant:
        labels["tenant"] = tenant
    METRICS.inc(ADMISSION_SHED, labels=labels)


class AdmissionGate:
    """The verdict-path admission decision. One instance per
    :class:`~cilium_tpu.runtime.service.VerdictService`; ``depth_fn``
    reads the MicroBatcher's queue occupancy so the bound tracks the
    real backlog, not a shadow counter."""

    def __init__(self, max_pending: int = 1024,
                 control_reserve: int = 64, enabled: bool = True,
                 depth_fn: Optional[Callable[[], int]] = None,
                 clock=None, surface: str = "service",
                 fairness=None, quotas=None):
        self.max_pending = max(1, int(max_pending))
        self.control_reserve = max(0, int(control_reserve))
        self.enabled = bool(enabled)
        self.depth_fn = depth_fn
        self.clock = clock if clock is not None else simclock.now
        self.surface = surface
        #: per-tenant weighted-fairness window
        #: (:class:`~cilium_tpu.runtime.tenant.FairShareWindow`); None
        #: = tenant-blind, the pre-ISSUE-20 behavior
        self.fairness = fairness
        #: TTL'd per-tenant share store
        #: (:class:`~cilium_tpu.runtime.tenant.TenantQuotas`) feeding
        #: the fairness ceiling; None = the window's static max_share
        self.quotas = quotas
        self._lock = threading.Lock()
        self._draining = False
        #: EWMA of the batcher's service rate (records/second) — the
        #: denominator of the deadline-feasibility estimate
        self._rate = 0.0

    @classmethod
    def from_config(cls, cfg, depth_fn=None,
                    surface: str = "service") -> "AdmissionGate":
        """Build from ``Config.admission`` (tolerates absence so
        standalone loaders/old configs keep working)."""
        return cls(
            max_pending=getattr(cfg, "max_pending", 1024),
            control_reserve=getattr(cfg, "control_reserve", 64),
            enabled=getattr(cfg, "enabled", True),
            depth_fn=depth_fn, surface=surface)

    # -- drain ------------------------------------------------------------
    @property
    def draining(self) -> bool:
        with self._lock:
            return self._draining

    def begin_drain(self) -> None:
        """Stop admitting data-path work (idempotent). Control traffic
        stays admitted — a draining service must still answer status,
        metrics, and the drain op itself."""
        with self._lock:
            self._draining = True

    # -- feasibility estimate ---------------------------------------------
    def note_batch(self, records: int, seconds: float) -> None:
        """Fold one completed batch into the service-rate EWMA (the
        MicroBatcher calls this per flush)."""
        if records <= 0 or seconds <= 0.0:
            return
        rate = records / seconds
        with self._lock:
            self._rate = rate if self._rate <= 0.0 \
                else 0.8 * self._rate + 0.2 * rate

    def estimated_wait(self, depth: int) -> float:
        """Seconds a request arriving now waits behind ``depth``
        queued records (0 until a rate estimate exists)."""
        with self._lock:
            rate = self._rate
        return depth / rate if rate > 0.0 else 0.0

    # -- the decision -----------------------------------------------------
    def admit(self, klass: str = CLASS_DATA,
              deadline: Optional[float] = None,
              tenant: str = "") -> Tuple[bool, str]:
        """(admitted, shed_reason). Sheds are counted; admitted
        requests are counted per class. Disabled gates only enforce
        drain mode — drain correctness trumps the knob. A non-empty
        ``tenant`` rides every shed's label and, when a fairness
        window is wired, subjects the request to the weighted-fair
        share check while the gate is congested (past half the
        data-path bound — a lone tenant bursting into idle capacity
        is never penalized)."""
        try:
            faults.maybe_fail(ADMIT_POINT)
        except Exception:  # noqa: BLE001 — plan-chosen exception
            # an injected admission fault IS a shed: the request is
            # refused explicitly, never half-admitted
            count_shed(self.surface, klass, SHED_FAULT, tenant)
            return False, SHED_FAULT
        with self._lock:
            draining = self._draining
        if draining and klass != CLASS_CONTROL:
            count_shed(self.surface, klass, SHED_DRAINING, tenant)
            return False, SHED_DRAINING
        if not self.enabled:
            return True, ""
        depth = self.depth_fn() if self.depth_fn is not None else 0
        METRICS.set_gauge(ADMISSION_QUEUE_DEPTH, float(depth),
                          labels={"surface": self.surface})
        bound = self.max_pending + (self.control_reserve
                                    if klass == CLASS_CONTROL else 0)
        if depth >= bound:
            count_shed(self.surface, klass, SHED_QUEUE_FULL, tenant)
            return False, SHED_QUEUE_FULL
        if (tenant and self.fairness is not None
                and klass != CLASS_CONTROL
                and depth > self.max_pending // 2):
            cap = (self.quotas.share_of(tenant)
                   if self.quotas is not None else None)
            if self.fairness.over_share(tenant, share_cap=cap):
                # the storming tenant sheds; every other tenant's
                # window share is untouched by this decision
                count_shed(self.surface, klass, SHED_TENANT_QUOTA,
                           tenant)
                return False, SHED_TENANT_QUOTA
        if deadline is not None:
            remaining = deadline - self.clock()
            if remaining <= 0.0 or remaining < self.estimated_wait(depth):
                # infeasible: the caller will have given up before we
                # could answer — admitting it only wastes a batch slot
                count_shed(self.surface, klass, SHED_DEADLINE, tenant)
                return False, SHED_DEADLINE
        if tenant and self.fairness is not None:
            self.fairness.note(tenant)
        METRICS.inc(ADMISSION_ADMITTED,
                    labels={"surface": self.surface, "class": klass})
        return True, ""

    def reap(self, count: int = 1) -> None:
        """Count entries dropped before dispatch (abandoned callers /
        expired deadlines) — the MicroBatcher's reaping face."""
        if count > 0:
            METRICS.inc(ADMISSION_REAPED, count)


class RequestSlots:
    """Bounded in-flight admission for the REST API: each request
    holds a slot for its handler's duration; data-class requests shed
    at ``max_inflight``, control-class requests get ``control_reserve``
    headroom so policy/config/drain ops land during overload."""

    def __init__(self, max_inflight: int = 64,
                 control_reserve: int = 8, enabled: bool = True):
        self.max_inflight = max(0, int(max_inflight))
        self.control_reserve = max(0, int(control_reserve))
        self.enabled = bool(enabled)
        self._lock = threading.Lock()
        self._inflight = 0

    @classmethod
    def from_config(cls, cfg) -> "RequestSlots":
        return cls(max_inflight=getattr(cfg, "api_max_inflight", 64),
                   control_reserve=getattr(cfg, "control_reserve", 64),
                   enabled=getattr(cfg, "enabled", True))

    def acquire(self, klass: str = CLASS_DATA) -> Tuple[bool, str]:
        if not self.enabled:
            with self._lock:
                self._inflight += 1
            return True, ""
        bound = self.max_inflight + (self.control_reserve
                                     if klass == CLASS_CONTROL else 0)
        with self._lock:
            if self._inflight >= bound:
                shed = True
            else:
                shed = False
                self._inflight += 1
        if shed:
            count_shed("api", klass, SHED_QUEUE_FULL)
            return False, SHED_QUEUE_FULL
        METRICS.inc(ADMISSION_ADMITTED,
                    labels={"surface": "api", "class": klass})
        return True, ""

    def release(self) -> None:
        with self._lock:
            if self._inflight > 0:
                self._inflight -= 1

    @property
    def inflight(self) -> int:
        with self._lock:
            return self._inflight
