"""Metrics + spanstat.

Counter/gauge/histogram registry with Prometheus text exposition, plus
``SpanStat`` duration spans (reference: ``pkg/metrics``,
``pkg/spanstat`` — SURVEY.md §5.5). Key series mirror the reference's:
``policy_regeneration_time_stats_seconds`` → compile spans;
``drop_count_total`` / ``policy_l7_total`` → verdict counters.
"""

from __future__ import annotations

import threading
import time
from collections import defaultdict
from typing import Dict, List, Optional, Tuple

# -- degraded-operation series (runtime/faults.py + the TPU→oracle
# circuit breaker in runtime/service.py). Named here so the emitting
# seams, the chaos suite, and dashboards agree on one spelling.
#: breaker CLOSED→OPEN transitions (N consecutive device failures)
BREAKER_TRIPS = "cilium_tpu_breaker_trips_total"
#: breaker HALF_OPEN→CLOSED transitions (a probe succeeded)
BREAKER_RECOVERIES = "cilium_tpu_breaker_recoveries_total"
#: verdicts served by the CPU oracle because the device lane was
#: tripped or the dispatch failed (correct-but-slower path)
BREAKER_FALLBACK_VERDICTS = "cilium_tpu_breaker_fallback_verdicts_total"
#: gauge: 0 = CLOSED (device serving), 1 = OPEN (oracle serving),
#: 2 = HALF_OPEN (probe in flight)
BREAKER_STATE = "cilium_tpu_breaker_state"
#: faults fired by an armed FaultPlan, labelled by injection point
FAULTS_INJECTED = "cilium_tpu_faults_injected_total"
#: regenerations rolled back mid-swap (previous table kept serving)
LOADER_ROLLBACKS = "cilium_tpu_loader_swap_rollbacks_total"
#: stream-client reconnect attempts that re-established the session
STREAM_RECONNECTS = "cilium_tpu_stream_reconnects_total"
#: watch callbacks that raised and were isolated (kvstore.py)
KVSTORE_WATCH_ERRORS = "cilium_tpu_kvstore_watch_errors_total"
#: banked-DFA DNS batch failures degraded to the CPU regex path
DNSPROXY_FALLBACKS = "cilium_tpu_dnsproxy_fallback_total"


class Metrics:
    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._counters: Dict[Tuple[str, Tuple], float] = defaultdict(float)
        self._gauges: Dict[Tuple[str, Tuple], float] = {}
        self._histos: Dict[Tuple[str, Tuple], List[float]] = defaultdict(list)

    @staticmethod
    def _key(name: str, labels: Optional[Dict[str, str]]):
        return (name, tuple(sorted((labels or {}).items())))

    def inc(self, name: str, value: float = 1.0,
            labels: Optional[Dict[str, str]] = None) -> None:
        with self._lock:
            self._counters[self._key(name, labels)] += value

    def set_gauge(self, name: str, value: float,
                  labels: Optional[Dict[str, str]] = None) -> None:
        with self._lock:
            self._gauges[self._key(name, labels)] = value

    def observe(self, name: str, value: float,
                labels: Optional[Dict[str, str]] = None) -> None:
        with self._lock:
            self._histos[self._key(name, labels)].append(value)

    def histo_sum(self, name: str,
                  labels: Optional[Dict[str, str]] = None) -> float:
        """Locked sum of a histogram's samples (phase-attribution
        deltas and similar read-side consumers)."""
        with self._lock:
            return float(sum(self._histos.get(
                self._key(name, labels), ())))

    def get(self, name: str, labels: Optional[Dict[str, str]] = None) -> float:
        with self._lock:
            k = self._key(name, labels)
            if k in self._counters:
                return self._counters[k]
            return self._gauges.get(k, 0.0)

    def quantile(self, name: str, q: float,
                 labels: Optional[Dict[str, str]] = None) -> float:
        with self._lock:
            vals = sorted(self._histos.get(self._key(name, labels), ()))
        if not vals:
            return 0.0
        idx = min(len(vals) - 1, int(q * len(vals)))
        return vals[idx]

    def expose(self) -> str:
        """Prometheus text format."""
        out = []
        with self._lock:
            for (name, labels), v in sorted(self._counters.items()):
                out.append(f"{_fmt(name, labels)} {v}")
            for (name, labels), v in sorted(self._gauges.items()):
                out.append(f"{_fmt(name, labels)} {v}")
            for (name, labels), vals in sorted(self._histos.items()):
                if vals:
                    out.append(f"{_fmt(name + '_count', labels)} {len(vals)}")
                    out.append(f"{_fmt(name + '_sum', labels)} {sum(vals)}")
        return "\n".join(out) + "\n"


def _fmt(name: str, labels: Tuple) -> str:
    if not labels:
        return name
    inner = ",".join(f'{k}="{v}"' for k, v in labels)
    return f"{name}{{{inner}}}"


#: process-global registry (like the reference's default registry)
METRICS = Metrics()


class SpanStat:
    """Duration span: ``with SpanStat("compile"): ...`` records seconds
    into the global histogram ``cilium_tpu_span_seconds{span=...}``."""

    def __init__(self, span: str, metrics: Metrics = METRICS):
        self.span = span
        self.metrics = metrics
        self.seconds = 0.0

    def __enter__(self) -> "SpanStat":
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc) -> None:
        self.seconds = time.perf_counter() - self._t0
        self.metrics.observe("cilium_tpu_span_seconds", self.seconds,
                             {"span": self.span})
