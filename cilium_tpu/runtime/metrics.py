"""Metrics + spanstat.

Counter/gauge/histogram registry with Prometheus text exposition, plus
``SpanStat`` duration spans (reference: ``pkg/metrics``,
``pkg/spanstat`` — SURVEY.md §5.5). Key series mirror the reference's:
``policy_regeneration_time_stats_seconds`` → compile spans;
``drop_count_total`` / ``policy_l7_total`` → verdict counters.

Histograms are FIXED-BUCKET (cumulative ``_bucket{le=...}`` series +
``_count``/``_sum``), not sample lists: a long-running agent must hold
constant memory per series. A small bounded reservoir of the most
recent observations is retained per series so :meth:`Metrics.quantile`
(benches, tests) still answers over the recent window. Exposition is
valid Prometheus text format (``# HELP``/``# TYPE`` per family, label
values escaped) — :func:`lint_exposition` is the scrape-lint the
``make obs`` lane runs against the live registry.
"""

from __future__ import annotations

import bisect
import re
import threading
import time
from collections import deque
from typing import Dict, List, Optional, Tuple

# -- degraded-operation series (runtime/faults.py + the TPU→oracle
# circuit breaker in runtime/service.py). Named here so the emitting
# seams, the chaos suite, and dashboards agree on one spelling.
#: breaker CLOSED→OPEN transitions (N consecutive device failures)
BREAKER_TRIPS = "cilium_tpu_breaker_trips_total"
#: breaker HALF_OPEN→CLOSED transitions (a probe succeeded)
BREAKER_RECOVERIES = "cilium_tpu_breaker_recoveries_total"
#: verdicts served by the CPU oracle because the device lane was
#: tripped or the dispatch failed (correct-but-slower path)
BREAKER_FALLBACK_VERDICTS = "cilium_tpu_breaker_fallback_verdicts_total"
#: gauge: 0 = CLOSED (device serving), 1 = OPEN (oracle serving),
#: 2 = HALF_OPEN (probe in flight)
BREAKER_STATE = "cilium_tpu_breaker_state"
#: faults fired by an armed FaultPlan, labelled by injection point
FAULTS_INJECTED = "cilium_tpu_faults_injected_total"
#: regenerations rolled back mid-swap (previous table kept serving)
LOADER_ROLLBACKS = "cilium_tpu_loader_swap_rollbacks_total"
#: stream-client reconnect attempts that re-established the session
STREAM_RECONNECTS = "cilium_tpu_stream_reconnects_total"
#: watch callbacks that raised and were isolated (kvstore.py)
KVSTORE_WATCH_ERRORS = "cilium_tpu_kvstore_watch_errors_total"
#: banked-DFA DNS batch failures degraded to the CPU regex path
DNSPROXY_FALLBACKS = "cilium_tpu_dnsproxy_fallback_total"
#: spans recorded by the flight recorder (runtime/tracing.py),
#: labelled by phase — the aggregate face of per-request attribution
TRACE_SPANS = "cilium_tpu_trace_spans_total"

# -- overload-resilience series (runtime/admission.py + the drain /
# warm-restart sequence in runtime/service.py + runtime/loader.py).
#: requests admitted past the gate, by surface (service/api) + class
ADMISSION_ADMITTED = "cilium_tpu_admission_admitted_total"
#: requests shed at (or behind) the gate, by surface/class/reason
ADMISSION_SHED = "cilium_tpu_admission_shed_total"
#: queued entries dropped before dispatch: caller abandoned (timed
#: out) or deadline expired while queued
ADMISSION_REAPED = "cilium_tpu_admission_reaped_total"
#: gauge: verdict-queue occupancy sampled at each admission decision
ADMISSION_QUEUE_DEPTH = "cilium_tpu_admission_queue_depth"
#: graceful drains completed (admission stopped, pending flushed)
DRAINS = "cilium_tpu_drains_total"
#: loader restorations from a warm-restart snapshot (no recompile)
WARM_RESTORES = "cilium_tpu_warm_restores_total"
#: corrupt artifact-cache entries deleted on read (recompile follows)
ARTIFACT_CACHE_CORRUPT = "cilium_tpu_artifact_cache_corrupt_total"
#: stream-client sends that blocked at zero credit (backpressure)
STREAM_CREDIT_WAITS = "cilium_tpu_stream_credit_waits_total"
#: credit grants sent by stream servers (one per answered chunk)
STREAM_CREDITS_GRANTED = "cilium_tpu_stream_credits_granted_total"

# -- perf-ledger series (device-time attribution + collective
# accounting: engine/phases.py, engine/verdict.py capture staging,
# parallel/collectives.py). Named here so the probes, the benches,
# and the obs-doc-parity lint agree on one spelling.
#: per-phase seconds from the engine phase probe (mapstate / dfa-scan
#: / resolve / gather / h2d / featurize / compile / execute)
ENGINE_PHASE_SECONDS = "cilium_tpu_engine_phase_seconds"
#: intentional host↔device sync points executed, by site — the phase
#: probes' completion-forcing readbacks. Every OTHER sync on the hot
#: path is a ctlint `implicit-sync` finding (docs/ANALYSIS.md v4);
#: this family makes the allowlisted remainder observable at runtime.
ENGINE_HOST_SYNCS = "cilium_tpu_engine_host_syncs_total"
#: capture-replay session staging, split by phase (tables / featurize
#: / dedup / table-h2d) — the 12.5s ``stage_ms`` decomposed
CAPTURE_STAGE_SECONDS = "cilium_tpu_capture_stage_seconds"
#: collective ops recorded by the trace-time ledger, by site/op/axis
#: (counts are per compiled block execution — see parallel/collectives)
COLLECTIVE_OPS = "cilium_tpu_collective_ops_total"
#: bytes moved by those collectives (as-traced payload shapes)
COLLECTIVE_BYTES = "cilium_tpu_collective_bytes_total"

# -- verdict-memo series (engine/memo.py: the device-resident verdict
# memo behind capture/stream replay — hits are chunk rows served by
# the on-device gather, misses are unique rows verdicted and
# inserted, invalidations are memo drops with a reason label
# (policy-swap / auth-change / session-reset, plus the bank-scoped
# partial drops: bank-swap)).
VERDICT_MEMO_HITS = "cilium_tpu_verdict_memo_hits_total"
VERDICT_MEMO_MISSES = "cilium_tpu_verdict_memo_misses_total"
VERDICT_MEMO_INVALIDATIONS = "cilium_tpu_verdict_memo_invalidations_total"

# -- churn-proof policy plane (policy/compiler/bankplan.py +
# runtime/loader.py): content-addressed automaton banks, per-bank
# quarantine, and the O(Δ) incremental-compile ledger.
#: bank groups compiled (a cache miss in the content-addressed
#: registry), by field — O(Δ) under churn is THE property
BANK_REBUILDS = "cilium_tpu_bank_rebuilds_total"
#: bank groups quarantined after a compile failure (old cover keeps
#: serving; TTL-retried), by field
BANK_QUARANTINED = "cilium_tpu_bank_quarantined_total"
#: bank groups hot-swapped into the serving plan by a committed
#: revision (new content-addressed key), by field
BANK_HOTSWAPS = "cilium_tpu_bank_hotswaps_total"

# -- fleet-scale compile plane (policy/compiler/compilequeue.py +
# runtime/checkpoint.py bank artifacts + the sharded registry/
# fingerprint stores): the parallel bank-compile work queue's
# lifecycle ledger, the artifact-distribution fetch results, and the
# byte-bound eviction counters.
#: compile tasks submitted to the work queue, by priority class
#: (serving = delta compiles blocking a regeneration; background =
#: proactive quarantine-TTL rebuilds)
COMPILE_QUEUE_SUBMITTED = "cilium_tpu_compile_queue_submitted_total"
#: submits coalesced onto an in-flight task with the same work key
#: (content-addressed dedup: N racing compilers, one compile)
COMPILE_QUEUE_DEDUP = "cilium_tpu_compile_queue_dedup_total"
#: tasks completed (success or permanent failure)
COMPILE_QUEUE_COMPLETED = "cilium_tpu_compile_queue_completed_total"
#: in-queue retries (worker death re-queues with backoff)
COMPILE_QUEUE_RETRIES = "cilium_tpu_compile_queue_retries_total"
#: serving-blocking waits that hit the per-bank compile deadline
#: (the bank serves its cover; the compile finishes in background)
COMPILE_DEADLINE_LAPSES = "cilium_tpu_compile_deadline_lapses_total"
#: worker threads killed by the ``compile.worker`` fault point (or a
#: crash in the pool machinery); the pool respawns
COMPILE_WORKER_DEATHS = "cilium_tpu_compile_worker_deaths_total"
#: gauge: pending + running compile tasks (bounded by
#: ``[compile] max_pending``)
COMPILE_QUEUE_DEPTH = "cilium_tpu_compile_queue_depth"
#: compile results that landed AFTER their serving-blocking waiter
#: lapsed (stored for the next regeneration — work never wasted)
COMPILE_LATE_RESULTS = "cilium_tpu_compile_late_results_total"
#: banks served from their last-good cover while their compile was
#: still PENDING in the queue (deadline lapse, not quarantine)
BANK_PENDING_SERVES = "cilium_tpu_bank_pending_serves_total"
#: compiled-bank artifact fetches, by result (hit / miss / corrupt —
#: a corrupt or faulted fetch degrades to recompile, never a crash)
BANK_ARTIFACT_FETCHES = "cilium_tpu_bank_artifact_fetches_total"
#: bank groups evicted from the byte-bounded registry shards
REGISTRY_SHARD_EVICTIONS = "cilium_tpu_registry_shard_evictions_total"
#: identity-fingerprint bundles evicted from the sharded store
#: (recomputed on next regeneration — cost, never correctness)
FP_CACHE_EVICTIONS = "cilium_tpu_fp_cache_evictions_total"
#: on-disk artifact-cache entries evicted by the byte-bound LRU
#: (the serving artifact and warm snapshot are protected)
ARTIFACT_CACHE_EVICTIONS = "cilium_tpu_artifact_cache_evictions_total"

# -- continuously-batched serving loop (runtime/serveloop.py +
# engine/ring.py): persistent verdict ring, stream slot leases, and
# the memo-bypass selective-copy accounting.
#: gauge: stream slots currently leased in the verdict ring
SERVE_RING_OCCUPANCY = "cilium_tpu_serve_ring_occupancy"
#: slot leases granted (one per admitted stream; a reconnect-with-
#: resume that finds its lease alive does NOT grant again)
SERVE_LEASE_GRANTS = "cilium_tpu_serve_lease_grants_total"
#: leases expired by TTL (no activity renewed them in time)
SERVE_LEASE_EXPIRIES = "cilium_tpu_serve_lease_expiries_total"
#: leases released cleanly (stream end / drain)
SERVE_LEASE_RELEASES = "cilium_tpu_serve_lease_releases_total"
#: H2D bytes that never crossed because the row was already ring-
#: resident (memo/dedup hit): featurized row bytes avoided minus the
#: 4-byte id actually shipped — the Libra selective-copy claim, as a
#: counter
SERVE_MEMO_BYPASS_BYTES = "cilium_tpu_serve_memo_bypass_bytes_total"
#: records per pack-cycle fused dispatch
SERVE_PACK_RECORDS = "cilium_tpu_serve_pack_records"
#: distinct streams contributing to one pack-cycle dispatch
SERVE_PACK_STREAMS = "cilium_tpu_serve_pack_streams"
#: submit→verdict latency through the serving loop (seconds, on the
#: installed clock — virtual under the DST load model)
SERVE_LATENCY = "cilium_tpu_serve_latency_seconds"
#: wall seconds one pack cycle spent in the fused dispatch (encode
#: excluded — submit-side host work is attributed to the submitter)
SERVE_PACK_DISPATCH_SECONDS = "cilium_tpu_serve_pack_dispatch_seconds"
#: leased-slot occupancy sampled once per pack cycle (the histogram
#: face of the occupancy gauge: burn-rate math wants distributions)
SERVE_PACK_OCCUPANCY = "cilium_tpu_serve_pack_occupancy"

# -- verdict provenance & SLO telemetry (engine/attribution.py,
# runtime/explain.py, runtime/slo.py)
#: gauge: error-budget burn rate per declared SLO and trailing
#: window ({slo="serve-p99"|"serve-shed", window="300s"|...}); 1.0 =
#: spending budget exactly as declared
SLO_BURN_RATE = "cilium_tpu_slo_burn_rate"
#: verdicts that passed through provenance recording, by result
#: (explained / unexplained) — the explanation-coverage numerator and
#: denominator the serve-soak gate holds ≥0.999
PROVENANCE_RECORDS = "cilium_tpu_provenance_records_total"
#: explain-plane queries (/v1/explain, the explain op/CLI), by result
#: (hit / miss)
EXPLAIN_QUERIES = "cilium_tpu_explain_queries_total"

# -- serving fleet (runtime/fleetserve.py): stream-affinity routing,
# host-death failover, and the fleet-coherent shedding ledger.
#: stream leases migrated off a dead/partitioned host and re-granted
#: on a survivor (one per stream that moved)
FLEET_HANDOFFS = "cilium_tpu_fleet_handoffs_total"
#: hosts declared dead by the suspicion state machine (missed
#: heartbeats past the TTL) or killed outright
FLEET_HOST_DEATHS = "cilium_tpu_fleet_host_deaths_total"
#: dead hosts warm-restored back into the placement ring
FLEET_REJOINS = "cilium_tpu_fleet_rejoins_total"
#: new streams the router placed AWAY from their rendezvous owner
#: because the owner was past its spill headroom
FLEET_SPILLED_STREAMS = "cilium_tpu_fleet_spilled_streams_total"
#: gauge: leased-slot occupancy per host, by host — the occupancy
#: digest the fleet-coherent shed/spill decision reads
FLEET_HOST_OCCUPANCY = "cilium_tpu_fleet_host_occupancy"

# -- fleet observability plane (runtime/fleetserve.py + hubble/
# flowagg.py): cross-host trace stitching, continuous flow export,
# fleet SLO roll-ups, and the fleet event journal.
#: gauge: fleet-wide burn-rate roll-up over the per-replica SLO
#: trackers, by slo, trailing window, and view (``worst`` = the worst
#: single host; ``weighted`` = fleet-weighted by request volume)
FLEET_SLO_BURN_RATE = "cilium_tpu_fleet_slo_burn_rate"
#: failover latency per handoff, by stage: ``death-to-regrant``
#: (death declared → lease re-granted on a survivor),
#: ``regrant-to-verdict`` (re-grant → first verdict after replay),
#: ``death-to-verdict`` (the end-to-end client-visible gap)
FLEET_FAILOVER_SECONDS = "cilium_tpu_fleet_failover_seconds"
#: fleet event-journal entries appended, by kind (the journal's
#: catalog is machine-checked against OBSERVABILITY.md)
FLEET_JOURNAL_EVENTS = "cilium_tpu_fleet_journal_events_total"
#: handoff-replayed chunks that resolved with a STITCHED trace — one
#: trace id spanning spans from both the dead host and the survivor
FLEET_TRACE_STITCHES = "cilium_tpu_fleet_trace_stitches_total"
#: provenance-stamped flow records fed into the per-host Hubble
#: FlowAggregator off the serve resolve path, by host
HUBBLE_FLOW_RECORDS = "cilium_tpu_hubble_flow_records_total"
#: flow aggregation keys dropped at the aggregator's bound (the
#: overflow counter that keeps the export honest about sampling)
HUBBLE_FLOW_OVERFLOW = "cilium_tpu_hubble_flow_overflow_total"

# -- multi-tenant control plane & policy canary (runtime/tenant.py,
# runtime/canary.py): per-tenant fairness attribution and the
# shadow-rollout verdict-diff gate.
#: per-tenant quota-store reads, by result (``live`` = an unexpired
#: declared share, ``lapsed`` = TTL expiry fell back to the
#: conservative default, ``fault-default`` = the ``tenant.quota``
#: read was lost and the conservative default applied)
TENANT_QUOTA_READS = "cilium_tpu_tenant_quota_reads_total"
#: canary double-dispatch samples, by result (``match`` / ``diff``)
CANARY_SAMPLES = "cilium_tpu_canary_samples_total"
#: canary commit attempts, by result (``committed`` / ``refused`` /
#: ``aborted``)
CANARY_COMMITS = "cilium_tpu_canary_commits_total"
#: gauge: observed verdict-diff fraction of the active canary
CANARY_DIFF_FRACTION = "cilium_tpu_canary_diff_fraction"

# -- megakernel scan autotuner (engine/megakernel.py): dense-DFA vs
# bitset-NFA measured per bank shape at engine staging
#: autotuner decisions, by winning impl and field (cache misses only —
#: a shape-key hit re-serves the recorded pick without re-benching)
KERNEL_AUTOTUNE_PICKS = "cilium_tpu_kernel_autotune_picks_total"
#: wall seconds spent measuring one bank shape (both arms)
KERNEL_AUTOTUNE_SECONDS = "cilium_tpu_kernel_autotune_seconds"

#: latency-shaped default boundaries (seconds; the Prometheus client
#: defaults) — covers every ``*_seconds`` series we emit
DEFAULT_BUCKETS: Tuple[float, ...] = (
    0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
    0.25, 0.5, 1.0, 2.5, 5.0, 10.0)
#: count-shaped boundaries (batch sizes, record counts): pow2, matching
#: the engine's pow2 padding buckets
SIZE_BUCKETS: Tuple[float, ...] = tuple(
    float(1 << i) for i in range(15))  # 1 .. 16384
#: most recent observations retained per series for quantile()
RESERVOIR = 1024


class _Histogram:
    """One series: cumulative fixed buckets + count/sum + a bounded
    reservoir of recent samples (quantile's window)."""

    __slots__ = ("buckets", "counts", "count", "sum", "reservoir")

    def __init__(self, buckets: Tuple[float, ...]):
        self.buckets = buckets
        self.counts = [0] * (len(buckets) + 1)  # +1: the +Inf bucket
        self.count = 0
        self.sum = 0.0
        self.reservoir: deque = deque(maxlen=RESERVOIR)

    def observe(self, value: float) -> None:
        self.counts[bisect.bisect_left(self.buckets, value)] += 1
        self.count += 1
        self.sum += value
        self.reservoir.append(value)


class Metrics:
    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._counters: Dict[Tuple[str, Tuple], float] = {}
        self._gauges: Dict[Tuple[str, Tuple], float] = {}
        self._histos: Dict[Tuple[str, Tuple], _Histogram] = {}
        #: name → explicit bucket boundaries (else shape heuristic)
        self._buckets: Dict[str, Tuple[float, ...]] = {}
        self._help: Dict[str, str] = {}

    @staticmethod
    def _key(name: str, labels: Optional[Dict[str, str]]):
        return (name, tuple(sorted((labels or {}).items())))

    def describe(self, name: str, help_text: str,
                 buckets: Optional[Tuple[float, ...]] = None) -> None:
        """Register HELP text (and, for histograms, explicit bucket
        boundaries) for a metric family."""
        with self._lock:
            # ctlint: disable=unbounded-registry  # bounded by declared metric families (metric-registry enforces the catalog)
            self._help[name] = help_text
            if buckets is not None:
                # ctlint: disable=unbounded-registry  # one entry per declared histogram family
                self._buckets[name] = tuple(sorted(float(b)
                                                   for b in buckets))

    def _buckets_for(self, name: str) -> Tuple[float, ...]:
        explicit = self._buckets.get(name)
        if explicit is not None:
            return explicit
        # shape heuristic: count-valued series get pow2 boundaries,
        # everything else is latency-shaped seconds
        if name.endswith(("_size", "_records", "_bytes")):
            return SIZE_BUCKETS
        return DEFAULT_BUCKETS

    def inc(self, name: str, value: float = 1.0,
            labels: Optional[Dict[str, str]] = None) -> None:
        k = self._key(name, labels)
        with self._lock:
            # ctlint: disable=unbounded-registry  # keyed by declared family x finite label enums
            self._counters[k] = self._counters.get(k, 0.0) + value

    def set_gauge(self, name: str, value: float,
                  labels: Optional[Dict[str, str]] = None) -> None:
        with self._lock:
            # ctlint: disable=unbounded-registry  # keyed by declared family x finite label enums
            self._gauges[self._key(name, labels)] = value

    def observe(self, name: str, value: float,
                labels: Optional[Dict[str, str]] = None) -> None:
        k = self._key(name, labels)
        with self._lock:
            h = self._histos.get(k)
            if h is None:
                h = self._histos[k] = _Histogram(self._buckets_for(name))
            h.observe(value)

    def histo_sum(self, name: str,
                  labels: Optional[Dict[str, str]] = None) -> float:
        """Locked cumulative sum of a histogram series (phase-attribution
        deltas and similar read-side consumers)."""
        with self._lock:
            h = self._histos.get(self._key(name, labels))
            return float(h.sum) if h is not None else 0.0

    def histo_count(self, name: str,
                    labels: Optional[Dict[str, str]] = None) -> int:
        """Cumulative observation count — monotone, so callers can use
        it as a mark for :meth:`samples_since`."""
        with self._lock:
            h = self._histos.get(self._key(name, labels))
            return int(h.count) if h is not None else 0

    def samples_since(self, name: str, mark: int,
                      labels: Optional[Dict[str, str]] = None
                      ) -> List[float]:
        """Observations recorded after ``mark`` (a prior
        :meth:`histo_count`), served from the bounded reservoir —
        truncated to the newest :data:`RESERVOIR` if more arrived."""
        with self._lock:
            h = self._histos.get(self._key(name, labels))
            if h is None:
                return []
            newer = h.count - mark
            if newer <= 0:
                return []
            return list(h.reservoir)[-min(newer, len(h.reservoir)):]

    def get(self, name: str, labels: Optional[Dict[str, str]] = None) -> float:
        with self._lock:
            k = self._key(name, labels)
            if k in self._counters:
                return self._counters[k]
            return self._gauges.get(k, 0.0)

    def quantile(self, name: str, q: float,
                 labels: Optional[Dict[str, str]] = None) -> float:
        """Quantile over the series' recent-sample reservoir (the
        bench/test face; dashboards use the bucket series)."""
        with self._lock:
            h = self._histos.get(self._key(name, labels))
            vals = sorted(h.reservoir) if h is not None else []
        if not vals:
            return 0.0
        idx = min(len(vals) - 1, int(q * len(vals)))
        return vals[idx]

    def expose(self) -> str:
        """Valid Prometheus text format: one ``# HELP``/``# TYPE`` pair
        per family, escaped label values, cumulative ``_bucket{le=...}``
        series (ending ``+Inf``) plus ``_count``/``_sum`` per
        histogram series."""
        out: List[str] = []
        with self._lock:
            counters = sorted(self._counters.items())
            gauges = sorted(self._gauges.items())
            histos = sorted(self._histos.items(),
                            key=lambda kv: kv[0])
            help_texts = dict(self._help)

        def _family(name: str, typ: str) -> None:
            help_text = help_texts.get(
                name, f"cilium_tpu {typ} {name}")
            out.append(f"# HELP {name} {_escape_help(help_text)}")
            out.append(f"# TYPE {name} {typ}")

        last = None
        for (name, labels), v in counters:
            if name != last:
                _family(name, "counter")
                last = name
            out.append(f"{_fmt(name, labels)} {_num(v)}")
        last = None
        for (name, labels), v in gauges:
            if name != last:
                _family(name, "gauge")
                last = name
            out.append(f"{_fmt(name, labels)} {_num(v)}")
        last = None
        for (name, labels), h in histos:
            if name != last:
                _family(name, "histogram")
                last = name
            cum = 0
            for bound, n in zip(h.buckets, h.counts):
                cum += n
                out.append(_fmt(name + "_bucket",
                                labels + (("le", _num(bound)),))
                           + f" {cum}")
            out.append(_fmt(name + "_bucket", labels + (("le", "+Inf"),))
                       + f" {h.count}")
            out.append(f"{_fmt(name + '_count', labels)} {h.count}")
            out.append(f"{_fmt(name + '_sum', labels)} {_num(h.sum)}")
        return "\n".join(out) + "\n"


def _num(v: float) -> str:
    """Canonical number rendering: integers without a trailing .0 (the
    Prometheus text convention for counts/bounds)."""
    f = float(v)
    return str(int(f)) if f.is_integer() and abs(f) < 1e15 else repr(f)


def _escape_label(v: str) -> str:
    return (str(v).replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


def _escape_help(v: str) -> str:
    return str(v).replace("\\", "\\\\").replace("\n", "\\n")


def _fmt(name: str, labels: Tuple) -> str:
    if not labels:
        return name
    inner = ",".join(f'{k}="{_escape_label(v)}"' for k, v in labels)
    return f"{name}{{{inner}}}"


# -- scrape lint ------------------------------------------------------------

_NAME_RE = re.compile(r"[a-zA-Z_:][a-zA-Z0-9_:]*")
_SAMPLE_RE = re.compile(
    r"^(?P<name>[a-zA-Z_:][a-zA-Z0-9_:]*)"
    r"(?P<labels>\{[^{}]*\})?"
    r" (?P<value>[+-]?(?:\d+\.?\d*(?:[eE][+-]?\d+)?|Inf|NaN))"
    r"(?: (?P<ts>[+-]?\d+))?$")
_LABEL_RE = re.compile(
    r'(?P<key>[a-zA-Z_][a-zA-Z0-9_]*)='
    r'"(?P<val>(?:[^"\\\n]|\\["\\n])*)"')


def lint_exposition(text: str) -> List[str]:
    """Parse Prometheus text exposition line-by-line; return a list of
    error strings (empty = clean). Checks: comment shape, sample-line
    grammar, label quoting/escaping, TYPE declared before a family's
    samples, histogram buckets cumulative and +Inf-terminated with
    ``_count`` equal to the +Inf bucket."""
    errors: List[str] = []
    typed: Dict[str, str] = {}
    buckets_seen: Dict[Tuple[str, Tuple], List[Tuple[str, int]]] = {}
    counts_seen: Dict[Tuple[str, Tuple], int] = {}
    for i, line in enumerate(text.splitlines(), 1):
        if not line:
            continue
        if line.startswith("#"):
            parts = line.split(" ", 3)
            if len(parts) < 4 or parts[1] not in ("HELP", "TYPE") \
                    or not _NAME_RE.fullmatch(parts[2]):
                errors.append(f"line {i}: malformed comment: {line!r}")
            elif parts[1] == "TYPE":
                if parts[3] not in ("counter", "gauge", "histogram",
                                    "summary", "untyped"):
                    errors.append(f"line {i}: unknown type {parts[3]!r}")
                typed[parts[2]] = parts[3]
            continue
        m = _SAMPLE_RE.match(line)
        if m is None:
            errors.append(f"line {i}: malformed sample: {line!r}")
            continue
        name = m.group("name")
        raw_labels = m.group("labels")
        labels: List[Tuple[str, str]] = []
        if raw_labels:
            body = raw_labels[1:-1]
            consumed = 0
            for lm in _LABEL_RE.finditer(body):
                labels.append((lm.group("key"), lm.group("val")))
                consumed = lm.end()
                if consumed < len(body) and body[consumed] == ",":
                    consumed += 1
            if consumed != len(body):
                errors.append(
                    f"line {i}: malformed labels: {raw_labels!r}")
        family = name
        for suffix in ("_bucket", "_count", "_sum"):
            base = name[:-len(suffix)] if name.endswith(suffix) else None
            if base and typed.get(base) == "histogram":
                family = base
                break
        if family not in typed:
            errors.append(f"line {i}: sample {name!r} has no # TYPE")
            continue
        if typed[family] == "histogram" and name == family + "_bucket":
            le = dict(labels).get("le")
            if le is None:
                errors.append(f"line {i}: bucket without le label")
                continue
            key = (family, tuple(sorted(
                (k, v) for k, v in labels if k != "le")))
            buckets_seen.setdefault(key, []).append(
                (le, int(float(m.group("value")))))
        if typed.get(family) == "histogram" and name == family + "_count":
            counts_seen[(family, tuple(sorted(labels)))] = \
                int(float(m.group("value")))
    for (family, labels), series in buckets_seen.items():
        values = [v for _, v in series]
        if values != sorted(values):
            errors.append(
                f"{family}{dict(labels)}: buckets not cumulative")
        if series[-1][0] != "+Inf":
            errors.append(f"{family}{dict(labels)}: missing +Inf bucket")
        else:
            total = counts_seen.get((family, labels))
            if total is not None and total != series[-1][1]:
                errors.append(
                    f"{family}{dict(labels)}: _count {total} != "
                    f"+Inf bucket {series[-1][1]}")
    return errors


#: process-global registry (like the reference's default registry)
METRICS = Metrics()
METRICS.describe("cilium_tpu_microbatch_size",
                 "records per MicroBatcher flush", buckets=SIZE_BUCKETS)
METRICS.describe("cilium_tpu_microbatch_seconds",
                 "MicroBatcher flush wall seconds")
METRICS.describe("cilium_tpu_span_seconds",
                 "SpanStat duration spans, labelled by span")
METRICS.describe(BREAKER_STATE,
                 "0=closed (device), 1=open (oracle), 2=half-open")
METRICS.describe(TRACE_SPANS,
                 "flight-recorder spans recorded, by phase")

# -- family catalog ---------------------------------------------------------
# ctlint (analysis/registry.py, rule metric-registry) requires every
# family written anywhere in the package to be declared here exactly
# once: the declaration is what turns a typo'd producer name into a
# lint error instead of a silently-dead series, and it gives every
# exposed family real # HELP text.
METRICS.describe(BREAKER_TRIPS,
                 "breaker CLOSED->OPEN transitions")
METRICS.describe(BREAKER_RECOVERIES,
                 "breaker HALF_OPEN->CLOSED transitions")
METRICS.describe(BREAKER_FALLBACK_VERDICTS,
                 "verdicts served by the CPU oracle while degraded")
METRICS.describe(FAULTS_INJECTED,
                 "faults fired by an armed FaultPlan, by point")
METRICS.describe(LOADER_ROLLBACKS,
                 "regenerations rolled back mid-swap")
METRICS.describe(STREAM_RECONNECTS,
                 "stream-client reconnects that resumed the session")
METRICS.describe(KVSTORE_WATCH_ERRORS,
                 "kvstore watch callbacks that raised and were isolated")
METRICS.describe(DNSPROXY_FALLBACKS,
                 "banked-DFA DNS batches degraded to the regex path")
METRICS.describe("cilium_tpu_accesslog_decode_errors_total",
                 "undecodable access-log records")
METRICS.describe("cilium_tpu_accesslog_records_total",
                 "access-log records ingested, by proto")
METRICS.describe("cilium_tpu_api_requests_total",
                 "REST API requests served")
METRICS.describe("cilium_tpu_auth_pairs",
                 "mutual-auth pairs currently authenticated")
METRICS.describe("cilium_tpu_clustermesh_decode_errors_total",
                 "undecodable remote-cluster kvstore events")
METRICS.describe("cilium_tpu_clustermesh_ready",
                 "1 when the remote cluster's session is live")
METRICS.describe("cilium_tpu_compile_seconds",
                 "policy snapshot compile wall seconds",
                 buckets=(0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0,
                          30.0, 60.0, 120.0))
METRICS.describe("cilium_tpu_controller_runs_total",
                 "controller loop runs, by name and status")
METRICS.describe("cilium_tpu_endpoint_regenerations_total",
                 "per-endpoint regeneration completions, by status")
METRICS.describe("cilium_tpu_identity_regen_coalesced_total",
                 "identity-churn events absorbed by an already-armed "
                 "regeneration debounce window (storm size minus the "
                 "one regeneration that covered it)")
METRICS.describe("cilium_tpu_endpoints",
                 "endpoints currently managed")
METRICS.describe("cilium_tpu_endpoints_restored_total",
                 "endpoints restored from the state dir at startup")
METRICS.describe("cilium_tpu_frontend_rules",
                 "protocol-frontend rules in the serving compiled "
                 "policy, by proto (policy/compiler/frontends)")
METRICS.describe("cilium_tpu_fqdn_handler_errors_total",
                 "DNS proxy handler threads that raised")
METRICS.describe("cilium_tpu_fqdn_malformed_queries_total",
                 "DNS queries that failed wire parsing")
METRICS.describe("cilium_tpu_fqdn_queries_total",
                 "DNS proxy queries, by verdict")
METRICS.describe("cilium_tpu_fqdn_unknown_client_total",
                 "DNS queries from unmapped client addresses")
METRICS.describe("cilium_tpu_fqdn_upstream_timeouts_total",
                 "upstream DNS resolutions that timed out")
METRICS.describe("cilium_tpu_health_probe_seconds",
                 "node-to-node health probe latency, by peer")
METRICS.describe("cilium_tpu_health_reachable",
                 "1 when the peer's last health probe succeeded")
METRICS.describe("cilium_tpu_identities_cluster",
                 "identities known to the cluster-scope cache")
METRICS.describe("cilium_tpu_ipam_endpoints_outside_cidr",
                 "restored endpoints whose IP is outside the node CIDR")
METRICS.describe("cilium_tpu_ipam_ips_allocated",
                 "IPs currently allocated from the node CIDR")
METRICS.describe("cilium_tpu_ipam_node_cidrs",
                 "node CIDRs carved from the cluster pool")
METRICS.describe("cilium_tpu_k8s_cnp_parse_errors_total",
                 "CNP/CCNP objects that failed rule parsing")
METRICS.describe("cilium_tpu_lb_services",
                 "load-balancer services installed")
METRICS.describe("cilium_tpu_leader",
                 "1 while this process holds the named leader lock")
METRICS.describe("cilium_tpu_monitor_events_total",
                 "monitor socket events fanned out, by type")
METRICS.describe("cilium_tpu_npds_pulls_total",
                 "NPDS mapstate pulls served to shims")
METRICS.describe("cilium_tpu_operator_cidrs_quarantined_total",
                 "pod CIDRs quarantined pending release confirmation")
METRICS.describe("cilium_tpu_operator_cidrs_reclaimed_total",
                 "pod CIDRs reclaimed from departed nodes")
METRICS.describe("cilium_tpu_operator_identities_gc_total",
                 "kvstore identities garbage-collected")
METRICS.describe("cilium_tpu_operator_pool_exhausted_total",
                 "node CIDR requests refused: cluster pool exhausted")
METRICS.describe("cilium_tpu_policy_l7_total",
                 "L7 proxy policy checks, by proto and verdict")
METRICS.describe("cilium_tpu_policy_watch_ops_total",
                 "policy-directory watch operations applied")
METRICS.describe("cilium_tpu_policy_watch_parse_errors_total",
                 "policy-directory files that failed YAML parsing")
METRICS.describe("cilium_tpu_proxy_redirects",
                 "proxy redirects currently installed")
METRICS.describe("cilium_tpu_proxy_redirects_created_total",
                 "proxy redirects created")
METRICS.describe("cilium_tpu_proxy_redirects_released_total",
                 "proxy redirects released")
METRICS.describe("cilium_tpu_regenerations_total",
                 "policy snapshot regenerations committed, by backend")
METRICS.describe("cilium_tpu_service_verdicts_total",
                 "flows verdicted via the bulk service op")
METRICS.describe("cilium_tpu_stream_unknown_frames_total",
                 "stream frames dropped for an unknown kind")
METRICS.describe("cilium_tpu_stream_verdicts_total",
                 "verdicts returned over the chunked binary stream")
METRICS.describe(ADMISSION_ADMITTED,
                 "requests admitted past the gate, by surface/class")
METRICS.describe(ADMISSION_SHED,
                 "requests shed, by surface/class/reason")
METRICS.describe(ADMISSION_REAPED,
                 "queued entries dropped before dispatch (abandoned "
                 "caller or expired deadline)")
METRICS.describe(ADMISSION_QUEUE_DEPTH,
                 "verdict-queue occupancy at the admission decision")
METRICS.describe(DRAINS,
                 "graceful drains completed")
METRICS.describe(WARM_RESTORES,
                 "loader restorations from a warm-restart snapshot")
METRICS.describe(ARTIFACT_CACHE_CORRUPT,
                 "corrupt artifact-cache entries deleted on read")
METRICS.describe(STREAM_CREDIT_WAITS,
                 "stream-client sends that blocked at zero credit")
METRICS.describe(STREAM_CREDITS_GRANTED,
                 "credit grants sent by stream servers")
METRICS.describe(ENGINE_PHASE_SECONDS,
                 "engine phase probe seconds, by phase",
                 buckets=(1e-5, 5e-5, 1e-4, 5e-4, 0.001, 0.0025,
                          0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
                          1.0, 2.5))
METRICS.describe(ENGINE_HOST_SYNCS,
                 "intentional host-device sync points executed, by "
                 "site (phase-probe completion forcing)")
METRICS.describe(CAPTURE_STAGE_SECONDS,
                 "capture-replay session staging seconds, by phase",
                 buckets=(0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0,
                          2.5, 5.0, 10.0, 30.0, 60.0, 120.0))
METRICS.describe(COLLECTIVE_OPS,
                 "collective ops recorded at trace time, by "
                 "site/op/axis (count per compiled block)")
METRICS.describe(COLLECTIVE_BYTES,
                 "collective payload bytes (as-traced shapes), by "
                 "site/op/axis")
METRICS.describe(VERDICT_MEMO_HITS,
                 "replay rows served from the device verdict memo")
METRICS.describe(VERDICT_MEMO_MISSES,
                 "unique rows verdicted and inserted into the memo")
METRICS.describe(VERDICT_MEMO_INVALIDATIONS,
                 "verdict-memo drops, by reason (policy-swap / "
                 "auth-change / session-reset / bank-swap)")
METRICS.describe(BANK_REBUILDS,
                 "automaton bank groups compiled, by field")
METRICS.describe(BANK_QUARANTINED,
                 "bank groups quarantined after compile failure, "
                 "by field")
METRICS.describe(BANK_HOTSWAPS,
                 "bank groups hot-swapped by a committed revision, "
                 "by field")
METRICS.describe(COMPILE_QUEUE_SUBMITTED,
                 "bank-compile tasks submitted, by priority class "
                 "(serving / background)")
METRICS.describe(COMPILE_QUEUE_DEDUP,
                 "compile submits coalesced onto an in-flight task "
                 "with the same work key")
METRICS.describe(COMPILE_QUEUE_COMPLETED,
                 "compile tasks completed (success or permanent "
                 "failure)")
METRICS.describe(COMPILE_QUEUE_RETRIES,
                 "in-queue compile retries (worker-death backoff "
                 "re-queues)")
METRICS.describe(COMPILE_DEADLINE_LAPSES,
                 "serving-blocking compile waits that hit the "
                 "per-bank deadline (bank rides its cover)")
METRICS.describe(COMPILE_WORKER_DEATHS,
                 "compile worker threads that died mid-task (pool "
                 "respawns)")
METRICS.describe(COMPILE_QUEUE_DEPTH,
                 "pending + running compile tasks in the work queue")
METRICS.describe(COMPILE_LATE_RESULTS,
                 "compile results stored after their waiter's "
                 "deadline lapsed")
METRICS.describe(BANK_PENDING_SERVES,
                 "banks served from their last-good cover while "
                 "their compile was still pending")
METRICS.describe(BANK_ARTIFACT_FETCHES,
                 "compiled-bank artifact fetches, by result "
                 "(hit / miss / corrupt)")
METRICS.describe(REGISTRY_SHARD_EVICTIONS,
                 "bank groups evicted from the byte-bounded registry "
                 "shards")
METRICS.describe(FP_CACHE_EVICTIONS,
                 "identity-fingerprint bundles evicted from the "
                 "sharded store")
METRICS.describe(ARTIFACT_CACHE_EVICTIONS,
                 "artifact-cache entries evicted by the byte-bound "
                 "LRU (serving + warm keys protected)")
METRICS.describe(KERNEL_AUTOTUNE_PICKS,
                 "megakernel scan-impl autotune decisions, by impl "
                 "and field")
METRICS.describe(KERNEL_AUTOTUNE_SECONDS,
                 "seconds measuring dense vs bitset-NFA for one bank "
                 "shape")
METRICS.describe(SERVE_RING_OCCUPANCY,
                 "stream slots currently leased in the verdict ring")
METRICS.describe(SERVE_LEASE_GRANTS,
                 "verdict-ring slot leases granted")
METRICS.describe(SERVE_LEASE_EXPIRIES,
                 "slot leases expired by TTL without renewal")
METRICS.describe(SERVE_LEASE_RELEASES,
                 "slot leases released cleanly (stream end / drain)")
METRICS.describe(SERVE_MEMO_BYPASS_BYTES,
                 "H2D bytes saved by ring-resident rows (memo/dedup "
                 "hits ship a 4-byte id, not the featurized row)")
METRICS.describe(SERVE_PACK_RECORDS,
                 "records per pack-cycle fused dispatch",
                 buckets=SIZE_BUCKETS)
METRICS.describe(SERVE_PACK_STREAMS,
                 "distinct streams contributing to one pack-cycle "
                 "dispatch", buckets=SIZE_BUCKETS)
METRICS.describe(SERVE_LATENCY,
                 "submit-to-verdict latency through the serving loop "
                 "(installed-clock seconds)")
METRICS.describe(SERVE_PACK_DISPATCH_SECONDS,
                 "wall seconds per pack-cycle fused dispatch")
METRICS.describe(SERVE_PACK_OCCUPANCY,
                 "leased-slot occupancy sampled per pack cycle",
                 buckets=SIZE_BUCKETS)
METRICS.describe(SLO_BURN_RATE,
                 "error-budget burn rate, by slo and trailing window "
                 "(1.0 = spending budget exactly as declared)")
METRICS.describe(PROVENANCE_RECORDS,
                 "verdicts through provenance recording, by result "
                 "(explained / unexplained)")
METRICS.describe(EXPLAIN_QUERIES,
                 "explain-plane queries, by result (hit / miss)")
METRICS.describe(FLEET_HANDOFFS,
                 "stream leases migrated off a dead host and "
                 "re-granted on a survivor")
METRICS.describe(FLEET_HOST_DEATHS,
                 "hosts declared dead (missed heartbeats past the "
                 "suspicion TTL, or killed)")
METRICS.describe(FLEET_REJOINS,
                 "dead hosts warm-restored back into rotation")
METRICS.describe(FLEET_SPILLED_STREAMS,
                 "new streams placed away from their rendezvous "
                 "owner for headroom")
METRICS.describe(FLEET_HOST_OCCUPANCY,
                 "leased-slot occupancy per fleet host, by host")
METRICS.describe(FLEET_SLO_BURN_RATE,
                 "fleet burn-rate roll-up, by slo, window, and view "
                 "(worst single host / fleet-weighted)")
METRICS.describe(FLEET_FAILOVER_SECONDS,
                 "failover latency per handoff, by stage (death-to-"
                 "regrant / regrant-to-verdict / death-to-verdict)")
METRICS.describe(FLEET_JOURNAL_EVENTS,
                 "fleet event-journal entries appended, by kind")
METRICS.describe(FLEET_TRACE_STITCHES,
                 "handoff-replayed chunks resolved under a stitched "
                 "cross-host trace")
METRICS.describe(HUBBLE_FLOW_RECORDS,
                 "flow records fed into the per-host Hubble flow "
                 "aggregator, by host")
METRICS.describe(HUBBLE_FLOW_OVERFLOW,
                 "flow aggregation keys dropped at the aggregator's "
                 "key bound")
METRICS.describe(TENANT_QUOTA_READS,
                 "per-tenant quota-store reads, by result (live / "
                 "lapsed / fault-default)")
METRICS.describe(CANARY_SAMPLES,
                 "canary double-dispatch samples, by result "
                 "(match / diff)")
METRICS.describe(CANARY_COMMITS,
                 "canary commit attempts, by result (committed / "
                 "refused / aborted)")
METRICS.describe(CANARY_DIFF_FRACTION,
                 "observed verdict-diff fraction of the active "
                 "canary")


class SpanStat:
    """Duration span: ``with SpanStat("compile"): ...`` records seconds
    into the global histogram ``cilium_tpu_span_seconds{span=...}``."""

    def __init__(self, span: str, metrics: Metrics = METRICS):
        self.span = span
        self.metrics = metrics
        self.seconds = 0.0

    def __enter__(self) -> "SpanStat":
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc) -> None:
        self.seconds = time.perf_counter() - self._t0
        self.metrics.observe("cilium_tpu_span_seconds", self.seconds,
                             {"span": self.span})
