"""Live SLO telemetry: multi-window burn-rate gauges over declared
targets.

The serve loop's histograms say what latency IS; they cannot say
whether the service is EATING ITS ERROR BUDGET — the question an
operator pages on. This module implements the standard multi-window
burn-rate formulation over the targets declared in ``Config.slo``:

* ``serve-p99`` — the latency SLO: "99% of served chunks complete
  under ``serve_p99_ms``". A request over the target is a bad event;
  the error budget is 1%. Burn rate = observed bad fraction / 0.01.
* ``serve-shed`` — the availability SLO: "the shed rate stays under
  ``shed_rate``". A shed is a bad event; burn rate = observed shed
  fraction / the declared rate.

Each SLO is tracked over every window in ``windows_s`` (default 5 min
and 1 h) with bounded bucketed counters — memory is constant, and
time comes off the installed simclock, so the DST load model and the
serve-soak lane read deterministic virtual-time burn rates.

Multi-tenant burn attribution (ISSUE 20): observations carrying a
tenant ALSO land in that tenant's own window set, published as
``cilium_tpu_slo_burn_rate{slo,window,tenant}`` series alongside the
aggregate. A tenant storming its own quota burns ITS series; the
isolation invariant reads the other tenants' series to prove they
stayed within SLO. A burn
rate of 1.0 means "spending budget exactly as declared"; the classic
page-worthy thresholds (14.4× over 5 min, 6× over 1 h) are the
operator's to pick — we publish the gauges
(``cilium_tpu_slo_burn_rate{slo,window}``), the `status` op carries
the same numbers, and the serve-soak lane gates on them.
"""

from __future__ import annotations

import threading
from collections import deque
from typing import Dict, Optional, Tuple

from cilium_tpu.runtime import simclock
from cilium_tpu.runtime.metrics import METRICS, SLO_BURN_RATE

#: buckets per window: granularity of expiry, not of the rate itself
_BUCKETS = 30


class _Window:
    """Bounded bucketed (bad, total) counters over one trailing
    window."""

    __slots__ = ("window_s", "bucket_s", "buckets")

    def __init__(self, window_s: float):
        self.window_s = float(window_s)
        self.bucket_s = self.window_s / _BUCKETS
        #: deque of [bucket_start, bad, total]
        self.buckets: deque = deque(maxlen=_BUCKETS + 1)

    def observe(self, now: float, bad: bool) -> None:
        start = now - (now % self.bucket_s)
        if not self.buckets or self.buckets[-1][0] != start:
            self.buckets.append([start, 0, 0])
        b = self.buckets[-1]
        b[1] += 1 if bad else 0
        b[2] += 1

    def fraction(self, now: float) -> Tuple[int, int]:
        cutoff = now - self.window_s
        bad = total = 0
        for start, b, t in self.buckets:
            if start + self.bucket_s <= cutoff:
                continue
            bad += b
            total += t
        return bad, total


class SLOTracker:
    """Burn-rate tracking for the serve loop's two declared SLOs.
    Thread-safe; observation is O(windows)."""

    def __init__(self, serve_p99_ms: float = 50.0,
                 shed_rate: float = 1e-3,
                 windows_s: Tuple[float, ...] = (300.0, 3600.0),
                 host: str = ""):
        #: which host this tracker burns FOR: fleet replicas set it so
        #: per-host gauges stay distinct series in the shared
        #: process-global registry (ISSUE 17 satellite); a standalone
        #: tracker publishes the pre-fleet unlabeled series
        self.host = str(host)
        self.serve_p99_s = float(serve_p99_ms) / 1e3
        #: the latency SLO's error budget: p99 ⇒ 1% may exceed
        self.latency_budget = 0.01
        self.shed_budget = max(float(shed_rate), 1e-9)
        self.windows_s = tuple(float(w) for w in windows_s) or (300.0,)
        self._lock = threading.Lock()
        self._lat = {w: _Window(w) for w in self.windows_s}
        self._shed = {w: _Window(w) for w in self.windows_s}
        #: per-tenant window sets, created on first observation —
        #: keyed by the CONFIGURED tenant set (plus "default"), so
        #: cardinality is operator-bounded, never flow-driven
        # ctlint: disable=unbounded-registry  # keyed by configured tenants
        self._tenant_lat: Dict[str, Dict[float, _Window]] = {}
        # ctlint: disable=unbounded-registry  # keyed by configured tenants
        self._tenant_shed: Dict[str, Dict[float, _Window]] = {}

    @classmethod
    def from_config(cls, cfg) -> Optional["SLOTracker"]:
        """Build from ``Config.slo``; None when disabled or absent
        (embedders with older configs keep working)."""
        if cfg is None or not getattr(cfg, "enabled", False):
            return None
        return cls(serve_p99_ms=getattr(cfg, "serve_p99_ms", 50.0),
                   shed_rate=getattr(cfg, "shed_rate", 1e-3),
                   windows_s=tuple(getattr(cfg, "windows_s",
                                           (300.0, 3600.0))))

    # -- observation ------------------------------------------------------
    def _tenant_windows_locked(self, registry, tenant: str):
        wins = registry.get(tenant)
        if wins is None:
            wins = {w: _Window(w) for w in self.windows_s}
            registry[tenant] = wins
        return wins

    def observe_latency(self, latency_s: float,
                        tenant: str = "") -> None:
        now = simclock.now()
        bad = latency_s > self.serve_p99_s
        with self._lock:
            for w in self._lat.values():
                w.observe(now, bad)
            if tenant:
                for w in self._tenant_windows_locked(
                        self._tenant_lat, tenant).values():
                    w.observe(now, bad)

    def observe_request(self, shed: bool, tenant: str = "") -> None:
        """One admission outcome (served or shed) for the
        availability SLO, attributed to ``tenant`` when given."""
        now = simclock.now()
        with self._lock:
            for w in self._shed.values():
                w.observe(now, shed)
            if tenant:
                for w in self._tenant_windows_locked(
                        self._tenant_shed, tenant).values():
                    w.observe(now, shed)

    # -- read-out ---------------------------------------------------------
    @staticmethod
    def _label(window_s: float) -> str:
        return f"{int(window_s)}s"

    def burn_rates(self) -> Dict[str, Dict[str, float]]:
        """{slo: {window label: burn rate}} over the trailing
        windows. Windows with no observations burn 0.0."""
        now = simclock.now()
        out: Dict[str, Dict[str, float]] = {"serve-p99": {},
                                            "serve-shed": {}}
        with self._lock:
            for ws, w in self._lat.items():
                bad, total = w.fraction(now)
                frac = bad / total if total else 0.0
                out["serve-p99"][self._label(ws)] = round(
                    frac / self.latency_budget, 4)
            for ws, w in self._shed.items():
                bad, total = w.fraction(now)
                frac = bad / total if total else 0.0
                out["serve-shed"][self._label(ws)] = round(
                    frac / self.shed_budget, 4)
        return out

    def tenant_burn_rates(self) -> Dict[str, Dict[str, Dict[str,
                                                            float]]]:
        """{tenant: {slo: {window label: burn rate}}} over every
        tenant that has observed — the isolation invariant's per-
        tenant SLO face."""
        now = simclock.now()
        out: Dict[str, Dict[str, Dict[str, float]]] = {}
        with self._lock:
            tenants = set(self._tenant_lat) | set(self._tenant_shed)
            for tenant in sorted(tenants):
                rates: Dict[str, Dict[str, float]] = {
                    "serve-p99": {}, "serve-shed": {}}
                for ws, w in self._tenant_lat.get(tenant,
                                                  {}).items():
                    bad, total = w.fraction(now)
                    frac = bad / total if total else 0.0
                    rates["serve-p99"][self._label(ws)] = round(
                        frac / self.latency_budget, 4)
                for ws, w in self._tenant_shed.get(tenant,
                                                   {}).items():
                    bad, total = w.fraction(now)
                    frac = bad / total if total else 0.0
                    rates["serve-shed"][self._label(ws)] = round(
                        frac / self.shed_budget, 4)
                out[tenant] = rates
        return out

    def publish(self) -> Dict[str, Dict[str, float]]:
        """Refresh the burn-rate gauges (called once per pack cycle —
        cheap, bounded by slos × windows × configured tenants) and
        return the aggregate rates."""
        rates = self.burn_rates()
        for slo, per_window in rates.items():
            for window, rate in per_window.items():
                labels = {"slo": slo, "window": window}
                if self.host:
                    labels["host"] = self.host
                METRICS.set_gauge(SLO_BURN_RATE, rate, labels=labels)
        for tenant, per_slo in self.tenant_burn_rates().items():
            for slo, per_window in per_slo.items():
                for window, rate in per_window.items():
                    labels = {"slo": slo, "window": window,
                              "tenant": tenant}
                    if self.host:
                        labels["host"] = self.host
                    METRICS.set_gauge(SLO_BURN_RATE, rate,
                                      labels=labels)
        return rates

    def window_totals(self) -> Dict[str, int]:
        """Requests observed per trailing window — the weights the
        fleet-weighted burn-rate roll-up multiplies each host's rate
        by (a quiet host must not dilute a burning one equally)."""
        now = simclock.now()
        out: Dict[str, int] = {}
        with self._lock:
            for ws, w in self._shed.items():
                _bad, total = w.fraction(now)
                out[self._label(ws)] = total
        return out

    def status(self) -> Dict[str, object]:
        out = {
            "targets": {"serve_p99_ms": self.serve_p99_s * 1e3,
                        "shed_rate": self.shed_budget},
            "windows_s": list(self.windows_s),
            "burn_rates": self.burn_rates(),
        }
        tenants = self.tenant_burn_rates()
        if tenants:
            out["tenants"] = tenants
        return out
