"""Request-scoped tracing: a sampled, always-on flight recorder.

The verdict path spans many seams — stream frame → MicroBatcher queue
→ ResilientVerdictor → engine dispatch (or oracle fallback) → ack —
and the degraded modes from the fault-injection layer (breaker trips,
reconnect-with-resume, loader rollback) were visible only as aggregate
counters. When a tail-latency regression appears, counters cannot say
*which phase* of *which request* paid. This module can: every ingress
(service op, stream chunk, CLI replay, DNS batch) draws a trace id,
the context rides a contextvar through the layers, and each layer
records **phase-attributed spans** into a bounded ring buffer:

=================  =====================================================
``queue-wait``     enqueue → drain pickup (MicroBatcher, stream queues)
``host-prep``      featurize/encode/pack on the host
``device-dispatch``  device transfer + jitted step + readback
``oracle-fallback``  the CPU oracle lane (breaker open, or gate off)
=================  =====================================================

Phase spans are LEAF and non-overlapping by construction, so a single
request's phase durations sum to (within scheduler noise) its measured
end-to-end latency — the property the round-5 regression hunt lacked.

Three export faces (one id joins all three):

* ``GET /v1/trace`` on the REST API (``runtime/api.py``);
* ``cilium-tpu trace dump`` / ``replay --trace-out`` emitting Chrome
  trace-event JSON (Perfetto-loadable, the same family as the
  ``jax.profiler`` device traces);
* the trace id is stamped on Hubble flow records
  (``hubble/observer.py``) and on JSONL log lines
  (``runtime/logging.py``), so metrics, flows, and logs correlate.

Design constraints, in order:

* **Near-zero cost disarmed.** ``TRACER.span(...)`` with tracing
  disabled or no active context returns a shared no-op context
  manager — one attribute read and one contextvar get. Nothing here
  runs per flow; instrumentation is per request/batch/chunk.
* **Bounded.** Completed spans land in a ``deque(maxlen=capacity)``;
  a long-running agent's recorder memory is a constant.
* **Batch-safe.** A MicroBatcher flush serves many requests at once;
  :meth:`Tracer.group` fans one measured span out to every sampled
  member context so each trace stays self-contained.

Wire propagation: the stream protocol (``runtime/stream.py``) gained
an optional TRACED frame kind whose payload prefixes the 16-hex-char
trace id; servers advertise ``"trace": true`` in the stream_start ack
and clients only send traced frames to peers that do — old peers on
either side are unaffected.
"""

from __future__ import annotations

import contextvars
import threading
import uuid
from collections import deque
from typing import Dict, List, Optional, Sequence, Tuple

from cilium_tpu.runtime import simclock
from cilium_tpu.runtime.metrics import METRICS, TRACE_SPANS

#: canonical phase names (ISSUE 2); free-form phases are allowed but
#: these four are what the attribution tooling groups by
PHASE_QUEUE = "queue-wait"
PHASE_HOST = "host-prep"
PHASE_DEVICE = "device-dispatch"
PHASE_FALLBACK = "oracle-fallback"
#: the request never reached the engine: shed at admission, or reaped
#: from the queue after its caller abandoned / its deadline expired
PHASE_SHED = "shed"
PHASES = (PHASE_QUEUE, PHASE_HOST, PHASE_DEVICE, PHASE_FALLBACK,
          PHASE_SHED)

#: trace ids on the wire are exactly this many ascii hex chars
TRACE_ID_CHARS = 16

_CURRENT: "contextvars.ContextVar[Optional[TraceContext]]" = \
    contextvars.ContextVar("cilium_tpu_trace", default=None)


def new_trace_id() -> str:
    return uuid.uuid4().hex[:TRACE_ID_CHARS]


class TraceContext:
    """One sampled request's identity: the trace id plus a span-id
    counter. The context object itself is what rides the contextvar
    (and thread handoffs, explicitly) — spans land in the tracer's
    ring, not here, so contexts are cheap to drop."""

    __slots__ = ("trace_id", "name", "t0", "attrs", "_next_span",
                 "epoch")

    def __init__(self, trace_id: str, name: str,
                 attrs: Optional[Dict] = None, epoch: int = 0):
        self.trace_id = trace_id
        self.name = name
        self.t0 = simclock.wall()
        self.attrs = attrs or {}
        #: causal epoch for cross-host stitching (ISSUE 17): 0 on the
        #: original host; each lease handoff bumps it, so a stitched
        #: timeline orders by (epoch, ts) even when the survivor's
        #: clock reads earlier than the dead host's last span
        self.epoch = int(epoch)
        self._next_span = [0]  # list: shared mutable counter, no lock
        # (span ids only need uniqueness per trace; a rare duplicate
        # under a race costs nothing — ids are for display grouping)

    def next_span_id(self) -> int:
        sid = self._next_span[0]
        self._next_span[0] = sid + 1
        return sid

    def members(self) -> Tuple["TraceContext", ...]:
        return (self,)


class GroupContext:
    """A batch's worth of contexts: one measured span fans out to
    every member (a MicroBatcher flush serves many requests; each
    request's trace must still show the batch's device phase)."""

    __slots__ = ("_members",)

    def __init__(self, members: Sequence[TraceContext]):
        self._members = tuple(members)

    @property
    def trace_id(self) -> str:
        # ambiguous on purpose: a group is not ONE trace. Log lines
        # and flow stamps use the first member so they stay joinable.
        return self._members[0].trace_id if self._members else ""

    def members(self) -> Tuple[TraceContext, ...]:
        return self._members

    def next_span_id(self) -> int:  # pragma: no cover - via members
        return 0


class _NoopSpan:
    """Shared do-nothing context manager (disarmed path)."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NOOP = _NoopSpan()


class _SpanCM:
    __slots__ = ("tracer", "ctx", "name", "phase", "attrs", "t0")

    def __init__(self, tracer, ctx, name, phase, attrs):
        self.tracer = tracer
        self.ctx = ctx
        self.name = name
        self.phase = phase
        self.attrs = attrs

    def __enter__(self):
        self.t0 = simclock.wall()
        return self

    def __exit__(self, exc_type, exc, tb):
        dur = simclock.wall() - self.t0
        if exc is not None:
            self.attrs = dict(self.attrs,
                              error=f"{exc_type.__name__}: {exc}")
        self.tracer._record(self.ctx, self.name, self.phase,
                            self.t0, dur, self.attrs)
        return False


class Tracer:
    """The flight recorder. One process-global instance (:data:`TRACER`)
    mirrors the metrics registry discipline; tests build their own."""

    def __init__(self, capacity: int = 4096, sample_rate: float = 1.0,
                 enabled: bool = True):
        self.enabled = bool(enabled)
        self.sample_rate = float(sample_rate)
        self._lock = threading.Lock()
        self._ring: deque = deque(maxlen=max(1, int(capacity)))
        #: monotone sampling counter: rate r admits every ceil(1/r)-th
        #: ingress — deterministic (tests, chaos replays) and fair
        #: under bursts, unlike a per-ingress coin flip
        self._ingress = 0
        self.dropped = 0  # records evicted by the ring bound
        #: span ids for by-id (contextvar-less) remote records
        self._remote_span = 1 << 20

    # -- configuration ----------------------------------------------------
    def configure(self, enabled: Optional[bool] = None,
                  sample_rate: Optional[float] = None,
                  capacity: Optional[int] = None) -> None:
        with self._lock:
            if enabled is not None:
                self.enabled = bool(enabled)
            if sample_rate is not None:
                self.sample_rate = min(1.0, max(0.0, float(sample_rate)))
            if capacity is not None and \
                    int(capacity) != self._ring.maxlen:
                self._ring = deque(self._ring,
                                   maxlen=max(1, int(capacity)))

    # -- trace lifecycle --------------------------------------------------
    def start(self, name: str, trace_id: Optional[str] = None,
              **attrs) -> Optional[TraceContext]:
        """Sampling decision + context creation. ``trace_id`` adopts a
        propagated id (stream server side: the CLIENT already sampled,
        so adoption bypasses the local sampler). Returns ``None`` when
        not sampled — every downstream call no-ops on None."""
        if not self.enabled:
            return None
        if trace_id is None:
            rate = self.sample_rate
            if rate <= 0.0:
                return None
            if rate < 1.0:
                with self._lock:
                    n = self._ingress
                    self._ingress = n + 1
                if (n % max(1, round(1.0 / rate))) != 0:
                    return None
            trace_id = new_trace_id()
        return TraceContext(trace_id, name, attrs or None)

    def activate(self, ctx) -> "_Activation":
        """``with TRACER.activate(ctx): ...`` — contextvar scope (no-op
        for None, so callers never branch)."""
        return _Activation(ctx)

    def trace(self, name: str, trace_id: Optional[str] = None,
              **attrs) -> "_RootTrace":
        """start + activate + a root span recorded on exit — the one
        ingress-side call: ``with TRACER.trace("service.check") as ctx``."""
        return _RootTrace(self, name, trace_id, attrs)

    def finish(self, ctx) -> None:
        """Record the root (end-to-end) span for a started context."""
        if ctx is None:
            return
        for m in ctx.members():
            self._record(m, m.name, "", m.t0, simclock.wall() - m.t0,
                         dict(m.attrs, root=True))

    @staticmethod
    def current() -> Optional[TraceContext]:
        return _CURRENT.get()

    @staticmethod
    def current_trace_id() -> str:
        ctx = _CURRENT.get()
        return ctx.trace_id if ctx is not None else ""

    def group(self, ctxs: Sequence[Optional[TraceContext]]):
        """Collapse a batch's member contexts: None when none are
        sampled, the single member, or a :class:`GroupContext`."""
        live = [c for c in ctxs if c is not None]
        if not live:
            return None
        if len(live) == 1:
            return live[0]
        return GroupContext(live)

    # -- recording --------------------------------------------------------
    def span(self, name: str, phase: str = "", ctx=None, **attrs):
        """Measured span context manager; no-op when no trace is
        active (the production disarmed path)."""
        ctx = ctx if ctx is not None else _CURRENT.get()
        if ctx is None or not self.enabled:
            return _NOOP
        return _SpanCM(self, ctx, name, phase, attrs)

    def add_span(self, ctx, name: str, phase: str,
                 t0: float, dur: float, **attrs) -> None:
        """Record a span with explicit timing — for durations measured
        elsewhere (queue-wait from enqueue stamps, writer-thread
        readbacks)."""
        if ctx is None or not self.enabled:
            return
        self._record(ctx, name, phase, t0, dur, attrs)

    def event(self, name: str, ctx=None, **attrs) -> None:
        """Point-in-time annotation (breaker trip, injected fault,
        loader rollback) attached to the active trace."""
        ctx = ctx if ctx is not None else _CURRENT.get()
        if ctx is None or not self.enabled:
            return
        now = simclock.wall()
        recs = [{"trace_id": m.trace_id, "span_id": m.next_span_id(),
                 "name": name, "event": True, "ts": round(now, 6),
                 "attrs": attrs} for m in ctx.members()]
        with self._lock:
            self._note_evictions(len(recs))
            self._ring.extend(recs)

    def _record(self, ctx, name, phase, t0, dur, attrs) -> None:
        recs = [{"trace_id": m.trace_id, "span_id": m.next_span_id(),
                 "name": name, "phase": phase, "ts": round(t0, 6),
                 "dur": round(max(0.0, dur), 9),
                 **({"epoch": m.epoch}
                    if getattr(m, "epoch", 0) else {}),
                 **({"attrs": attrs} if attrs else {})}
                for m in ctx.members()]
        with self._lock:
            self._note_evictions(len(recs))
            self._ring.extend(recs)
        METRICS.inc(TRACE_SPANS, len(recs),
                    labels={"phase": phase or "root"})

    # -- cross-host stitching (ISSUE 17) ----------------------------------
    def _append_remote(self, rec: Dict, phase: str) -> None:
        with self._lock:
            rec["span_id"] = self._remote_span
            self._remote_span += 1
            self._note_evictions(1)
            self._ring.append(rec)
        METRICS.inc(TRACE_SPANS, labels={"phase": phase or "root"})

    def record_remote(self, trace_id: str, name: str, phase: str = "",
                      t0: Optional[float] = None, dur: float = 0.0,
                      host: str = "", epoch: int = 0,
                      parent: Optional[int] = None, **attrs) -> None:
        """Append a span to a trace BY ID — for code that holds no
        contextvar for the trace: the pack thread resolving another
        stream's ticket, or the router minting handoff spans for a
        dead host's streams. ``host``/``epoch``/``parent`` land as
        record keys only when set, so pre-fleet record shapes are
        unchanged."""
        if not self.enabled or not trace_id:
            return
        ts = simclock.wall() if t0 is None else t0
        rec: Dict = {"trace_id": trace_id, "name": name,
                     "phase": phase, "ts": round(ts, 6),
                     "dur": round(max(0.0, dur), 9)}
        if host:
            rec["host"] = host
        if epoch:
            rec["epoch"] = int(epoch)
        if parent is not None:
            rec["parent"] = int(parent)
        if attrs:
            rec["attrs"] = attrs
        self._append_remote(rec, phase)

    def event_remote(self, trace_id: str, name: str, host: str = "",
                     epoch: int = 0, **attrs) -> None:
        """Point-in-time annotation appended BY trace id (the
        handoff/abandon markers that stitch a failover timeline)."""
        if not self.enabled or not trace_id:
            return
        rec: Dict = {"trace_id": trace_id, "name": name,
                     "event": True, "ts": round(simclock.wall(), 6)}
        if host:
            rec["host"] = host
        if epoch:
            rec["epoch"] = int(epoch)
        if attrs:
            rec["attrs"] = attrs
        self._append_remote(rec, "")

    def stitch(self, trace_id: str) -> Dict:
        """One stream's causally-ordered cross-host timeline: every
        record for the trace, ordered by (causal epoch, timestamp) —
        NOT timestamp alone, because a survivor's span can carry an
        earlier wall reading than the dead host's last span — plus
        the distinct hosts that contributed and whether the timeline
        actually crossed a handoff (``stitched``)."""
        recs = self.dump(trace_id=trace_id)
        recs.sort(key=lambda r: (r.get("epoch", 0), r["ts"]))
        hosts: List[str] = []
        for r in recs:
            h = r.get("host") or (r.get("attrs") or {}).get("host")
            if h and h not in hosts:
                hosts.append(h)
        epochs = sorted({int(r.get("epoch", 0)) for r in recs})
        handoff = any(r.get("event") and r["name"] == "fleet.handoff"
                      for r in recs)
        return {
            "trace_id": trace_id,
            "records": recs,
            "hosts": hosts,
            "epochs": epochs,
            "stitched": bool(handoff or len(hosts) > 1
                             or any(epochs[1:])
                             or (epochs and epochs[0] > 0)),
        }

    def _note_evictions(self, incoming: int) -> None:
        room = self._ring.maxlen - len(self._ring)
        if incoming > room:
            self.dropped += incoming - room

    # -- export -----------------------------------------------------------
    def dump(self, trace_id: Optional[str] = None,
             limit: Optional[int] = None) -> List[Dict]:
        """Snapshot of recorded spans/events (oldest first), optionally
        filtered to one trace and/or bounded to the newest ``limit``."""
        with self._lock:
            recs = list(self._ring)
        if trace_id is not None:
            recs = [r for r in recs if r["trace_id"] == trace_id]
        if limit is not None and limit > 0:
            recs = recs[-limit:]
        return recs

    def trace_ids(self) -> List[str]:
        """Distinct trace ids currently in the ring, oldest first."""
        seen: Dict[str, None] = {}
        for r in self.dump():
            seen.setdefault(r["trace_id"], None)
        return list(seen)

    def chrome_trace(self, trace_id: Optional[str] = None) -> Dict:
        """Chrome trace-event JSON (the ``chrome://tracing`` /
        Perfetto format; same family as the ``jax.profiler`` dumps).
        Each trace renders as its own thread track; phase spans are
        complete ('X') events, trace events are instants ('i')."""
        events = []
        tids: Dict[str, int] = {}
        for r in self.dump(trace_id=trace_id):
            tid = tids.setdefault(r["trace_id"], len(tids) + 1)
            base = {
                "pid": 1,
                "tid": tid,
                "ts": round(r["ts"] * 1e6, 3),
                "name": r["name"],
                "args": dict(r.get("attrs") or {},
                             trace_id=r["trace_id"]),
            }
            if r.get("event"):
                events.append(dict(base, ph="i", s="t"))
            else:
                events.append(dict(base, ph="X",
                                   cat=r.get("phase") or "span",
                                   dur=round(r["dur"] * 1e6, 3)))
        meta = [{"ph": "M", "pid": 1, "tid": tid, "name": "thread_name",
                 "args": {"name": f"trace {tr}"}}
                for tr, tid in tids.items()]
        return {"traceEvents": meta + events,
                "displayTimeUnit": "ms"}

    def phase_totals(self, trace_id: str) -> Dict[str, float]:
        """Per-phase summed duration for one trace (attribution math:
        phases are leaf + non-overlapping, so their sum approximates
        the root span's end-to-end duration)."""
        totals: Dict[str, float] = {}
        for r in self.dump(trace_id=trace_id):
            if not r.get("event") and r.get("phase"):
                totals[r["phase"]] = \
                    totals.get(r["phase"], 0.0) + r["dur"]
        return totals

    def clear(self) -> None:
        with self._lock:
            self._ring.clear()
            self.dropped = 0
            self._ingress = 0


class _Activation:
    __slots__ = ("ctx", "_token")

    def __init__(self, ctx):
        self.ctx = ctx

    def __enter__(self):
        self._token = (_CURRENT.set(self.ctx)
                       if self.ctx is not None else None)
        return self.ctx

    def __exit__(self, *exc):
        if self._token is not None:
            _CURRENT.reset(self._token)
        return False


class _RootTrace:
    __slots__ = ("tracer", "name", "trace_id", "attrs", "ctx", "_token")

    def __init__(self, tracer, name, trace_id, attrs):
        self.tracer = tracer
        self.name = name
        self.trace_id = trace_id
        self.attrs = attrs

    def __enter__(self) -> Optional[TraceContext]:
        self.ctx = self.tracer.start(self.name, trace_id=self.trace_id,
                                     **self.attrs)
        self._token = (_CURRENT.set(self.ctx)
                       if self.ctx is not None else None)
        return self.ctx

    def __exit__(self, exc_type, exc, tb):
        if self._token is not None:
            _CURRENT.reset(self._token)
        if self.ctx is not None:
            if exc is not None:
                self.ctx.attrs = dict(self.ctx.attrs,
                                      error=f"{exc_type.__name__}: {exc}")
            self.tracer.finish(self.ctx)
        return False


#: process-global flight recorder (like the metrics registry)
TRACER = Tracer()
