"""Transparent DNS proxy server (UDP + TCP wire paths).

Reference: ``pkg/fqdn/dnsproxy/proxy.go`` — the agent TPROXYs pod DNS
to this proxy; per query it (1) maps the client source address to its
endpoint, (2) runs ``CheckAllowed``, (3) on deny answers REFUSED
without touching the network, (4) on allow forwards upstream, relays
the answer, and feeds the observed IPs to the NameManager so FQDN
selectors materialize as ipcache identities (SURVEY.md §3.5). A TCP
listener shares the same verdict path (RFC 7766 length framing) — the
truncation fallback clients take when a UDP answer sets TC.

This is the wire half on top of :class:`cilium_tpu.fqdn.dnsproxy
.DNSProxy` (the verdict half), using the stdlib codec in ``wire.py``.
Each query is served on a worker thread — upstream RTT never blocks
the receive loop (the reference serves each request on a goroutine).
"""

from __future__ import annotations

import socket
import threading
from concurrent.futures import ThreadPoolExecutor
from typing import Callable, Optional, Tuple

from cilium_tpu.fqdn import wire
from cilium_tpu.fqdn.dnsproxy import DNSProxy
from cilium_tpu.runtime import simclock
from cilium_tpu.runtime.metrics import METRICS

#: verdict callback signature: (qname, endpoint_id, allowed, rcode)
VerdictHook = Callable[[str, int, bool, int], None]


class DNSProxyServer:
    """Serve DNS on a UDP socket, enforcing the proxy's allow-rules.

    ``endpoint_of``: maps a client source IP to its endpoint id
    (the reference derives this from the socket's original destination
    + endpoint lookup); return None for unknown clients → REFUSED.
    """

    def __init__(
        self,
        proxy: DNSProxy,
        endpoint_of: Callable[[str], Optional[int]],
        upstream: Tuple[str, int] = ("127.0.0.53", 53),
        bind: Tuple[str, int] = ("127.0.0.1", 0),
        dport: int = 53,
        timeout: float = 2.0,
        on_verdict: Optional[VerdictHook] = None,
    ) -> None:
        self.proxy = proxy
        self.endpoint_of = endpoint_of
        self.upstream = upstream
        self.dport = dport
        self.timeout = timeout
        self.on_verdict = on_verdict
        # UDP + TCP on the SAME address (reference proxy.go serves
        # both; clients fall back to TCP on truncated UDP answers).
        # With an ephemeral request (port 0) the kernel picks the UDP
        # port blind to the TCP namespace, so an occupied TCP port
        # retries with a fresh UDP bind; an EXPLICIT port conflict is
        # the caller's error and raises
        for attempt in range(10):
            self._sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
            self._sock.bind(bind)
            self.address = self._sock.getsockname()
            self._tcp_sock = socket.socket(socket.AF_INET,
                                           socket.SOCK_STREAM)
            self._tcp_sock.setsockopt(socket.SOL_SOCKET,
                                      socket.SO_REUSEADDR, 1)
            try:
                self._tcp_sock.bind((self.address[0], self.address[1]))
                break
            except OSError:
                self._sock.close()
                self._tcp_sock.close()
                if bind[1] != 0 or attempt == 9:
                    raise
        self._sock.settimeout(0.5)
        self._tcp_sock.listen(16)
        self._tcp_sock.settimeout(0.5)
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._tcp_thread: Optional[threading.Thread] = None
        # bounded worker pool; stop() drains it so no handler outlives
        # the server (a late upstream answer must not race agent teardown)
        self._pool = ThreadPoolExecutor(max_workers=16,
                                        thread_name_prefix="dns-handler")
        # TCP connections get their OWN pool: a handler owns its
        # connection for its whole lifetime (idle clients renew the
        # timeout indefinitely), so sharing the UDP pool would let 16
        # idle TCP clients starve every UDP forward
        self._tcp_pool = ThreadPoolExecutor(max_workers=32,
                                            thread_name_prefix="dns-tcp")

    # -- lifecycle --------------------------------------------------------
    def start(self) -> "DNSProxyServer":
        self._thread = threading.Thread(
            target=self._serve, name="dns-proxy", daemon=True)
        self._thread.start()
        self._tcp_thread = threading.Thread(
            target=self._serve_tcp, name="dns-proxy-tcp", daemon=True)
        self._tcp_thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread:
            self._thread.join(timeout=5)
        if self._tcp_thread:
            self._tcp_thread.join(timeout=5)
        self._pool.shutdown(wait=True)  # bounded by the upstream timeout
        self._tcp_pool.shutdown(wait=True)  # handlers exit on _stop
        self._sock.close()
        self._tcp_sock.close()

    # -- serve loop -------------------------------------------------------
    def _serve(self) -> None:
        while not self._stop.is_set():
            try:
                data, client = self._sock.recvfrom(4096)
            except socket.timeout:
                continue
            except OSError:
                break
            # decode + endpoint + verdict run INLINE (microseconds, no
            # network I/O) so denials never convoy behind handlers stuck
            # on a dead upstream; only allowed queries hit the pool.
            # User callbacks (endpoint_of / on_verdict) may raise — a
            # bad query must drop that query, never the serve loop
            try:
                fwd = self._verdict_phase(
                    data, client[0],
                    lambda rcode: self._reply(client, data, rcode))
            except Exception:
                METRICS.inc("cilium_tpu_fqdn_handler_errors_total", 1)
                continue
            if fwd is None:
                continue
            try:
                self._pool.submit(self._forward, data, client, *fwd)
            except RuntimeError:
                break  # pool shut down mid-stop

    def _reply(self, client, query: bytes, rcode: int) -> None:
        try:
            self._sock.sendto(wire.encode_response(query, rcode), client)
        except (OSError, wire.DNSDecodeError):
            pass

    def _verdict_phase(self, data: bytes, client_ip: str, reply):
        """Fast path (shared by the UDP loop and TCP handlers): decode,
        map the client to an endpoint, evaluate the verdict, answer
        denials immediately via ``reply(rcode)``. Returns
        (msg, qname, ep) when the query should be forwarded."""
        try:
            msg = wire.decode(data)
        except wire.DNSDecodeError:
            METRICS.inc("cilium_tpu_fqdn_malformed_queries_total", 1)
            return None  # not even parseable enough to answer
        if msg.is_response or not msg.questions:
            return None
        qname = msg.qname
        ep = self.endpoint_of(client_ip)
        if ep is None:
            METRICS.inc("cilium_tpu_fqdn_unknown_client_total", 1)
            reply(wire.RCODE_REFUSED)
            return None
        allowed = self.proxy.check_allowed(ep, self.dport, qname)
        METRICS.inc("cilium_tpu_fqdn_queries_total", 1,
                    labels={"verdict": "allow" if allowed else "deny"})
        if not allowed:
            if self.on_verdict:
                self.on_verdict(qname, ep, False, wire.RCODE_REFUSED)
            reply(wire.RCODE_REFUSED)
            return None
        return (msg, qname, ep)

    # -- TCP path (truncation fallback; RFC 7766 length framing) ----------
    def _serve_tcp(self) -> None:
        while not self._stop.is_set():
            try:
                conn, addr = self._tcp_sock.accept()
            except socket.timeout:
                continue
            except OSError:
                break
            try:
                self._tcp_pool.submit(self._handle_tcp_conn, conn, addr)
            except RuntimeError:
                conn.close()
                break

    @staticmethod
    def _recvn(conn, n: int) -> Optional[bytes]:
        buf = b""
        while len(buf) < n:
            chunk = conn.recv(n - len(buf))
            if not chunk:
                return None
            buf += chunk
        return buf

    def _handle_tcp_conn(self, conn, addr) -> None:
        """One TCP connection; queries are pipelined (many frames per
        connection, answered in order — the reference handles each
        sequentially per connection too)."""
        with conn:
            conn.settimeout(self.timeout)
            while not self._stop.is_set():
                try:
                    hdr = self._recvn(conn, 2)
                    if hdr is None:
                        return
                    data = self._recvn(conn, int.from_bytes(hdr, "big"))
                    if data is None:
                        return
                except (socket.timeout, OSError):
                    return

                def reply(rcode, _data=data):
                    try:
                        resp = wire.encode_response(_data, rcode)
                        conn.sendall(len(resp).to_bytes(2, "big") + resp)
                    except (OSError, wire.DNSDecodeError):
                        pass

                try:
                    fwd = self._verdict_phase(data, addr[0], reply)
                except Exception:
                    METRICS.inc("cilium_tpu_fqdn_handler_errors_total", 1)
                    continue
                if fwd is None:
                    continue
                resp = self._forward_tcp_upstream(data, *fwd)
                if resp is None:
                    reply(wire.RCODE_SERVFAIL)
                    continue
                try:
                    conn.sendall(len(resp).to_bytes(2, "big") + resp)
                except OSError:
                    return

    def _forward_tcp_upstream(self, data: bytes, msg, qname: str,
                              ep: int) -> Optional[bytes]:
        """Forward one query upstream over TCP; returns the validated
        response bytes (txid + question checked) or None."""
        try:
            with socket.create_connection(self.upstream,
                                          timeout=self.timeout) as up:
                up.sendall(len(data).to_bytes(2, "big") + data)
                hdr = self._recvn(up, 2)
                if hdr is None:
                    raise OSError("upstream closed")
                resp = self._recvn(up, int.from_bytes(hdr, "big"))
                if resp is None:
                    raise OSError("upstream closed mid-frame")
        except (socket.timeout, OSError):
            METRICS.inc("cilium_tpu_fqdn_upstream_timeouts_total", 1)
            return None
        try:
            parsed = wire.decode(resp)
        except wire.DNSDecodeError:
            return None
        if not (parsed.txid == msg.txid and parsed.is_response
                and parsed.qname.lower() == qname.lower()):
            return None
        ips = [a.ip for a in parsed.answers if a.ip]
        if ips and parsed.rcode == wire.RCODE_NOERROR:
            ttl = min((a.ttl for a in parsed.answers if a.ip), default=0)
            self.proxy.observe_response(simclock.wall(), qname, ips,
                                        ttl=int(ttl))
        if self.on_verdict:
            self.on_verdict(qname, ep, True, parsed.rcode)
        return resp

    def _forward(self, data: bytes, client, msg, qname: str,
                 ep: int) -> None:
        # forward upstream on a fresh, CONNECTED socket: connect() makes
        # the kernel reject datagrams from any other source address, and
        # the txid + question check below rejects off-path forgeries
        # racing the resolver — both must pass before the answer is
        # relayed or observed (cache-poisoning guard)
        up = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        resp = None
        try:
            up.settimeout(self.timeout)
            up.connect(self.upstream)
            up.send(data)
            deadline = simclock.now() + self.timeout
            while resp is None:
                remaining = deadline - simclock.now()
                if remaining <= 0:
                    raise socket.timeout()
                up.settimeout(remaining)
                candidate = up.recv(4096)
                try:
                    parsed = wire.decode(candidate)
                except wire.DNSDecodeError:
                    continue  # garbage from the right address: keep waiting
                if (parsed.txid == msg.txid and parsed.is_response
                        and parsed.qname.lower() == qname.lower()):
                    resp = candidate
        except (socket.timeout, OSError):
            METRICS.inc("cilium_tpu_fqdn_upstream_timeouts_total", 1)
            self._reply(client, data, wire.RCODE_SERVFAIL)
            return
        finally:
            up.close()

        ips = [a.ip for a in parsed.answers if a.ip]
        if ips and parsed.rcode == wire.RCODE_NOERROR:
            ttl = min((a.ttl for a in parsed.answers if a.ip), default=0)
            self.proxy.observe_response(simclock.wall(), qname, ips,
                                        ttl=int(ttl))
        if self.on_verdict:
            self.on_verdict(qname, ep, True, parsed.rcode)
        try:
            self._sock.sendto(resp, client)
        except OSError:
            pass
