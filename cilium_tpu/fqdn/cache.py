"""DNS TTL cache.

Reference: ``pkg/fqdn/cache.go`` ``DNSCache`` — per-name IP sets with
TTL expiry, min-TTL clamping, and JSON persist/restore
(``pkg/fqdn/restore``, SURVEY.md §5.4).
"""

from __future__ import annotations

import json
import threading
from typing import Dict, Iterable, List, Optional, Set

from cilium_tpu.runtime import simclock
from cilium_tpu.policy.compiler import matchpattern


class DNSCache:
    def __init__(self, min_ttl: int = 60) -> None:
        self._lock = threading.Lock()
        self.min_ttl = min_ttl
        # name → ip → expiry time
        self._names: Dict[str, Dict[str, float]] = {}

    def update(self, lookup_time: float, name: str, ips: Iterable[str],
               ttl: int) -> bool:
        """Record a DNS answer. Returns True if new IPs appeared."""
        name = matchpattern.sanitize_name(name)
        ttl = max(ttl, self.min_ttl)
        expiry = lookup_time + ttl
        changed = False
        with self._lock:
            entry = self._names.setdefault(name, {})
            for ip in ips:
                if ip not in entry:
                    changed = True
                old = entry.get(ip, 0.0)
                entry[ip] = max(old, expiry)
        return changed

    def lookup(self, name: str, now: Optional[float] = None) -> List[str]:
        name = matchpattern.sanitize_name(name)
        now = simclock.wall() if now is None else now
        with self._lock:
            entry = self._names.get(name, {})
            return sorted(ip for ip, exp in entry.items() if exp > now)

    def lookup_by_regex(self, regex, now: Optional[float] = None
                        ) -> Dict[str, List[str]]:
        now = simclock.wall() if now is None else now
        out: Dict[str, List[str]] = {}
        with self._lock:
            for name, entry in self._names.items():
                if regex.match(name):
                    live = sorted(ip for ip, exp in entry.items() if exp > now)
                    if live:
                        out[name] = live
        return out

    def expire(self, now: Optional[float] = None) -> Set[str]:
        """Drop expired IPs; returns names that lost IPs (the reference's
        GC feeds these into policy updates)."""
        now = simclock.wall() if now is None else now
        affected: Set[str] = set()
        with self._lock:
            for name, entry in list(self._names.items()):
                dead = [ip for ip, exp in entry.items() if exp <= now]
                for ip in dead:
                    del entry[ip]
                    affected.add(name)
                if not entry:
                    del self._names[name]
        return affected

    # -- persist/restore (checkpoint/resume, SURVEY.md §5.4) -------------
    def to_json(self) -> str:
        with self._lock:
            return json.dumps(self._names)

    @classmethod
    def from_json(cls, data: str, min_ttl: int = 60) -> "DNSCache":
        c = cls(min_ttl=min_ttl)
        c._names = {n: dict(v) for n, v in json.loads(data).items()}
        return c

    def names(self) -> List[str]:
        with self._lock:
            return sorted(self._names)
