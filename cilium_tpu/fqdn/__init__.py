"""toFQDNs subsystem: DNS cache, NameManager, DNS proxy verdict path.

Reference: ``pkg/fqdn`` (SURVEY.md §2.1, §3.5) — the glob→regex compile
lives in ``cilium_tpu.policy.compiler.matchpattern``; this package holds
the runtime: per-name TTL cache, observed-answer → identity plumbing,
and the DNS-proxy ``CheckAllowed`` verdict hot path (BASELINE config[0]).
"""

from cilium_tpu.fqdn.cache import DNSCache
from cilium_tpu.fqdn.namemanager import NameManager
from cilium_tpu.fqdn.dnsproxy import DNSProxy
from cilium_tpu.fqdn.server import DNSProxyServer

__all__ = ["DNSCache", "NameManager", "DNSProxy", "DNSProxyServer"]
