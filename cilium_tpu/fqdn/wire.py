"""Minimal DNS wire-format codec (stdlib-only).

Reference: ``pkg/fqdn/dnsproxy`` uses miekg/dns to parse queries and
responses in its transparent proxy; we need just enough of RFC 1035 for
that role: header decode/encode, QNAME (with compression pointers on
decode), question section, and A/AAAA/CNAME answer extraction. No
external DNS dependency (the environment bakes none in).
"""

from __future__ import annotations

import dataclasses
import ipaddress
import struct
from typing import List, Optional, Tuple

QTYPE_A = 1
QTYPE_CNAME = 5
QTYPE_AAAA = 28

RCODE_NOERROR = 0
RCODE_SERVFAIL = 2
RCODE_NXDOMAIN = 3
RCODE_REFUSED = 5


class DNSDecodeError(ValueError):
    pass


@dataclasses.dataclass
class Question:
    qname: str          # presentation form, no trailing dot
    qtype: int
    qclass: int = 1     # IN


@dataclasses.dataclass
class Answer:
    name: str
    rtype: int
    ttl: int
    rdata: bytes

    @property
    def ip(self) -> Optional[str]:
        if self.rtype == QTYPE_A and len(self.rdata) == 4:
            return str(ipaddress.IPv4Address(self.rdata))
        if self.rtype == QTYPE_AAAA and len(self.rdata) == 16:
            return str(ipaddress.IPv6Address(self.rdata))
        return None


@dataclasses.dataclass
class Message:
    txid: int
    flags: int
    questions: List[Question]
    answers: List[Answer]

    @property
    def is_response(self) -> bool:
        return bool(self.flags & 0x8000)

    @property
    def rcode(self) -> int:
        return self.flags & 0xF

    @property
    def qname(self) -> str:
        return self.questions[0].qname if self.questions else ""


def _decode_name(data: bytes, off: int) -> Tuple[str, int]:
    """Decode a (possibly compressed) name; returns (name, next offset)."""
    labels: List[str] = []
    jumps = 0
    end: Optional[int] = None
    while True:
        if off >= len(data):
            raise DNSDecodeError("name runs past message end")
        length = data[off]
        if length & 0xC0 == 0xC0:  # compression pointer
            if off + 1 >= len(data):
                raise DNSDecodeError("truncated compression pointer")
            if end is None:
                end = off + 2
            off = ((length & 0x3F) << 8) | data[off + 1]
            jumps += 1
            if jumps > 63:  # loop guard
                raise DNSDecodeError("compression pointer loop")
            continue
        if length & 0xC0:
            raise DNSDecodeError(f"bad label length byte {length:#x}")
        off += 1
        if length == 0:
            break
        if off + length > len(data):
            raise DNSDecodeError("label runs past message end")
        labels.append(data[off:off + length].decode("ascii", "replace"))
        off += length
    return ".".join(labels), (end if end is not None else off)


def encode_name(name: str) -> bytes:
    out = bytearray()
    for label in name.rstrip(".").split("."):
        if not label:
            continue
        try:
            raw = label.encode("ascii")
        except UnicodeEncodeError as e:
            # names decoded with replacement chars (non-ASCII labels on
            # the wire) must fail as a DNS error the caller handles, not
            # as a stray UnicodeEncodeError killing a handler thread
            raise DNSDecodeError(f"non-ASCII label {label!r}") from e
        if len(raw) > 63:
            raise DNSDecodeError(f"label too long: {label!r}")
        out.append(len(raw))
        out += raw
    out.append(0)
    return bytes(out)


def decode(data: bytes) -> Message:
    if len(data) < 12:
        raise DNSDecodeError("message shorter than header")
    txid, flags, qd, an, ns, ar = struct.unpack("!6H", data[:12])
    off = 12
    questions: List[Question] = []
    for _ in range(qd):
        qname, off = _decode_name(data, off)
        if off + 4 > len(data):
            raise DNSDecodeError("truncated question")
        qtype, qclass = struct.unpack("!HH", data[off:off + 4])
        off += 4
        questions.append(Question(qname, qtype, qclass))
    answers: List[Answer] = []
    for _ in range(an):
        name, off = _decode_name(data, off)
        if off + 10 > len(data):
            raise DNSDecodeError("truncated answer")
        rtype, rclass, ttl, rdlen = struct.unpack(
            "!HHIH", data[off:off + 10])
        off += 10
        if off + rdlen > len(data):
            raise DNSDecodeError("answer rdata past message end")
        answers.append(Answer(name, rtype, ttl, data[off:off + rdlen]))
        off += rdlen
    # authority/additional sections are not needed by the proxy
    return Message(txid, flags, questions, answers)


def encode_query(txid: int, qname: str, qtype: int = QTYPE_A) -> bytes:
    header = struct.pack("!6H", txid, 0x0100, 1, 0, 0, 0)  # RD set
    return header + encode_name(qname) + struct.pack("!HH", qtype, 1)


def _question_section_end(data: bytes, qd: int) -> int:
    """Offset one past the last question (reuses the decode walker)."""
    off = 12
    for _ in range(qd):
        _, off = _decode_name(data, off)
        off += 4  # qtype + qclass
        if off > len(data):
            raise DNSDecodeError("truncated question")
    return off


def encode_response(query: bytes, rcode: int,
                    answers: Optional[List[Tuple[str, int, int, bytes]]] =
                    None) -> bytes:
    """Build a response reusing the query's header id + question bytes.

    The question section is echoed VERBATIM (non-ASCII labels survive
    round-trip, as real servers do). ``answers``: (name, rtype, ttl,
    rdata) tuples, names encoded uncompressed.
    """
    if len(query) < 12:
        raise DNSDecodeError("query shorter than header")
    txid, _flags, qd = struct.unpack("!3H", query[:6])[0:3]
    qend = _question_section_end(query, qd)
    flags = 0x8180 | (rcode & 0xF)  # QR|RD|RA + rcode
    answers = answers or []
    out = bytearray(struct.pack("!6H", txid, flags, qd, len(answers), 0, 0))
    out += query[12:qend]
    for name, rtype, ttl, rdata in answers:
        out += encode_name(name) + struct.pack(
            "!HHIH", rtype, 1, ttl, len(rdata)) + rdata
    return bytes(out)
