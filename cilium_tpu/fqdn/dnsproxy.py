"""DNS proxy verdict path.

Reference: ``pkg/fqdn/dnsproxy/proxy.go`` (SURVEY.md §2.2, §3.5): a
transparent proxy holding per-(endpoint, port) allow-rules;
``CheckAllowed(endpoint, dport, qname)`` is the verdict hot path
(BASELINE config[0]); allowed responses feed the NameManager.

Two matchers behind one interface, mirroring the feature gate:
* CPU: compiled-regex LRU (the reference's ``pkg/fqdn/re`` role)
* TPU: batch qnames through the banked-DFA engine (``check_batch``)
"""

from __future__ import annotations

import re
import threading
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

import numpy as np

from cilium_tpu.fqdn.namemanager import NameManager
from cilium_tpu.policy.compiler import matchpattern
from cilium_tpu.policy.compiler.dfa import compile_patterns
from cilium_tpu.policy.api.l7 import PortRuleDNS
from cilium_tpu.runtime import faults
from cilium_tpu.runtime.metrics import DNSPROXY_FALLBACKS, METRICS
from cilium_tpu.runtime.tracing import (
    PHASE_DEVICE,
    PHASE_FALLBACK,
    PHASE_HOST,
    TRACER,
)

#: fires in the banked-DFA batch path; a device fault degrades the
#: batch to the CPU regex matcher (same verdicts, slower)
QUERY_POINT = faults.register_point(
    "dnsproxy.query", "banked-DFA DNS batch verdict")


class DNSProxy:
    def __init__(self, name_manager: Optional[NameManager] = None,
                 use_tpu: bool = False) -> None:
        self._lock = threading.Lock()
        self.name_manager = name_manager
        self.use_tpu = use_tpu
        # (endpoint_id, dport) → list of regex sources
        self._rules: Dict[Tuple[int, int], List[str]] = {}
        self._compiled: Dict[Tuple[int, int], List["re.Pattern"]] = {}
        self._banked: Dict[Tuple[int, int], object] = {}

    def update_allowed(self, endpoint_id: int, dport: int,
                       rules: Sequence[PortRuleDNS]) -> None:
        """Install the allow-set for an endpoint+port (the reference's
        UpdateAllowed, called at regeneration time)."""
        srcs: List[str] = []
        for r in rules:
            if r.match_name:
                srcs.append(matchpattern.name_to_regex(r.match_name))
            elif r.match_pattern:
                srcs.append(matchpattern.to_regex(r.match_pattern))
        key = (endpoint_id, dport)
        with self._lock:
            if not srcs:
                self._rules.pop(key, None)
                self._compiled.pop(key, None)
                self._banked.pop(key, None)
                return
            self._rules[key] = srcs
            self._compiled[key] = [re.compile(s) for s in srcs]
            self._banked.pop(key, None)  # lazily rebuilt

    def check_allowed(self, endpoint_id: int, dport: int,
                      qname: str) -> bool:
        """The per-query hot path (CPU)."""
        q = matchpattern.sanitize_name(qname)
        with self._lock:
            pats = self._compiled.get((endpoint_id, dport))
        if pats is None:
            return False  # no rules installed → deny (proxy is opt-in)
        return any(p.match(q) for p in pats)

    def check_batch(self, endpoint_id: int, dport: int,
                    qnames: Sequence[str]) -> np.ndarray:
        """Batched verdicts; uses the banked-DFA engine when the TPU
        gate is on, else the regex set."""
        key = (endpoint_id, dport)
        with self._lock:
            srcs = self._rules.get(key)
            pats = self._compiled.get(key)
        if srcs is None or pats is None:
            return np.zeros(len(qnames), dtype=bool)
        # DNS batch = its own trace ingress (ISSUE 2): phase spans show
        # whether the batch rode the banked DFA or degraded to regex
        with TRACER.trace("dnsproxy.batch", endpoint=endpoint_id,
                          queries=len(qnames)):
            with TRACER.span("dns.sanitize", phase=PHASE_HOST,
                             records=len(qnames)):
                sanitized = [matchpattern.sanitize_name(q)
                             for q in qnames]
            if not self.use_tpu:
                with TRACER.span("dns.regex", phase=PHASE_FALLBACK,
                                 records=len(sanitized)):
                    return np.array(
                        [any(p.match(q) for p in pats)
                         for q in sanitized], dtype=bool)
            try:
                faults.maybe_fail(QUERY_POINT)
                with TRACER.span("dns.dfa", phase=PHASE_DEVICE,
                                 records=len(sanitized)):
                    st = self._get_banked(key, srcs)
                    from cilium_tpu.engine.dfa_kernel import (
                        dfa_scan_banked,
                        resolve_impl,
                    )

                    data = np.zeros((len(sanitized), 256),
                                    dtype=np.uint8)
                    lengths = np.zeros(len(sanitized), dtype=np.int32)
                    for i, q in enumerate(sanitized):
                        bs = q.encode("utf-8")[:256]
                        data[i, : len(bs)] = np.frombuffer(
                            bs, dtype=np.uint8)
                        lengths[i] = len(bs)
                    # host-side eager call: the env pick resolves HERE,
                    # not under trace (dfa_kernel.resolve_impl contract)
                    words = np.asarray(dfa_scan_banked(
                        st["trans"], st["byteclass"], st["start"],
                        st["accept"], data, lengths,
                        impl=resolve_impl()))
                    return (words.reshape(len(sanitized), -1)
                            .any(axis=1) != 0)
            except Exception:  # noqa: BLE001 — device sick: degrade
                # the regex set and the banked DFA are compiled from
                # the SAME sources, so the fallback answers identically
                # — correct but per-query (the reference's pkg/fqdn/re
                # path)
                METRICS.inc(DNSPROXY_FALLBACKS)
                with TRACER.span("dns.regex", phase=PHASE_FALLBACK,
                                 records=len(sanitized)):
                    return np.array(
                        [any(p.match(q) for p in pats)
                         for q in sanitized], dtype=bool)

    def _get_banked(self, key, srcs):
        """Staged device tensors for the key's automaton, cached keyed
        by the rule sources (a concurrent update_allowed can't leave a
        stale automaton, and steady-state calls skip stack+upload)."""
        import jax

        want = tuple(srcs)
        with self._lock:
            cached = self._banked.get(key)
            if cached is not None and cached[0] == want:
                return cached[1]
        stacked = compile_patterns(list(want)).stacked()
        # one batched pytree upload on a cache miss, not one
        # jnp.asarray transfer per table
        staged = jax.device_put({k: v for k, v in stacked.items()
                                 if k != "lane_of"})
        with self._lock:
            # only install if the rules haven't moved on meanwhile
            if self._rules.get(key) == list(want):
                self._banked[key] = (want, staged)
        return staged

    def observe_response(self, lookup_time: float, qname: str,
                         ips: Iterable[str], ttl: int = 0) -> None:
        """Forwarded-response hook → NameManager (§3.5 tail)."""
        if self.name_manager is not None:
            self.name_manager.update_generate_dns(lookup_time, qname, ips,
                                                  ttl)
