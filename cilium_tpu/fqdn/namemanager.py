"""NameManager: observed DNS answers → identities → policy updates.

Reference: ``pkg/fqdn/name_manager.go`` (SURVEY.md §2.1, §3.5 tail):
registered ``FQDNSelector``s are matched against every observed DNS
answer; matching IPs get CIDR identities via the ipcache, and the
SelectorCache's FQDN selections are updated so dependent MapStates can
be regenerated incrementally.
"""

from __future__ import annotations

import re
import threading
from typing import Callable, Dict, Iterable, List, Optional, Set

from cilium_tpu.fqdn.cache import DNSCache
from cilium_tpu.ipcache import IPCache
from cilium_tpu.policy.api.selector import FQDNSelector
from cilium_tpu.policy.compiler import matchpattern
from cilium_tpu.policy.selectorcache import SelectorCache


class NameManager:
    def __init__(self, selector_cache: SelectorCache, ipcache: IPCache,
                 dns_cache: Optional[DNSCache] = None) -> None:
        self._lock = threading.Lock()
        self.selector_cache = selector_cache
        self.ipcache = ipcache
        self.cache = dns_cache or DNSCache()
        self._selectors: Dict[FQDNSelector, "re.Pattern"] = {}
        #: called with the set of selectors whose selections changed —
        #: the hook that triggers endpoint regeneration (§3.2 tail)
        self.on_update: Optional[Callable[[Set[FQDNSelector]], None]] = None

    def register_selector(self, sel: FQDNSelector) -> None:
        if sel.match_name:
            src = matchpattern.name_to_regex(sel.match_name)
        else:
            src = matchpattern.to_regex(sel.match_pattern)
        with self._lock:
            self._selectors[sel] = re.compile(src)
        self.selector_cache.add_selector(sel)
        # replay cached names against the new selector
        self._resync([sel])

    def unregister_selector(self, sel: FQDNSelector) -> None:
        with self._lock:
            self._selectors.pop(sel, None)
        self.selector_cache.remove_selector(sel)

    def registered_selectors(self) -> List[FQDNSelector]:
        with self._lock:
            return list(self._selectors)

    def update_generate_dns(self, lookup_time: float, name: str,
                            ips: Iterable[str], ttl: int = 0) -> bool:
        """Ingest one DNS answer (the reference's UpdateGenerateDNS).
        Returns True if any selector's selections changed."""
        ips = list(ips)
        changed = self.cache.update(lookup_time, name, ips, ttl)
        qname = matchpattern.sanitize_name(name)
        with self._lock:
            matching = [s for s, rx in self._selectors.items()
                        if rx.match(qname)]
        if not matching:
            return False
        return self._resync(matching, now=lookup_time)

    def _resync(self, selectors: List[FQDNSelector],
                now: Optional[float] = None) -> bool:
        """Recompute selections for ``selectors`` from the DNS cache."""
        updated: Set[FQDNSelector] = set()
        with self._lock:
            rx_of = {s: self._selectors[s] for s in selectors
                     if s in self._selectors}
        for sel, rx in rx_of.items():
            ips: Set[str] = set()
            for name, name_ips in self.cache.lookup_by_regex(
                    rx, now=now).items():
                ips.update(name_ips)
            ids = {self.ipcache.upsert(f"{ip}/32" if ":" not in ip
                                       else f"{ip}/128")
                   for ip in ips}
            # update_fqdn_selections is a no-op (False) for selectors a
            # concurrent policy delete already removed — no resurrection
            if self.selector_cache.update_fqdn_selections(sel, ids):
                updated.add(sel)
        if updated and self.on_update is not None:
            self.on_update(updated)
        return bool(updated)

    def gc(self, now: Optional[float] = None) -> None:
        """Expire TTLs and resync affected selectors (the reference's
        periodic DNS GC controller)."""
        affected_names = self.cache.expire(now)
        if not affected_names:
            return
        with self._lock:
            selectors = [
                s for s, rx in self._selectors.items()
                if any(rx.match(n) for n in affected_names)
            ]
        if selectors:
            self._resync(selectors, now=now)
