"""bugtool: one-shot diagnostics bundle.

Reference: ``bugtool/`` (SURVEY.md §2.5, §5.5) — ``cilium-bugtool``
collects agent status, config, BPF map dumps, metrics, and logs into
an archive for support. Ours dumps the same strata of our stack:
agent/status, config, compiled-engine summary (the "BPF map dump"
analog: staged tensor shapes + revision), metrics exposition, JAX
device/platform info, and clustermesh/controller state — one JSON
file per section plus a MANIFEST, optionally tarred.
"""

from __future__ import annotations

import dataclasses
import json
import os
import platform
import shutil
import sys
import tarfile
import time
from typing import Dict

from cilium_tpu.runtime.metrics import METRICS


def _engine_summary(agent) -> Dict:
    eng = agent.loader.engine
    if eng is None:
        return {"staged": False}
    arrays = getattr(eng, "_arrays", {})
    return {
        "staged": True,
        "revision": agent.loader.revision,
        "tensors": {
            k: {"shape": list(getattr(v, "shape", ())),
                "dtype": str(getattr(v, "dtype", "?"))}
            for k, v in sorted(arrays.items())
        },
        "hbm_bytes": int(sum(
            getattr(v, "size", 0) * getattr(v, "dtype", None).itemsize
            for v in arrays.values()
            if getattr(v, "dtype", None) is not None)),
    }


def _jax_info() -> Dict:
    try:
        import jax
        return {
            "version": jax.__version__,
            "backend": jax.default_backend(),
            "devices": [str(d) for d in jax.devices()],
        }
    except Exception as e:  # pragma: no cover - jax import is baked in
        return {"error": str(e)}


def collect(agent, out_dir: str, archive: bool = True) -> str:
    """Write the bundle; returns the archive (or directory) path."""
    ts = time.strftime("%Y%m%d-%H%M%S")
    root = os.path.join(out_dir, f"cilium-tpu-bugtool-{ts}")
    os.makedirs(root, exist_ok=True)
    sections = {
        "status": agent.status(),
        "config": dataclasses.asdict(agent.config),
        "engine": _engine_summary(agent),
        "endpoints": [dict(ep.to_json(), state=str(ep.state))
                      for ep in agent.endpoint_manager.endpoints()],
        "identities": {
            str(nid): list(lbls.format())
            for nid, lbls in sorted(
                (n, agent.allocator.lookup(n))
                for n in agent.allocator.identities())
            if lbls is not None
        },
        "metrics": METRICS.expose(),
        "environment": {
            "python": sys.version,
            "platform": platform.platform(),
            "argv": sys.argv,
            "jax": _jax_info(),
        },
    }
    names = []
    for name, payload in sections.items():
        fname = f"{name}.json" if not isinstance(payload, str) else f"{name}.txt"
        with open(os.path.join(root, fname), "w") as f:
            if isinstance(payload, str):
                f.write(payload)
            else:
                json.dump(payload, f, indent=2, default=str)
        names.append(fname)
    with open(os.path.join(root, "MANIFEST.json"), "w") as f:
        json.dump({"created": ts, "files": sorted(names)}, f, indent=2)
    if not archive:
        return root
    tar_path = root + ".tar.gz"
    with tarfile.open(tar_path, "w:gz") as tar:
        tar.add(root, arcname=os.path.basename(root))
    shutil.rmtree(root)  # only the archive survives
    return tar_path
