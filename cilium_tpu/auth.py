"""Mutual-auth state: which identity pairs have completed a handshake.

Reference: the auth map + ``pkg/auth`` (SURVEY §2.1's AuthType slot is
the demand side; this is the supply side) — traffic whose winning
policy entry demands authentication DROPS until the pair
(src identity, dst identity) appears here, with expiration like the
datapath's auth map entries. The agent stages the pair set as a sorted
tensor next to the policy (same discipline as rule tensors: host
mutates, device consumes a snapshot).
"""

from __future__ import annotations

import threading
from typing import Dict, Optional, Tuple

import numpy as np

from cilium_tpu.runtime import simclock
from cilium_tpu.runtime.metrics import METRICS

#: padding sentinel GREATER than any real identity (identities are
#: non-negative int32): sentinel rows at the tail keep the padded
#: table lexicographically sorted. Two int32 words, not one packed
#: int64 — jax runs with x64 disabled, where an int64 shift would
#: silently truncate.
PAIR_SENTINEL = np.iinfo(np.int32).max

#: Explicit opt-out for ``authed_pairs`` on VerdictEngine/Oracle
#: verdict calls: auth demand surfaces as an output lane only and
#: auth-requiring traffic still forwards. Passing ``None`` instead is
#: fail-closed — auth-demanding flows drop until a pairs table is
#: supplied (a verdict path wired up without an AuthManager must not
#: silently waive handshakes the policy requires).
AUTH_UNENFORCED = object()


class AuthManager:
    """Authed (src, dst) identity pairs with expiry."""

    def __init__(self, default_ttl: float = 3600.0):
        self.default_ttl = default_ttl
        self._lock = threading.Lock()
        self._pairs: Dict[Tuple[int, int], float] = {}  # pair → expiry
        self._version = 0
        #: (version, earliest_expiry_among_cached, array)
        self._cached: Optional[Tuple[int, float, np.ndarray]] = None

    def authenticate(self, src_identity: int, dst_identity: int,
                     ttl: Optional[float] = None) -> None:
        """Record a completed handshake (the reference's auth map
        upsert after the auth service signs off)."""
        src, dst = int(src_identity), int(dst_identity)
        for nid in (src, dst):
            # one out-of-range pair would make every later
            # pairs_array() build raise (int32 overflow) and poison the
            # whole verdict path; == PAIR_SENTINEL would match padding
            if not (0 <= nid < PAIR_SENTINEL):
                raise ValueError(f"identity {nid} outside int32 range")
        expiry = simclock.wall() + (self.default_ttl if ttl is None else ttl)
        with self._lock:
            self._pairs[(src, dst)] = expiry
            self._version += 1
            METRICS.set_gauge("cilium_tpu_auth_pairs",
                              float(len(self._pairs)))

    def revoke(self, src_identity: int, dst_identity: int) -> bool:
        with self._lock:
            hit = self._pairs.pop((int(src_identity),
                                   int(dst_identity)), None)
            if hit is not None:
                self._version += 1
            METRICS.set_gauge("cilium_tpu_auth_pairs",
                              float(len(self._pairs)))
        return hit is not None

    def expire(self) -> int:
        """GC lapsed entries (controller duty). Returns count removed."""
        now = simclock.wall()
        with self._lock:
            dead = [p for p, exp in self._pairs.items() if exp <= now]
            for p in dead:
                del self._pairs[p]
            if dead:
                self._version += 1
            METRICS.set_gauge("cilium_tpu_auth_pairs",
                              float(len(self._pairs)))
        return len(dead)

    def is_authed(self, src_identity: int, dst_identity: int) -> bool:
        with self._lock:
            exp = self._pairs.get((int(src_identity), int(dst_identity)))
        return exp is not None and exp > simclock.wall()

    def pairs(self) -> Dict[Tuple[int, int], float]:
        with self._lock:
            return dict(self._pairs)

    def pairs_array(self) -> np.ndarray:
        """Live pairs as a lexicographically sorted [P, 2] int32 table
        (src, dst columns), padded to the next power of two with
        sentinel rows so jit sees few distinct shapes. Cached behind a
        version counter AND the earliest expiry of the cached set: the
        hot path pays a dict lookup when nothing changed, yet a lapsed
        TTL invalidates at the next call — expiry binds at lookup time
        (as the reference datapath checks auth-map expiration inline),
        not at the next GC sweep."""
        now = simclock.wall()
        with self._lock:
            if (self._cached is not None
                    and self._cached[0] == self._version
                    and now < self._cached[1]):
                return self._cached[2]
            live = sorted((s, d) for (s, d), exp in self._pairs.items()
                          if exp > now)
            earliest = min((exp for exp in self._pairs.values()
                            if exp > now), default=float("inf"))
            size = 8
            while size < len(live):
                size *= 2
            out = np.full((size, 2), PAIR_SENTINEL, dtype=np.int32)
            for i, (s, d) in enumerate(live):
                out[i] = (s, d)
            self._cached = (self._version, earliest, out)
            return out
