"""Proxy manager: proxy-port allocation + redirect lifecycle.

Reference: ``pkg/proxy`` (SURVEY §2.2) — when an L4Filter carries L7
rules, the agent allocates a proxy port for the (parser, direction)
pair, installs a datapath redirect (TPROXY) steering matched traffic
into the proxy, and tracks the redirect's lifecycle across policy
regenerations (ref-counted; released when no filter needs it; ports
reused after release).

TPU-native role: the datapath's ``proxy_port`` slot is our MapState
``is_redirect`` lane — flows the engine marks REDIRECTED are already
"in the proxy" (the shim/verdict service). What remains of pkg/proxy
is exactly this object: a stable proxy-port number per (l7proto,
direction) that the shim listens on, held while any resolved policy
references it and released afterwards, so external proxies (Envoy)
can bind deterministically.
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional, Tuple

from cilium_tpu.runtime.metrics import METRICS

#: default allocation range (reference: proxy ports come from an
#: ephemeral range the datapath knows to trust)
PROXY_PORT_MIN = 10000
PROXY_PORT_MAX = 20000


class ProxyPortExhausted(RuntimeError):
    pass


class Redirect:
    """One live (l7proto, ingress) redirect: a bound proxy port plus
    the set of policy users holding it."""

    __slots__ = ("l7proto", "ingress", "proxy_port", "users")

    def __init__(self, l7proto: str, ingress: bool, proxy_port: int):
        self.l7proto = l7proto
        self.ingress = ingress
        self.proxy_port = proxy_port
        #: (endpoint_identity, dport) pairs whose policy references
        #: this redirect — lifecycle follows this set
        self.users: set = set()

    def to_dict(self) -> Dict:
        return {"l7proto": self.l7proto,
                "ingress": self.ingress,
                "proxy_port": self.proxy_port,
                "users": sorted(list(self.users))}


class ProxyManager:
    """Allocates proxy ports and reconciles redirects against each
    policy snapshot (the reference updates redirects during endpoint
    regeneration; ours reconciles per resolved snapshot)."""

    def __init__(self, port_min: int = PROXY_PORT_MIN,
                 port_max: int = PROXY_PORT_MAX) -> None:
        self._lock = threading.Lock()
        self._port_min = port_min
        self._port_max = port_max
        self._next = port_min
        self._free: List[int] = []          # released ports, reused LIFO
        self._redirects: Dict[Tuple[str, bool], Redirect] = {}

    # -- allocation -------------------------------------------------------
    def _alloc_port(self) -> int:
        if self._free:
            return self._free.pop()
        if self._next > self._port_max:
            raise ProxyPortExhausted(
                f"proxy port range {self._port_min}-{self._port_max} "
                "exhausted")
        port = self._next
        self._next += 1
        return port

    def acquire(self, l7proto: str, ingress: bool,
                user: Tuple[int, int]) -> Redirect:
        """Get-or-create the redirect for (l7proto, direction) and
        register ``user`` (endpoint identity, dport) on it."""
        with self._lock:
            key = (l7proto, ingress)
            r = self._redirects.get(key)
            if r is None:
                r = Redirect(l7proto, ingress, self._alloc_port())
                self._redirects[key] = r
                METRICS.inc("cilium_tpu_proxy_redirects_created_total",
                            labels={"l7proto": l7proto})
            r.users.add(user)
            self._set_gauge()
            return r

    def release(self, l7proto: str, ingress: bool,
                user: Tuple[int, int]) -> None:
        with self._lock:
            key = (l7proto, ingress)
            r = self._redirects.get(key)
            if r is None:
                return
            r.users.discard(user)
            if not r.users:
                del self._redirects[key]
                self._free.append(r.proxy_port)
                METRICS.inc("cilium_tpu_proxy_redirects_released_total",
                            labels={"l7proto": l7proto})
            self._set_gauge()

    def _set_gauge(self) -> None:
        METRICS.set_gauge("cilium_tpu_proxy_redirects",
                          len(self._redirects))

    # -- snapshot reconciliation -----------------------------------------
    @staticmethod
    def _snapshot_users(per_identity) -> Dict[Tuple[str, bool],
                                              set]:
        """(l7proto, ingress) → users demanded by a resolved snapshot:
        every redirect MapState entry contributes one user per
        protocol family its rule set carries."""
        from cilium_tpu.core.flow import TrafficDirection

        want: Dict[Tuple[str, bool], set] = {}
        for ep_id, ms in per_identity.items():
            for key, entry in ms.entries.items():
                if not entry.is_redirect:
                    continue
                ingress = key.direction == int(TrafficDirection.INGRESS)
                protos = set()
                for lr in entry.l7_rules:
                    if lr.http:
                        protos.add("http")
                    if lr.kafka:
                        protos.add("kafka")
                    if lr.dns:
                        protos.add("dns")
                    if lr.l7proto:
                        protos.add(lr.l7proto)
                for proto in protos:
                    want.setdefault((proto, ingress), set()).add(
                        (ep_id, key.dport))
        return want

    def reconcile(self, per_identity) -> Dict[Tuple[str, bool], int]:
        """Sync redirects to a resolved policy snapshot: acquire what
        the snapshot demands, release what nothing references anymore.
        Returns the live (l7proto, ingress) → proxy_port map. Ports
        are STABLE across reconciles while any user persists (the
        reference keeps a redirect's port for its lifetime)."""
        want = self._snapshot_users(per_identity)
        with self._lock:
            # a redirect still demanded by the snapshot keeps its PORT
            # even when its user set is fully replaced (e.g. an
            # endpoint re-identified): release only keys nothing wants
            # — a delete-then-recreate could swap ports between live
            # redirects and misroute an externally-bound proxy
            for key in list(self._redirects):
                if key in want:
                    self._redirects[key].users = set(want[key])
                    continue
                r = self._redirects.pop(key)
                self._free.append(r.proxy_port)
                METRICS.inc(
                    "cilium_tpu_proxy_redirects_released_total",
                    labels={"l7proto": r.l7proto})
            for key, users in want.items():
                if key not in self._redirects:
                    r = Redirect(key[0], key[1], self._alloc_port())
                    r.users = set(users)
                    self._redirects[key] = r
                    METRICS.inc(
                        "cilium_tpu_proxy_redirects_created_total",
                        labels={"l7proto": key[0]})
            self._set_gauge()
            return {k: r.proxy_port
                    for k, r in self._redirects.items()}

    # -- introspection ----------------------------------------------------
    def lookup(self, l7proto: str, ingress: bool) -> Optional[int]:
        with self._lock:
            r = self._redirects.get((l7proto, ingress))
            return r.proxy_port if r else None

    def dump(self) -> List[Dict]:
        with self._lock:
            return [r.to_dict() for _, r in sorted(
                self._redirects.items())]
