"""Hubble flow JSONL reader/writer.

Schema mirrors ``flowpb.Flow`` JSON encoding (reference:
``api/v1/flow/flow.proto``, SURVEY.md §2.5) for the fields the engine
consumes. A "Hubble capture replay" (north star) is a stream of these
JSON objects, one per line — the exporter's on-disk format.
"""

from __future__ import annotations

import json
from typing import Dict, Iterable, Iterator, Optional

from cilium_tpu.core.flow import (
    DNSInfo,
    Flow,
    GenericL7Info,
    HTTPInfo,
    KafkaInfo,
    L7Type,
    PolicyMatchType,
    Protocol,
    TrafficDirection,
    Verdict,
)

_VERDICT_NAMES = {v.name: v for v in Verdict}
_DIR_NAMES = {"INGRESS": TrafficDirection.INGRESS,
              "EGRESS": TrafficDirection.EGRESS}


def _to_time(v) -> float:
    """flowpb encodes time as an RFC3339 string; our writer uses epoch
    floats. Accept both. Protobuf Timestamps carry NANOSECOND fractions
    (9 digits) which fromisoformat rejects — truncate to microseconds
    first."""
    if not v:
        return 0.0
    if isinstance(v, (int, float)):
        return float(v)
    import datetime
    import re as _re

    s = str(v).replace("Z", "+00:00")
    s = _re.sub(r"(\.\d{6})\d+", r"\1", s)  # ns → µs precision
    try:
        return datetime.datetime.fromisoformat(s).timestamp()
    except ValueError:
        return 0.0


def flow_to_dict(f: Flow) -> Dict:
    d: Dict = {
        "verdict": Verdict(f.verdict).name,
        "traffic_direction": TrafficDirection(f.direction).name,
        "source": {"identity": f.src_identity,
                   **({"labels": list(f.src_labels)}
                      if f.src_labels else {})},
        "destination": {"identity": f.dst_identity,
                        **({"labels": list(f.dst_labels)}
                           if f.dst_labels else {})},
    }
    if f.time:
        d["time"] = f.time
    if f.node_name:
        d["node_name"] = f.node_name
    if f.trace_id:
        d["trace_id"] = f.trace_id
    if f.policy_match_type != PolicyMatchType.NONE:
        # flowpb policy_match_type, finally filled honestly (the
        # attribution lane); omitted when NONE so old flows and new
        # no-match flows serialize identically
        d["policy_match_type"] = int(f.policy_match_type)
    if f.prov_word:
        # verdict provenance (engine/attribution.py): absent on old
        # writers; old READERS ignore the unknown key — both
        # directions pinned by tests/test_provenance.py
        prov = {"word": int(f.prov_word)}
        if f.prov_rule:
            prov["rule"] = f.prov_rule
        if f.prov_bank:
            prov["bank"] = f.prov_bank
        if f.prov_generation >= 0:
            prov["generation"] = int(f.prov_generation)
        if f.prov_memo:
            prov["memo"] = True
        d["provenance"] = prov
    if f.src_ip or f.dst_ip:
        d["IP"] = {"source": f.src_ip, "destination": f.dst_ip}
    l4_proto = Protocol(f.protocol)
    port_obj = {"destination_port": f.dport}
    if f.sport:
        port_obj["source_port"] = f.sport
    if l4_proto == Protocol.TCP:
        d["l4"] = {"TCP": port_obj}
    elif l4_proto == Protocol.UDP:
        d["l4"] = {"UDP": port_obj}
    elif l4_proto == Protocol.SCTP:
        d["l4"] = {"SCTP": port_obj}
    elif l4_proto == Protocol.ICMP:
        d["l4"] = {"ICMPv4": {"type": f.dport}}
    elif l4_proto == Protocol.ICMPV6:
        d["l4"] = {"ICMPv6": {"type": f.dport}}
    if f.l7 == L7Type.HTTP and f.http:
        d["l7"] = {"type": "REQUEST", "http": {
            "method": f.http.method,
            "url": f.http.path,
            "protocol": f.http.protocol,
            "headers": [{"key": k, "value": v} for k, v in f.http.headers],
            **({"host": f.http.host} if f.http.host else {}),
        }}
    elif f.l7 == L7Type.KAFKA and f.kafka:
        d["l7"] = {"type": "REQUEST", "kafka": {
            "api_key": f.kafka.api_key,
            "api_version": f.kafka.api_version,
            "correlation_id": f.kafka.correlation_id,
            "topic": f.kafka.topic,
            **({"client_id": f.kafka.client_id} if f.kafka.client_id else {}),
        }}
    elif f.l7 == L7Type.DNS and f.dns:
        d["l7"] = {"type": "REQUEST", "dns": {
            "query": f.dns.query,
            "qtypes": list(f.dns.qtypes),
            "ips": list(f.dns.ips),
            "ttl": f.dns.ttl,
        }}
    elif f.l7 >= L7Type.GENERIC and f.generic:
        # flowpb models proxylib records as {proto, fields} key/value
        # pairs (flow.proto L7 "kind: generic")
        d["l7"] = {"type": "REQUEST", "generic": {
            "proto": f.generic.proto,
            "fields": dict(f.generic.fields),
        }}
    return d


def split_http_url(url: str) -> tuple:
    """flowpb's ``http.url`` is ABSOLUTE (pkg/hubble/parser/seven
    builds scheme://host/path); policy regexes match the PATH. Returns
    ``(path_with_query, host)`` — host empty for bare paths. Shared by
    the JSONL and protobuf ingest paths so they can never disagree on
    what a policy regex sees."""
    if "://" not in url:
        return url, ""
    from urllib.parse import urlsplit

    parts = urlsplit(url)
    path = parts.path or "/"
    if parts.query:
        path = f"{path}?{parts.query}"
    return path, parts.hostname or ""


def flow_from_dict(d: Dict) -> Flow:
    if isinstance(d.get("flow"), dict):
        # the reference hubble exporter / `hubble observe -o jsonl`
        # envelope: {"flow": {...}, "node_name": ..., "time": ...}
        inner = dict(d["flow"])
        for k in ("node_name", "time"):
            inner.setdefault(k, d.get(k))
        d = inner
    f = Flow()
    f.time = _to_time(d.get("time"))
    f.verdict = _VERDICT_NAMES.get(d.get("verdict", ""),
                                   Verdict.VERDICT_UNKNOWN)
    f.direction = _DIR_NAMES.get(d.get("traffic_direction", ""),
                                 TrafficDirection.INGRESS)
    src = d.get("source") or {}
    dst = d.get("destination") or {}
    f.src_identity = int(src.get("identity", 0) or 0)
    f.dst_identity = int(dst.get("identity", 0) or 0)
    f.src_labels = tuple(src.get("labels") or ())
    f.dst_labels = tuple(dst.get("labels") or ())
    f.node_name = d.get("node_name", "") or ""
    f.trace_id = d.get("trace_id", "") or ""
    try:
        # absent (old writers) decodes to NONE — the compat contract
        f.policy_match_type = PolicyMatchType(
            int(d.get("policy_match_type", 0) or 0))
    except ValueError:
        f.policy_match_type = PolicyMatchType.NONE
    prov = d.get("provenance") or {}
    if isinstance(prov, dict) and prov:
        f.prov_word = int(prov.get("word", 0) or 0)
        f.prov_rule = str(prov.get("rule", "") or "")
        f.prov_bank = str(prov.get("bank", "") or "")
        f.prov_generation = int(prov.get("generation", -1)
                                if prov.get("generation") is not None
                                else -1)
        f.prov_memo = bool(prov.get("memo", False))
    ip = d.get("IP") or {}
    f.src_ip = ip.get("source", "")
    f.dst_ip = ip.get("destination", "")
    l4 = d.get("l4") or {}
    for proto_name, proto in (("TCP", Protocol.TCP), ("UDP", Protocol.UDP),
                              ("SCTP", Protocol.SCTP)):
        if proto_name in l4:
            f.protocol = proto
            f.dport = int(l4[proto_name].get("destination_port", 0))
            f.sport = int(l4[proto_name].get("source_port", 0))
    for proto_name, proto in (("ICMPv4", Protocol.ICMP),
                              ("ICMPv6", Protocol.ICMPV6)):
        if proto_name in l4:
            # flowpb carries {type, code}; the engine keys ICMP rules
            # by type in the port slot (bpf encodes it the same way)
            f.protocol = proto
            f.dport = int(l4[proto_name].get("type", 0))
    l7 = d.get("l7") or {}
    if "http" in l7:
        h = l7["http"]
        f.l7 = L7Type.HTTP
        url, url_host = split_http_url(h.get("url", ""))
        f.http = HTTPInfo(
            method=h.get("method", ""),
            path=url,
            host=h.get("host", "") or url_host,
            headers=tuple((x.get("key", ""), x.get("value", ""))
                          for x in (h.get("headers") or ())),
            protocol=h.get("protocol", "HTTP/1.1"),
            code=int(h.get("code", 0)),
        )
    elif "kafka" in l7:
        k = l7["kafka"]
        f.l7 = L7Type.KAFKA
        f.kafka = KafkaInfo(
            api_key=int(k.get("api_key", 0)),
            api_version=int(k.get("api_version", 0)),
            client_id=k.get("client_id", ""),
            topic=k.get("topic", ""),
            correlation_id=int(k.get("correlation_id", 0)),
        )
    elif "dns" in l7:
        dd = l7["dns"]
        f.l7 = L7Type.DNS
        f.dns = DNSInfo(
            query=dd.get("query", ""),
            qtypes=tuple(dd.get("qtypes") or ("A",)),
            ips=tuple(dd.get("ips") or ()),
            ttl=int(dd.get("ttl", 0)),
        )
    elif "generic" in l7:
        g = l7["generic"]
        f.l7 = L7Type.GENERIC
        f.generic = GenericL7Info(
            proto=g.get("proto", ""),
            fields={str(k): str(v)
                    for k, v in (g.get("fields") or {}).items()},
        )
    return f


def flow_dict_to_columns(d: Dict) -> tuple:
    """One flowpb JSON object → the flat column tuple of
    ``ingest.columnar`` (COLUMN_FIELDS order) — the Flow-object-free
    half of :func:`flow_from_dict`, sharing its field semantics
    (url split, host lowering, header serialization, qname
    sanitization) so the columnar and object ingest paths can never
    disagree on what a policy regex sees."""
    from cilium_tpu.engine.verdict import serialize_headers
    from cilium_tpu.policy.compiler import matchpattern

    if isinstance(d.get("flow"), dict):
        inner = dict(d["flow"])
        for k in ("node_name", "time"):
            inner.setdefault(k, d.get(k))
        d = inner
    verdict = int(_VERDICT_NAMES.get(d.get("verdict", ""),
                                     Verdict.VERDICT_UNKNOWN))
    direction = int(_DIR_NAMES.get(d.get("traffic_direction", ""),
                                   TrafficDirection.INGRESS))
    src = d.get("source") or {}
    dst = d.get("destination") or {}
    proto, dport, sport = int(Protocol.TCP), 0, 0  # Flow() default
    l4 = d.get("l4") or {}
    for name, p in (("TCP", Protocol.TCP), ("UDP", Protocol.UDP),
                    ("SCTP", Protocol.SCTP)):
        if name in l4:
            proto = int(p)
            dport = int(l4[name].get("destination_port", 0))
            sport = int(l4[name].get("source_port", 0))
    for name, p in (("ICMPv4", Protocol.ICMP),
                    ("ICMPv6", Protocol.ICMPV6)):
        if name in l4:
            proto = int(p)
            dport = int(l4[name].get("type", 0))
    l7t = int(L7Type.NONE)
    path = method = host = headers = qname = kclient = ktopic = b""
    kapi = kver = 0
    gproto = b""
    gpairs: tuple = ()
    l7 = d.get("l7") or {}
    if "http" in l7:
        h = l7["http"]
        l7t = int(L7Type.HTTP)
        url, url_host = split_http_url(h.get("url", ""))
        path = url.encode("utf-8")
        method = (h.get("method", "") or "").encode("utf-8")
        host = ((h.get("host", "") or url_host).lower()
                .encode("utf-8"))
        headers = serialize_headers(tuple(
            (x.get("key", ""), x.get("value", ""))
            for x in (h.get("headers") or ())))
    elif "kafka" in l7:
        k = l7["kafka"]
        l7t = int(L7Type.KAFKA)
        kapi = int(k.get("api_key", 0))
        kver = int(k.get("api_version", 0))
        kclient = (k.get("client_id", "") or "").encode("utf-8")
        ktopic = (k.get("topic", "") or "").encode("utf-8")
    elif "dns" in l7:
        q = l7["dns"].get("query", "")
        l7t = int(L7Type.DNS)
        if q:
            qname = matchpattern.sanitize_name(q).encode("utf-8")
    elif "generic" in l7:
        g = l7["generic"]
        l7t = int(L7Type.GENERIC)
        gproto = (g.get("proto", "") or "").encode("utf-8")
        gpairs = tuple(
            (str(k).encode("utf-8"), str(v).encode("utf-8"))
            for k, v in sorted((g.get("fields") or {}).items())
            if str(k))
    return (_to_time(d.get("time")), verdict, direction,
            int(src.get("identity", 0) or 0),
            int(dst.get("identity", 0) or 0),
            sport, dport, proto, l7t,
            path, method, host, headers, qname,
            kclient, ktopic, kapi, kver, gproto, gpairs)


def write_jsonl(path: str, flows: Iterable[Flow]) -> int:
    n = 0
    with open(path, "w") as fp:
        for f in flows:
            fp.write(json.dumps(flow_to_dict(f)) + "\n")
            n += 1
    return n


def read_jsonl(path: str, start: int = 0,
               limit: Optional[int] = None) -> Iterator[Flow]:
    """Stream flows from a JSONL capture; ``start`` supports replay-
    cursor resume (SURVEY.md §5.4). Lines may be flowpb JSON (bare or
    exporter-enveloped) or Envoy accesslog entries — see
    ingest/accesslog.py."""
    from cilium_tpu.ingest.accesslog import parse_capture_line

    with open(path) as fp:
        for i, line in enumerate(fp):
            if i < start:
                continue
            if limit is not None and i >= start + limit:
                return
            line = line.strip()
            if line:
                yield parse_capture_line(json.loads(line))
