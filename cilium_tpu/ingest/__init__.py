"""Flow ingest: Hubble JSONL replay + synthetic benchmark generators.

Reference: Hubble exporter JSONL / ``flowpb.Flow`` (SURVEY.md §2.5) is
the ingest schema; the five BASELINE.json configs are generated
synthetically here (§6).
"""

from cilium_tpu.ingest.hubble import flow_to_dict, flow_from_dict, read_jsonl, write_jsonl
from cilium_tpu.ingest.synth import (
    SynthScenario,
    synth_fqdn_scenario,
    synth_http_scenario,
    synth_kafka_scenario,
    synth_mixed_scenario,
    synth_clustermesh_scenario,
)

__all__ = [
    "flow_to_dict",
    "flow_from_dict",
    "read_jsonl",
    "write_jsonl",
    "SynthScenario",
    "synth_fqdn_scenario",
    "synth_http_scenario",
    "synth_kafka_scenario",
    "synth_mixed_scenario",
    "synth_clustermesh_scenario",
]
