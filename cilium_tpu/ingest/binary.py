"""Binary flow captures: zero-copy ingest of fixed-size records.

Reference: the datapath's perf-ring events are fixed-size C structs
(``bpf/lib/events.h`` — PolicyVerdictNotify et al.) consumed by
``pkg/monitor`` (SURVEY.md §2.5, §2.7 "perf/ring buffer"). Ours mirrors
that split: L3/L4 flow tuples ride a packed 32-byte little-endian
record (written/validated by the native codec,
``native/capture/capture.cpp`` → ``libcilium_capture.so``), and the
Python side maps them STRAIGHT into a numpy structured array — no
per-record parsing between disk and the engine's ``encode_flows``.

Version 2 adds an L7 SIDECAR (the accesslog-path analog, columnar):
a shared string table (u32 offsets + one blob, string 0 = "") plus a
fixed 32-byte L7 record per flow referencing it, carrying
path/method/host/headers/qname/kafka fields. Strings are normalized at
WRITE time (host lowercased, qname sanitized, headers canonically
serialized) so replay featurizes with pure numpy gathers — zero
per-flow Python (``engine.verdict.encode_l7_records``). Version 3
adds a GENERIC section so ``l7proto`` records ride the binary
file→verdict path too (VERDICT r3 item 3): per flow, the proto name
and up to fmax (key, value) field pairs as indices into the SAME
string table; a capture with no generic flows stays byte-identical
v2.

The native library is built on demand (``make -C native/capture``,
same discipline as the proxylib shim); if the toolchain is missing, a
pure-numpy fallback reads/writes the identical format — the reference
likewise pairs its C event layout with a Go reader.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Dict, Iterable, List, Optional, Tuple

import numpy as np

from cilium_tpu.core.flow import (
    Flow,
    L7Type,
    Protocol,
    TrafficDirection,
    Verdict,
)

REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
NATIVE_DIR = os.path.join(REPO, "native", "capture")
LIB_PATH = os.path.join(NATIVE_DIR, "libcilium_capture.so")

MAGIC = b"CTCAP1\x00\x00"
VERSION = 1
VERSION_L7 = 2
#: version 3 = v2 + a GENERIC section after the L7 records: one fixed
#: record per flow carrying the ``l7proto`` name and up to fmax
#: (key, value) field pairs as string-table indices (VERDICT r3 item
#: 3 — generic traffic rides the binary file→verdict path too). fmax
#: lives in the L7Header's reserved word; a capture with no generic
#: flows still writes byte-identical v2.
VERSION_L7G = 3
HEADER = np.dtype([("magic", "S8"), ("version", "<u4"),
                   ("count", "<u4")])
L7HEADER = np.dtype([("n_strings", "<u4"), ("reserved", "<u4"),
                     ("blob_bytes", "<u8")])


def gen_dtype(fmax: int) -> np.dtype:
    """Per-flow generic record: l7proto string index + fmax (key,
    value) string-index pairs (index 0 = "" = unused slot)."""
    return np.dtype([("proto", "<u4"), ("pairs", "<u4", (fmax, 2))])

#: numpy view of the C Record struct (keep in lockstep with
#: native/capture/capture.cpp)
RECORD = np.dtype([
    ("src_identity", "<u4"), ("dst_identity", "<u4"),
    ("dport", "<u2"), ("sport", "<u2"),
    ("proto", "u1"), ("direction", "u1"), ("l7_type", "u1"),
    ("verdict", "u1"),
    ("time", "<f8"),
    ("reserved0", "<u4"), ("reserved1", "<u4"),
])
assert RECORD.itemsize == 32

#: numpy view of the C L7Record struct (v2 sidecar; keep in lockstep
#: with native/capture/capture.cpp). Fields are indices into the
#: capture's shared string table; index 0 is always the empty string.
L7REC = np.dtype([
    ("path", "<u4"), ("method", "<u4"), ("host", "<u4"),
    ("headers", "<u4"), ("qname", "<u4"),
    ("kafka_client", "<u4"), ("kafka_topic", "<u4"),
    ("kafka_api_key", "<i2"), ("kafka_api_version", "<i2"),
])
assert L7REC.itemsize == 32

_lib_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_lib_tried = False


def _native() -> Optional[ctypes.CDLL]:
    """The native codec, built on demand; None if unbuildable."""
    global _lib, _lib_tried
    with _lib_lock:
        if _lib is not None or _lib_tried:
            return _lib
        _lib_tried = True
        # rebuild when missing OR older than its sources (a stale
        # pre-v3 library would reject version-3 files the Python
        # writer just produced); a current .so costs two stat()s, not
        # a make fork, per process
        srcs = [os.path.join(NATIVE_DIR, n)
                for n in ("capture.cpp", "Makefile")]
        try:
            stale = (not os.path.exists(LIB_PATH)
                     or os.path.getmtime(LIB_PATH)
                     < max(os.path.getmtime(s) for s in srcs))
        except OSError:
            stale = True
        if stale:
            try:
                subprocess.run(["make", "-C", NATIVE_DIR],
                               check=True, capture_output=True)
            except (OSError, subprocess.CalledProcessError):
                if not os.path.exists(LIB_PATH):
                    return None
        try:
            lib = ctypes.CDLL(LIB_PATH)
        except OSError:
            return None
        lib.ct_capture_record_size.restype = ctypes.c_int
        if lib.ct_capture_record_size() != RECORD.itemsize:
            return None  # layout drift: refuse rather than corrupt
        if not hasattr(lib, "ct_capture_write_l7g"):
            return None  # pre-v3 ABI: fall back to the numpy codec
        lib.ct_capture_write.restype = ctypes.c_int
        lib.ct_capture_write.argtypes = [ctypes.c_char_p,
                                         ctypes.c_void_p,
                                         ctypes.c_uint32]
        lib.ct_capture_count.restype = ctypes.c_int
        lib.ct_capture_count.argtypes = [ctypes.c_char_p]
        lib.ct_capture_read.restype = ctypes.c_int
        lib.ct_capture_read.argtypes = [ctypes.c_char_p,
                                        ctypes.c_void_p,
                                        ctypes.c_uint32,
                                        ctypes.c_uint32]
        lib.ct_capture_write_l7.restype = ctypes.c_int
        lib.ct_capture_write_l7.argtypes = [
            ctypes.c_char_p, ctypes.c_void_p, ctypes.c_uint32,
            ctypes.c_void_p, ctypes.POINTER(ctypes.c_uint32),
            ctypes.c_uint32, ctypes.c_void_p, ctypes.c_uint64]
        # the v3 writer was the ONE symbol bound without argtypes —
        # its calls hand-wrapped every scalar and nothing checked the
        # pointer marshaling (ctlint abi-surface); declared here with
        # the rest of the surface
        lib.ct_capture_write_l7g.restype = ctypes.c_int
        lib.ct_capture_write_l7g.argtypes = [
            ctypes.c_char_p, ctypes.c_void_p, ctypes.c_uint32,
            ctypes.c_void_p, ctypes.POINTER(ctypes.c_uint32),
            ctypes.c_uint32, ctypes.c_void_p, ctypes.c_uint64,
            ctypes.c_void_p, ctypes.c_uint32]
        if not hasattr(lib, "ct_capture_writer_open"):
            return None  # pre-batch-writer ABI: numpy codec instead
        # streaming columnar record-batch writer (ingest/columnar.py):
        # base records stream to disk per batch, trailing sections
        # buffer natively, finish() lays down the string table
        lib.ct_capture_writer_open.restype = ctypes.c_void_p
        lib.ct_capture_writer_open.argtypes = [ctypes.c_char_p,
                                               ctypes.c_uint32]
        lib.ct_capture_writer_batch.restype = ctypes.c_int
        lib.ct_capture_writer_batch.argtypes = [
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,
            ctypes.c_void_p, ctypes.c_uint32]
        lib.ct_capture_writer_finish.restype = ctypes.c_int
        lib.ct_capture_writer_finish.argtypes = [
            ctypes.c_void_p, ctypes.POINTER(ctypes.c_uint32),
            ctypes.c_uint32, ctypes.c_void_p, ctypes.c_uint64]
        lib.ct_capture_writer_abort.restype = ctypes.c_int
        lib.ct_capture_writer_abort.argtypes = [ctypes.c_void_p]
        lib.ct_capture_l7_info.restype = ctypes.c_int
        lib.ct_capture_l7_info.argtypes = [
            ctypes.c_char_p, ctypes.POINTER(ctypes.c_uint32),
            ctypes.POINTER(ctypes.c_uint64)]
        lib.ct_capture_read_l7.restype = ctypes.c_int
        lib.ct_capture_read_l7.argtypes = [
            ctypes.c_char_p, ctypes.c_void_p,
            ctypes.POINTER(ctypes.c_uint32), ctypes.c_void_p]
        _lib = lib
        return _lib


class CaptureError(ValueError):
    pass


_ERRORS = {-1: "io error", -2: "bad magic", -3: "unsupported version",
           -4: "truncated capture"}


def _check(rc: int) -> int:
    if rc < 0:
        raise CaptureError(_ERRORS.get(rc, f"error {rc}"))
    return rc


# -- record array ↔ Flow ----------------------------------------------------

def flows_to_records(flows: Iterable[Flow]) -> np.ndarray:
    flows = list(flows)
    rec = np.zeros(len(flows), dtype=RECORD)
    for i, f in enumerate(flows):
        # l7_type is recorded as NONE: the record carries no payload,
        # and a NONE-payload HTTP/Kafka flow would re-verdict
        # DIFFERENTLY than its source (empty path vs the real one) —
        # a converted capture must replay as the L3/L4 tuple it is
        rec[i] = (f.src_identity, f.dst_identity, f.dport, f.sport,
                  int(f.protocol), int(f.direction), int(L7Type.NONE),
                  int(f.verdict), f.time, 0, 0)
    return rec


def records_to_flows(rec: np.ndarray) -> List[Flow]:
    return [
        Flow(src_identity=int(r["src_identity"]),
             dst_identity=int(r["dst_identity"]),
             dport=int(r["dport"]), sport=int(r["sport"]),
             protocol=Protocol(int(r["proto"])),
             direction=TrafficDirection(int(r["direction"])),
             l7=L7Type(int(r["l7_type"])),
             verdict=Verdict(int(r["verdict"])),
             time=float(r["time"]))
        for r in rec
    ]


# -- file IO ---------------------------------------------------------------

def write_capture_records(path: str, rec: np.ndarray) -> int:
    """Write a v1 capture straight from a RECORD array (the columnar
    tooling path — no Flow objects)."""
    lib = _native()
    if lib is not None:
        buf = np.ascontiguousarray(rec)
        _check(lib.ct_capture_write(
            path.encode(), buf.ctypes.data_as(ctypes.c_void_p),
            len(buf)))
        return len(buf)
    header = np.zeros(1, dtype=HEADER)
    header[0] = (MAGIC, VERSION, len(rec))
    with open(path, "wb") as fp:
        fp.write(header.tobytes())
        fp.write(np.ascontiguousarray(rec).tobytes())
    return len(rec)


def write_capture(path: str, flows: Iterable[Flow]) -> int:
    rec = flows_to_records(flows)
    lib = _native()
    if lib is not None:
        buf = np.ascontiguousarray(rec)
        _check(lib.ct_capture_write(
            path.encode(), buf.ctypes.data_as(ctypes.c_void_p),
            len(buf)))
        return len(buf)
    header = np.zeros(1, dtype=HEADER)
    header[0] = (MAGIC, VERSION, len(rec))
    with open(path, "wb") as fp:
        fp.write(header.tobytes())
        fp.write(rec.tobytes())
    return len(rec)


def capture_count(path: str) -> int:
    lib = _native()
    if lib is not None:
        return _check(lib.ct_capture_count(path.encode()))
    with open(path, "rb") as fp:
        raw = fp.read(HEADER.itemsize)
        if len(raw) < HEADER.itemsize:
            raise CaptureError("truncated capture")
        h = np.frombuffer(raw, dtype=HEADER)[0]
        if bytes(h["magic"]).ljust(8, b"\x00") != MAGIC:
            raise CaptureError("bad magic")
        version, count = int(h["version"]), int(h["count"])
        if version not in (VERSION, VERSION_L7, VERSION_L7G):
            raise CaptureError("unsupported version")
        want = HEADER.itemsize + count * RECORD.itemsize
        if version in (VERSION_L7, VERSION_L7G):
            fp.seek(want)
            lraw = fp.read(L7HEADER.itemsize)
            if len(lraw) < L7HEADER.itemsize:
                raise CaptureError("truncated capture")
            lh = np.frombuffer(lraw, dtype=L7HEADER)[0]
            want += (L7HEADER.itemsize
                     + (int(lh["n_strings"]) + 1) * 4
                     + int(lh["blob_bytes"])
                     + count * L7REC.itemsize)
            if version == VERSION_L7G:
                fmax = int(lh["reserved"])
                if fmax <= 0:
                    raise CaptureError("truncated capture")
                want += count * gen_dtype(fmax).itemsize
        fp.seek(0, os.SEEK_END)
        if fp.tell() != want:
            raise CaptureError("truncated capture")
        return count


def read_records(path: str, start: int = 0,
                 limit: Optional[int] = None) -> np.ndarray:
    """Records as a structured array — the zero-parse ingest path."""
    total = capture_count(path)
    start = min(start, total)
    n = total - start if limit is None else min(limit, total - start)
    if n <= 0:
        return np.zeros(0, dtype=RECORD)
    lib = _native()
    if lib is not None:
        out = np.zeros(n, dtype=RECORD)
        got = _check(lib.ct_capture_read(
            path.encode(), out.ctypes.data_as(ctypes.c_void_p), n,
            start))
        return out[:got]
    with open(path, "rb") as fp:
        fp.seek(HEADER.itemsize + start * RECORD.itemsize)
        return np.frombuffer(fp.read(n * RECORD.itemsize),
                             dtype=RECORD).copy()


def read_capture(path: str, start: int = 0,
                 limit: Optional[int] = None) -> List[Flow]:
    return records_to_flows(read_records(path, start=start, limit=limit))


def map_capture(path: str):
    """Validate once, then expose the records as a read-only memmap —
    the chunked-replay path: one open, no per-chunk revalidation.
    Works for both versions: base records immediately follow the
    header either way."""
    total = capture_count(path)
    if total == 0:
        return np.zeros(0, dtype=RECORD)
    return np.memmap(path, dtype=RECORD, mode="r",
                     offset=HEADER.itemsize, shape=(total,))


# -- v2: L7 sidecar --------------------------------------------------------

def capture_version(path: str) -> int:
    with open(path, "rb") as fp:
        raw = fp.read(HEADER.itemsize)
    if len(raw) < HEADER.itemsize:
        raise CaptureError("truncated capture")
    return int(np.frombuffer(raw, dtype=HEADER)[0]["version"])


def flows_to_capture_l7(flows: Iterable[Flow]):
    """Flows → (records, l7_records, offsets, blob, gen, fmax): the
    v2/v3 capture sections (``gen`` is None and fmax 0 when no flow
    carries a generic payload — the file stays v2). String
    normalization happens HERE, at write time (host lowercased, qname
    sanitized, headers serialized canonically), so the replay hot path
    does zero per-string transformation — the same split the reference
    uses (accesslog entries arrive normalized from Envoy; the ring
    consumer never re-parses)."""
    from cilium_tpu.engine.verdict import serialize_headers
    from cilium_tpu.policy.compiler import matchpattern

    flows = list(flows)
    strings: List[bytes] = [b""]
    index: dict = {b"": 0}

    def intern(b: bytes) -> int:
        i = index.get(b)
        if i is None:
            i = index[b] = len(strings)
            strings.append(b)
        return i

    rec = np.zeros(len(flows), dtype=RECORD)
    l7 = np.zeros(len(flows), dtype=L7REC)
    gen_rows: List[Tuple[int, List[Tuple[int, int]]]] = []
    fmax = 0
    for i, f in enumerate(flows):
        g = f.generic
        carriable = (f.l7 >= L7Type.GENERIC and g is not None
                     and g.proto)
        # a GENERIC flow with no payload/proto can never match a rule;
        # flatten it to the L4 tuple (same invariant as v1: an
        # uncarriable payload must not re-verdict against EMPTY
        # fields). Frontend-family flows carry like GENERIC and
        # normalize to the canonical GENERIC code — replay re-derives
        # the family from the record's proto.
        if f.l7 >= L7Type.GENERIC:
            l7t = L7Type.GENERIC if carriable else L7Type.NONE
        else:
            l7t = f.l7
        rec[i] = (f.src_identity, f.dst_identity, f.dport, f.sport,
                  int(f.protocol), int(f.direction), int(l7t),
                  int(f.verdict), f.time, 0, 0)
        if carriable:
            pairs = [(intern(k.encode("utf-8")),
                      intern(v.encode("utf-8")))
                     for k, v in sorted(g.fields.items()) if k]
            gen_rows.append((intern(g.proto.encode("utf-8")), pairs))
            # a carriable flow forces the GENERIC section even with
            # zero field pairs — a proto-only flow written as v2 would
            # re-verdict against an ABSENT payload on replay
            fmax = max(fmax, len(pairs), 1)
        else:
            gen_rows.append((0, []))
        h = f.http
        if h is not None:
            l7[i]["path"] = intern(h.path.encode("utf-8"))
            l7[i]["method"] = intern(h.method.encode("utf-8"))
            l7[i]["host"] = intern(h.host.lower().encode("utf-8"))
            l7[i]["headers"] = intern(serialize_headers(h.headers))
        d = f.dns
        if d is not None and d.query:
            l7[i]["qname"] = intern(
                matchpattern.sanitize_name(d.query).encode("utf-8"))
        k = f.kafka
        if k is not None:
            l7[i]["kafka_client"] = intern(k.client_id.encode("utf-8"))
            l7[i]["kafka_topic"] = intern(k.topic.encode("utf-8"))
            l7[i]["kafka_api_key"] = k.api_key
            l7[i]["kafka_api_version"] = k.api_version
    lens = np.array([len(s) for s in strings], dtype=np.uint64)
    total = int(lens.sum())
    if total > 0xFFFFFFFF:
        # u32 offsets cap the string table at 4 GiB; wrapping silently
        # would gather garbage slices on replay
        raise CaptureError(f"string table too large ({total} bytes)")
    offsets = np.zeros(len(strings) + 1, dtype=np.uint32)
    offsets[1:] = np.cumsum(lens)
    blob = np.frombuffer(b"".join(strings), dtype=np.uint8)
    gen = None
    if fmax > 0:
        gen = np.zeros(len(flows), dtype=gen_dtype(fmax))
        for i, (proto, pairs) in enumerate(gen_rows):
            gen[i]["proto"] = proto
            for j, (k, v) in enumerate(pairs):
                gen[i]["pairs"][j] = (k, v)
    return rec, l7, offsets, blob, gen, fmax


class CaptureWriter:
    """Streaming columnar record-batch writer (the Python face of
    ``ct_capture_writer_*``; a pure-numpy fallback buffers batches and
    writes the identical layout when the native codec is unbuildable).

    Usage: ``write_batch`` per record batch (base records + aligned L7
    rows + — for ``fmax > 0`` — aligned GENERIC rows), then ``finish``
    with the shared string table. A writer abandoned without finish
    leaves a file readers reject as truncated, never misparse."""

    def __init__(self, path: str, fmax: int = 0):
        self.path = path
        self.fmax = int(fmax)
        self.n = 0
        self._lib = _native()
        self._handle = None
        self._batches: List[tuple] = []  # fallback buffering
        if self._lib is not None:
            self._handle = self._lib.ct_capture_writer_open(
                path.encode(), self.fmax)
            if not self._handle:
                raise CaptureError("io error")

    def write_batch(self, rec: np.ndarray, l7: np.ndarray,
                    gen: Optional[np.ndarray] = None) -> None:
        if len(rec) != len(l7) or (
                self.fmax > 0 and (gen is None or len(gen) != len(rec))):
            raise CaptureError("batch sections misaligned")
        if self._handle is not None:
            _check(self._lib.ct_capture_writer_batch(
                self._handle,
                np.ascontiguousarray(rec).ctypes.data_as(
                    ctypes.c_void_p),
                np.ascontiguousarray(l7).ctypes.data_as(
                    ctypes.c_void_p),
                (np.ascontiguousarray(gen).ctypes.data_as(
                    ctypes.c_void_p) if self.fmax > 0 else None),
                len(rec)))
        else:
            self._batches.append(
                (np.asarray(rec).copy(), np.asarray(l7).copy(),
                 None if gen is None else np.asarray(gen).copy()))
        self.n += len(rec)

    def finish(self, offsets: np.ndarray, blob: np.ndarray) -> int:
        offsets = np.ascontiguousarray(offsets, dtype=np.uint32)
        blob = np.ascontiguousarray(blob, dtype=np.uint8)
        if self._handle is not None:
            handle, self._handle = self._handle, None
            return _check(self._lib.ct_capture_writer_finish(
                handle,
                offsets.ctypes.data_as(
                    ctypes.POINTER(ctypes.c_uint32)),
                len(offsets) - 1,
                blob.ctypes.data_as(ctypes.c_void_p),
                int(blob.size)))
        rec = (np.concatenate([b[0] for b in self._batches])
               if self._batches else np.zeros(0, dtype=RECORD))
        l7 = (np.concatenate([b[1] for b in self._batches])
              if self._batches else np.zeros(0, dtype=L7REC))
        gen = (np.concatenate([b[2] for b in self._batches])
               if self.fmax > 0 else None)
        header = np.zeros(1, dtype=HEADER)
        version = VERSION_L7 if self.fmax == 0 else VERSION_L7G
        header[0] = (MAGIC, version, len(rec))
        l7h = np.zeros(1, dtype=L7HEADER)
        l7h[0] = (len(offsets) - 1, self.fmax, int(blob.size))
        with open(self.path, "wb") as fp:
            fp.write(header.tobytes())
            fp.write(rec.tobytes())
            fp.write(l7h.tobytes())
            fp.write(offsets.tobytes())
            fp.write(blob.tobytes())
            fp.write(l7.tobytes())
            if gen is not None:
                fp.write(gen.tobytes())
        self._batches = []
        return len(rec)

    def abort(self) -> None:
        if self._handle is not None:
            handle, self._handle = self._handle, None
            self._lib.ct_capture_writer_abort(handle)
        self._batches = []

    def __enter__(self) -> "CaptureWriter":
        return self

    def __exit__(self, exc_type, *exc) -> None:
        if self._handle is not None:
            self.abort()


def write_capture_columns(path: str, cols,
                          batch_size: int = 1 << 16) -> int:
    """Write :class:`~cilium_tpu.ingest.columnar.CaptureColumns`
    through the streaming record-batch writer (native when built),
    chunked at ``batch_size`` records."""
    w = CaptureWriter(path, fmax=cols.fmax)
    try:
        for s in range(0, len(cols.rec), batch_size):
            w.write_batch(
                cols.rec[s:s + batch_size],
                cols.l7[s:s + batch_size],
                (cols.gen[s:s + batch_size]
                 if cols.gen is not None else None))
        return w.finish(cols.offsets, cols.blob)
    except BaseException:
        w.abort()
        raise


def write_capture_l7(path: str, flows: Iterable[Flow]) -> int:
    """Write a version-2 capture (base records + L7 sidecar); version
    3 when any flow carries a generic ``l7proto`` payload (the extra
    GENERIC section, see ``VERSION_L7G``). Encoding is columnar
    (``ingest.columnar.flows_to_columns`` → the streaming batch
    writer): one batch intern per string column instead of per-record
    interleaved interning, so the string-table ORDER differs from the
    historical per-record writer (``flows_to_capture_l7``, kept as the
    differential reference) while every resolved field is identical."""
    from cilium_tpu.ingest.columnar import flows_to_columns

    return write_capture_columns(path, flows_to_columns(flows))


def _write_capture_l7_rowmajor(path: str, flows: Iterable[Flow]) -> int:
    """The historical per-record write path (row-major intern order).
    Reference/differential use only — ``write_capture_l7`` is the
    product path."""
    rec, l7, offsets, blob, gen, fmax = flows_to_capture_l7(flows)
    lib = _native()
    if lib is not None and gen is None:
        _check(lib.ct_capture_write_l7(
            path.encode(),
            np.ascontiguousarray(rec).ctypes.data_as(ctypes.c_void_p),
            len(rec),
            np.ascontiguousarray(l7).ctypes.data_as(ctypes.c_void_p),
            offsets.ctypes.data_as(ctypes.POINTER(ctypes.c_uint32)),
            len(offsets) - 1,
            blob.ctypes.data_as(ctypes.c_void_p),
            int(blob.size)))
        return len(rec)
    if lib is not None and gen is not None:
        # _native() guarantees the v3 symbol (pre-v3 ABIs load as
        # None) and declared its argtypes/restype with the rest
        _check(lib.ct_capture_write_l7g(
            path.encode(),
            np.ascontiguousarray(rec).ctypes.data_as(ctypes.c_void_p),
            ctypes.c_uint32(len(rec)),
            np.ascontiguousarray(l7).ctypes.data_as(ctypes.c_void_p),
            offsets.ctypes.data_as(ctypes.POINTER(ctypes.c_uint32)),
            ctypes.c_uint32(len(offsets) - 1),
            blob.ctypes.data_as(ctypes.c_void_p),
            ctypes.c_uint64(int(blob.size)),
            np.ascontiguousarray(gen).ctypes.data_as(ctypes.c_void_p),
            ctypes.c_uint32(fmax)))
        return len(rec)
    header = np.zeros(1, dtype=HEADER)
    version = VERSION_L7 if gen is None else VERSION_L7G
    header[0] = (MAGIC, version, len(rec))
    l7h = np.zeros(1, dtype=L7HEADER)
    # the reserved word carries gen fmax in v3 (0 in v2)
    l7h[0] = (len(offsets) - 1, fmax, int(blob.size))
    with open(path, "wb") as fp:
        fp.write(header.tobytes())
        fp.write(rec.tobytes())
        fp.write(l7h.tobytes())
        fp.write(offsets.tobytes())
        fp.write(blob.tobytes())
        fp.write(l7.tobytes())
        if gen is not None:
            fp.write(gen.tobytes())
    return len(rec)


def sections_to_bytes(rec, l7, offsets, blob,
                      gen: Optional[np.ndarray] = None,
                      fmax: int = 0) -> bytes:
    """Capture sections → one in-memory v2/v3 capture image (byte-
    identical to what ``write_capture_l7`` puts on disk). The unit of
    the verdict socket's STREAM mode (runtime/stream.py): each frame's
    payload is a self-contained capture image, so the server parses
    chunks with the same zero-copy section readers as files."""
    header = np.zeros(1, dtype=HEADER)
    version = VERSION_L7 if gen is None else VERSION_L7G
    header[0] = (MAGIC, version, len(rec))
    l7h = np.zeros(1, dtype=L7HEADER)
    l7h[0] = (len(offsets) - 1, fmax, int(blob.size))
    parts = [header.tobytes(), np.ascontiguousarray(rec).tobytes(),
             l7h.tobytes(), np.ascontiguousarray(offsets).tobytes(),
             np.ascontiguousarray(blob).tobytes(),
             np.ascontiguousarray(l7).tobytes()]
    if gen is not None:
        parts.append(np.ascontiguousarray(gen).tobytes())
    return b"".join(parts)


def capture_to_bytes(flows: Iterable[Flow]) -> bytes:
    """Flows → in-memory v2/v3 capture image (client side of the
    stream protocol; columnar-encoded like :func:`write_capture_l7`)."""
    from cilium_tpu.ingest.columnar import flows_to_columns

    return flows_to_columns(flows).to_bytes()


def capture_from_bytes(buf: bytes):
    """Capture image → (rec, l7, offsets, blob, gen) views. Validates
    the full layout (magic, version, section sizes) like
    ``capture_count`` does for files; raises CaptureError on anything
    short, long, or misversioned — a stream server must fail a bad
    frame loudly, never gather garbage slices."""
    if len(buf) < HEADER.itemsize:
        raise CaptureError("truncated capture image")
    h = np.frombuffer(buf[:HEADER.itemsize], dtype=HEADER)[0]
    if bytes(h["magic"]).ljust(8, b"\x00") != MAGIC:
        raise CaptureError("bad magic")
    version, count = int(h["version"]), int(h["count"])
    if version not in (VERSION_L7, VERSION_L7G):
        raise CaptureError(f"unsupported stream version {version}")
    off = HEADER.itemsize
    want = off + count * RECORD.itemsize + L7HEADER.itemsize
    if len(buf) < want:
        raise CaptureError("truncated capture image")
    rec = np.frombuffer(buf, dtype=RECORD, count=count, offset=off)
    off += count * RECORD.itemsize
    lh = np.frombuffer(buf, dtype=L7HEADER, count=1, offset=off)[0]
    off += L7HEADER.itemsize
    n_strings = int(lh["n_strings"])
    blob_bytes = int(lh["blob_bytes"])
    fmax = int(lh["reserved"])
    want = (off + (n_strings + 1) * 4 + blob_bytes
            + count * L7REC.itemsize)
    if version == VERSION_L7G:
        if fmax <= 0:
            raise CaptureError("truncated capture image")
        want += count * gen_dtype(fmax).itemsize
    if len(buf) != want:
        raise CaptureError(
            f"capture image size {len(buf)} != expected {want}")
    offsets = np.frombuffer(buf, dtype="<u4", count=n_strings + 1,
                            offset=off)
    off += (n_strings + 1) * 4
    blob = np.frombuffer(buf, dtype=np.uint8, count=blob_bytes,
                         offset=off)
    off += blob_bytes
    l7 = np.frombuffer(buf, dtype=L7REC, count=count, offset=off)
    off += count * L7REC.itemsize
    gen = None
    if version == VERSION_L7G:
        gen = np.frombuffer(buf, dtype=gen_dtype(fmax), count=count,
                            offset=off)
    return rec, l7, offsets, blob, gen


def capture_field_widths(l7, offsets, cfg=None,
                         pad_multiple: int = 32) -> Dict[str, int]:
    """Per-field padded widths over a WHOLE capture — pass to the
    engine's ``encode_l7_records`` so every chunk of a chunked replay
    encodes to identical shapes (one jit compile for the stream).
    Lives here (pure numpy) so the replay cursor can compute it
    without touching jax."""
    from cilium_tpu.core.config import EngineConfig

    cfg = cfg or EngineConfig()
    caps = {"path": max(cfg.http_path_buckets),
            "method": cfg.http_method_len, "host": cfg.http_host_len,
            "headers": 1024, "qname": cfg.dns_name_len}
    widths = {}
    for field, cap in caps.items():
        idx = l7[field]
        lens = (offsets[idx + 1].astype(np.int64)
                - offsets[idx].astype(np.int64))
        longest = int(lens.max()) if len(lens) else 1
        widths[field] = min(
            cap, max(pad_multiple,
                     -(-max(longest, 1) // pad_multiple) * pad_multiple))
    return widths


def l7_info(path: str):
    """O(1) sidecar geometry: (n_strings, blob_bytes) from the 16-byte
    L7Header ((0, 0) for a v1 capture) — the ct_capture_l7_info analog."""
    total = capture_count(path)  # full-layout validation
    if capture_version(path) not in (VERSION_L7, VERSION_L7G):
        return 0, 0
    with open(path, "rb") as fp:
        fp.seek(HEADER.itemsize + total * RECORD.itemsize)
        lh = np.frombuffer(fp.read(L7HEADER.itemsize), dtype=L7HEADER)[0]
    return int(lh["n_strings"]), int(lh["blob_bytes"])


def read_l7_sidecar(path: str):
    """(l7_records, offsets, blob) of a v2/v3 capture — one sequential
    read per section, no per-record parsing."""
    total = capture_count(path)  # full-layout validation
    if capture_version(path) not in (VERSION_L7, VERSION_L7G):
        raise CaptureError("capture has no L7 sidecar (v1)")
    with open(path, "rb") as fp:
        fp.seek(HEADER.itemsize + total * RECORD.itemsize)
        lh = np.frombuffer(fp.read(L7HEADER.itemsize), dtype=L7HEADER)[0]
        n_strings = int(lh["n_strings"])
        blob_bytes = int(lh["blob_bytes"])
        offsets = np.fromfile(fp, dtype="<u4", count=n_strings + 1)
        blob = np.fromfile(fp, dtype=np.uint8, count=blob_bytes)
        l7 = np.fromfile(fp, dtype=L7REC, count=total)
    return l7, offsets, blob


def read_gen_sidecar(path: str):
    """The v3 GENERIC section as a ``gen_dtype(fmax)`` array, or None
    for v1/v2 captures (one sequential read, like the L7 sidecar)."""
    total = capture_count(path)  # full-layout validation
    if capture_version(path) != VERSION_L7G:
        return None
    with open(path, "rb") as fp:
        fp.seek(HEADER.itemsize + total * RECORD.itemsize)
        lh = np.frombuffer(fp.read(L7HEADER.itemsize), dtype=L7HEADER)[0]
        fmax = int(lh["reserved"])
        fp.seek((int(lh["n_strings"]) + 1) * 4 + int(lh["blob_bytes"])
                + total * L7REC.itemsize, os.SEEK_CUR)
        return np.fromfile(fp, dtype=gen_dtype(fmax), count=total)


def _table_get(offsets: np.ndarray, blob: np.ndarray, idx: int) -> bytes:
    return blob[int(offsets[idx]):int(offsets[idx + 1])].tobytes()


def read_capture_flows_l7(path: str) -> List[Flow]:
    """Object-path reconstruction of a v2 capture (tooling/tests; the
    hot path is engine.verdict.encode_l7_records over the raw
    sections)."""
    rec = read_records(path)
    l7, offsets, blob = read_l7_sidecar(path)
    return records_to_flows_l7(rec, l7, offsets, blob,
                               gen=read_gen_sidecar(path))


def records_to_flows_l7(rec: np.ndarray, l7: np.ndarray,
                        offsets: np.ndarray, blob: np.ndarray,
                        gen: Optional[np.ndarray] = None
                        ) -> List[Flow]:
    from cilium_tpu.core.flow import (
        DNSInfo,
        GenericL7Info,
        HTTPInfo,
        KafkaInfo,
    )

    flows = []
    for i, (r, s) in enumerate(zip(rec, l7)):
        f = Flow(src_identity=int(r["src_identity"]),
                 dst_identity=int(r["dst_identity"]),
                 dport=int(r["dport"]), sport=int(r["sport"]),
                 protocol=Protocol(int(r["proto"])),
                 direction=TrafficDirection(int(r["direction"])),
                 l7=L7Type(int(r["l7_type"])),
                 verdict=Verdict(int(r["verdict"])),
                 time=float(r["time"]))
        if f.l7 == L7Type.HTTP:
            hdr_block = _table_get(offsets, blob, int(s["headers"]))
            headers = tuple(
                tuple(line.split(":", 1))
                for line in hdr_block.decode("utf-8").splitlines() if line)
            f.http = HTTPInfo(
                method=_table_get(offsets, blob,
                                  int(s["method"])).decode("utf-8"),
                path=_table_get(offsets, blob,
                                int(s["path"])).decode("utf-8"),
                host=_table_get(offsets, blob,
                                int(s["host"])).decode("utf-8"),
                headers=headers)
        elif f.l7 == L7Type.DNS:
            f.dns = DNSInfo(query=_table_get(
                offsets, blob, int(s["qname"])).decode("utf-8"))
        elif f.l7 == L7Type.KAFKA:
            f.kafka = KafkaInfo(
                api_key=int(s["kafka_api_key"]),
                api_version=int(s["kafka_api_version"]),
                client_id=_table_get(offsets, blob,
                                     int(s["kafka_client"])).decode("utf-8"),
                topic=_table_get(offsets, blob,
                                 int(s["kafka_topic"])).decode("utf-8"))
        elif f.l7 == L7Type.GENERIC and gen is not None:
            g = gen[i]
            fields = {}
            for k_idx, v_idx in g["pairs"]:
                if k_idx:  # index 0 = "" = unused slot
                    fields[_table_get(offsets, blob,
                                      int(k_idx)).decode("utf-8")] = \
                        _table_get(offsets, blob,
                                   int(v_idx)).decode("utf-8")
            f.generic = GenericL7Info(
                proto=_table_get(offsets, blob,
                                 int(g["proto"])).decode("utf-8"),
                fields=fields)
        flows.append(f)
    return flows
