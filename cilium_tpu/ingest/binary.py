"""Binary flow captures: zero-copy ingest of fixed-size records.

Reference: the datapath's perf-ring events are fixed-size C structs
(``bpf/lib/events.h`` — PolicyVerdictNotify et al.) consumed by
``pkg/monitor`` (SURVEY.md §2.5, §2.7 "perf/ring buffer"). Ours mirrors
that split: L3/L4 flow tuples ride a packed 32-byte little-endian
record (written/validated by the native codec,
``native/capture/capture.cpp`` → ``libcilium_capture.so``), and the
Python side maps them STRAIGHT into a numpy structured array — no
per-record parsing between disk and the engine's ``encode_flows``. L7
payloads (paths/qnames/topics) are not carried — they aren't in the
reference's ring events either (L7 arrives via the accesslog path);
JSONL remains the capture format for L7 flows.

The native library is built on demand (``make -C native/capture``,
same discipline as the proxylib shim); if the toolchain is missing, a
pure-numpy fallback reads/writes the identical format — the reference
likewise pairs its C event layout with a Go reader.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Iterable, List, Optional

import numpy as np

from cilium_tpu.core.flow import (
    Flow,
    L7Type,
    Protocol,
    TrafficDirection,
    Verdict,
)

REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
NATIVE_DIR = os.path.join(REPO, "native", "capture")
LIB_PATH = os.path.join(NATIVE_DIR, "libcilium_capture.so")

MAGIC = b"CTCAP1\x00\x00"
VERSION = 1
HEADER = np.dtype([("magic", "S8"), ("version", "<u4"),
                   ("count", "<u4")])

#: numpy view of the C Record struct (keep in lockstep with
#: native/capture/capture.cpp)
RECORD = np.dtype([
    ("src_identity", "<u4"), ("dst_identity", "<u4"),
    ("dport", "<u2"), ("sport", "<u2"),
    ("proto", "u1"), ("direction", "u1"), ("l7_type", "u1"),
    ("verdict", "u1"),
    ("time", "<f8"),
    ("reserved0", "<u4"), ("reserved1", "<u4"),
])
assert RECORD.itemsize == 32

_lib_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_lib_tried = False


def _native() -> Optional[ctypes.CDLL]:
    """The native codec, built on demand; None if unbuildable."""
    global _lib, _lib_tried
    with _lib_lock:
        if _lib is not None or _lib_tried:
            return _lib
        _lib_tried = True
        if not os.path.exists(LIB_PATH):
            try:
                subprocess.run(["make", "-C", NATIVE_DIR],
                               check=True, capture_output=True)
            except (OSError, subprocess.CalledProcessError):
                return None
        try:
            lib = ctypes.CDLL(LIB_PATH)
        except OSError:
            return None
        lib.ct_capture_record_size.restype = ctypes.c_int
        if lib.ct_capture_record_size() != RECORD.itemsize:
            return None  # layout drift: refuse rather than corrupt
        lib.ct_capture_write.restype = ctypes.c_int
        lib.ct_capture_write.argtypes = [ctypes.c_char_p,
                                         ctypes.c_void_p,
                                         ctypes.c_uint32]
        lib.ct_capture_count.restype = ctypes.c_int
        lib.ct_capture_count.argtypes = [ctypes.c_char_p]
        lib.ct_capture_read.restype = ctypes.c_int
        lib.ct_capture_read.argtypes = [ctypes.c_char_p,
                                        ctypes.c_void_p,
                                        ctypes.c_uint32,
                                        ctypes.c_uint32]
        _lib = lib
        return _lib


class CaptureError(ValueError):
    pass


_ERRORS = {-1: "io error", -2: "bad magic", -3: "unsupported version",
           -4: "truncated capture"}


def _check(rc: int) -> int:
    if rc < 0:
        raise CaptureError(_ERRORS.get(rc, f"error {rc}"))
    return rc


# -- record array ↔ Flow ----------------------------------------------------

def flows_to_records(flows: Iterable[Flow]) -> np.ndarray:
    flows = list(flows)
    rec = np.zeros(len(flows), dtype=RECORD)
    for i, f in enumerate(flows):
        # l7_type is recorded as NONE: the record carries no payload,
        # and a NONE-payload HTTP/Kafka flow would re-verdict
        # DIFFERENTLY than its source (empty path vs the real one) —
        # a converted capture must replay as the L3/L4 tuple it is
        rec[i] = (f.src_identity, f.dst_identity, f.dport, f.sport,
                  int(f.protocol), int(f.direction), int(L7Type.NONE),
                  int(f.verdict), f.time, 0, 0)
    return rec


def records_to_flows(rec: np.ndarray) -> List[Flow]:
    return [
        Flow(src_identity=int(r["src_identity"]),
             dst_identity=int(r["dst_identity"]),
             dport=int(r["dport"]), sport=int(r["sport"]),
             protocol=Protocol(int(r["proto"])),
             direction=TrafficDirection(int(r["direction"])),
             l7=L7Type(int(r["l7_type"])),
             verdict=Verdict(int(r["verdict"])),
             time=float(r["time"]))
        for r in rec
    ]


# -- file IO ---------------------------------------------------------------

def write_capture(path: str, flows: Iterable[Flow]) -> int:
    rec = flows_to_records(flows)
    lib = _native()
    if lib is not None:
        buf = np.ascontiguousarray(rec)
        _check(lib.ct_capture_write(
            path.encode(), buf.ctypes.data_as(ctypes.c_void_p),
            len(buf)))
        return len(buf)
    header = np.zeros(1, dtype=HEADER)
    header[0] = (MAGIC, VERSION, len(rec))
    with open(path, "wb") as fp:
        fp.write(header.tobytes())
        fp.write(rec.tobytes())
    return len(rec)


def capture_count(path: str) -> int:
    lib = _native()
    if lib is not None:
        return _check(lib.ct_capture_count(path.encode()))
    with open(path, "rb") as fp:
        raw = fp.read(HEADER.itemsize)
        if len(raw) < HEADER.itemsize:
            raise CaptureError("truncated capture")
        h = np.frombuffer(raw, dtype=HEADER)[0]
        if bytes(h["magic"]).ljust(8, b"\x00") != MAGIC:
            raise CaptureError("bad magic")
        if int(h["version"]) != VERSION:
            raise CaptureError("unsupported version")
        fp.seek(0, os.SEEK_END)
        want = HEADER.itemsize + int(h["count"]) * RECORD.itemsize
        if fp.tell() != want:
            raise CaptureError("truncated capture")
        return int(h["count"])


def read_records(path: str, start: int = 0,
                 limit: Optional[int] = None) -> np.ndarray:
    """Records as a structured array — the zero-parse ingest path."""
    total = capture_count(path)
    start = min(start, total)
    n = total - start if limit is None else min(limit, total - start)
    if n <= 0:
        return np.zeros(0, dtype=RECORD)
    lib = _native()
    if lib is not None:
        out = np.zeros(n, dtype=RECORD)
        got = _check(lib.ct_capture_read(
            path.encode(), out.ctypes.data_as(ctypes.c_void_p), n,
            start))
        return out[:got]
    with open(path, "rb") as fp:
        fp.seek(HEADER.itemsize + start * RECORD.itemsize)
        return np.frombuffer(fp.read(n * RECORD.itemsize),
                             dtype=RECORD).copy()


def read_capture(path: str, start: int = 0,
                 limit: Optional[int] = None) -> List[Flow]:
    return records_to_flows(read_records(path, start=start, limit=limit))


def map_capture(path: str):
    """Validate once, then expose the records as a read-only memmap —
    the chunked-replay path: one open, no per-chunk revalidation."""
    total = capture_count(path)
    if total == 0:
        return np.zeros(0, dtype=RECORD)
    return np.memmap(path, dtype=RECORD, mode="r",
                     offset=HEADER.itemsize, shape=(total,))
