"""Replay cursor: durable progress through a flow capture.

Reference discipline: SURVEY.md §5.4 ("flow-replay cursor
checkpointing") / §5.3 ("replay harness supports kill/resume
mid-stream") — a replay killed at any point resumes where it left off
instead of re-verdicting (and double-counting in metrics/observers)
everything before the kill. The cursor is a tiny JSON file updated
atomically (tmp + rename) after every committed chunk, the same
write-then-rename pattern the agent's checkpoint files use.
"""

from __future__ import annotations

import dataclasses
import json
import os
from typing import Optional


@dataclasses.dataclass
class RawChunk:
    """One ``decode=False`` chunk of a binary capture: the record
    slice plus whole-capture context (sidecar sections, widths, the
    full L7 array) so columnar consumers never re-read the file.
    ``l7``/``offsets``/``blob``/``widths``/``l7_all`` are None for v1
    (L3/L4-only) captures; ``gen``/``gen_all`` (the v3 GENERIC
    section slice / whole array) are None below v3. ``start`` is the
    chunk's global record index — CaptureReplay uses it to slice its
    row-aligned generic columns."""

    records: object
    l7: object = None
    offsets: object = None
    blob: object = None
    widths: object = None
    l7_all: object = None
    gen: object = None
    gen_all: object = None
    start: int = 0
    #: the whole capture's record array (memmap) — lets a replay
    #: session featurize the file ONCE (CaptureReplay.stage_rows)
    records_all: object = None

    def __len__(self) -> int:  # noqa: D105 — chunk length = records
        return len(self.records)


class ReplayCursor:
    """Durable index into a capture file, keyed to that capture."""

    def __init__(self, path: str, capture: str):
        self.path = path
        self.capture = os.path.abspath(capture)

    def load(self) -> int:
        """Resume index, or 0 when absent/corrupt/for another capture
        (a cursor from a different capture must not skip flows)."""
        try:
            with open(self.path) as f:
                data = json.load(f)
            if data.get("capture") != self.capture:
                return 0
            return max(0, int(data["index"]))
        except (OSError, ValueError, KeyError, TypeError,
                AttributeError):  # valid JSON of the wrong shape too
            return 0

    def commit(self, index: int) -> None:
        tmp = self.path + ".tmp"
        with open(tmp, "w") as f:
            json.dump({"capture": self.capture, "index": int(index)}, f)
        os.replace(tmp, self.path)

    def clear(self) -> None:
        try:
            os.unlink(self.path)
        except FileNotFoundError:
            pass


def replay_chunks(capture: str, chunk_size: int = 8192,
                  cursor: Optional[ReplayCursor] = None,
                  start: int = 0, limit: Optional[int] = None,
                  decode: bool = True):
    """Yield ``(commit_index, flows)`` chunks, resuming from the cursor
    when one is given. ``commit_index`` is the LINE index just past the
    chunk — commit it verbatim after fully processing the chunk
    (commit-after-process: a kill re-runs at most one chunk, never
    skips one). Line-indexed, not flow-indexed, so blank lines can
    neither double-deliver nor silently truncate a resume. One open
    file handle for the whole pass (a per-chunk reopen-and-skip would
    be quadratic in capture size). ``limit`` counts flows.
    ``decode=False`` (binary captures only) yields raw record arrays
    instead of Flow lists — the columnar fast path — under the SAME
    cursor protocol, so kill/resume semantics live in one place."""
    from cilium_tpu.ingest.accesslog import parse_capture_line

    index = max(start, cursor.load() if cursor is not None else 0)
    emitted = 0
    from cilium_tpu.ingest.binary import MAGIC

    with open(capture, "rb") as probe:
        is_binary = probe.read(len(MAGIC)) == MAGIC
    if is_binary:
        # binary captures (ingest/binary.py): the cursor indexes
        # records — fixed-size, so no blank-line concerns; validated
        # once and memmapped, so chunking costs one open total. A v2
        # capture's L7 sidecar is loaded once; decode=True rebuilds
        # Flow objects WITH payloads, decode=False yields
        # (records, l7_records) so the columnar path can gather
        # against the (whole-capture) string table.
        from cilium_tpu.ingest.binary import (
            VERSION_L7,
            VERSION_L7G,
            capture_field_widths,
            capture_version,
            map_capture,
            read_gen_sidecar,
            read_l7_sidecar,
            records_to_flows,
            records_to_flows_l7,
        )

        records = map_capture(capture)
        version = capture_version(capture)
        side = (read_l7_sidecar(capture)
                if version in (VERSION_L7, VERSION_L7G) else None)
        gen_all = read_gen_sidecar(capture)  # None below v3
        # whole-capture field widths ride along so the columnar
        # consumer encodes every chunk to identical shapes (one jit
        # compile for the stream) without re-reading the sidecar
        widths = (capture_field_widths(side[0], side[1])
                  if side is not None and not decode else None)
        while index < len(records):
            take = chunk_size if limit is None else min(
                chunk_size, limit - emitted)
            if take <= 0:
                return
            raw = records[index:index + take]
            if side is not None:
                l7, offsets, blob = side
                l7raw = l7[index:index + len(raw)]
                genraw = (gen_all[index:index + len(raw)]
                          if gen_all is not None else None)
                chunk = (records_to_flows_l7(raw, l7raw, offsets, blob,
                                             gen=genraw)
                         if decode else RawChunk(
                             raw, l7raw, offsets, blob, widths, l7,
                             genraw, gen_all, index, records))
            else:
                chunk = (records_to_flows(raw) if decode
                         else RawChunk(raw))
            yield index + len(raw), chunk
            index += len(raw)
            emitted += len(raw)
        return
    from cilium_tpu.ingest.flowpb import looks_like_pb_capture

    if looks_like_pb_capture(capture):
        # protobuf flow stream (api/v1/flow framing): cursor indexes
        # MESSAGES; decode is per-flow by nature (object path only)
        if not decode:
            from cilium_tpu.ingest.binary import CaptureError

            raise CaptureError("bad magic")  # columnar needs CTCAP
        from cilium_tpu.ingest.flowpb import iter_pb_capture

        flows = []
        for f in iter_pb_capture(capture, start=index, limit=limit):
            flows.append(f)
            index += 1
            if len(flows) >= chunk_size:
                yield index, flows
                flows = []
        if flows:
            yield index, flows
        return
    if not decode:
        from cilium_tpu.ingest.binary import CaptureError

        raise CaptureError("bad magic")  # raw mode is binary-only
    with open(capture) as fp:
        for _ in range(index):
            if not fp.readline():
                return  # cursor beyond EOF: nothing left
        line_no = index
        flows = []
        for line in fp:
            line_no += 1
            s = line.strip()
            if s:
                flows.append(parse_capture_line(json.loads(s)))
                emitted += 1
            done = limit is not None and emitted >= limit
            if len(flows) >= chunk_size or done:
                yield line_no, flows
                flows = []
                if done:
                    return
        if flows:
            yield line_no, flows
