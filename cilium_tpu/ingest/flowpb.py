"""Protobuf wire-format codec for the Hubble ``flowpb.Flow`` subset
the verdict engine consumes — no protoc/generated code, just the wire
grammar (varint, 64-bit, length-delimited, 32-bit) with unknown fields
skipped, so REAL pb captures replay without a schema compile step.

Reference: ``api/v1/flow/flow.proto`` (SURVEY.md §2.5). Field numbers
follow the upstream layout; per the SURVEY provenance note they are
UNVERIFIED against /root/reference (empty at survey time) — they are
kept in one table (`_FLOW_FIELDS` et al.) so re-anchoring against the
real proto is a constant-table edit. The encoder writes the same
numbers, giving self-consistent fixtures and exporter parity either
way.

Captures are streams of varint-length-prefixed Flow messages (the
standard protobuf stream framing; Hubble's gRPC messages are delimited
the same way once off the wire).
"""

from __future__ import annotations

from typing import Iterator, List, Optional, Tuple

from cilium_tpu.core.flow import (
    DNSInfo,
    Flow,
    HTTPInfo,
    KafkaInfo,
    L7Type,
    Protocol,
    TrafficDirection,
    Verdict,
)
from cilium_tpu.ingest.binary import CaptureError

# -- wire primitives -------------------------------------------------------

_VARINT, _I64, _LEN, _I32 = 0, 1, 2, 5


class PBError(CaptureError):
    """Wire-grammar failure. Subclasses CaptureError so the cursor /
    CLI paths that degrade cleanly on a corrupt CTCAP degrade the same
    way on a corrupt pb stream (ADVICE r3 #4)."""


def _read_varint(buf: memoryview, pos: int) -> Tuple[int, int]:
    out = 0
    shift = 0
    while True:
        if pos >= len(buf):
            raise PBError("truncated varint")
        b = buf[pos]
        pos += 1
        out |= (b & 0x7F) << shift
        if not b & 0x80:
            return out, pos
        shift += 7
        if shift > 63:
            raise PBError("varint too long")


def _write_varint(out: bytearray, v: int) -> None:
    if v < 0:
        # a negative Python int never reaches 0 under >>= 7; protobuf
        # negative ints are a 10-byte two's-complement encoding we
        # deliberately don't emit (no field needs it) — error loudly
        # instead of hanging the encoder (ADVICE r3 #3)
        raise PBError(f"negative varint {v}")
    while True:
        b = v & 0x7F
        v >>= 7
        if v:
            out.append(b | 0x80)
        else:
            out.append(b)
            return


def _fields(data: memoryview) -> Iterator[Tuple[int, int, object]]:
    """Yield (field_number, wire_type, value); LEN values come back as
    memoryviews, unknown-but-valid wire types are decoded so callers
    can skip them for free."""
    pos = 0
    while pos < len(data):
        tag, pos = _read_varint(data, pos)
        field, wt = tag >> 3, tag & 7
        if wt == _VARINT:
            v, pos = _read_varint(data, pos)
        elif wt == _I64:
            if pos + 8 > len(data):
                raise PBError("truncated i64")
            v = bytes(data[pos:pos + 8])
            pos += 8
        elif wt == _LEN:
            n, pos = _read_varint(data, pos)
            if pos + n > len(data):
                raise PBError("truncated length-delimited field")
            v = data[pos:pos + n]
            pos += n
        elif wt == _I32:
            if pos + 4 > len(data):
                raise PBError("truncated i32")
            v = bytes(data[pos:pos + 4])
            pos += 4
        else:
            raise PBError(f"unsupported wire type {wt}")
        yield field, wt, v


def _tag(out: bytearray, field: int, wt: int) -> None:
    _write_varint(out, (field << 3) | wt)


def _put_len(out: bytearray, field: int, payload: bytes) -> None:
    _tag(out, field, _LEN)
    _write_varint(out, len(payload))
    out += payload


def _put_varint(out: bytearray, field: int, v: int) -> None:
    if v:
        _tag(out, field, _VARINT)
        _write_varint(out, v)


def _put_str(out: bytearray, field: int, s: str) -> None:
    if s:
        _put_len(out, field, s.encode("utf-8"))


# -- flow.proto field tables (upstream layout, UNVERIFIED — see module
#    docstring; keep every number here, nowhere else) ----------------------

#: Flow message
_F_TIME, _F_VERDICT, _F_L4, _F_SOURCE, _F_DEST = 1, 2, 6, 8, 9
_F_NODE_NAME, _F_L7, _F_TRAFFIC_DIR, _F_MATCH_TYPE = 11, 15, 22, 23
#: Endpoint message
_E_IDENTITY, _E_NAMESPACE, _E_LABELS, _E_POD = 2, 3, 4, 5
#: Layer4 oneof
_L4_TCP, _L4_UDP, _L4_ICMP4, _L4_ICMP6, _L4_SCTP = 1, 2, 3, 4, 5
#: TCP/UDP/SCTP port messages
_P_SPORT, _P_DPORT = 1, 2
#: ICMP message
_ICMP_TYPE = 1
#: Layer7 message (oneof record uses high field numbers upstream)
_L7_TYPE, _L7_DNS, _L7_HTTP, _L7_KAFKA = 1, 100, 101, 102
#: HTTP message
_H_CODE, _H_METHOD, _H_URL, _H_PROTOCOL, _H_HEADERS = 1, 2, 3, 4, 5
_HDR_KEY, _HDR_VALUE = 1, 2
#: DNS message (query=1 … observation_source=5, rcode=6 per the
#: upstream flow.proto ordering — rcode at 5 was knowably off,
#: ADVICE r3 #2)
_D_QUERY, _D_RCODE = 1, 6
#: Kafka message
_K_ERROR, _K_VERSION, _K_APIKEY, _K_CORRELATION, _K_TOPIC = 1, 2, 3, 4, 5

#: flowpb L7FlowType REQUEST
_L7_REQUEST = 1

#: Kafka.api_key rides the wire as the ROLE STRING upstream
#: ("produce"/"fetch"/...); numeric api keys map both ways. DERIVED
#: from the repo's one canonical table (``policy/api/l7.py
#: ·KAFKA_API_KEYS``, mirroring upstream ``pkg/policy/api/kafka.go``)
#: so the wire codec and the ACL matcher cannot diverge (ADVICE r3
#: #1: an unknown name must NOT collapse to 0/produce, which would
#: falsely match produce-scoped ACLs).
from cilium_tpu.policy.api.l7 import (  # noqa: E402
    KAFKA_API_KEYS as _KAFKA_APIKEY_NUMS,
)

_KAFKA_APIKEY_NAMES = {v: k for k, v in _KAFKA_APIKEY_NUMS.items()}
#: unknown-role sentinel: matches only api-key-unconstrained rules
KAFKA_APIKEY_UNKNOWN = -1


# -- decode ----------------------------------------------------------------

def _dec_endpoint(data: memoryview) -> Tuple[int, Tuple[str, ...]]:
    identity = 0
    labels: List[str] = []
    for field, wt, v in _fields(data):
        if field == _E_IDENTITY and wt == _VARINT:
            identity = int(v)
        elif field == _E_LABELS and wt == _LEN:
            labels.append(bytes(v).decode("utf-8", "replace"))
    return identity, tuple(labels)


def _dec_ports(data: memoryview) -> Tuple[int, int]:
    sport = dport = 0
    for field, wt, v in _fields(data):
        if field == _P_SPORT and wt == _VARINT:
            sport = int(v)
        elif field == _P_DPORT and wt == _VARINT:
            dport = int(v)
    return sport, dport


def _dec_http(data: memoryview) -> HTTPInfo:
    h = HTTPInfo()
    headers: List[Tuple[str, str]] = []
    for field, wt, v in _fields(data):
        if field == _H_METHOD and wt == _LEN:
            h.method = bytes(v).decode("utf-8", "replace")
        elif field == _H_URL and wt == _LEN:
            from cilium_tpu.ingest.hubble import split_http_url

            path, url_host = split_http_url(
                bytes(v).decode("utf-8", "replace"))
            h.path = path
            if url_host and not h.host:
                h.host = url_host
        elif field == _H_PROTOCOL and wt == _LEN:
            h.protocol = bytes(v).decode("utf-8", "replace")
        elif field == _H_CODE and wt == _VARINT:
            h.code = int(v)
        elif field == _H_HEADERS and wt == _LEN:
            k = val = ""
            for hf, hwt, hv in _fields(v):
                if hf == _HDR_KEY and hwt == _LEN:
                    k = bytes(hv).decode("utf-8", "replace")
                elif hf == _HDR_VALUE and hwt == _LEN:
                    val = bytes(hv).decode("utf-8", "replace")
            headers.append((k, val))
    h.headers = tuple(headers)
    return h


def _dec_dns(data: memoryview) -> DNSInfo:
    d = DNSInfo(qtypes=())
    for field, wt, v in _fields(data):
        if field == _D_QUERY and wt == _LEN:
            d.query = bytes(v).decode("utf-8", "replace")
        elif field == _D_RCODE and wt == _VARINT:
            d.rcode = int(v)
    return d


def _dec_kafka(data: memoryview) -> KafkaInfo:
    k = KafkaInfo()
    for field, wt, v in _fields(data):
        if field == _K_VERSION and wt == _VARINT:
            k.api_version = int(v)
        elif field == _K_APIKEY and wt == _LEN:
            name = bytes(v).decode("utf-8", "replace")
            if name in _KAFKA_APIKEY_NUMS:
                k.api_key = _KAFKA_APIKEY_NUMS[name]
            elif name.isdigit():
                # our encoder (and any numeric exporter) writes the
                # raw api key for roles without a name — mapping those
                # to 0/produce would rewrite the ACL being checked
                k.api_key = int(name)
            else:
                # unknown role string: sentinel, never 0/produce
                k.api_key = KAFKA_APIKEY_UNKNOWN
        elif field == _K_CORRELATION and wt == _VARINT:
            k.correlation_id = int(v)
        elif field == _K_TOPIC and wt == _LEN:
            k.topic = bytes(v).decode("utf-8", "replace")
    return k


def _dec_l7(data: memoryview, f: Flow) -> None:
    for field, wt, v in _fields(data):
        if field == _L7_HTTP and wt == _LEN:
            f.l7 = L7Type.HTTP
            f.http = _dec_http(v)
        elif field == _L7_DNS and wt == _LEN:
            f.l7 = L7Type.DNS
            f.dns = _dec_dns(v)
        elif field == _L7_KAFKA and wt == _LEN:
            f.l7 = L7Type.KAFKA
            f.kafka = _dec_kafka(v)


def _dec_l4(data: memoryview, f: Flow) -> None:
    for field, wt, v in _fields(data):
        if wt != _LEN:
            continue
        if field == _L4_TCP:
            f.protocol = Protocol.TCP
            f.sport, f.dport = _dec_ports(v)
        elif field == _L4_UDP:
            f.protocol = Protocol.UDP
            f.sport, f.dport = _dec_ports(v)
        elif field == _L4_SCTP:
            f.protocol = Protocol.SCTP
            f.sport, f.dport = _dec_ports(v)
        elif field in (_L4_ICMP4, _L4_ICMP6):
            f.protocol = (Protocol.ICMP if field == _L4_ICMP4
                          else Protocol.ICMPV6)
            for pf, pwt, pv in _fields(v):
                if pf == _ICMP_TYPE and pwt == _VARINT:
                    f.dport = int(pv)  # type rides the port slot


def decode_flow(data: bytes) -> Flow:
    f = Flow()
    for field, wt, v in _fields(memoryview(data)):
        if field == _F_VERDICT and wt == _VARINT:
            try:
                f.verdict = Verdict(int(v))
            except ValueError:
                pass
        elif field == _F_L4 and wt == _LEN:
            _dec_l4(v, f)
        elif field == _F_SOURCE and wt == _LEN:
            f.src_identity, f.src_labels = _dec_endpoint(v)
        elif field == _F_DEST and wt == _LEN:
            f.dst_identity, f.dst_labels = _dec_endpoint(v)
        elif field == _F_NODE_NAME and wt == _LEN:
            f.node_name = bytes(v).decode("utf-8", "replace")
        elif field == _F_L7 and wt == _LEN:
            _dec_l7(v, f)
        elif field == _F_TRAFFIC_DIR and wt == _VARINT:
            # flowpb: 1=INGRESS 2=EGRESS (0 unknown → default ingress)
            f.direction = (TrafficDirection.EGRESS if int(v) == 2
                           else TrafficDirection.INGRESS)
        elif field == _F_TIME and wt == _LEN:
            secs = nanos = 0
            for tf, twt, tv in _fields(v):
                if tf == 1 and twt == _VARINT:
                    secs = int(tv)
                elif tf == 2 and twt == _VARINT:
                    nanos = int(tv)
            f.time = secs + nanos / 1e9
    return f


# -- encode (fixture/exporter parity) --------------------------------------

def _enc_endpoint(identity: int, labels: Tuple[str, ...]) -> bytes:
    out = bytearray()
    _put_varint(out, _E_IDENTITY, identity)
    for lbl in labels or ():
        _put_str(out, _E_LABELS, lbl)
    return bytes(out)


def encode_flow(f: Flow) -> bytes:
    out = bytearray()
    if f.time:
        ts = bytearray()
        _put_varint(ts, 1, int(f.time))
        _put_varint(ts, 2, int((f.time % 1) * 1e9))
        _put_len(out, _F_TIME, bytes(ts))
    _put_varint(out, _F_VERDICT, int(f.verdict))
    l4 = bytearray()
    ports = bytearray()
    if f.protocol in (Protocol.ICMP, Protocol.ICMPV6):
        _put_varint(ports, _ICMP_TYPE, f.dport)
        _put_len(l4, _L4_ICMP4 if f.protocol == Protocol.ICMP
                 else _L4_ICMP6, bytes(ports))
    else:
        _put_varint(ports, _P_SPORT, f.sport)
        _put_varint(ports, _P_DPORT, f.dport)
        oneof = {Protocol.TCP: _L4_TCP, Protocol.UDP: _L4_UDP,
                 Protocol.SCTP: _L4_SCTP}.get(f.protocol, _L4_TCP)
        _put_len(l4, oneof, bytes(ports))
    _put_len(out, _F_L4, bytes(l4))
    _put_len(out, _F_SOURCE,
             _enc_endpoint(f.src_identity, getattr(f, "src_labels", ())))
    _put_len(out, _F_DEST,
             _enc_endpoint(f.dst_identity, getattr(f, "dst_labels", ())))
    _put_str(out, _F_NODE_NAME, getattr(f, "node_name", ""))
    if f.l7 != L7Type.NONE:
        l7 = bytearray()
        _put_varint(l7, _L7_TYPE, _L7_REQUEST)
        if f.l7 == L7Type.HTTP and f.http:
            h = bytearray()
            _put_varint(h, _H_CODE, f.http.code)
            _put_str(h, _H_METHOD, f.http.method)
            _put_str(h, _H_URL, f.http.path)
            _put_str(h, _H_PROTOCOL, f.http.protocol)
            for k, v in f.http.headers or ():
                hdr = bytearray()
                _put_str(hdr, _HDR_KEY, k)
                _put_str(hdr, _HDR_VALUE, v)
                _put_len(h, _H_HEADERS, bytes(hdr))
            _put_len(l7, _L7_HTTP, bytes(h))
        elif f.l7 == L7Type.DNS and f.dns:
            d = bytearray()
            _put_str(d, _D_QUERY, f.dns.query)
            _put_varint(d, _D_RCODE, f.dns.rcode)
            _put_len(l7, _L7_DNS, bytes(d))
        elif f.l7 == L7Type.KAFKA and f.kafka:
            k = bytearray()
            _put_varint(k, _K_VERSION, f.kafka.api_version)
            _put_str(k, _K_APIKEY,
                     _KAFKA_APIKEY_NAMES.get(f.kafka.api_key,
                                             str(f.kafka.api_key)))
            _put_varint(k, _K_CORRELATION, f.kafka.correlation_id)
            _put_str(k, _K_TOPIC, f.kafka.topic)
            _put_len(l7, _L7_KAFKA, bytes(k))
        _put_len(out, _F_L7, bytes(l7))
    _put_varint(out, _F_TRAFFIC_DIR,
                2 if f.direction == TrafficDirection.EGRESS else 1)
    return bytes(out)


# -- stream framing --------------------------------------------------------

def write_pb_capture(path: str, flows) -> int:
    """Varint-length-prefixed Flow stream (protobuf stream framing)."""
    n = 0
    with open(path, "wb") as fp:
        for f in flows:
            msg = encode_flow(f)
            pre = bytearray()
            _write_varint(pre, len(msg))
            fp.write(pre)
            fp.write(msg)
            n += 1
    return n


def read_pb_capture(path: str, start: int = 0,
                    limit: Optional[int] = None) -> List[Flow]:
    return list(iter_pb_capture(path, start=start, limit=limit))


def iter_pb_capture(path: str, start: int = 0,
                    limit: Optional[int] = None) -> Iterator[Flow]:
    import mmap

    with open(path, "rb") as fp:
        if not fp.read(1):
            return  # empty capture
        fp.seek(0)
        # mmap keeps memory flat on multi-GB captures (same discipline
        # as the CTCAP path's memmap); skipped messages before `start`
        # cost a varint read each, never a decode
        with mmap.mmap(fp.fileno(), 0, access=mmap.ACCESS_READ) as mm:
            data = memoryview(mm)
            try:
                pos = 0
                idx = 0
                emitted = 0
                while pos < len(data):
                    n, pos = _read_varint(data, pos)
                    if pos + n > len(data):
                        raise PBError("truncated message")
                    if idx >= start:
                        if limit is not None and emitted >= limit:
                            return
                        yield decode_flow(bytes(data[pos:pos + n]))
                        emitted += 1
                    idx += 1
                    pos += n
            finally:
                data.release()  # else mmap.close() raises BufferError


def looks_like_pb_capture(path: str) -> bool:
    """Sniff: not our CTCAP binary, not JSONL — and the FIRST full
    message must actually decode as a Flow (a leading varint alone
    accepts ~any binary garbage and would route corrupt files into the
    pb replay path — ADVICE r3 #4)."""
    with open(path, "rb") as fp:
        head = fp.read(16)
        if not head or head[:1] in (b"{", b"[", b" ", b"\n"):
            return False
        from cilium_tpu.ingest.binary import MAGIC

        if head.startswith(MAGIC):
            return False
        try:
            n, pos = _read_varint(memoryview(head), 0)
            if not 0 < n < 1 << 24:
                return False
            fp.seek(pos)
            msg = fp.read(n)
            if len(msg) < n:
                return False
            decode_flow(msg)
            return True
        except ValueError:
            # PBError, but also e.g. urlsplit errors from a bogus URL
            # field — any first-message decode failure means "not ours"
            return False
