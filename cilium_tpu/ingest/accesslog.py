"""Envoy accesslog ingest: reference-shaped L7 capture lines → Flow.

Reference: the cilium-envoy accesslog (``pkg/envoy`` accesslog server,
``proxylib/accesslog`` proto — ``LogEntry`` with ``http``/``kafka``
sub-records and source/destination security identities) feeds
``pkg/hubble/parser/seven``. This module accepts the JSON encoding of
those entries so a capture taken against the reference proxy can
replay through this engine directly (VERDICT r1 missing #7).

Accepted line shape (tolerant; unknown fields ignored)::

    {"entry_type": "Request"|"Denied",
     "timestamp": <epoch or RFC3339>,
     "is_ingress": true,
     "source_security_id": 1234, "destination_security_id": 5678,
     "source_address": "10.0.0.1:42342",
     "destination_address": "10.0.0.2:80",
     "http": {"http_protocol": "HTTP/1.1", "host": "svc.local",
              "path": "/api/v1", "method": "GET",
              "headers": [{"key": "X-A", "value": "b"}, ...]},
     "kafka": {"api_key": 0, "api_version": 3, "topic": "t",
               "correlation_id": 7}}

``parse_capture_line`` dispatches between this shape and the flowpb
JSON shape (ingest/hubble.py), so one capture file may mix both.
"""

from __future__ import annotations

from typing import Dict

from cilium_tpu.core.flow import (
    Flow,
    HTTPInfo,
    KafkaInfo,
    L7Type,
    Protocol,
    TrafficDirection,
)
from cilium_tpu.ingest.hubble import _to_time, flow_from_dict


def is_accesslog_entry(d: Dict) -> bool:
    """Accesslog entries carry the proxy-side field names; flowpb
    flows carry ``source``/``destination``/``l4``/``l7`` objects."""
    return ("source" not in d and "flow" not in d) and (
        "entry_type" in d or "is_ingress" in d
        or "source_security_id" in d or "http" in d or "kafka" in d)


def _split_addr(addr: str) -> tuple:
    """``ip:port`` → (ip, port), handling IPv6: bracketed
    ``[2001:db8::1]:80`` and bare v6 literals (no port — a bare
    literal's last hextet must NOT be read as a port)."""
    if not addr:
        return "", 0
    if addr.startswith("["):
        host, _, rest = addr[1:].partition("]")
        if rest.startswith(":"):
            try:
                return host, int(rest[1:])
            except ValueError:
                return host, 0
        return host, 0
    if addr.count(":") == 1:
        host, _, port = addr.partition(":")
        try:
            return host, int(port)
        except ValueError:
            return host, 0
    return addr, 0  # bare IPv6 literal (or plain v4 host)


def accesslog_to_flow(d: Dict) -> Flow:
    from cilium_tpu.core.flow import Verdict

    f = Flow()
    f.time = _to_time(d.get("timestamp"))
    if str(d.get("entry_type", "")).lower() == "denied":
        # a Denied entry IS the proxy's verdict — hubble metrics and
        # GetFlows must see DROPPED, not VERDICT_UNKNOWN
        f.verdict = Verdict.DROPPED
    ingress = bool(d.get("is_ingress", True))
    f.direction = (TrafficDirection.INGRESS if ingress
                   else TrafficDirection.EGRESS)
    f.src_identity = int(d.get("source_security_id", 0) or 0)
    f.dst_identity = int(d.get("destination_security_id", 0) or 0)
    f.src_ip, f.sport = _split_addr(d.get("source_address", "") or "")
    f.dst_ip, f.dport = _split_addr(
        d.get("destination_address", "") or "")
    f.protocol = Protocol.TCP  # the proxy only fronts TCP
    if isinstance(d.get("http"), dict):
        h = d["http"]
        f.l7 = L7Type.HTTP
        f.http = HTTPInfo(
            method=h.get("method", "") or "",
            path=h.get("path", "") or "",
            host=h.get("host", "") or "",
            headers=tuple((x.get("key", ""), x.get("value", ""))
                          for x in (h.get("headers") or ())),
            protocol=h.get("http_protocol", "HTTP/1.1") or "HTTP/1.1",
            code=int(h.get("status", 0) or 0),
        )
    elif isinstance(d.get("kafka"), dict):
        k = d["kafka"]
        f.l7 = L7Type.KAFKA
        f.kafka = KafkaInfo(
            api_key=int(k.get("api_key", 0) or 0),
            api_version=int(k.get("api_version", 0) or 0),
            client_id=k.get("client_id", "") or "",
            topic=k.get("topic", "") or "",
            correlation_id=int(k.get("correlation_id", 0) or 0),
        )
    return f


def parse_capture_line(d: Dict) -> Flow:
    """One capture line (either schema) → Flow."""
    if is_accesslog_entry(d):
        return accesslog_to_flow(d)
    return flow_from_dict(d)


def accesslog_to_columns(d: Dict) -> tuple:
    """One accesslog entry → the flat column tuple of
    ``ingest.columnar`` (COLUMN_FIELDS order) — the Flow-object-free
    half of :func:`accesslog_to_flow`, sharing its normalization
    (header serialization, host lowering, Denied→DROPPED) exactly."""
    from cilium_tpu.core.flow import Verdict
    from cilium_tpu.engine.verdict import serialize_headers

    verdict = (int(Verdict.DROPPED)
               if str(d.get("entry_type", "")).lower() == "denied"
               else int(Verdict.VERDICT_UNKNOWN))
    ingress = bool(d.get("is_ingress", True))
    direction = int(TrafficDirection.INGRESS if ingress
                    else TrafficDirection.EGRESS)
    _, sport = _split_addr(d.get("source_address", "") or "")
    _, dport = _split_addr(d.get("destination_address", "") or "")
    l7t = int(L7Type.NONE)
    path = method = host = headers = b""
    kclient = ktopic = b""
    kapi = kver = 0
    if isinstance(d.get("http"), dict):
        h = d["http"]
        l7t = int(L7Type.HTTP)
        path = (h.get("path", "") or "").encode("utf-8")
        method = (h.get("method", "") or "").encode("utf-8")
        host = (h.get("host", "") or "").lower().encode("utf-8")
        headers = serialize_headers(tuple(
            (x.get("key", ""), x.get("value", ""))
            for x in (h.get("headers") or ())))
    elif isinstance(d.get("kafka"), dict):
        k = d["kafka"]
        l7t = int(L7Type.KAFKA)
        kapi = int(k.get("api_key", 0) or 0)
        kver = int(k.get("api_version", 0) or 0)
        kclient = (k.get("client_id", "") or "").encode("utf-8")
        ktopic = (k.get("topic", "") or "").encode("utf-8")
    return (_to_time(d.get("timestamp")), verdict, direction,
            int(d.get("source_security_id", 0) or 0),
            int(d.get("destination_security_id", 0) or 0),
            sport, dport, int(Protocol.TCP), l7t,
            path, method, host, headers, b"",
            kclient, ktopic, kapi, kver, b"", ())


def capture_line_to_columns(d: Dict) -> tuple:
    """One capture line (either schema) → column tuple (the
    Flow-object-free twin of :func:`parse_capture_line`)."""
    from cilium_tpu.ingest.hubble import flow_dict_to_columns

    if is_accesslog_entry(d):
        return accesslog_to_columns(d)
    return flow_dict_to_columns(d)
