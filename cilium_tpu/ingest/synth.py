"""Synthetic generators for the five BASELINE.json benchmark configs.

Each returns a :class:`SynthScenario`: rules + endpoint label sets +
flows, ready to resolve and replay. Shapes follow BASELINE.md:

0. toFQDNs matchPattern — 100 DNS names × 10 rules
1. L7 HTTP — 1k path/header regex rules × 10k flows
2. Kafka — topic/API-key ACLs × 100k produce/fetch records
3. Mixed L3–L7 — examples/policies corpus × 1M identity/flow tuples
4. Cluster mesh — 10k identities × 5k CNPs, streaming
"""

from __future__ import annotations

import dataclasses
import random
from typing import Dict, List, Optional, Tuple

from cilium_tpu.core.flow import (
    DNSInfo,
    Flow,
    HTTPInfo,
    KafkaInfo,
    L7Type,
    Protocol,
    TrafficDirection,
)
from cilium_tpu.policy.api import (
    EndpointSelector,
    EgressRule,
    IngressRule,
    L7Rules,
    PortProtocol,
    PortRule,
    PortRuleDNS,
    PortRuleHTTP,
    PortRuleKafka,
    Rule,
)
from cilium_tpu.policy.api.l7 import PortRuleL7

ING = TrafficDirection.INGRESS
EG = TrafficDirection.EGRESS


@dataclasses.dataclass
class SynthScenario:
    name: str
    rules: List[Rule]
    endpoints: Dict[str, Dict[str, str]]   # name → label dict
    flows: List[Flow]
    # filled by the harness after identity allocation:
    ids: Optional[Dict[str, int]] = None


def _sel(**kv) -> EndpointSelector:
    return EndpointSelector.from_labels(**kv)


# ------------------------------------------------------- config 0: FQDN --
def synth_fqdn_scenario(n_names: int = 100, n_rules: int = 10,
                        n_flows: Optional[int] = None,
                        seed: int = 0) -> SynthScenario:
    rng = random.Random(seed)
    domains = ["cilium.io", "example.com", "k8s.local", "corp.internal",
               "cdn.net"]
    dns_rules = []
    for i in range(n_rules):
        base = domains[i % len(domains)]
        if i % 3 == 0:
            dns_rules.append(PortRuleDNS(match_name=f"svc{i}.{base}"))
        elif i % 3 == 1:
            dns_rules.append(PortRuleDNS(match_pattern=f"*.{base}"))
        else:
            dns_rules.append(PortRuleDNS(match_pattern=f"api-*.sub{i}.{base}"))
    rule = Rule(
        endpoint_selector=_sel(app="crawler"),
        egress=(EgressRule(to_ports=(PortRule(
            ports=(PortProtocol(53, Protocol.UDP),),
            rules=L7Rules(dns=tuple(dns_rules)),
        ),),),),
        labels=("synth=fqdn",),
    )
    names = []
    for i in range(n_names):
        base = domains[i % len(domains)]
        kind = rng.random()
        if kind < 0.3:
            names.append(f"svc{rng.randrange(n_rules)}.{base}")
        elif kind < 0.6:
            names.append(f"host{i}.{base}")
        elif kind < 0.8:
            names.append(f"api-{i}.sub{rng.randrange(n_rules)}.{base}")
        else:
            names.append(f"deep{i}.x.y.{base}")
    flows = []
    for i in range(n_flows or n_names):
        flows.append(Flow(
            src_identity=0, dst_identity=0, dport=53, protocol=Protocol.UDP,
            direction=EG, l7=L7Type.DNS,
            dns=DNSInfo(query=names[i % len(names)]),
        ))
    return SynthScenario(
        name="fqdn", rules=[rule],
        endpoints={"crawler": {"app": "crawler"},
                   "peer": {"app": "peer"}},
        flows=flows,
    )


# ------------------------------------------------------- config 1: HTTP --
def synth_http_scenario(n_rules: int = 1000, n_flows: int = 10000,
                        seed: int = 0) -> SynthScenario:
    rng = random.Random(seed)
    http_rules = []
    for i in range(n_rules):
        kind = i % 5
        if kind == 0:
            http_rules.append(PortRuleHTTP(
                method="GET", path=f"/api/v{i % 9}/svc{i}/[a-z0-9]+"))
        elif kind == 1:
            http_rules.append(PortRuleHTTP(
                method="POST", path=f"/api/v1/items/{i}(/.*)?"))
        elif kind == 2:
            http_rules.append(PortRuleHTTP(
                path=f"/public/{i}/.*", host=f"svc{i % 50}[.]local"))
        elif kind == 3:
            http_rules.append(PortRuleHTTP(
                method="GET|HEAD", path=f"/static/{i}/[0-9]+/[a-f0-9]+"))
        else:
            http_rules.append(PortRuleHTTP(
                method="PUT", path=f"/admin/{i}/config",
                headers=(f"X-Role: admin{i % 10}",)))
    rule = Rule(
        endpoint_selector=_sel(app="server"),
        ingress=(IngressRule(
            from_endpoints=(_sel(app="client"),),
            to_ports=(PortRule(
                ports=(PortProtocol(80, Protocol.TCP),),
                rules=L7Rules(http=tuple(http_rules)),
            ),),
        ),),
        labels=("synth=http",),
    )
    flows = []
    for _ in range(n_flows):
        i = rng.randrange(n_rules)
        hit = rng.random() < 0.5
        kind = i % 5
        if kind == 0:
            path = f"/api/v{i % 9}/svc{i}/x9y" if hit else f"/api/v{i % 9}/svc{i}/"
            method = "GET"
            headers: Tuple = ()
        elif kind == 1:
            path = f"/api/v1/items/{i}/sub" if hit else f"/api/v1/items/{i}x"
            method = "POST"
            headers = ()
        elif kind == 2:
            path = f"/public/{i}/a/b" if hit else f"/private/{i}/a"
            method = "GET"
            headers = ()
        elif kind == 3:
            path = (f"/static/{i}/123/abc9" if hit
                    else f"/static/{i}/123/XYZ")
            method = "HEAD"
            headers = ()
        else:
            path = f"/admin/{i}/config"
            method = "PUT"
            headers = ((("X-Role", f"admin{i % 10}"),) if hit
                       else (("X-Role", "nobody"),))
        flows.append(Flow(
            src_identity=0, dst_identity=0, dport=80, protocol=Protocol.TCP,
            direction=ING, l7=L7Type.HTTP,
            http=HTTPInfo(method=method, path=path,
                          host=f"svc{i % 50}.local", headers=headers),
        ))
    return SynthScenario(
        name="http", rules=[rule],
        endpoints={"server": {"app": "server"},
                   "client": {"app": "client"}},
        flows=flows,
    )


# ------------------------------------------------------ config 2: Kafka --
def synth_kafka_scenario(n_rules: int = 20, n_records: int = 100000,
                         seed: int = 0) -> SynthScenario:
    rng = random.Random(seed)
    kafka_rules = []
    for i in range(n_rules):
        if i % 2 == 0:
            kafka_rules.append(PortRuleKafka(role="produce",
                                             topic=f"topic-{i}"))
        else:
            kafka_rules.append(PortRuleKafka(role="consume",
                                             topic=f"topic-{i}",
                                             client_id=f"client-{i % 5}"))
    rule = Rule(
        endpoint_selector=_sel(app="kafka"),
        ingress=(IngressRule(
            from_endpoints=(_sel(app="producer"),),
            to_ports=(PortRule(
                ports=(PortProtocol(9092, Protocol.TCP),),
                rules=L7Rules(kafka=tuple(kafka_rules)),
            ),),
        ),),
        labels=("synth=kafka",),
    )
    flows = []
    for _ in range(n_records):
        i = rng.randrange(n_rules + 5)  # some topics unmatched
        produce = rng.random() < 0.5
        flows.append(Flow(
            src_identity=0, dst_identity=0, dport=9092,
            protocol=Protocol.TCP, direction=ING, l7=L7Type.KAFKA,
            kafka=KafkaInfo(
                api_key=0 if produce else 1,
                api_version=rng.randint(0, 5),
                client_id=f"client-{rng.randrange(8)}",
                topic=f"topic-{i}",
            ),
        ))
    return SynthScenario(
        name="kafka", rules=[rule],
        endpoints={"kafka": {"app": "kafka"},
                   "producer": {"app": "producer"}},
        flows=flows,
    )


# ------------------------------------------------- generic l7proto lane --
def synth_generic_scenario(n_rules: int = 200, n_flows: int = 100000,
                           seed: int = 0) -> SynthScenario:
    """Generic ``l7proto`` ACLs (the proxylib r2d2 template shape):
    key/value field constraints matched by the engine's pair-subset
    path — the lane that proves generic traffic rides the binary
    capture file→verdict path (VERDICT r3 item 3)."""
    from cilium_tpu.core.flow import GenericL7Info

    rng = random.Random(seed)
    gen_rules = []
    for i in range(n_rules):
        if i % 3 == 0:
            gen_rules.append({"cmd": "READ", "file": f"f{i}.txt"})
        elif i % 3 == 1:
            gen_rules.append({"cmd": "WRITE", "file": f"f{i}.txt"})
        else:
            gen_rules.append({"cmd": "HALT"})
    rule = Rule(
        endpoint_selector=_sel(app="r2d2"),
        ingress=(IngressRule(
            from_endpoints=(_sel(app="droid"),),
            to_ports=(PortRule(
                ports=(PortProtocol(4242, Protocol.TCP),),
                rules=L7Rules(l7proto="r2d2", l7=tuple(gen_rules)),
            ),),
        ),),
        labels=("synth=generic",),
    )
    flows = []
    for _ in range(n_flows):
        i = rng.randrange(n_rules + n_rules // 4 + 1)  # some unmatched
        cmd = ("READ", "WRITE", "HALT")[i % 3]
        fields = {"cmd": cmd}
        if cmd != "HALT":
            fields["file"] = f"f{i}.txt"
        flows.append(Flow(
            src_identity=0, dst_identity=0, dport=4242,
            protocol=Protocol.TCP, direction=ING, l7=L7Type.GENERIC,
            generic=GenericL7Info(proto="r2d2", fields=fields),
        ))
    return SynthScenario(
        name="generic", rules=[rule],
        endpoints={"r2d2": {"app": "r2d2"},
                   "droid": {"app": "droid"}},
        flows=flows,
    )


# ------------------------------------------- protocol-frontend lane --
#: per-protocol traffic shares of the mixed protocols scenario (and
#: the serve-soak load model's protocol-mix knob default)
PROTOCOL_MIX = (("cassandra", 0.4), ("memcache", 0.4), ("r2d2", 0.2))

#: dports per frontend protocol (the well-known service ports)
PROTOCOL_PORTS = {"cassandra": 9042, "memcache": 11211, "r2d2": 4040}


def synth_protocols_scenario(n_rules: int = 120, n_flows: int = 100000,
                             seed: int = 0,
                             mix=PROTOCOL_MIX) -> SynthScenario:
    """Mixed protocol-frontend traffic (ISSUE 15): cassandra,
    memcached, and r2d2 records against per-protocol rule sets on one
    endpoint — every record compiles through the frontend registry
    onto the l7g banked automaton and rides the same fused dispatch.
    ``mix`` weights the per-protocol traffic shares (the serve-soak
    protocol-mix knob reuses it)."""
    from cilium_tpu.core.flow import GenericL7Info

    rng = random.Random(seed)
    protos = [p for p, _ in mix]
    weights = [w for _, w in mix]
    per = max(1, n_rules // max(1, len(protos)))
    rules_of: Dict[str, list] = {}
    for proto in protos:
        rr = []
        for i in range(per):
            if proto == "cassandra":
                rr.append({"query_action":
                           ("select", "insert", "update")[i % 3],
                           "query_table": f"ks.t{i}"})
            elif proto == "memcache":
                rr.append({"cmd": ("get", "set", "delete")[i % 3],
                           "key": f"k{i}"})
            else:
                rr.append({"cmd": ("READ", "WRITE")[i % 2],
                           "file": f"f{i}.dat"})
        rules_of[proto] = rr
    ports = tuple(
        PortRule(ports=(PortProtocol(PROTOCOL_PORTS[p], Protocol.TCP),),
                 rules=L7Rules(l7proto=p,
                               l7=tuple(PortRuleL7.from_dict(r)
                                        for r in rules_of[p])))
        for p in protos)
    rule = Rule(
        endpoint_selector=_sel(app="polysvc"),
        ingress=(IngressRule(from_endpoints=(_sel(app="client"),),
                             to_ports=ports),),
        labels=("synth=protocols",),
    )
    flows = []
    for _ in range(n_flows):
        proto = rng.choices(protos, weights=weights)[0]
        rr = rules_of[proto]
        i = rng.randrange(len(rr) + len(rr) // 4 + 1)  # some unmatched
        if i < len(rr):
            fields = dict(rr[i])
            if rng.random() < 0.25 and len(fields) > 1:
                # matched command, wrong second field → denied
                k = sorted(fields)[-1]
                fields[k] = fields[k] + ".nope"
        else:
            fields = ({"query_action": "drop",
                       "query_table": "forbidden"}
                      if proto == "cassandra" else
                      {"cmd": "flush_all"} if proto == "memcache"
                      else {"cmd": "HALT"})
        flows.append(Flow(
            src_identity=0, dst_identity=0,
            dport=PROTOCOL_PORTS[proto],
            protocol=Protocol.TCP, direction=ING, l7=L7Type.GENERIC,
            generic=GenericL7Info(proto=proto, fields=fields),
        ))
    return SynthScenario(
        name="protocols", rules=[rule],
        endpoints={"polysvc": {"app": "polysvc"},
                   "client": {"app": "client"}},
        flows=flows,
    )


# ------------------------------------------------------ config 3: mixed --
def synth_mixed_scenario(corpus_dir: str, n_tuples: int = 1_000_000,
                         seed: int = 0) -> SynthScenario:
    """examples/policies corpus × synthetic identity/flow tuples."""
    from cilium_tpu.policy.api import load_cnp_dir

    rng = random.Random(seed)
    cnps = load_cnp_dir(corpus_dir)
    rules: List[Rule] = []
    for c in cnps:
        rules.extend(c.rules)
    # endpoints covering the corpus selectors
    endpoints = {
        "frontend": {"app": "frontend"},
        "backend": {"app": "backend"},
        "db": {"app": "db"},
        "service": {"app": "service"},
        "kafka": {"app": "kafka"},
        "empire-hq": {"app": "empire-hq"},
        "crawler": {"app": "crawler"},
        "scraper": {"app": "scraper"},
        "exporters": {"app": "exporters"},
        "web": {"tier": "web", "env": "prod"},
        "cache": {"tier": "cache"},
        "bystander": {"app": "bystander"},
        # realistic/ corpus coverage (round 3): representative
        # endpoints per namespace so the 1M-tuple stream exercises the
        # production-shaped rules too
        "storefront": {"app": "storefront", "tier": "web",
                       "env": "prod"},
        "catalog": {"app": "catalog", "tier": "backend", "env": "prod"},
        "payments": {"app": "payments", "tier": "backend",
                     "env": "prod"},
        "orders-db": {"app": "orders-db"},
        "broker": {"app": "broker"},
        "orders-svc": {"app": "orders-svc"},
        "analytics": {"app": "analytics"},
        "apigw": {"app": "apigw"},
        "internal": {"zone": "internal"},
        "team-a": {"team": "a"},
        "team-b": {"team": "b"},
        "prom": {"app": "prom"},
        "ledger": {"app": "ledger", "ns": "fintech"},
        "transfer-svc": {"app": "transfer-svc", "ns": "fintech"},
        "registry": {"app": "registry"},
        "ci-runner": {"app": "ci-runner"},
        "webapp": {"app": "webapp", "ns": "saas"},
        "api-paid": {"app": "api", "plan": "paid"},
        "worker": {"role": "worker"},
        "tenant-db": {"app": "tenant-db"},
    }
    names = list(endpoints)
    ports = [80, 443, 5432, 9092, 53, 9100, 9105, 8080,
             8443, 7443, 5000, 6379, 9080, 5672, 50051]
    flows = []
    for _ in range(n_tuples):
        src, dst = rng.choice(names), rng.choice(names)
        port = rng.choice(ports)
        proto = Protocol.UDP if port == 53 else Protocol.TCP
        f = Flow(src_identity=0, dst_identity=0, dport=port, protocol=proto,
                 direction=ING)
        if port == 80 and rng.random() < 0.5:
            f.l7 = L7Type.HTTP
            f.http = HTTPInfo(
                method=rng.choice(["GET", "PUT", "POST"]),
                path=rng.choice(["/api/v1/x", "/api/v1/config",
                                 "/other", "/api/v9/y"]),
                headers=((("X-Admin", "true"),) if rng.random() < 0.5
                         else ()),
            )
        elif port == 9092 and rng.random() < 0.5:
            f.l7 = L7Type.KAFKA
            f.kafka = KafkaInfo(
                api_key=rng.choice([0, 1, 3]),
                topic=rng.choice(["deathstar-plans", "empire-announce",
                                  "other"]),
                client_id="c")
        elif port == 53 and rng.random() < 0.5:
            f.l7 = L7Type.DNS
            f.dns = DNSInfo(query=rng.choice(
                ["www.cilium.io", "example.com", "evil.io"]))
        f._src_name = src  # filled to identities by the harness
        f._dst_name = dst
        flows.append(f)
    return SynthScenario(name="mixed", rules=rules, endpoints=endpoints,
                        flows=flows)


# ------------------------------------------------ config 4: clustermesh --
def synth_clustermesh_scenario(n_identities: int = 10000,
                               n_policies: int = 5000,
                               n_flows: int = 100000,
                               seed: int = 0) -> SynthScenario:
    """10k identities × 5k CNPs. Policies select label shards; peers
    select other shards; sprinkled L7."""
    rng = random.Random(seed)
    n_apps = 500
    endpoints = {
        f"ep{i}": {"app": f"app{i % n_apps}",
                   "shard": f"s{i % 64}",
                   "cluster": f"c{i % 4}"}
        for i in range(n_identities)
    }
    rules: List[Rule] = []
    for i in range(n_policies):
        app = f"app{i % n_apps}"
        peer_shard = f"s{(i * 7) % 64}"
        port = 1000 + (i % 200)
        l7 = None
        if i % 10 == 0:
            l7 = L7Rules(http=(
                PortRuleHTTP(method="GET", path=f"/p{i}/.*"),))
        rules.append(Rule(
            endpoint_selector=_sel(app=app),
            ingress=(IngressRule(
                from_endpoints=(_sel(shard=peer_shard),),
                to_ports=(PortRule(
                    ports=(PortProtocol(port, Protocol.TCP),),
                    rules=l7,
                ),),
                deny=(i % 17 == 0) and l7 is None,
            ),),
            labels=(f"synth=mesh{i}",),
        ))
    names = list(endpoints)
    flows = []
    for _ in range(n_flows):
        src, dst = rng.choice(names), rng.choice(names)
        port = 1000 + rng.randrange(220)
        f = Flow(src_identity=0, dst_identity=0, dport=port,
                 protocol=Protocol.TCP, direction=ING)
        if rng.random() < 0.1:
            f.l7 = L7Type.HTTP
            f.http = HTTPInfo(method="GET",
                              path=f"/p{rng.randrange(n_policies)}/x")
        f._src_name = src
        f._dst_name = dst
        flows.append(f)
    return SynthScenario(name="clustermesh", rules=rules,
                        endpoints=endpoints, flows=flows)


# ----------------------------------------------------------- harness ----
def scenario_by_name(name: str, n_rules: int, n_flows: int,
                     seed: int = 0) -> "SynthScenario":
    """One dispatch for the BASELINE scenario shapes — shared by
    bench.py and `cilium-tpu capture synth` so both generate
    identically shaped inputs (incl. fqdn's 100-name universe)."""
    if n_rules < 1:
        raise ValueError("n_rules must be >= 1")
    if name == "http":
        return synth_http_scenario(n_rules=n_rules, n_flows=n_flows,
                                   seed=seed)
    if name == "fqdn":
        return synth_fqdn_scenario(n_names=100, n_rules=n_rules,
                                   n_flows=n_flows, seed=seed)
    if name == "kafka":
        return synth_kafka_scenario(n_rules=n_rules, n_records=n_flows,
                                    seed=seed)
    if name == "generic":
        return synth_generic_scenario(n_rules=n_rules, n_flows=n_flows,
                                      seed=seed)
    if name == "protocols":
        return synth_protocols_scenario(n_rules=n_rules,
                                        n_flows=n_flows, seed=seed)
    raise ValueError(f"unknown scenario {name!r}")


def realize_scenario(scenario: SynthScenario, resolve: bool = True):
    """Allocate identities, resolve policies, fix up flow identities.
    Returns (per_identity_mapstates, scenario with ids filled);
    ``resolve=False`` skips policy resolution (capture writers only
    need the identity fixup) and returns ``None`` for the mapstates."""
    from cilium_tpu.core.identity import IdentityAllocator
    from cilium_tpu.core.labels import LabelSet
    from cilium_tpu.policy.mapstate import PolicyResolver
    from cilium_tpu.policy.repository import Repository
    from cilium_tpu.policy.selectorcache import SelectorCache

    alloc = IdentityAllocator()
    ids: Dict[str, int] = {}
    labelsets: Dict[str, "LabelSet"] = {}
    for name, lbls in scenario.endpoints.items():
        ls = LabelSet.from_dict(lbls)
        ids[name] = alloc.allocate(ls)
        labelsets[name] = ls
    per_identity = None
    if resolve:
        cache = SelectorCache(alloc)
        repo = Repository()
        repo.add(scenario.rules, sanitize=False)  # well-formed by synth
        resolver = PolicyResolver(repo, cache)
        per_identity = {ids[n]: resolver.resolve(labelsets[n])
                        for n in scenario.endpoints}
    scenario.ids = ids
    # default src/dst for scenarios that use symbolic names
    for f in scenario.flows:
        src = getattr(f, "_src_name", None)
        dst = getattr(f, "_dst_name", None)
        if src is not None:
            f.src_identity = ids[src]
        if dst is not None:
            f.dst_identity = ids[dst]
    # single-policy scenarios: default identities
    if scenario.name == "http":
        for f in scenario.flows:
            f.src_identity = ids["client"]
            f.dst_identity = ids["server"]
    elif scenario.name == "kafka":
        for f in scenario.flows:
            f.src_identity = ids["producer"]
            f.dst_identity = ids["kafka"]
    elif scenario.name == "generic":
        for f in scenario.flows:
            f.src_identity = ids["droid"]
            f.dst_identity = ids["r2d2"]
    elif scenario.name == "protocols":
        for f in scenario.flows:
            f.src_identity = ids["client"]
            f.dst_identity = ids["polysvc"]
    elif scenario.name == "fqdn":
        for f in scenario.flows:
            f.src_identity = ids["crawler"]
            f.dst_identity = ids["peer"]
    return per_identity, scenario


def scenario_capture_columns(scenario, n_records: int):
    """A realized scenario's flows, replicated to ``n_records`` and
    encoded straight into capture columns (``ingest.columnar``) — the
    shared capture-writing face of ``bench.py``'s e2e lane and the
    ``make bench-stage`` staging microbench, so both write the same
    traffic the same columnar way."""
    from cilium_tpu.ingest.columnar import flows_to_columns

    flows = scenario.flows
    reps = -(-n_records // len(flows))
    return flows_to_columns((flows * reps)[:n_records])


def write_scenario_capture(path: str, scenario, n_records: int) -> int:
    """``scenario_capture_columns`` → the streaming record-batch
    writer; returns the record count."""
    from cilium_tpu.ingest.binary import write_capture_columns

    return write_capture_columns(
        path, scenario_capture_columns(scenario, n_records))
