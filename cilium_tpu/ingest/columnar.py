"""Columnar (struct-of-arrays) capture encoding.

The per-record featurize path — build a ``Flow`` object per record,
walk its attributes, intern its strings one call at a time — was the
last per-record Python on the ingest side (ROADMAP "zero-copy columnar
ingest": staging 200k records cost ~12.5s host-side). This module is
the replacement: capture sources encode into :class:`CaptureColumns`
— the v2/v3 binary capture sections (base records, L7 sidecar indices,
shared string table, GENERIC section) held as plain numpy arrays —
with one column-major pass and batch interning, and JSONL captures
parse STRAIGHT into columns with no ``Flow`` objects anywhere
("Libra"'s argument at the socket layer, PAPERS.md: copy selectively,
never per-record).

``CaptureColumns`` is wire/disk-compatible with the existing format:
``to_bytes`` is the stream frame image, ``ingest.binary``'s writers
put it on disk (the native streaming record-batch writer when the
codec is built), and every replay path consumes the sections
unchanged. Differential suites in tests/test_ingest_columnar.py pin
the columnar encoders to the per-record reference encoders
(``binary.flows_to_capture_l7`` / the Flow object path) field by
field.
"""

from __future__ import annotations

import dataclasses
import json
from typing import Dict, Iterable, List, Optional, Tuple

import numpy as np

from cilium_tpu.ingest.binary import (
    L7REC,
    RECORD,
    gen_dtype,
)

#: the flat per-record column tuple the line/flow extractors emit
#: (``hubble.flow_dict_to_columns`` / ``accesslog.accesslog_to_columns``
#: / :func:`flow_to_column_tuple`), in order. ``gpairs`` is a tuple of
#: (key bytes, value bytes) pairs, already key-sorted.
COLUMN_FIELDS = (
    "time", "verdict", "direction", "src_identity", "dst_identity",
    "sport", "dport", "proto", "l7_type",
    "path", "method", "host", "headers", "qname",
    "kafka_client", "kafka_topic", "kafka_api_key",
    "kafka_api_version", "gen_proto", "gpairs",
)

_STRING_COLS = ("path", "method", "host", "headers", "qname",
                "kafka_client", "kafka_topic")


@dataclasses.dataclass
class CaptureColumns:
    """One capture as struct-of-arrays: exactly the v2/v3 binary
    sections, in memory. ``gen`` is None (and ``fmax`` 0) when no
    record carries a generic payload — the capture stays v2."""

    rec: np.ndarray                 # [N] RECORD
    l7: np.ndarray                  # [N] L7REC (string-table indices)
    offsets: np.ndarray             # [S+1] u32
    blob: np.ndarray                # [blob_bytes] u8
    gen: Optional[np.ndarray] = None
    fmax: int = 0
    #: GENERIC records flattened to their L4 tuple (no proto — an
    #: uncarriable payload must not re-verdict against EMPTY fields);
    #: tooling reports these as dropped payloads, never hides them
    gen_dropped: int = 0

    def __len__(self) -> int:
        return len(self.rec)

    @property
    def n_strings(self) -> int:
        return len(self.offsets) - 1

    def to_bytes(self) -> bytes:
        """The in-memory v2/v3 capture image (stream-frame unit)."""
        from cilium_tpu.ingest.binary import sections_to_bytes

        return sections_to_bytes(self.rec, self.l7, self.offsets,
                                 self.blob, self.gen, self.fmax)

    def write(self, path: str) -> int:
        from cilium_tpu.ingest.binary import write_capture_columns

        return write_capture_columns(path, self)


class StringInterner:
    """First-occurrence string interner producing the shared capture
    string table (string 0 = b""). ``ids`` interns a whole column in
    one pass — per-record Python never re-enters above the dict
    lookup, and repeated values (the common case: capture strings draw
    from small sets) cost one dict hit each."""

    def __init__(self) -> None:
        self._index: Dict[bytes, int] = {b"": 0}
        self._strings: List[bytes] = [b""]

    def intern(self, s: bytes) -> int:
        i = self._index.get(s)
        if i is None:
            i = self._index[s] = len(self._strings)
            self._strings.append(s)
        return i

    def ids(self, column: Iterable[bytes]) -> np.ndarray:
        index = self._index
        strings = self._strings
        out = np.empty(len(column), dtype=np.uint32)
        for i, s in enumerate(column):
            j = index.get(s)
            if j is None:
                j = index[s] = len(strings)
                strings.append(s)
            out[i] = j
        return out

    def table(self) -> Tuple[np.ndarray, np.ndarray]:
        """(offsets, blob) of the interned table."""
        from cilium_tpu.ingest.binary import CaptureError

        lens = np.array([len(s) for s in self._strings],
                        dtype=np.uint64)
        total = int(lens.sum())
        if total > 0xFFFFFFFF:
            raise CaptureError(
                f"string table too large ({total} bytes)")
        offsets = np.zeros(len(self._strings) + 1, dtype=np.uint32)
        offsets[1:] = np.cumsum(lens)
        blob = np.frombuffer(b"".join(self._strings), dtype=np.uint8)
        return offsets, blob


def flow_to_column_tuple(f) -> tuple:
    """One ``Flow`` → the COLUMN_FIELDS tuple (write-time
    normalization identical to ``binary.flows_to_capture_l7``: host
    lowered, qname sanitized, headers canonically serialized, generic
    pairs key-sorted)."""
    from cilium_tpu.core.flow import L7Type
    from cilium_tpu.engine.verdict import serialize_headers
    from cilium_tpu.policy.compiler import matchpattern

    path = method = host = headers = qname = b""
    kclient = ktopic = b""
    kapi = kver = 0
    gproto = b""
    gpairs: tuple = ()
    h = f.http
    if h is not None:
        path = h.path.encode("utf-8")
        method = h.method.encode("utf-8")
        host = h.host.lower().encode("utf-8")
        headers = serialize_headers(h.headers)
    d = f.dns
    if d is not None and d.query:
        qname = matchpattern.sanitize_name(d.query).encode("utf-8")
    k = f.kafka
    if k is not None:
        kclient = k.client_id.encode("utf-8")
        ktopic = k.topic.encode("utf-8")
        kapi = k.api_key
        kver = k.api_version
    g = f.generic
    # frontend-family flows (l7 > GENERIC) carry like GENERIC: the
    # capture's canonical l7_type stays GENERIC — replay re-derives
    # the family from the record's proto, so old readers never see
    # codes past the v3 universe
    l7t_out = int(f.l7)
    if f.l7 >= L7Type.GENERIC and g is not None:
        gproto = g.proto.encode("utf-8")
        gpairs = tuple((kk.encode("utf-8"), vv.encode("utf-8"))
                       for kk, vv in sorted(g.fields.items()) if kk)
        l7t_out = int(L7Type.GENERIC)
    return (f.time, int(f.verdict), int(f.direction),
            f.src_identity, f.dst_identity, f.sport, f.dport,
            int(f.protocol), l7t_out,
            path, method, host, headers, qname,
            kclient, ktopic, kapi, kver, gproto, gpairs)


def tuples_to_columns(rows: List[tuple]) -> CaptureColumns:
    """COLUMN_FIELDS tuples → :class:`CaptureColumns`: one batch
    intern per string column, vectorized record/sidecar assembly, and
    the same carriability flattening as the per-record writer (a
    GENERIC record with no proto can never match a rule — it must
    replay as the L3/L4 tuple it is, and a carriable record forces the
    GENERIC section even with zero field pairs)."""
    from cilium_tpu.core.flow import L7Type

    n = len(rows)
    col = {name: i for i, name in enumerate(COLUMN_FIELDS)}

    def c(name: str) -> list:
        i = col[name]
        return [r[i] for r in rows]

    l7t = np.array(c("l7_type"), dtype=np.int64)
    gproto_col = c("gen_proto")
    carriable = np.array(
        [bool(p) for p in gproto_col], dtype=bool) \
        & (l7t >= int(L7Type.GENERIC))
    # flatten uncarriable generic records to their L4 tuple (same
    # invariant as v1: no payload must not re-verdict against EMPTY
    # fields); carriable ones normalize to the canonical GENERIC code
    l7t = np.where((l7t >= int(L7Type.GENERIC)) & ~carriable,
                   int(L7Type.NONE), l7t)
    l7t = np.where(carriable, int(L7Type.GENERIC), l7t)

    rec = np.zeros(n, dtype=RECORD)
    rec["src_identity"] = c("src_identity")
    rec["dst_identity"] = c("dst_identity")
    rec["dport"] = c("dport")
    rec["sport"] = c("sport")
    rec["proto"] = c("proto")
    rec["direction"] = c("direction")
    rec["l7_type"] = l7t
    rec["verdict"] = c("verdict")
    rec["time"] = c("time")

    interner = StringInterner()
    l7 = np.zeros(n, dtype=L7REC)
    for name in _STRING_COLS:
        l7[name] = interner.ids(c(name))
    l7["kafka_api_key"] = c("kafka_api_key")
    l7["kafka_api_version"] = c("kafka_api_version")

    gen = None
    fmax = 0
    if carriable.any():
        gpairs_col = c("gpairs")
        fmax = max(max((len(p) for p in gpairs_col), default=0), 1)
        gen = np.zeros(n, dtype=gen_dtype(fmax))
        proto_ids = interner.ids(
            [p if carr else b""
             for p, carr in zip(gproto_col, carriable)])
        gen["proto"] = proto_ids
        rows_idx = np.nonzero(carriable)[0]
        for i in rows_idx:
            for j, (kk, vv) in enumerate(gpairs_col[i]):
                gen[i]["pairs"][j] = (interner.intern(kk),
                                      interner.intern(vv))
    offsets, blob = interner.table()
    return CaptureColumns(
        rec=rec, l7=l7, offsets=offsets, blob=blob, gen=gen,
        fmax=fmax,
        gen_dropped=int(
            ((np.array(c("l7_type")) >= int(L7Type.GENERIC))
             & ~carriable).sum()))


def flows_to_columns(flows: Iterable) -> CaptureColumns:
    """Flows → :class:`CaptureColumns` (column-major twin of
    ``binary.flows_to_capture_l7``; intern order is column-major, so
    the string table ORDER differs from the per-record writer while
    every resolved field is identical — pinned by the differential
    suite)."""
    return tuples_to_columns([flow_to_column_tuple(f) for f in flows])


def jsonl_to_columns(path: str, start: int = 0,
                     limit: Optional[int] = None) -> CaptureColumns:
    """Parse a JSONL capture (flowpb JSON, exporter envelopes, and
    Envoy accesslog entries, freely mixed) STRAIGHT into capture
    columns — no ``Flow`` objects anywhere between the file and the
    padded arrays. This is the columnar face of ``capture convert``
    and the zero-object ingest of the north star's "replaying a
    Hubble capture"."""
    from cilium_tpu.ingest.accesslog import capture_line_to_columns

    rows: List[tuple] = []
    with open(path) as fp:
        for i, line in enumerate(fp):
            if i < start:
                continue
            if limit is not None and len(rows) >= limit:
                break
            line = line.strip()
            if line:
                rows.append(capture_line_to_columns(json.loads(line)))
    return tuples_to_columns(rows)


def columns_from_capture(path: str) -> CaptureColumns:
    """A stored binary capture, re-opened as columns (zero-parse:
    memmapped records + one sequential read per sidecar section)."""
    from cilium_tpu.ingest import binary

    rec = binary.map_capture(path)
    version = binary.capture_version(path)
    if version not in (binary.VERSION_L7, binary.VERSION_L7G):
        l7 = np.zeros(len(rec), dtype=L7REC)
        offsets = np.zeros(2, dtype=np.uint32)
        return CaptureColumns(rec=rec, l7=l7, offsets=offsets,
                              blob=np.zeros(0, dtype=np.uint8))
    l7, offsets, blob = binary.read_l7_sidecar(path)
    gen = binary.read_gen_sidecar(path)
    return CaptureColumns(rec=rec, l7=l7, offsets=offsets, blob=blob,
                          gen=gen,
                          fmax=(gen["pairs"].shape[1]
                                if gen is not None else 0))
