"""Clustermesh: multi-cluster identity and ipcache synchronisation.

Reference: ``pkg/clustermesh`` + ``clustermesh-apiserver`` (SURVEY.md
§2.4) — each agent watches remote clusters' kvstores for identities,
endpoint IPs, and nodes, making remote workloads matchable by local
policy. Key layout mirrors the reference's shared-state paths
(``cilium/state/{identities,ip,nodes}/v1/...``, unverified per the
SURVEY provenance note).

Design differences from the reference, deliberate:

- Remote label sets are **re-allocated through the local
  IdentityAllocator** rather than trusting remote numeric IDs — local
  numeric identities stay dense, which keeps the compiled policy
  tensors small (remote IDs from k clusters would otherwise fragment
  the identity axis the TPU engine gathers over).
- Every remote entry is tagged with a ``cluster=<name>`` label
  (reference: ``io.cilium.k8s.policy.cluster``) so policies can select
  by cluster.
- A `LocalStatePublisher` mirrors the local agent's ipcache into its
  own kvstore under a TTL lease, so a crashed agent's state ages out
  of peer clusters (reference: etcd lease GC).
"""

from __future__ import annotations

import json
import threading
from typing import Callable, Dict, List, Optional

from cilium_tpu.core.identity import IdentityAllocator, NumericIdentity
from cilium_tpu.core.labels import Label, LabelSet, SOURCE_K8S
from cilium_tpu.kvstore import Event, EVENT_DELETE, KVStore, Watch
from cilium_tpu.runtime import faults
from cilium_tpu.runtime.metrics import METRICS

#: fires per remote-cluster event ingest: a session fault costs one
#: event (isolated by the kvstore's per-watcher delivery), and the
#: next announcement of the key repairs the entry
SESSION_POINT = faults.register_point(
    "clustermesh.session", "remote-cluster event ingest")
#: fires in the publisher heartbeat: the owning Controller's
#: exponential backoff (runtime/controller.py) absorbs it and the
#: lease keeps published state alive until the next beat lands
HEARTBEAT_POINT = faults.register_point(
    "clustermesh.heartbeat", "local-state publisher heartbeat")

IP_PREFIX = "cilium/state/ip/v1/default/"
IDENTITY_PREFIX = "cilium/state/identities/v1/id/"
NODES_PREFIX = "cilium/state/nodes/v1/"
SERVICES_PREFIX = "cilium/state/services/v1/"

#: Label key marking which cluster an identity/IP came from
#: (reference's ``io.cilium.k8s.policy.cluster``; the namespaced key
#: cannot collide with ordinary workload labels like ``cluster=c0``).
#: Shared with the policy layer: the `cluster` entity selects on it.
from cilium_tpu.policy.api.rule import CLUSTER_LABEL_KEY  # noqa: E402


def _encode_labels(labels: LabelSet) -> List[str]:
    return list(labels.format())


def _decode_labels(items: List[str]) -> LabelSet:
    return LabelSet.parse(items)


class LocalStatePublisher:
    """Mirror the local ipcache (IP → identity labels) into a kvstore.

    The reference's agent writes its ipcache/identity state into the
    shared etcd (or the clustermesh-apiserver proxies it); peers watch
    it. Keys live under a lease refreshed by `heartbeat()` — wire that
    to a ControllerManager interval so agent death expires the state.
    """

    def __init__(self, store: KVStore, cluster_name: str,
                 allocator: IdentityAllocator, ipcache,
                 lease_ttl: float = 60.0, services=None) -> None:
        self.store = store
        self.cluster_name = cluster_name
        self._allocator = allocator
        self._lease = store.lease(lease_ttl)
        self._ipcache = ipcache
        #: optional ServiceManager — SHARED services are exported for
        #: peers' global-service merge (reference: the clustermesh
        #: apiserver exports services annotated service.cilium.io/global)
        self._services = services
        self._published_services: Dict[str, str] = {}  # key → value
        ipcache.subscribe(self._on_ipcache)

    def _key(self, prefix: str) -> str:
        return f"{IP_PREFIX}{self.cluster_name}/{prefix}"

    def _on_ipcache(self, prefix: str, nid: NumericIdentity,
                    upsert: bool) -> None:
        labels = self._allocator.lookup(nid)
        # Never re-export state learned FROM another cluster — in a
        # full mesh (A watches B, B watches A) re-publishing remote
        # entries under our own prefix would echo them back forever.
        if labels is not None:
            tag = labels.get(CLUSTER_LABEL_KEY, SOURCE_K8S)
            if tag is not None and tag.value != self.cluster_name:
                return
        if not upsert:
            self.store.delete(self._key(prefix))
            return
        self.store.set(
            self._key(prefix),
            json.dumps({"prefix": prefix, "identity": int(nid),
                        "labels": _encode_labels(labels) if labels else [],
                        "cluster": self.cluster_name}),
            lease=self._lease)

    def publish_services(self) -> None:
        """Export SHARED services (+ their active local backends) under
        the services prefix; un-shared/deleted ones are withdrawn.
        Reconcile-style (called from heartbeat): eventual consistency
        under a lease, like the rest of the published state."""
        if self._services is None:
            return
        current: Dict[str, str] = {}
        for svc in self._services.list():
            if not svc.shared:
                continue
            key = (f"{SERVICES_PREFIX}{self.cluster_name}/"
                   f"{svc.namespace}/{svc.name}")
            current[key] = json.dumps({
                "cluster": self.cluster_name,
                "namespace": svc.namespace,
                "name": svc.name,
                "shared": True,
                "backends": [{"ip": b.ip, "port": b.port,
                              "weight": b.weight}
                             for b in svc.active_backends()],
            }, sort_keys=True)
            # re-setting an unchanged value every heartbeat would emit
            # MODIFY to every watching peer → full policy regeneration
            # mesh-wide every 15s; only publish real changes (the
            # lease keepalive keeps unchanged keys alive)
            if self._published_services.get(key) != current[key]:
                self.store.set(key, current[key], lease=self._lease)
        for key in self._published_services.keys() - current.keys():
            self.store.delete(key)
        self._published_services = current

    def heartbeat(self) -> None:
        faults.maybe_fail(HEARTBEAT_POINT)
        self._lease.keepalive()
        self.publish_services()
        self.store.expire_leases()


class RemoteCluster:
    """Watch one remote cluster's kvstore; feed local ipcache/selectors.

    Mirrors ``pkg/clustermesh ·remoteCluster``: ListAndWatch the remote
    ip/identity prefixes; each remote IP is upserted into the local
    ipcache under a locally-allocated identity for its labels (plus the
    cluster label). Deleting/disconnecting removes everything again.
    """

    def __init__(self, name: str, store: KVStore,
                 allocator: IdentityAllocator, ipcache,
                 selector_cache=None, services=None) -> None:
        self.name = name
        self.store = store
        self._allocator = allocator
        self._ipcache = ipcache
        self._selector_cache = selector_cache
        #: optional ServiceManager: remote GLOBAL services feed its
        #: clustermesh overlay (pkg/clustermesh services sync)
        self._services = services
        self._lock = threading.Lock()
        # remote key → (local prefix, local nid); nid refcounted so the
        # selector cache drops a remote identity when its last IP goes
        self._prefixes: Dict[str, tuple] = {}
        self._nid_refs: Dict[NumericIdentity, int] = {}
        #: remote service key → (namespace, name) for delete events
        self._service_keys: Dict[str, tuple] = {}
        self._watch: Optional[Watch] = None
        self._svc_watch: Optional[Watch] = None
        self.ready = False

    def connect(self) -> "RemoteCluster":
        self._watch = self.store.watch_prefix(IP_PREFIX, self._on_event,
                                              replay=True)
        if self._services is not None:
            self._svc_watch = self.store.watch_prefix(
                SERVICES_PREFIX, self._on_service_event, replay=True)
        self.ready = True
        METRICS.set_gauge("cilium_tpu_clustermesh_ready", 1.0,
                          labels={"cluster": self.name})
        return self

    def disconnect(self) -> None:
        if self._watch is not None:
            self._watch.stop()
            self._watch = None
        if self._svc_watch is not None:
            self._svc_watch.stop()
            self._svc_watch = None
        with self._lock:
            entries = list(self._prefixes.values())
            nids = list(self._nid_refs)
            self._prefixes.clear()
            self._nid_refs.clear()
            self._service_keys.clear()
        for prefix, _ in entries:
            self._ipcache.delete(prefix)
        for nid in nids:
            self._release_identity(nid)
        if self._services is not None:
            self._services.remove_remote_cluster(self.name)
        self.ready = False
        METRICS.set_gauge("cilium_tpu_clustermesh_ready", 0.0,
                          labels={"cluster": self.name})

    def _on_service_event(self, ev: Event) -> None:
        from cilium_tpu.loadbalancer.service import Backend

        if ev.typ == EVENT_DELETE:
            with self._lock:
                ns_name = self._service_keys.pop(ev.key, None)
            if ns_name is not None:
                self._services.set_remote_backends(
                    self.name, ns_name[0], ns_name[1], [])
            return
        try:
            entry = json.loads(ev.value)
            namespace = entry["namespace"]
            name = entry["name"]
            backends = [Backend(ip=b["ip"], port=int(b["port"]),
                                weight=int(b.get("weight", 1)))
                        for b in entry.get("backends", ())]
        except (ValueError, KeyError, TypeError):
            METRICS.inc("cilium_tpu_clustermesh_decode_errors_total",
                        labels={"cluster": self.name})
            return
        # accept only the watched cluster's own announcements: in a
        # shared-store topology another cluster's keys would otherwise
        # be double-ingested under the wrong cluster tag
        if entry.get("cluster") not in (None, self.name):
            return
        with self._lock:
            self._service_keys[ev.key] = (namespace, name)
        self._services.set_remote_backends(self.name, namespace, name,
                                           backends)

    def _release_identity(self, nid: NumericIdentity) -> None:
        from cilium_tpu.core.identity import IDENTITY_USER_MIN

        # a remote cluster's host maps to the reserved REMOTE_NODE
        # identity (core.identity allocate) — reserved registrations
        # are process invariants this refcount must never tear down
        if nid < IDENTITY_USER_MIN:
            return
        if self._selector_cache is not None:
            self._selector_cache.remove_identity(nid)
        self._allocator.release(nid)

    def _drop_key(self, key: str) -> None:
        with self._lock:
            entry = self._prefixes.pop(key, None)
            last = False
            if entry is not None:
                _, nid = entry
                self._nid_refs[nid] -= 1
                if self._nid_refs[nid] == 0:
                    del self._nid_refs[nid]
                    last = True
        if entry is not None:
            self._ipcache.delete(entry[0])
            if last:
                self._release_identity(entry[1])

    def _on_event(self, ev: Event) -> None:
        faults.maybe_fail(SESSION_POINT)
        if ev.typ == EVENT_DELETE:
            self._drop_key(ev.key)
            return
        try:
            entry = json.loads(ev.value)
            prefix = entry["prefix"]
            labels = _decode_labels(entry.get("labels", []))
        except (ValueError, KeyError):
            METRICS.inc("cilium_tpu_clustermesh_decode_errors_total",
                        labels={"cluster": self.name})
            return
        tagged = LabelSet(list(labels) + [
            Label(key=CLUSTER_LABEL_KEY, value=self.name,
                  source=SOURCE_K8S)])
        nid = self._allocator.allocate(tagged)
        with self._lock:
            prev = self._prefixes.get(ev.key)
            if prev == (prefix, nid):
                return  # unchanged re-announce
            old_last = False
            if prev is not None:  # remapped prefix or labels
                _, old_nid = prev
                self._nid_refs[old_nid] -= 1
                if self._nid_refs[old_nid] == 0:
                    del self._nid_refs[old_nid]
                    old_last = True
            self._prefixes[ev.key] = (prefix, nid)
            self._nid_refs[nid] = self._nid_refs.get(nid, 0) + 1
        if prev is not None and prev[0] != prefix:
            self._ipcache.delete(prev[0])
        if self._selector_cache is not None:
            self._selector_cache.add_identity(nid, tagged)
        self._ipcache.upsert(prefix, nid)
        # release AFTER the new mapping is live, and never when the key
        # kept the same identity (old_nid == nid keeps a refcount)
        if prev is not None and old_last and prev[1] != nid:
            self._release_identity(prev[1])

    def num_entries(self) -> int:
        with self._lock:
            return len(self._prefixes)


class ClusterMesh:
    """The set of connected remote clusters (``pkg/clustermesh``)."""

    def __init__(self, allocator: IdentityAllocator, ipcache,
                 selector_cache=None,
                 on_change: Optional[Callable[[], None]] = None,
                 services=None) -> None:
        self._allocator = allocator
        self._ipcache = ipcache
        self._selector_cache = selector_cache
        self._on_change = on_change
        self._services = services
        self._clusters: Dict[str, RemoteCluster] = {}

    def connect(self, name: str, store: KVStore) -> RemoteCluster:
        old = self._clusters.pop(name, None)
        if old is not None:
            # reconnect: tear down without firing on_change — one
            # recompile after the new connection is live suffices, and
            # it never sees the torn-down intermediate state
            old.disconnect()
        rc = RemoteCluster(name, store, self._allocator, self._ipcache,
                           self._selector_cache,
                           services=self._services).connect()
        self._clusters[name] = rc
        if self._on_change is not None:
            self._on_change()
        return rc

    def disconnect(self, name: str) -> None:
        rc = self._clusters.pop(name, None)
        if rc is not None:
            rc.disconnect()
            if self._on_change is not None:
                self._on_change()

    def close(self) -> None:
        """Disconnect everything WITHOUT firing on_change — shutdown
        teardown must not queue policy recompiles that get discarded."""
        for name in list(self._clusters):
            rc = self._clusters.pop(name)
            rc.disconnect()

    def status(self) -> Dict[str, Dict]:
        return {
            name: {"ready": rc.ready, "num-entries": rc.num_entries()}
            for name, rc in self._clusters.items()
        }

    def __len__(self) -> int:
        return len(self._clusters)
