"""The agent: assembly of every subsystem.

Reference: ``daemon/`` + ``pkg/hive`` (SURVEY.md §2.4, §3.1) — the
agent is a dependency-ordered assembly of cells. Ours wires, in
dependency order: identity allocator → selector cache → ipcache →
policy repository → FQDN (cache/NameManager/DNS proxy) → loader
(feature-gated engine) → endpoint manager → verdict service →
controllers (DNS GC, checkpoint). One object, explicit start/stop —
the DI graph is small enough to read.
"""

from __future__ import annotations

import os
import threading
from typing import Dict, List, Optional

from cilium_tpu.auth import AuthManager
from cilium_tpu.clustermesh import ClusterMesh, LocalStatePublisher
from cilium_tpu.core.config import Config
from cilium_tpu.core.identity import IdentityAllocator, ReservedIdentity
from cilium_tpu.kvstore import KVStore
from cilium_tpu.core.labels import LabelSet
from cilium_tpu.endpoint import EndpointManager
from cilium_tpu.fqdn import DNSCache, DNSProxy, NameManager
from cilium_tpu.health import HealthChecker
from cilium_tpu.hubble import FlowMetrics, Observer, annotate_flows
from cilium_tpu.ipam import NodeAllocator, PoolExhausted
from cilium_tpu.ipcache import IPCache
from cilium_tpu.loadbalancer import ServiceManager
from cilium_tpu.monitor import AggregationLevel, MonitorAgent
from cilium_tpu.policy.api import CiliumNetworkPolicy, load_cnp_yaml
from cilium_tpu.policy.repository import Repository
from cilium_tpu.policy.selectorcache import SelectorCache
from cilium_tpu.runtime.controller import ControllerManager
from cilium_tpu.runtime.loader import Loader
from cilium_tpu.runtime.logging import get_logger, setup as setup_logging
from cilium_tpu.runtime.metrics import METRICS
from cilium_tpu.runtime.service import VerdictService

LOG = get_logger("daemon")


class Agent:
    def __init__(self, config: Optional[Config] = None,
                 state_dir: Optional[str] = None,
                 socket_path: Optional[str] = None,
                 api_socket_path: Optional[str] = None,
                 policy_dir: Optional[str] = None,
                 dns_proxy_bind: Optional[tuple] = None,
                 dns_upstream: tuple = ("127.0.0.53", 53),
                 dns_endpoint_of=None,
                 hubble_socket_path: Optional[str] = None,
                 accesslog_socket_path: Optional[str] = None,
                 monitor_socket_path: Optional[str] = None,
                 kvstore: Optional[KVStore] = None):
        self.config = config or Config.from_env()
        self.state_dir = state_dir
        # the flight recorder follows daemon config (the one knob set
        # per process, like the metrics registry): sampling/capacity
        # apply to every ingress this agent serves
        from cilium_tpu.runtime.tracing import TRACER

        TRACER.configure(enabled=self.config.tracing.enabled,
                         sample_rate=self.config.tracing.sample_rate,
                         capacity=self.config.tracing.ring_capacity)
        # serializes compound mutations (endpoint/policy upserts) from
        # concurrent writers: REST API threads, watcher controller, CLI
        self.write_lock = threading.RLock()
        # the kvstore comes first: cluster-wide identity allocation and
        # cluster-pool IPAM both build on it
        self.kvstore = kvstore if kvstore is not None else KVStore()
        if self.config.identity_allocation_mode == "kvstore":
            from cilium_tpu.identity_kvstore import ClusterIdentityAllocator

            self.allocator = ClusterIdentityAllocator(self.kvstore)
        elif self.config.identity_allocation_mode == "crd":
            if not self.config.k8s_api_socket:
                raise ValueError(
                    "identity_allocation_mode=crd requires "
                    "k8s_api_socket (the CiliumIdentity store)")
            from cilium_tpu.k8s.apiserver import K8sClient
            from cilium_tpu.k8s.identity_crd import CRDIdentityAllocator

            self.allocator = CRDIdentityAllocator(
                K8sClient(self.config.k8s_api_socket))
        else:
            self.allocator = IdentityAllocator()
        self.selector_cache = SelectorCache(self.allocator)
        self.ipcache = IPCache(self.allocator, self.selector_cache)
        self.repo = Repository()
        self.dns_cache = DNSCache()
        self.name_manager = NameManager(self.selector_cache, self.ipcache,
                                        self.dns_cache)
        self.dns_proxy = DNSProxy(self.name_manager,
                                  use_tpu=self.config.enable_tpu_offload)
        # k8s-Secret analog: secret-backed header-match values resolve
        # against this at compile (SecretStore docstring)
        from cilium_tpu.secrets import SecretStore

        self.secrets = SecretStore()
        self.loader = Loader(self.config, secrets=self.secrets)
        # services / kube-proxy replacement (§2.4): Maglev selection;
        # built before the endpoint manager so toServices policy rules
        # resolve against it (backend IPs → identities via the ipcache)
        self.services = ServiceManager()
        #: toGroups provider registry (reference pkg/policy/api/groups
        #: callbacks): name → fn(GroupsSpec) -> [cidr]; resolution
        #: happens at every regeneration so provider refreshes land via
        #: regenerate_all()
        self.group_providers = {}
        #: CiliumCIDRGroup registry (v2alpha1): name → member CIDRs;
        #: fed by the k8s bridge's ciliumcidrgroups informer (or
        #: set_cidr_group directly); resolved at every regeneration
        self.cidr_groups: Dict[str, Tuple[str, ...]] = {}
        # proxy-port allocation + redirect lifecycle (pkg/proxy role):
        # reconciled against every resolved snapshot at regeneration
        from cilium_tpu.proxy_manager import ProxyManager

        self.proxy_manager = ProxyManager()
        self.endpoint_manager = EndpointManager(
            self.repo, self.selector_cache, self.allocator, self.loader,
            dns_proxy=self.dns_proxy, state_dir=state_dir,
            services=self.services,
            backend_identity=lambda ip: self.ipcache.lookup(ip),
            cluster_name=self.config.cluster_name,
            group_cidrs=self._resolve_group,
            cidr_group_cidrs=lambda name: self.cidr_groups.get(name, ()),
            proxy_manager=self.proxy_manager)
        # identity-churn regeneration debounce (ISSUE-10 satellite):
        # burst add/delete events from the cluster watch coalesce into
        # one regeneration per quiet window instead of one per event
        from cilium_tpu.identity_kvstore import RegenDebouncer

        self._identity_debounce = RegenDebouncer(
            lambda: self.endpoint_manager.regenerate_all(),
            window_s=self.config.loader.identity_regen_debounce_s)
        # backend-set changes alter toServices resolution → regenerate,
        # but only when some rule actually uses toServices: routine
        # backend churn must not trigger full-policy recomputation in
        # clusters with no such rules
        self.services.on_change = self._on_service_change
        # clustermesh (§2.4): publish local state into our kvstore;
        # watch remote clusters' stores for their identities/IPs. A
        # caller-supplied store is how this agent shares state with an
        # Operator (cluster-pool IPAM) and other agents in-process.
        self.publisher = LocalStatePublisher(
            self.kvstore, self.config.cluster_name, self.allocator,
            self.ipcache, services=self.services)
        self.clustermesh = ClusterMesh(
            self.allocator, self.ipcache, self.selector_cache,
            on_change=lambda: self.endpoint_manager.regenerate_all(),
            services=self.services)
        # observability (§2.5): monitor event fan-out + hubble observer
        try:
            # `or`: a YAML null/"" means "use the dataclass default",
            # not AggregationLevel[str(None)] == NONE
            level = AggregationLevel[
                str(self.config.monitor_aggregation
                    or Config.monitor_aggregation).upper()]
        except KeyError:
            raise ValueError(
                f"monitor_aggregation "
                f"{self.config.monitor_aggregation!r} — expected one "
                f"of {[m.name.lower() for m in AggregationLevel]}"
            ) from None
        self.monitor = MonitorAgent(level=level)
        self.observer = Observer(handlers=[FlowMetrics()])
        # health probe mesh (§5.3); peers register via health.add_node
        # or kvstore discovery (HealthPeerWatcher at start())
        self.health = HealthChecker(node_name=self.config.node_name)
        self._hubble_ad = None
        self._health_ad = None
        self._health_watcher = None
        # IPAM (§2.4): endpoint IPs come from this node's podCIDR when
        # the caller doesn't pin one. In "cluster-pool" mode the CIDR
        # arrives from the operator at start(); until then the static
        # pod_cidr stands in so construction stays non-blocking.
        self.ipam = NodeAllocator(self.config.pod_cidr)
        self.node_registration = None
        # mutual-auth state: pairs that completed a handshake; entries
        # demanding auth DROP until their pair lands here (§2.1 AuthType)
        self.auth = AuthManager()
        self.controllers = ControllerManager()
        self.service: Optional[VerdictService] = None
        self.socket_path = socket_path
        # REST API (pkg/client-consumable; SURVEY.md §2.4) + the k8s
        # CNP-watcher analog (a policy directory watcher)
        self.api_server = None
        self.api_socket_path = api_socket_path
        self.policy_watcher = None
        self.policy_dir = policy_dir
        # pkg/k8s watcher-layer analog: CNP/CCNP informers feeding the
        # repo + CEP/CiliumNode status publication (config.k8s_api_socket)
        self.k8s_bridge = None
        # transparent DNS proxy UDP wire path (§3.5); endpoint resolved
        # from the client source address, as the reference's TPROXY does
        self.dns_server = None
        self.dns_proxy_bind = dns_proxy_bind
        self.dns_upstream = dns_upstream
        self.dns_endpoint_of = dns_endpoint_of  # client IP → endpoint id
        # hubble observer socket (GetFlows/ServerStatus analog)
        self.hubble_server = None
        self.hubble_socket_path = hubble_socket_path
        # proxy→agent L7 record channel (pkg/envoy accesslog server):
        # proxies write JSON records; parsed flows land in the observer
        self.accesslog_server = None
        self.accesslog_socket_path = accesslog_socket_path
        # monitor Unix socket (`cilium-dbg monitor` contract): second
        # processes stream PolicyVerdict/Drop/Trace events with
        # per-subscriber aggregation
        self.monitor_server = None
        self.monitor_socket_path = monitor_socket_path
        # FQDN updates retrigger regeneration (§3.2 tail)
        self.name_manager.on_update = (
            lambda sels: self.endpoint_manager.regenerate_all())

    # -- lifecycle --------------------------------------------------------
    def start(self) -> "Agent":
        # the daemon owns process logging (reference: daemon_main
        # configures logrus); hosts that embed the agent and own their
        # process's logging opt out via configure_logging=False
        if self.config.configure_logging:
            setup_logging(self.config.log_level)
        if self.config.identity_allocation_mode in ("kvstore", "crd"):
            # remote allocations reach policy through the selector
            # cache (the reference's identity-cache events); start()
            # replays existing cluster identities before anything
            # resolves policy against them
            self.allocator.on_change = self._on_cluster_identity
            self.allocator.start()
        if self.config.ipam_mode == "cluster-pool":
            # register with the operator and adopt its assignment BEFORE
            # endpoint restore, so restored IPs re-adopt into the right
            # allocator (reference: agents block on IPAM readiness)
            from cilium_tpu.operator import NodeRegistration

            self.node_registration = NodeRegistration(
                self.kvstore, self.config.node_name,
                on_cidr_change=self._on_pod_cidr_change)
            try:
                self.node_registration.wait_for_cidr(timeout=30.0)
            except TimeoutError:
                # don't leave a registered node (holding a reconcile
                # slot — it would be assigned a CIDR nobody consumes)
                # or live watches behind a failed start; a retry builds
                # fresh subscriptions instead of stacking them
                self.node_registration.deregister()
                self.node_registration = None
                if hasattr(self.allocator, "close"):
                    self.allocator.close()
                raise
            with self.write_lock:
                # fresh read, not the wait result: a re-carve landing
                # between the wait and this swap must not be reverted
                # (the watch event for it may have already fired)
                self.ipam = NodeAllocator(self.node_registration.pod_cidr())
            self.controllers.update(
                "node-registration", self.node_registration.heartbeat,
                interval=15.0)
        # tag kube-apiserver IPs with the reserved identity so the
        # `kube-apiserver` entity selects real traffic (flows from
        # these IPs resolve to ReservedIdentity.KUBE_APISERVER)
        import ipaddress as _ipaddress

        for ip in self.config.kube_apiserver_ips:
            if "/" not in ip:
                # family-aware host prefix: a bare IPv6 address must
                # become /128, not /32 (which would tag a 2^96 block)
                ip = f"{ip}/{_ipaddress.ip_address(ip).max_prefixlen}"
            self.ipcache.upsert(ip, int(ReservedIdentity.KUBE_APISERVER))
        restored = self.endpoint_manager.restore()
        if restored:
            METRICS.inc("cilium_tpu_endpoints_restored_total", restored)
            # re-adopt ip→identity mappings for restored endpoints (the
            # reference re-adopts the pinned ipcache BPF map on restart)
            for ep in self.endpoint_manager.endpoints():
                if ep.ipv4:
                    self.ipcache.upsert(f"{ep.ipv4}/32", ep.identity)
                    try:  # IPAM re-adopts restored addresses (§5.4)
                        self.ipam.allocate_ip(ep.ipv4)
                    except (ValueError, PoolExhausted):
                        # outside the re-carved node CIDR, or already
                        # taken: the ipam audit gauge surfaces it —
                        # restore must not abort over one address
                        pass
        if self.state_dir:
            dns_path = os.path.join(self.state_dir, "dnscache.json")
            if os.path.exists(dns_path):
                with open(dns_path) as f:
                    self.dns_cache = DNSCache.from_json(f.read())
                    self.name_manager.cache = self.dns_cache
        if self.config.loader.warm_restore and self.loader.revision == 0:
            # warm restart: rebuild the serving engine from the last
            # drain's snapshot BEFORE any server socket opens, so the
            # first request is answered verdict-identically with no
            # recompile (pinned-map restart discipline, SURVEY §5.3)
            if self.loader.restore_warm():
                LOG.info("warm state restored", extra={"fields": {
                    "revision": self.loader.revision}})
        if self.socket_path:
            self.service = VerdictService(self.loader, self.socket_path,
                                          agent=self)
            self.service.start()
        if self.api_socket_path:
            import json as _json

            from cilium_tpu.health import PEERS_PREFIX, HealthPeerWatcher
            from cilium_tpu.runtime.advertise import Advertisement
            from cilium_tpu.runtime.api import APIServer

            self.api_server = APIServer(self, self.api_socket_path).start()
            # advertise the health endpoint and probe every other
            # advertised node (pkg/health's full probe mesh, §5.3)
            self._health_ad = Advertisement(
                self.kvstore, PEERS_PREFIX + self.config.node_name,
                _json.dumps({"socket": self.api_socket_path}))
            self.controllers.update("health-peer-heartbeat",
                                    self._health_ad.heartbeat,
                                    interval=15.0)
            self._health_watcher = HealthPeerWatcher(
                self.kvstore, self.health).start()
        if self.policy_dir:
            from cilium_tpu.runtime.watcher import PolicyDirWatcher

            self.policy_watcher = PolicyDirWatcher(self, self.policy_dir)
            self.policy_watcher.register(self.controllers)
        if self.config.k8s_api_socket and self.k8s_bridge is None:
            # None-guard: a retried Agent.start() must not stack a
            # second set of informer threads (same rule as the
            # allocator watch above)
            from cilium_tpu.k8s.agent_bridge import K8sWatcherBridge

            self.k8s_bridge = K8sWatcherBridge(
                self, self.config.k8s_api_socket).start()
        if self.hubble_socket_path:
            from cilium_tpu.hubble.server import HubbleServer

            self.hubble_server = HubbleServer(
                self.observer, self.hubble_socket_path).start()
            # advertise this node's observer for relay discovery (the
            # Hubble Peer service analog), lease-backed so a dead
            # agent's entry ages out of the relay's peer set
            import json as _json

            from cilium_tpu.hubble.relay import PeerDirectory
            from cilium_tpu.runtime.advertise import Advertisement

            self._hubble_ad = Advertisement(
                self.kvstore,
                PeerDirectory.PREFIX + self.config.node_name,
                _json.dumps({"socket": self.hubble_socket_path}))
            self.controllers.update("hubble-peer-heartbeat",
                                    self._hubble_ad.heartbeat,
                                    interval=15.0)
        if self.accesslog_socket_path:
            from cilium_tpu.hubble.accesslog_server import AccessLogServer

            self.accesslog_server = AccessLogServer(
                self.observer, self.accesslog_socket_path).start()
        if self.monitor_socket_path:
            from cilium_tpu.monitor import MonitorServer

            self.monitor_server = MonitorServer(
                self.monitor, self.monitor_socket_path).start()
        if self.dns_proxy_bind is not None:
            from cilium_tpu.fqdn.server import DNSProxyServer

            self.dns_server = DNSProxyServer(
                self.dns_proxy,
                self.dns_endpoint_of or self._endpoint_of_ip,
                upstream=self.dns_upstream,
                bind=self.dns_proxy_bind).start()
        self.controllers.update("dns-gc", self._dns_gc, interval=60.0)
        self.controllers.update("auth-gc", self.auth.expire, interval=60.0)
        self.controllers.update("clustermesh-heartbeat",
                                self.publisher.heartbeat, interval=15.0)
        self.controllers.update("health-probe", self.health.probe_all,
                                interval=60.0)
        if self.state_dir:
            self.controllers.update("checkpoint", self._checkpoint,
                                    interval=30.0)
        LOG.info("agent started", extra={"fields": {
            "backend": "tpu" if self.config.enable_tpu_offload
            else "oracle",
            "ipam_mode": self.config.ipam_mode,
            "pod_cidr": str(self.ipam.cidr),
            "endpoints_restored": restored,
        }})
        return self

    def drain(self) -> dict:
        """Graceful drain (SIGTERM / ``POST /v1/drain``): the verdict
        service stops admitting data-path work, flushes — not errors —
        pending batches, and snapshots warm-restart state. Control
        surfaces keep answering; ``stop()`` completes the shutdown."""
        if self.service is None:
            return {"ok": True, "flushed": 0, "warm_snapshot": False,
                    "revision": self.loader.revision}
        return self.service.drain()

    def stop(self) -> None:
        # close() skips the on_change regeneration hook — recompiling
        # policy for a shutdown teardown would be discarded work
        self.clustermesh.close()
        self.controllers.stop_all()
        if self.k8s_bridge is not None:
            self.k8s_bridge.stop()
        if self.node_registration is not None:
            # stop watching, but stay registered: the node keeps its
            # CIDR across an agent restart (the lease lapses only if we
            # stay down past the TTL — the reference's pinned-map
            # discipline, SURVEY.md §5.3/§5.4)
            self.node_registration.close()
        if hasattr(self.allocator, "close"):
            self.allocator.close()
        # after the watch is closed no new churn events arrive; a
        # pending debounced regeneration is discarded work on shutdown
        self._identity_debounce.close()
        if self._health_watcher is not None:
            self._health_watcher.stop()
        for ad in (self._hubble_ad, self._health_ad):
            if ad is not None:  # clean departure: peers drop us now
                ad.withdraw()  # instead of waiting out the lease
        if self.hubble_server is not None:
            self.hubble_server.stop()
        if self.accesslog_server is not None:
            self.accesslog_server.stop()
        if self.monitor_server is not None:
            self.monitor_server.stop()
        if self.dns_server is not None:
            self.dns_server.stop()
        if self.api_server is not None:
            self.api_server.stop()
        if self.service is not None:
            self.service.stop()
        if self.state_dir:
            self._checkpoint()
        self.endpoint_manager.shutdown()
        LOG.info("agent stopped")

    def _dns_gc(self) -> None:
        self.name_manager.gc()

    def _on_service_change(self) -> None:
        if any(er.to_services for rule in self.repo.rules()
               for er in rule.egress):
            self.endpoint_manager.regenerate_all()

    def _on_cluster_identity(self, nid: int, labels) -> None:
        """A (possibly remote) cluster identity appeared or vanished in
        the kvstore: update selector resolution and regenerate, so
        policies selecting that identity's labels enforce on this node
        too (§3.2's incremental path for identity churn). The selector
        cache updates synchronously; the regeneration is DEBOUNCED —
        a churn storm of N events costs one selector pass per event
        but O(1) regenerations (identity_kvstore.RegenDebouncer)."""
        if labels is None:
            self.selector_cache.remove_identity(nid)
        else:
            self.selector_cache.add_identity(nid, labels)
        self._identity_debounce.note()

    def _on_pod_cidr_change(self, old: Optional[str],
                            new: Optional[str]) -> None:
        """The operator rewrote this node's assignment (re-carve after a
        pool reconfiguration, or reassignment after our lease lapsed).
        Rebuild the allocator on the new CIDR so fresh endpoint IPs come
        from a range we actually own; existing endpoints keep their
        addresses (pods can't be renumbered in place — the reference
        restarts them), counted so operators can see the skew. A delete
        (new=None) is left alone: the fresh assignment follows."""
        # write_lock: endpoint_add may be mid-allocation from the old
        # allocator on an API thread — swapping under it un-serialized
        # would hand out an address the new allocator never adopted
        with self.write_lock:
            if new is None or new == str(self.ipam.cidr):
                return
            alloc = NodeAllocator(new)
            stale = 0
            for ep in self.endpoint_manager.endpoints():
                if not ep.ipv4:
                    continue
                try:
                    alloc.allocate_ip(ep.ipv4)
                except Exception:
                    stale += 1
            self.ipam = alloc
            # unconditional: the gauge must drop back to 0 once the
            # skew clears, not report the last nonzero value forever
            METRICS.set_gauge("cilium_tpu_ipam_endpoints_outside_cidr",
                              float(stale))

    def _checkpoint(self) -> None:
        self.endpoint_manager.checkpoint()
        if self.state_dir:
            os.makedirs(self.state_dir, exist_ok=True)
            tmp = os.path.join(self.state_dir, "dnscache.json.tmp")
            with open(tmp, "w") as f:
                f.write(self.dns_cache.to_json())
            os.replace(tmp, os.path.join(self.state_dir, "dnscache.json"))

    # -- policy API (PolicyAdd/PolicyDelete, §3.2) -----------------------
    def policy_add(self, cnp: CiliumNetworkPolicy, wait: bool = True) -> int:
        rev = self.repo.add(cnp.rules)
        self._register_fqdn_selectors(cnp)
        self.endpoint_manager.regenerate_all(wait=wait)
        return rev

    def policy_add_file(self, path: str, wait: bool = True) -> int:
        rev = 0
        for cnp in load_cnp_yaml(path):
            rev = self.policy_add(cnp, wait=False)
        self.endpoint_manager.regenerate_all(wait=wait)
        return rev

    def policy_delete(self, labels: List[str], wait: bool = True) -> int:
        n, rev = self.repo.delete_by_labels(labels)
        if n:
            self._gc_fqdn_selectors()
            self.endpoint_manager.regenerate_all(wait=wait)
        return n

    def _register_fqdn_selectors(self, cnp: CiliumNetworkPolicy) -> None:
        for rule in cnp.rules:
            for er in rule.egress:
                for fsel in er.to_fqdns:
                    self.name_manager.register_selector(fsel)

    def _gc_fqdn_selectors(self) -> None:
        """Unregister FQDN selectors no remaining rule references —
        otherwise deleted toFQDNs policies keep allocating CIDR
        identities and retriggering regeneration on every DNS answer."""
        active = {
            fsel
            for rule in self.repo.rules()
            for er in rule.egress
            for fsel in er.to_fqdns
        }
        for sel in self.name_manager.registered_selectors():
            if sel not in active:
                self.name_manager.unregister_selector(sel)

    def _endpoint_of_ip(self, ip: str) -> Optional[int]:
        """Client source IP → endpoint id (DNS proxy's TPROXY role).
        Unknown sources get None → REFUSED; pass ``dns_endpoint_of`` to
        override the mapping (e.g. loopback harnesses)."""
        for ep in self.endpoint_manager.endpoints():
            if ep.ipv4 == ip:
                return ep.endpoint_id
        return None

    # -- endpoint API -----------------------------------------------------
    def endpoint_add(self, endpoint_id: int, labels: Dict[str, str],
                     ipv4: str = "", named_ports=None,
                     host: bool = False):
        # write_lock (reentrant — API handlers already hold it): the
        # allocate-then-register sequence must not interleave with a
        # cluster-pool allocator swap (_on_pod_cidr_change), which
        # adopts only already-registered endpoints' addresses
        with self.write_lock:
            ep = self._endpoint_add_locked(endpoint_id, labels, ipv4,
                                           named_ports=named_ports,
                                           host=host)
        if self.k8s_bridge is not None:  # outside the lock: socket IO
            self.k8s_bridge.publish_endpoint(ep)
        return ep

    def host_endpoint_add(self, labels: Dict[str, str],
                          ipv4: str = "", endpoint_id: int = 0):
        """Register THIS node's host endpoint: node labels +
        ``reserved:host`` → fixed identity 1, subject to CCNP
        nodeSelector policies only (reference: the host endpoint +
        host firewall)."""
        return self.endpoint_add(endpoint_id, labels, ipv4=ipv4,
                                 host=True)

    def _endpoint_add_locked(self, endpoint_id: int,
                             labels: Dict[str, str], ipv4: str = "",
                             named_ports=None, host: bool = False):
        old = self.endpoint_manager.get(endpoint_id)
        if old is not None and old.ipv4 and not ipv4:
            ipv4 = old.ipv4  # re-add (CNI ADD retry) keeps the IP
        if old is not None and named_ports is None:
            # same asymmetry guard as the IP: a re-add without
            # named_ports must not wipe the table (named toPorts rules
            # would silently resolve to nothing)
            named_ports = old.named_ports
        if old is not None and old.ipv4 and old.ipv4 == ipv4:
            pass  # unchanged — nothing to allocate or release
        else:
            # acquire the new address FIRST: if it is unavailable the
            # old pin must stay intact (no torn release-then-fail)
            if not ipv4:
                ipv4 = self.ipam.allocate()
            else:
                try:
                    self.ipam.allocate_ip(ipv4)
                except ValueError:
                    pass  # out-of-pool pin is fine; an in-pool duplicate
                          # (PoolExhausted) must raise, not silently share
            if old is not None and old.ipv4:
                self.ipcache.delete(f"{old.ipv4}/32")
                self.ipam.release(old.ipv4)
        label_set = LabelSet.from_dict(labels)
        if host:
            from cilium_tpu.core.labels import SOURCE_RESERVED, Label

            label_set = LabelSet(
                list(label_set) + [Label(key="host", value="",
                                         source=SOURCE_RESERVED)])
        ep = self.endpoint_manager.add_endpoint(
            endpoint_id, label_set, ipv4=ipv4,
            named_ports=named_ports)
        self.ipcache.upsert(f"{ipv4}/32", ep.identity)
        return ep

    def register_group_provider(self, name: str, fn) -> None:
        """``fn(GroupsSpec) -> Iterable[str]`` (CIDRs). Registering
        re-resolves policies so existing toGroups rules pick it up."""
        self.group_providers[name] = fn
        self.endpoint_manager.regenerate_all(wait=True)

    def _resolve_group(self, spec):
        fn = self.group_providers.get(spec.provider)
        if fn is None:
            return ()
        try:
            return tuple(fn(spec))
        except Exception:
            LOG.warning("group provider %s failed; rule selects nothing",
                        spec.provider)
            return ()

    def secret_set(self, namespace: str, name: str, value: str) -> None:
        """Upsert a secret and re-resolve policies referencing it (the
        reference's secret-sync watcher triggers regeneration too)."""
        self.secrets.set(namespace, name, value)
        self.endpoint_manager.regenerate_all(wait=True)

    def secret_delete(self, namespace: str, name: str) -> None:
        self.secrets.delete(namespace, name)
        self.endpoint_manager.regenerate_all(wait=True)

    def endpoint_config(self, endpoint_id: int,
                        policy_audit_mode: Optional[bool] = None,
                        wait: bool = True):
        """Per-endpoint option surface (reference: ``cilium-dbg
        endpoint config <id> PolicyAuditMode=...``). Changing an
        option regenerates so the staged tables pick up the bit."""
        with self.write_lock:  # like every mutating entry point:
            # must not interleave with endpoint_remove / allocator swap
            ep = self.endpoint_manager.get(endpoint_id)
            if ep is None:
                raise KeyError(f"no endpoint {endpoint_id}")
            changed = False
            if policy_audit_mode is not None \
                    and ep.policy_audit_mode != policy_audit_mode:
                ep.policy_audit_mode = bool(policy_audit_mode)
                changed = True
        if changed:
            self.endpoint_manager.regenerate_all(wait=wait)
        return ep

    def endpoint_remove(self, endpoint_id: int) -> None:
        with self.write_lock:
            ep = self.endpoint_manager.get(endpoint_id)
            if ep is not None and ep.ipv4:
                self.ipcache.delete(f"{ep.ipv4}/32")
                self.ipam.release(ep.ipv4)
            self.endpoint_manager.remove_endpoint(endpoint_id)
        if self.k8s_bridge is not None:  # outside the lock: socket IO
            self.k8s_bridge.withdraw_endpoint(endpoint_id)

    # -- flow pipeline (engine → monitor → hubble, §3.6) -----------------
    def process_flows(self, flows: List) -> Dict:
        """Verdict a batch and fan it out to observability: monitor
        events (PolicyVerdict/Drop/Trace) and the hubble observer ring.
        Returns the output arrays as host numpy."""
        import numpy as np

        engine = self.loader.engine
        if engine is None and self.endpoint_manager.endpoints():
            # endpoint_add queues its regeneration asynchronously; a
            # caller that verdicts immediately after adding endpoints
            # used to win that race only by scheduler luck — block on
            # the queued regeneration instead of failing on timing
            self.endpoint_manager.regenerate_all(wait=True)
            engine = self.loader.engine
        if engine is None:
            raise RuntimeError(
                "no policy staged — add an endpoint or policy first")
        # one device→host readback, shared by monitor + annotate
        # (readbacks are the expensive sync point, docs/PLATFORM.md)
        outputs = {
            k: np.asarray(v)
            for k, v in engine.verdict_flows(
                flows, authed_pairs=self.auth.pairs_array()).items()
        }
        self.fan_out(flows, outputs)
        return outputs

    def fan_out(self, flows: List, outputs: Dict) -> None:
        """Observability fan-out for one verdicted batch: monitor
        events (→ the monitor socket), verdict/match annotation
        (honest ``policy_match_type`` + provenance stamps when the
        engine outputs carry the attribution lane), and the hubble
        observer ring. The ONE place the sequence lives — the replay
        pipeline and the verdict service both call it."""
        self.monitor.notify_batch(flows, outputs)
        annotate_flows(flows, outputs,
                       amap=getattr(self.loader.engine, "attribution",
                                    None))
        self.observer.observe(flows)

    # -- introspection (cilium-dbg surface) ------------------------------
    def status(self) -> Dict:
        return {
            "revision": self.repo.revision,
            "rules": len(self.repo),
            "endpoints": len(self.endpoint_manager.endpoints()),
            "identities": len(self.allocator),
            "backend": ("tpu" if self.config.enable_tpu_offload
                        else "oracle"),
            "engine_revision": self.loader.revision,
            "controllers": self.controllers.status(),
            "clustermesh": self.clustermesh.status(),
            "health": {n: s.reachable
                       for n, s in self.health.status().items()},
            "ipam": {"mode": self.config.ipam_mode,
                     "node": self.config.node_name,
                     "cidr": str(self.ipam.cidr),
                     "available": self.ipam.available},
            "services": len(self.services.list()),
        }
