"""config-surface: four-way parity across the knob surfaces.

A configuration knob exists four times: as a ``Config`` dataclass
field, as a TOML key ``Config.from_toml`` accepts, as a
``CILIUM_TPU_*`` environment override, and as a documented contract
in ``docs/``. Nothing ties those together — ``from_toml`` silently
drops unknown keys, ``from_env`` silently ignores typo'd variables,
and an ad-hoc ``os.environ`` read deep in a kernel module bypasses
``Config`` entirely. Each drift face is a check:

* **env ⇄ field** — every variable ``from_env`` reads must assign a
  real field (a typo'd setattr is a knob that never takes effect);
* **env ⇄ docs** — every ``CILIUM_TPU_*`` variable read anywhere in
  the package must be documented in ``docs/``/``README.md`` (ad-hoc
  knobs the operator cannot discover), and every variable the docs
  mention must still be read by code (stale docs teach dead knobs);
* **toml ⇄ field** — every explicit top-level key ``from_toml``
  copies must name a real field (section keys are hasattr-guarded by
  construction);
* **field ⇄ docs** — every ``Config``/section field must appear in
  the docs (the operator-facing catalog is docs/CONFIG.md);
* **field ⇄ code** — a field no module outside ``core/config.py``
  reads is a dead knob (checked by attribute name; a shared name
  anywhere keeps it alive — miss, don't invent).
"""

from __future__ import annotations

import ast
import os
import re
from typing import Dict, List, Optional, Set, Tuple

from cilium_tpu.analysis.core import Finding, ProjectIndex, checker

RULE = "config-surface"

CONFIG_MODULE = "cilium_tpu.core.config"
ENV_PREFIX = "CILIUM_TPU_"
#: doc surfaces scanned for mentions (repo-relative)
DOC_SOURCES = ("docs", "README.md")
#: env vars owned by the bench/watch tooling, not the daemon config
#: surface — they live in bench scripts outside the package
_ENV_EXEMPT_PREFIXES = ("CILIUM_TPU_BENCH_", "CILIUM_TPU_WATCH_")

_ENV_RE = re.compile(r"\b%s[A-Z0-9_]+\b" % ENV_PREFIX)


class ConfigModel:
    """The parsed config surface of ``core/config.py``."""

    def __init__(self) -> None:
        #: "" → top-level Config field names; section attr → fields
        self.fields: Dict[str, Dict[str, int]] = {"": {}}
        #: env var → (field path it assigns or None, line)
        self.env_reads: Dict[str, Tuple[Optional[str], int]] = {}
        #: explicit top-level TOML keys → line
        self.toml_keys: Dict[str, int] = {}
        #: section attr name → section class name
        self.sections: Dict[str, str] = {}
        self.path = ""


def _class_fields(cls: ast.ClassDef) -> Dict[str, int]:
    out: Dict[str, int] = {}
    for node in cls.body:
        if isinstance(node, ast.AnnAssign) \
                and isinstance(node.target, ast.Name):
            out[node.target.id] = node.lineno
    return out


def _assigned_field(stmt: ast.stmt) -> Optional[str]:
    """``cfg.engine.bank_size = …`` → "engine.bank_size"; ``cfg.x = …``
    → "x"."""
    targets = []
    if isinstance(stmt, ast.Assign):
        targets = stmt.targets
    elif isinstance(stmt, ast.AugAssign):
        targets = [stmt.target]
    for tgt in targets:
        parts: List[str] = []
        node = tgt
        while isinstance(node, ast.Attribute):
            parts.append(node.attr)
            node = node.value
        if isinstance(node, ast.Name) and node.id == "cfg" and parts:
            return ".".join(reversed(parts))
    return None


def _strings_in(node: ast.AST) -> List[str]:
    return [n.value for n in ast.walk(node)
            if isinstance(n, ast.Constant) and isinstance(n.value, str)]


def parse_config(index: ProjectIndex,
                 config_module: str = CONFIG_MODULE
                 ) -> Optional[ConfigModel]:
    sf = index.get(config_module)
    if sf is None:
        return None
    model = ConfigModel()
    model.path = sf.path
    classes: Dict[str, ast.ClassDef] = {
        n.name: n for n in sf.tree.body if isinstance(n, ast.ClassDef)}
    cfg_cls = classes.get("Config")
    if cfg_cls is None:
        return None
    model.fields[""] = _class_fields(cfg_cls)
    # section fields: a Config field whose default_factory names
    # another local dataclass
    for node in cfg_cls.body:
        if not (isinstance(node, ast.AnnAssign)
                and isinstance(node.target, ast.Name)):
            continue
        val = node.value
        if isinstance(val, ast.Call):
            for kw in val.keywords:
                if kw.arg == "default_factory" \
                        and isinstance(kw.value, ast.Name) \
                        and kw.value.id in classes:
                    section = node.target.id
                    model.sections[section] = kw.value.id
                    model.fields[section] = _class_fields(
                        classes[kw.value.id])
    # from_env: each `if env.get("X")…: cfg.y = …` / `if "X" in env:`
    for node in cfg_cls.body:
        if isinstance(node, ast.FunctionDef) and node.name == "from_env":
            for stmt in node.body:
                if not isinstance(stmt, ast.If):
                    continue
                env_vars = [s for s in _strings_in(stmt.test)
                            if s.startswith(ENV_PREFIX)]
                field = None
                for sub in stmt.body:
                    field = _assigned_field(sub) or field
                for var in env_vars:
                    model.env_reads[var] = (field, stmt.lineno)
        if isinstance(node, ast.FunctionDef) and node.name == "from_toml":
            for sub in ast.walk(node):
                # explicit key copies: data.get("key"…) /
                # "key" in data / for key in ("a", "b"…)
                if isinstance(sub, ast.Call) \
                        and isinstance(sub.func, ast.Attribute) \
                        and sub.func.attr == "get" \
                        and isinstance(sub.func.value, ast.Name) \
                        and sub.func.value.id == "data" and sub.args \
                        and isinstance(sub.args[0], ast.Constant) \
                        and isinstance(sub.args[0].value, str):
                    model.toml_keys[sub.args[0].value] = sub.lineno
                elif isinstance(sub, ast.Compare) \
                        and isinstance(sub.left, ast.Constant) \
                        and isinstance(sub.left.value, str) \
                        and any(isinstance(op, ast.In)
                                for op in sub.ops) \
                        and any(isinstance(c, ast.Name)
                                and c.id == "data"
                                for c in sub.comparators):
                    model.toml_keys[sub.left.value] = sub.lineno
                elif isinstance(sub, ast.For) \
                        and isinstance(sub.iter, (ast.Tuple, ast.List)):
                    keys = [e.value for e in sub.iter.elts
                            if isinstance(e, ast.Constant)
                            and isinstance(e.value, str)]
                    # only the `for key in (…): if key in data` idiom
                    body_txt = ast.dump(sub)
                    if "'data'" in body_txt and keys:
                        for k in keys:
                            model.toml_keys[k] = sub.lineno
    return model


def _env_vars_in_tree(index: ProjectIndex, config_module: str
                      ) -> Dict[str, Tuple[str, int]]:
    """Every CILIUM_TPU_* string literal in the package outside the
    config module (ad-hoc knob reads), var → (path, line)."""
    out: Dict[str, Tuple[str, int]] = {}
    for name, sf in sorted(index.files.items()):
        if name == config_module:
            continue
        for node in ast.walk(sf.tree):
            if isinstance(node, ast.Constant) \
                    and isinstance(node.value, str):
                for var in _ENV_RE.findall(node.value):
                    out.setdefault(var, (sf.path, node.lineno))
    return out


def _load_docs(root: Optional[str],
               docs: Optional[Dict[str, str]]) -> Dict[str, str]:
    if docs is not None:
        return docs
    out: Dict[str, str] = {}
    if root is None:
        return out
    for target in DOC_SOURCES:
        full = os.path.join(root, target)
        if os.path.isfile(full):
            with open(full, encoding="utf-8") as f:
                out[target] = f.read()
        elif os.path.isdir(full):
            for name in sorted(os.listdir(full)):
                if name.endswith(".md"):
                    with open(os.path.join(full, name),
                              encoding="utf-8") as f:
                        out[os.path.join(target, name)] = f.read()
    return out


def _doc_mentions(docs: Dict[str, str], token: str) -> bool:
    pat = re.compile(r"\b%s\b" % re.escape(token))
    return any(pat.search(text) for text in docs.values())


def _names_used_outside(index: ProjectIndex,
                        config_module: str) -> Set[str]:
    """Every attribute/keyword/string-constant name appearing outside
    the config module — one tree walk, shared by every dead-knob
    check. Name-level: a shared name keeps a dead knob alive (miss,
    don't invent)."""
    used: Set[str] = set()
    for name, sf in index.files.items():
        if name == config_module:
            continue
        for node in ast.walk(sf.tree):
            if isinstance(node, ast.Attribute):
                used.add(node.attr)
            elif isinstance(node, ast.keyword) and node.arg:
                used.add(node.arg)
            elif isinstance(node, ast.Constant) \
                    and isinstance(node.value, str):
                used.add(node.value)
    return used


def check_config(index: ProjectIndex,
                 config_module: str = CONFIG_MODULE,
                 docs: Optional[Dict[str, str]] = None
                 ) -> List[Finding]:
    model = parse_config(index, config_module)
    if model is None:
        return []
    root = getattr(index, "root", None)
    doc_texts = _load_docs(root, docs)
    findings: List[Finding] = []

    def field_exists(path: str) -> bool:
        if "." in path:
            section, leaf = path.split(".", 1)
            return leaf in model.fields.get(section, {})
        return path in model.fields[""]

    # env ⇄ field
    for var, (field, line) in sorted(model.env_reads.items()):
        if field is None:
            findings.append(Finding(
                model.path, line, RULE,
                f"from_env reads `{var}` but assigns no Config "
                f"field — the override never takes effect"))
        elif not field_exists(field):
            findings.append(Finding(
                model.path, line, RULE,
                f"from_env maps `{var}` to `cfg.{field}`, which is "
                f"not a Config field"))

    # toml ⇄ field
    for key, line in sorted(model.toml_keys.items()):
        if not field_exists(key):
            findings.append(Finding(
                model.path, line, RULE,
                f"from_toml copies key `{key}`, which is not a "
                f"Config field"))

    # env ⇄ docs (both directions) over the whole package
    tree_envs = _env_vars_in_tree(index, config_module)
    all_code_envs: Set[str] = set(tree_envs) | set(model.env_reads)
    if doc_texts:
        for var in sorted(all_code_envs):
            if var.startswith(_ENV_EXEMPT_PREFIXES):
                continue
            if not _doc_mentions(doc_texts, var):
                path, line = tree_envs.get(var, (model.path, 1))
                if var in model.env_reads:
                    path, line = model.path, model.env_reads[var][1]
                findings.append(Finding(
                    path, line, RULE,
                    f"env knob `{var}` is read here but documented "
                    f"nowhere under docs/ — operators cannot "
                    f"discover it"))
        doc_envs: Set[str] = set()
        for text in doc_texts.values():
            doc_envs.update(_ENV_RE.findall(text))
        for var in sorted(doc_envs - all_code_envs):
            if var.startswith(_ENV_EXEMPT_PREFIXES):
                continue
            findings.append(Finding(
                model.path, 1, RULE,
                f"docs mention env var `{var}` but nothing in the "
                f"package reads it — stale documentation"))

    # field ⇄ docs and field ⇄ code
    used_names = _names_used_outside(index, config_module)
    for section, fields in sorted(model.fields.items()):
        for field, line in sorted(fields.items()):
            label = f"{section}.{field}" if section else field
            if doc_texts and not _doc_mentions(doc_texts, field):
                findings.append(Finding(
                    model.path, line, RULE,
                    f"Config field `{label}` is documented nowhere "
                    f"under docs/ — add it to the docs/CONFIG.md "
                    f"catalog"))
            if field not in used_names:
                findings.append(Finding(
                    model.path, line, RULE,
                    f"Config field `{label}` is never read outside "
                    f"{model.path} — dead knob (delete it or wire "
                    f"it up)"))
    return findings


def field_count(index: ProjectIndex,
                config_module: str = CONFIG_MODULE) -> int:
    """Config fields visible to the rule — non-vacuity guard hook."""
    model = parse_config(index, config_module)
    if model is None:
        return 0
    return sum(len(f) for f in model.fields.values())


@checker
def check(index: ProjectIndex) -> List[Finding]:
    return check_config(index)
check.emits = (RULE,)
