"""wall-clock: behavioral time in the serving plane routes through
the injected Clock.

The DST layer (runtime/simclock.py + runtime/dst.py) can only search
fault schedules deterministically if every time-driven state machine —
breaker probes, quarantine TTLs, admission deadlines, backoff,
leases, cache expiry — reads the INSTALLED clock. One stray
``time.monotonic()`` in a deadline comparison and virtual time
silently diverges from the state machine it is supposed to drive:
the schedule that would have exposed a race becomes unreachable, and
the soak lanes go back to sleeping through wall-clock TTLs.

This rule flags direct calls to ``time.time`` / ``time.monotonic`` /
``time.sleep`` (and their ``_ns`` variants, and ``Event.wait``-style
timeouts are left to review) in the serving-plane module scope:

* ``cilium_tpu/runtime/`` (except ``simclock.py`` — it IS the seam)
* ``cilium_tpu/engine/``, ``cilium_tpu/policy/``, ``cilium_tpu/fqdn/``
* the root serving modules: ``kvstore``, ``kvstore_service``,
  ``identity_kvstore``, ``clustermesh``, ``auth``

``time.perf_counter`` is exempt everywhere: it measures how long real
work took (bench, phase attribution, EWMA denominators are routed
explicitly via ``Clock.perf``), and a virtual clock has nothing
truthful to say about real CPU seconds.

Genuine wall-of-the-real-world reads — provenance capture stamps,
the profiler's sampling sleeps — carry the standard justified
pragma::

    # ctlint: disable=wall-clock  # why real time is the right clock
"""

from __future__ import annotations

import ast
from typing import List

from cilium_tpu.analysis.callgraph import dotted
from cilium_tpu.analysis.core import Finding, ProjectIndex, checker

RULE = "wall-clock"

#: the behavioral time surface; perf_counter/process_time measure the
#: real world and stay direct
_BANNED = {
    "time.time", "time.time_ns",
    "time.monotonic", "time.monotonic_ns",
    "time.sleep",
}

#: repo-relative path prefixes in scope
_SCOPE_PREFIXES = (
    "cilium_tpu/runtime/",
    "cilium_tpu/engine/",
    "cilium_tpu/policy/",
    "cilium_tpu/fqdn/",
)

#: root serving-plane modules in scope
_SCOPE_FILES = (
    "cilium_tpu/kvstore.py",
    "cilium_tpu/kvstore_service.py",
    "cilium_tpu/identity_kvstore.py",
    "cilium_tpu/clustermesh.py",
    "cilium_tpu/auth.py",
)

#: the clock seam itself — the one module allowed to touch time.*
_EXEMPT = ("cilium_tpu/runtime/simclock.py",)

_REPLACEMENT = {
    "time.time": "simclock.wall()",
    "time.time_ns": "simclock.wall()",
    "time.monotonic": "simclock.now()",
    "time.monotonic_ns": "simclock.now()",
    "time.sleep": "simclock.sleep()",
}


def in_scope(path: str) -> bool:
    p = path.replace("\\", "/")
    if p in _EXEMPT:
        return False
    return p.startswith(_SCOPE_PREFIXES) or p in _SCOPE_FILES


@checker
def check(index: ProjectIndex) -> List[Finding]:
    from cilium_tpu.analysis.callgraph import Project

    project = Project(index)
    findings: List[Finding] = []
    for mi in project.modules.values():
        if not in_scope(mi.sf.path):
            continue
        for node in ast.walk(mi.sf.tree):
            if not isinstance(node, ast.Call):
                continue
            q = mi.qualify(node.func) or (dotted(node.func) or "")
            if q not in _BANNED:
                continue
            findings.append(Finding(
                mi.sf.path, node.lineno, RULE,
                f"direct `{q}()` in a serving-plane module — "
                f"behavioral time must route through the injected "
                f"Clock ({_REPLACEMENT.get(q, 'runtime/simclock.py')}) "
                f"or the DST schedule search cannot reach the states "
                f"this call gates; justify real-world reads "
                f"(provenance stamps, profiler sampling) with a "
                f"disable pragma"))
    return findings
check.emits = (RULE,)
