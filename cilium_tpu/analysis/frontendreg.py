"""frontend-registry: the ``l7proto`` universe has ONE registry and
the family enums can't drift from it.

ISSUE 15's unification closed the gap where ``proxylib`` parser
selection and the engine's L7-type enum were maintained by hand in
two places: a parser could exist that no policy could legally name
(or worse — a policy could name a proto the engine silently matched
as plain generic while the proxy dispatched a real state machine).
This rule keeps the halves pinned together statically:

* every ``register_parser("<name>", ...)`` in ``cilium_tpu/proxylib/``
  must either have an engine frontend (a ``FrontendSpec(name=
  "<name>", ...)`` under ``cilium_tpu/policy/compiler/frontends/``)
  or carry a justified proxy-only pragma
  (``# ctlint: disable=frontend-registry  # why``) — http/kafka are
  the canonical allowlist entries (the engine speaks them natively),
  the ``test.*`` fixtures ride the generic pair path by design;
* every frontend's declared ``family``/``family_name`` must appear in
  each family enum a verdict's lifecycle reads: the ``L7Type``
  member universe (``core/flow.py``), the memo/delta family map
  (``engine/memo.py FAMILY_OF_L7TYPE`` — what bank-reference
  invalidation keys on), and the attribution decode table
  (``engine/attribution.py FAMILY_NAMES`` — what the explain plane
  resolves through). A frontend missing from any of them would
  verdict on a family the rest of the plane can't invalidate or
  explain;
* every frontend ``name`` must have a ``register_parser`` under
  ``proxylib/`` — the ``OnData`` parser is the family's differential
  CPU oracle, and a frontend without one is untestable.

The checks are literal-level (AST over the four declaration sites),
like the other registry rules: a real registration satisfies them, a
drifted enum cannot.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Tuple

from cilium_tpu.analysis.core import Finding, ProjectIndex, checker

RULE = "frontend-registry"

_PROXYLIB_PREFIX = "cilium_tpu/proxylib/"
_FRONTENDS_PREFIX = "cilium_tpu/policy/compiler/frontends/"
_FLOW_PATH = "cilium_tpu/core/flow.py"
_MEMO_PATH = "cilium_tpu/engine/memo.py"
_ATTR_PATH = "cilium_tpu/engine/attribution.py"


def _const_str(node) -> Optional[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


def _const_int(node) -> Optional[int]:
    if isinstance(node, ast.Constant) and isinstance(node.value, int):
        return node.value
    return None


def _parser_registrations(index: ProjectIndex
                          ) -> Dict[str, Tuple[str, int]]:
    """name → (path, line) of every proxylib ``register_parser``."""
    out: Dict[str, Tuple[str, int]] = {}
    for f in index.files.values():
        if not f.path.startswith(_PROXYLIB_PREFIX):
            continue
        for node in ast.walk(f.tree):
            if not isinstance(node, ast.Call):
                continue
            fn = node.func
            name = (fn.id if isinstance(fn, ast.Name)
                    else fn.attr if isinstance(fn, ast.Attribute)
                    else None)
            if name != "register_parser" or not node.args:
                continue
            pname = _const_str(node.args[0])
            if pname is not None:
                out.setdefault(pname, (f.path, node.lineno))
    return out


def _frontend_specs(index: ProjectIndex) -> List[Dict]:
    """Every ``FrontendSpec(...)`` literal under the frontends
    package: {name, family, family_name, path, line}."""
    out: List[Dict] = []
    for f in index.files.values():
        if not f.path.startswith(_FRONTENDS_PREFIX):
            continue
        for node in ast.walk(f.tree):
            if not isinstance(node, ast.Call):
                continue
            fn = node.func
            cname = (fn.id if isinstance(fn, ast.Name)
                     else fn.attr if isinstance(fn, ast.Attribute)
                     else None)
            if cname != "FrontendSpec":
                continue
            kw = {k.arg: k.value for k in node.keywords}
            name = _const_str(kw.get("name"))
            family = _const_int(kw.get("family"))
            family_name = _const_str(kw.get("family_name"))
            if name is None:
                continue  # the base-class docstring example, if any
            out.append({"name": name, "family": family,
                        "family_name": family_name,
                        "path": f.path, "line": node.lineno})
    return out


def _l7type_values(index: ProjectIndex) -> Dict[int, str]:
    """L7Type enum literal: value → member name."""
    f = index.by_path.get(_FLOW_PATH)
    out: Dict[int, str] = {}
    if f is None:
        return out
    for node in ast.walk(f.tree):
        if isinstance(node, ast.ClassDef) and node.name == "L7Type":
            for stmt in node.body:
                if isinstance(stmt, ast.Assign) and len(stmt.targets) \
                        == 1 and isinstance(stmt.targets[0], ast.Name):
                    v = _const_int(stmt.value)
                    if v is not None:
                        out[v] = stmt.targets[0].id
    return out


def _dict_literal(index: ProjectIndex, path: str, var: str,
                  l7types: Dict[int, str]) -> Dict[int, str]:
    """An ``{int-or-int(L7Type.X): "name"}`` module-level dict."""
    f = index.by_path.get(path)
    out: Dict[int, str] = {}
    if f is None:
        return out
    name_to_val = {n: v for v, n in l7types.items()}
    for node in ast.walk(f.tree):
        if not (isinstance(node, ast.Assign) and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)
                and node.targets[0].id == var
                and isinstance(node.value, ast.Dict)):
            continue
        for k, v in zip(node.value.keys, node.value.values):
            key = _const_int(k)
            if key is None and isinstance(k, ast.Call) and k.args:
                # int(L7Type.X)
                arg = k.args[0]
                if isinstance(arg, ast.Attribute):
                    key = name_to_val.get(arg.attr)
            val = _const_str(v)
            if key is not None and val is not None:
                out[key] = val
    return out


@checker
def check_frontend_registry(index: ProjectIndex) -> List[Finding]:
    parsers = _parser_registrations(index)
    specs = _frontend_specs(index)
    if not parsers and not specs:
        return []  # corpus without either surface: nothing to hold
    l7types = _l7type_values(index)
    memo_fams = _dict_literal(index, _MEMO_PATH, "FAMILY_OF_L7TYPE",
                              l7types)
    attr_fams = _dict_literal(index, _ATTR_PATH, "FAMILY_NAMES",
                              l7types)
    frontend_names = {s["name"] for s in specs}
    findings: List[Finding] = []

    for pname, (path, line) in sorted(parsers.items()):
        if pname not in frontend_names:
            findings.append(Finding(
                path, line, RULE,
                f"register_parser({pname!r}) has no engine frontend "
                f"under policy/compiler/frontends/ — add one (see "
                f"frontends/r2d2.py) or justify proxy-only with "
                f"`# ctlint: disable={RULE}  # why`"))

    for s in specs:
        where = (s["path"], s["line"])
        fam, fname = s["family"], s["family_name"]
        if s["name"] not in parsers:
            findings.append(Finding(
                *where, RULE,
                f"frontend {s['name']!r} has no proxylib "
                f"register_parser — the OnData parser is the "
                f"family's differential CPU oracle and must exist"))
        if fam is None or fname is None:
            continue  # dynamically-built spec: nothing literal to pin
        if fam not in l7types and l7types:
            findings.append(Finding(
                *where, RULE,
                f"frontend {s['name']!r} family {fam} has no L7Type "
                f"member (core/flow.py)"))
        if memo_fams and memo_fams.get(fam) != fname:
            findings.append(Finding(
                *where, RULE,
                f"frontend {s['name']!r} family {fam}/{fname!r} "
                f"missing from engine/memo.py FAMILY_OF_L7TYPE "
                f"(got {memo_fams.get(fam)!r}) — bank-reference "
                f"invalidation would skip its rows"))
        if attr_fams and attr_fams.get(fam) != fname \
                and attr_fams.get(fam) != s["name"]:
            findings.append(Finding(
                *where, RULE,
                f"frontend {s['name']!r} family {fam} missing from "
                f"engine/attribution.py FAMILY_NAMES — the explain "
                f"plane could not decode its verdicts"))
    return findings
check_frontend_registry.emits = (RULE,)
