"""recompile-hazard: jit cache-key churn on the serving path.

A recompile never fails a test — it just stalls the hot path for
hundreds of milliseconds while XLA re-lowers a kernel the process
already compiled. The cache key of a jitted callable is (function
identity, static args, arg shapes/dtypes), which gives three churn
faces, each checked here:

* **per-call re-wrapping** — ``jax.jit(fn)`` / ``shard_map(fn, …)`` /
  ``functools.partial(jax.jit, …)`` executed *inside* a function body
  builds a fresh wrapper (and usually a fresh closure) per call: every
  invocation is a cache miss that re-traces. Module-level wrapping,
  decorator forms, wrappers built inside jitted bodies (trace-time
  only), and wrappers memoized onto ``self`` (``self._step = …`` or a
  ``self._cache[key] = …`` store) are exempt.
* **shape-dependent Python branching** — an ``if``/``while``/ternary
  over a value the dataflow core proves derives from a traced
  ``.shape``: one compile per distinct shape reaching the branch. In
  a bucketed engine this can be intended — which is what the
  justification-carrying allowlist is for.
* **config/closure scalars in static positions** — a value traced to
  ``Config`` (or an ``os.environ`` read) reaching a shape-determining
  argument (``reshape``/``zeros``/``arange``/``one_hot``…) inside a
  jitted body: every config flip silently recompiles the entry. The
  finding names the entry and the churning variable.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from cilium_tpu.analysis import dataflow
from cilium_tpu.analysis.callgraph import ModuleInfo, Project, dotted
from cilium_tpu.analysis.core import Finding, ProjectIndex, checker
from cilium_tpu.analysis.dataflow import AbsVal, EventSink, Interp
from cilium_tpu.analysis.purity import _is_jit_decorator, find_entries

RULE = "recompile-hazard"

#: call forms that build a jit wrapper
_WRAP_CALLS = {
    "jax.jit", "jit", "jax.pmap", "jax.shard_map", "shard_map",
    "jax.experimental.shard_map.shard_map", "pl.pallas_call",
    "pallas_call", "cilium_tpu.parallel.compat.shard_map",
}


def _is_wrap_call(mi: ModuleInfo, node: ast.Call) -> Optional[str]:
    q = mi.qualify(node.func)
    if q is None:
        return None
    if q in _WRAP_CALLS or q.endswith(".shard_map") \
            or q.endswith(".pallas_call"):
        return q
    if q in ("functools.partial", "partial") and node.args:
        inner = mi.qualify(node.args[0])
        if inner in ("jax.jit", "jit", "jax.pmap"):
            return f"partial({inner})"
    return None


def _is_memo_decorator(mi: ModuleInfo, dec: ast.expr) -> bool:
    q = mi.qualify(dec if not isinstance(dec, ast.Call) else dec.func)
    return q in ("functools.lru_cache", "lru_cache",
                 "functools.cache", "cache")


def _memoized_names(fn: ast.AST) -> Set[str]:
    """Names whose value is stored onto ``self`` (attribute or
    subscript) anywhere in ``fn`` — the engine's jit-memo idiom
    (``self._step = jax.jit(…)``, ``self._blob_steps[layout] = fn``)."""
    out: Set[str] = set()
    for node in ast.walk(fn):
        if not isinstance(node, ast.Assign):
            continue
        for tgt in node.targets:
            base = tgt
            while isinstance(base, (ast.Attribute, ast.Subscript)):
                base = base.value
            if isinstance(base, ast.Name) and base.id == "self" \
                    and isinstance(tgt, (ast.Attribute, ast.Subscript)):
                if isinstance(node.value, ast.Name):
                    out.add(node.value.id)
    return out


def _self_stored_directly(node: ast.Assign) -> bool:
    for tgt in node.targets:
        base = tgt
        while isinstance(base, (ast.Attribute, ast.Subscript)):
            base = base.value
        if isinstance(base, ast.Name) and base.id == "self":
            return True
    return False


def check_rewrap(index: ProjectIndex,
                 project: Optional[Project] = None) -> List[Finding]:
    """Face 1: per-call wrapper construction."""
    project = project or Project(index)
    findings: List[Finding] = []
    for mi in project.modules.values():
        # every function body (module-level wrap calls are the GOOD
        # pattern and are skipped by construction)
        for fns in mi.all_functions.values():
            for fn in fns:
                if any(_is_jit_decorator(mi, d)
                       for d in getattr(fn, "decorator_list", [])):
                    continue  # wrapper built at trace time only
                if any(_is_memo_decorator(mi, d)
                       for d in getattr(fn, "decorator_list", [])):
                    # an lru_cache'd factory builds each wrapper ONCE
                    # per key — the memoization fix itself
                    continue
                memo = _memoized_names(fn)
                for node in ast.iter_child_nodes(fn):
                    findings.extend(
                        self_scan(mi, fn, node, memo))
    return findings


def _walk_shallow(stmt: ast.AST):
    """ast.walk that does NOT descend into nested function defs —
    those get their own ``check_rewrap`` pass (double-reporting a
    nested def's wrap call against its parent would be noise)."""
    stack = [stmt]
    while stack:
        node = stack.pop()
        yield node
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef,
                                  ast.AsyncFunctionDef, ast.Lambda)):
                continue
            stack.append(child)


def self_scan(mi: ModuleInfo, fn: ast.AST, stmt: ast.stmt,
              memo: Set[str]) -> List[Finding]:
    out: List[Finding] = []
    # immediate Assign owning each wrap call (the call may sit under
    # an `if fn is None:` memo guard, so the Assign is found by its
    # own shallow walk, not by being the top statement)
    owner: dict = {}
    for node in _walk_shallow(stmt):
        if isinstance(node, ast.Assign) and isinstance(node.value,
                                                       ast.Call):
            owner[id(node.value)] = node
    for node in _walk_shallow(stmt):
        if not isinstance(node, ast.Call):
            continue
        wrapped = _is_wrap_call(mi, node)
        if wrapped is None:
            continue
        # exempt: result memoized onto self (directly, or through a
        # local later stored into a self-held dict/attribute)
        assign = owner.get(id(node))
        if assign is not None:
            if _self_stored_directly(assign):
                continue
            if len(assign.targets) == 1 \
                    and isinstance(assign.targets[0], ast.Name) \
                    and assign.targets[0].id in memo:
                continue
        name = getattr(fn, "name", "<lambda>")
        out.append(Finding(
            mi.sf.path, node.lineno, RULE,
            f"`{wrapped}` built per call inside `{name}` — every "
            f"invocation constructs a fresh wrapper (new cache key) "
            f"and re-traces; hoist to module level or memoize it"))
    return out


class _Sink(EventSink):
    """Faces 2+3, fed by the dataflow interpreter over jitted
    bodies. Events land in the CALLEE's file under the
    interprocedural walk, hence the per-event ``path``."""

    def __init__(self, entry: str):
        self.entry = entry
        self.findings: List[Finding] = []
        self._seen: Set[Tuple[str, int, str]] = set()

    def _add(self, path: str, line: int, msg: str) -> None:
        key = (path, line, msg)
        if key in self._seen:
            return
        self._seen.add(key)
        self.findings.append(Finding(path, line, RULE, msg))

    def shape_branch(self, path: str, line: int, kind: str,
                     origin: str) -> None:
        self._add(path, line,
                  f"shape-dependent Python branch on {origin} inside "
                  f"jitted entry `{self.entry}` — one compile per "
                  f"distinct input shape reaching it")

    def shape_position(self, path: str, line: int, fn: str,
                       val: AbsVal) -> None:
        candidates = val.items if val.kind == "tuple" else [val]
        for v in candidates:
            if v.kind not in ("const", "host") or not v.origin:
                continue
            if not _is_config_origin(v.origin):
                continue
            self._add(path, line,
                      f"config-derived scalar {v.origin} fixes a "
                      f"shape (`{fn}`) inside jitted entry "
                      f"`{self.entry}` — every config change "
                      f"recompiles; freeze it at wrap time "
                      f"(static_argnums/closure) deliberately")
            return


def _is_config_origin(origin: str) -> bool:
    low = origin.lower()
    return "cfg." in low or "config" in low or "environ" in low


def check_dynamic(index: ProjectIndex,
                  project: Optional[Project] = None) -> List[Finding]:
    """Faces 2+3: run the interpreter over every jitted entry."""
    project = project or Project(index)
    findings: List[Finding] = []
    seen: Set[int] = set()
    for mi, fn in find_entries(project):
        if id(fn) in seen:
            continue
        seen.add(id(fn))
        sink = _Sink(getattr(fn, "name", "<lambda>"))
        interp = Interp(project, sink)
        env = _seed_with_config(mi, fn)
        interp.run_function(mi, fn, env)
        findings.extend(sink.findings)
    # one finding per site: the first entry to reach a shared helper
    # line owns the attribution
    out = {}
    for f in sorted(set(findings)):
        out.setdefault((f.path, f.line), f)
    return sorted(out.values())


def _seed_with_config(mi: ModuleInfo, fn: ast.AST
                      ) -> Dict[str, AbsVal]:
    env = dataflow.param_shapes(mi, fn)
    # free names that read like config objects seed as consts with a
    # config origin so shape-position hits can name the churn source
    for node in ast.walk(fn):
        if isinstance(node, ast.Attribute):
            d = dotted(node)
            if d is None:
                continue
            root = d.split(".")[0]
            if root in env:
                continue
            if _is_config_origin(d) or root in ("cfg", "config"):
                env.setdefault(root, AbsVal.host(origin=f"`{root}`"))
    return env


@checker
def check(index: ProjectIndex) -> List[Finding]:
    project = Project(index)
    findings = check_rewrap(index, project)
    findings.extend(check_dynamic(index, project))
    return sorted(set(findings))
check.emits = (RULE,)
