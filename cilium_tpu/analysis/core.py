"""ctlint core: findings, the disable-comment allowlist, and the
project index every rule runs against.

The framework's correctness rests on invariants no unit test
enumerates — jitted kernels must stay pure, the threaded runtime must
acquire locks in one order, string registries (metric families, fault
points, stream frame kinds) must agree across producer and consumer
sites. ctlint machine-checks those contracts from the stdlib ``ast``
alone (zero dependencies — the lane must run in any environment that
can import the package), the same way Hyperflex's compiler enforces
the pattern↔kernel contract rather than trusting it (PAPERS.md).

Allowlisting: an INTENTIONAL violation carries an inline

    # ctlint: disable=rule-id[,rule-id]  # why it is safe

on the finding's line, or on a comment-only line directly above it.
A disable with no justification text after the rule list is itself a
finding (``bare-disable``) — the allowlist is an audit trail, not an
off switch.
"""

from __future__ import annotations

import ast
import hashlib
import json
import os
import pickle
import re
import time
from concurrent.futures import ThreadPoolExecutor
from dataclasses import dataclass
from typing import Callable, Dict, List, Optional, Sequence, Tuple

#: repo-relative package directory ctlint analyzes by default
DEFAULT_TARGET = "cilium_tpu"

#: CTLINT.json schema. 2 = adds schema_version + timings_ms (v2
#: dataflow families). 3 = findings may carry ``roots`` (the racing
#: concurrency roots a thread-safety finding names) and the report
#: carries ``wall_budget_ms``. 4 = findings may carry ``residency``
#: (the device-dataflow family's def-site chain proving the value
#: device-resident). Findings/count/suppressed/wall_budget_ms are
#: byte-stable for a clean tree; timings_ms is measured and varies
#: run to run.
SCHEMA_VERSION = 4

#: ``make lint`` wall-time budget (ms): 2× the v4 tree-wide warm
#: baseline (~20 s measured with the device-dataflow family; 18-22.5 s
#: across runs on the CI host). The CLI gate (--wall-budget-ms) fails
#: the lane if a full run exceeds it — rule families must stay cheap
#: enough for the pre-commit face.
WALL_BUDGET_MS = 40000

_DISABLE_RE = re.compile(
    r"#\s*ctlint:\s*disable=(?P<rules>[A-Za-z0-9_,\- ]+?)"
    r"(?:\s*#\s*(?P<why>.*))?$")


@dataclass(frozen=True, order=True)
class Finding:
    """One rule violation, anchored to a file:line."""

    path: str      # repo-relative
    line: int      # 1-based
    rule: str      # stable rule id (docs/ANALYSIS.md catalog)
    message: str
    #: the racing concurrency roots (thread-safety family) — empty
    #: for rules where the concept does not apply
    roots: Tuple[str, ...] = ()
    #: residency provenance (device-dataflow family): the ``path:line
    #: what`` def-site chain that made the flagged value
    #: device-resident — empty for rules where it does not apply
    residency: Tuple[str, ...] = ()

    def format(self) -> str:
        return f"{self.path}:{self.line}: [{self.rule}] {self.message}"

    def as_dict(self) -> Dict:
        d = {"path": self.path, "line": self.line,
             "rule": self.rule, "message": self.message}
        if self.roots:
            d["roots"] = list(self.roots)
        if self.residency:
            d["residency"] = list(self.residency)
        return d


class SourceFile:
    """One parsed module: source, AST, and its disable allowlist."""

    def __init__(self, path: str, module: str, source: str,
                 tree: Optional[ast.AST] = None):
        self.path = path          # repo-relative
        self.module = module      # dotted module name
        self.source = source
        self.tree = tree if tree is not None \
            else ast.parse(source, filename=path)
        self.lines = source.splitlines()
        #: line (1-based) → set of disabled rule ids on that line
        self.disables: Dict[int, set] = {}
        #: disable comments with no justification → bare-disable finding
        self.bare_disables: List[int] = []
        self._scan_disables()

    def _scan_disables(self) -> None:
        for i, text in enumerate(self.lines, 1):
            m = _DISABLE_RE.search(text)
            if m is None:
                continue
            rules = {r.strip() for r in m.group("rules").split(",")
                     if r.strip()}
            if not (m.group("why") or "").strip():
                self.bare_disables.append(i)
            self.disables.setdefault(i, set()).update(rules)
            # a comment-only line covers the next line of code, so a
            # long statement can carry its allowlist above itself
            if text[:m.start()].strip() == "":
                self.disables.setdefault(i + 1, set()).update(rules)

    def disabled(self, line: int, rule: str) -> bool:
        return rule in self.disables.get(line, ())


class ProjectIndex:
    """Every analyzed module, parsed once and shared by all rules."""

    def __init__(self, files: Dict[str, SourceFile],
                 root: Optional[str] = None):
        #: dotted module name → SourceFile
        self.files = files
        self.by_path = {f.path: f for f in files.values()}
        #: repo root when indexed from a tree (None for in-memory
        #: corpora) — rules that read non-Python surfaces (C++ ABI,
        #: docs) anchor here
        self.root = root

    @classmethod
    def from_tree(cls, root: str,
                  targets: Sequence[str] = (DEFAULT_TARGET,),
                  jobs: Optional[int] = None
                  ) -> Tuple["ProjectIndex", List[Finding]]:
        """Index ``targets`` (repo-relative dirs/files) under ``root``.
        Unparseable files become findings, not crashes — a linter that
        dies on a syntax error hides every other finding. Files are
        read and hashed on a thread pool; a per-content-hash AST cache
        under ``<root>/.ctlint_cache/`` skips re-parsing unchanged
        files across runs (ast.parse dominates a warm lint run)."""
        paths: List[Tuple[str, str]] = []   # (rel, full)
        for target in targets:
            full = os.path.join(root, target)
            if os.path.isfile(full):
                paths.append((target, full))
                continue
            for dirpath, _dirnames, filenames in sorted(os.walk(full)):
                for name in sorted(filenames):
                    if not name.endswith(".py"):
                        continue
                    path = os.path.join(dirpath, name)
                    paths.append((os.path.relpath(path, root), path))
        cache = _AstCache(root)
        with ThreadPoolExecutor(max_workers=min(8, max(1, len(paths)))
                                ) as pool:
            sources = dict(pool.map(
                lambda rf: (rf[0], _read(rf[1])), paths))
        index, errors = cls.from_sources(sources, root=root,
                                         cache=cache)
        cache.flush()
        return index, errors

    @classmethod
    def from_sources(cls, sources: Dict[str, str],
                     root: Optional[str] = None,
                     cache: Optional["_AstCache"] = None
                     ) -> Tuple["ProjectIndex", List[Finding]]:
        """Build from ``{repo-relative path: source}`` — the test
        corpus face: rules run against in-memory snippets exactly as
        they run against the tree."""
        files: Dict[str, SourceFile] = {}
        errors: List[Finding] = []
        for rel, source in sorted(sources.items()):
            module = _module_name(rel)
            try:
                tree = cache.tree_for(rel, source) if cache else None
                files[module] = SourceFile(rel, module, source,
                                           tree=tree)
                if cache is not None:
                    cache.store(rel, source, files[module].tree)
            except SyntaxError as e:
                errors.append(Finding(rel, e.lineno or 1, "parse-error",
                                      f"cannot parse: {e.msg}"))
        return cls(files, root=root), errors

    def get(self, module: str) -> Optional[SourceFile]:
        return self.files.get(module)


class _AstCache:
    """Content-hash → pickled-AST cache (one file per lint run, not
    per module — a single read/write beats 250 tiny files). A stale
    or unreadable cache is ignored wholesale; the format is an
    implementation detail keyed on the pickle protocol."""

    NAME = ".ctlint_cache/ast.pkl"

    def __init__(self, root: Optional[str]):
        self.path = os.path.join(root, self.NAME) if root else None
        self._old: Dict[str, bytes] = {}
        self._new: Dict[str, bytes] = {}
        self._dirty = False
        if self.path and os.path.exists(self.path):
            try:
                with open(self.path, "rb") as f:
                    self._old = pickle.load(f)
            except Exception:
                self._old = {}

    @staticmethod
    def _key(source: str) -> str:
        return hashlib.sha256(source.encode("utf-8")).hexdigest()

    def tree_for(self, rel: str, source: str) -> Optional[ast.AST]:
        blob = self._old.get(self._key(source))
        if blob is None:
            return None
        try:
            return pickle.loads(blob)
        except Exception:
            return None

    def store(self, rel: str, source: str, tree: ast.AST) -> None:
        key = self._key(source)
        blob = self._old.get(key)
        if blob is None:
            try:
                blob = pickle.dumps(tree, protocol=4)
            except Exception:
                return
            self._dirty = True
        self._new[key] = blob

    def flush(self) -> None:
        if self.path is None:
            return
        if not self._dirty and set(self._new) == set(self._old):
            return
        try:
            os.makedirs(os.path.dirname(self.path), exist_ok=True)
            tmp = self.path + ".tmp"
            with open(tmp, "wb") as f:
                pickle.dump(self._new, f, protocol=4)
            os.replace(tmp, self.path)
        except OSError:
            pass  # cache is best-effort; the lint result is identical


def _read(path: str) -> str:
    with open(path, encoding="utf-8") as f:
        return f.read()


def _module_name(rel_path: str) -> str:
    mod = rel_path[:-3] if rel_path.endswith(".py") else rel_path
    mod = mod.replace(os.sep, ".").replace("/", ".")
    if mod.endswith(".__init__"):
        mod = mod[: -len(".__init__")]
    return mod


# -- rule registry ----------------------------------------------------------

#: rule id → one-line description (the docs/ANALYSIS.md catalog source)
RULES: Dict[str, str] = {
    "jit-purity": "no host effects (clock, RNG, I/O, locks, host "
                  "syncs, traced-value branching) reachable from a "
                  "jitted/pallas entry point",
    "lock-order": "the static lock-acquisition graph of the threaded "
                  "runtime has no cycles and no nested re-acquire of "
                  "a non-reentrant lock",
    "metric-registry": "every metric name is declared once in "
                       "runtime/metrics.py, Prometheus-legal, and "
                       "used with exactly one instrument kind",
    "fault-registry": "every faults.maybe_fail seam names a "
                      "register_point'd point; every point has a seam",
    "frame-kind": "every KIND_* stream frame constant is handled in "
                  "both server and client dispatch",
    "swallowed-exception": "no bare except, and no except "
                           "Exception whose body only passes",
    "unused-import": "no unused module-level imports (outside "
                     "__init__ re-export surfaces)",
    "shape-dtype": "abstract shape/dtype interpretation of every "
                   "jitted entry: provable broadcast/matmul/reshape "
                   "mismatches, overflow-prone narrow-int "
                   "accumulations, weak-type wraps",
    "recompile-hazard": "jit cache-key churn: per-call wrapper "
                        "construction, shape-dependent Python "
                        "branching, config scalars fixing shapes "
                        "under trace",
    "abi-surface": "extern \"C\" signatures diffed bidirectionally "
                   "against every ctypes argtypes/restype/call in "
                   "the package and test/bench surfaces",
    "config-surface": "Config field ⇄ TOML key ⇄ CILIUM_TPU_* env "
                      "var ⇄ docs mention, four-way parity",
    "unbounded-queue": "no queue.Queue() without maxsize and no "
                       "list-as-queue append without a bound/shed "
                       "path in threaded runtime modules",
    "unbounded-registry": "no dict/set registry in long-lived "
                          "runtime/engine/policy modules inserted "
                          "into on an event path without an "
                          "eviction, bound, or TTL",
    "pallas-block-shape": "pallas_call block shapes align to the "
                          "(8, 128) TPU tile where literally provable, "
                          "and every matmul inside a pallas kernel "
                          "pins preferred_element_type",
    "obs-doc-parity": "every metric family declared in "
                      "runtime/metrics.py and every phase label "
                      "(tracing PHASE_*, engine-probe phases, capture "
                      "staging phases) is documented in "
                      "docs/OBSERVABILITY.md, and the doc names no "
                      "family that no longer exists",
    "thread-safety": "guarded-field inference + atomicity over the "
                     "serving plane: mutations/compound reads of an "
                     "inferred-guarded attribute outside its guard, "
                     "check-then-act after lock release, lock-release "
                     "windows in read-modify-write sequences, unsafe "
                     "publication from __init__ — each finding names "
                     "the racing concurrency roots",
    "wall-clock": "behavioral time (time.time/monotonic/sleep) in "
                  "serving-plane modules routes through the injected "
                  "Clock (runtime/simclock.py); real-world reads "
                  "carry a justified disable",
    "frontend-registry": "every proxylib register_parser name has an "
                         "engine frontend or a justified proxy-only "
                         "pragma, and every frontend's family appears "
                         "in the L7Type / memo / attribution family "
                         "enums",
    "implicit-sync": "no device-resident value is coerced to host "
                     "(float()/int()/bool(), .item()/.tolist(), "
                     "truthiness branching; np.asarray/device_get/"
                     "block_until_ready inside a loop) on a serving "
                     "hot path — each finding names the hot root and "
                     "carries the residency chain",
    "hot-loop-h2d": "no per-iteration host→device transfer "
                    "(device_put / jnp.asarray of host data) inside "
                    "a loop on a hot path; staging into instance "
                    "state (the prefetch/double-buffer idiom) is "
                    "exempt",
    "missing-donation": "every jitted step that overwrites a device "
                        "buffer it also takes as input "
                        "(.at[].set / dynamic_update_slice on a "
                        "parameter) donates that argument",
    "readback-ordering": "no host readback of one dispatch's result "
                         "before an independent later dispatch is "
                         "issued — reordering restores the "
                         "dispatch pipeline",
    "bare-disable": "every ctlint disable comment carries a "
                    "justification",
    "parse-error": "every analyzed file parses",
}

#: checker callables; each may emit findings for several rule ids.
#: A checker may declare the rule ids it can emit by setting
#: ``check.emits = ("rule-a", ...)`` after definition; ``run()`` then
#: skips it entirely when a ``--rules`` filter selects none of them
#: (the pre-commit face pays for the families it asks for, not the
#: whole catalog). The declaration is an optimization, never a
#: correctness gate: findings are still post-filtered by rule id, so
#: an undeclared checker simply always runs.
CHECKERS: List[Callable[[ProjectIndex], List[Finding]]] = []


def checker(fn: Callable[[ProjectIndex], List[Finding]]):
    CHECKERS.append(fn)
    return fn


def _bare_disable_findings(index: ProjectIndex) -> List[Finding]:
    out = []
    for f in index.files.values():
        for line in f.bare_disables:
            out.append(Finding(
                f.path, line, "bare-disable",
                "ctlint disable without a justification comment "
                "(write `# ctlint: disable=RULE  # why`)"))
    return out


#: per-rule wall time of the last run() (milliseconds) — rendered
#: into CTLINT.json as ``timings_ms``; measured, so NOT byte-stable
LAST_TIMINGS: Dict[str, float] = {}


def run(root: str, targets: Sequence[str] = (DEFAULT_TARGET,),
        rules: Optional[Sequence[str]] = None,
        only_paths: Optional[Sequence[str]] = None
        ) -> Tuple[List[Finding], int]:
    """Run all checkers; returns (active findings, suppressed count).
    ``rules`` filters to a subset of rule ids. ``only_paths`` (the
    ``--changed-only`` face) restricts the REPORTED findings to those
    repo-relative paths — the whole tree is still indexed, because
    every interesting rule here is cross-file."""
    # rule modules register their checkers on import
    from cilium_tpu.analysis import (  # noqa: F401
        abi,
        configsurface,
        devicedataflow,
        exceptions,
        frontendreg,
        imports,
        locks,
        obsdocs,
        pallas_shapes,
        purity,
        queues,
        recompile,
        registry,
        shapes,
        threadsafety,
        unboundedreg,
        wallclock,
    )

    LAST_TIMINGS.clear()
    t_run = time.monotonic()
    index, findings = ProjectIndex.from_tree(root, targets)
    LAST_TIMINGS["parse"] = (time.monotonic() - t_run) * 1000.0

    # checkers are independent of each other (shared state — the
    # callgraph Project and lock analyzer — is built behind memo
    # locks), so they run on a thread pool; findings are collected
    # in registration order, so the report stays deterministic.
    # Per-rule timings_ms are each checker's own wall time and
    # overlap under the GIL — their sum exceeds the ``wall`` key.
    # Two workers measured fastest on the real tree (14.4s vs 15.3s
    # serial / 17.9s at 8): checkers are mostly pure-Python and the
    # GIL turns wider pools into convoy overhead, while one extra
    # worker still overlaps the C-level ast/IO slices.
    def _timed(check):
        t0 = time.monotonic()
        found = check(index)
        return found, (time.monotonic() - t0) * 1000.0

    wanted_rules = set(rules) if rules else None

    def _selected(check) -> bool:
        if wanted_rules is None:
            return True
        emits = getattr(check, "emits", None)
        # no declaration -> always run (findings post-filter below)
        return emits is None or bool(wanted_rules & set(emits))

    selected = [c for c in CHECKERS if _selected(c)]
    with ThreadPoolExecutor(
            max_workers=min(2, max(1, len(selected) or 1))) as pool:
        futures = [(check, pool.submit(_timed, check))
                   for check in selected]
        for check, fut in futures:
            found, ms = fut.result()
            label = check.__module__.rsplit(".", 1)[-1]
            LAST_TIMINGS[label] = LAST_TIMINGS.get(label, 0.0) + ms
            findings.extend(found)
    findings.extend(_bare_disable_findings(index))
    LAST_TIMINGS["wall"] = (time.monotonic() - t_run) * 1000.0
    if rules:
        wanted = set(rules)
        findings = [f for f in findings if f.rule in wanted]
    if only_paths is not None:
        wanted_paths = set(only_paths)
        findings = [f for f in findings if f.path in wanted_paths]
    active: List[Finding] = []
    suppressed = 0
    for f in sorted(set(findings)):
        sf = index.by_path.get(f.path)
        if sf is not None and sf.disabled(f.line, f.rule):
            suppressed += 1
            continue
        active.append(f)
    return active, suppressed


def render_text(findings: Sequence[Finding], suppressed: int) -> str:
    lines = [f.format() for f in findings]
    lines.append(f"ctlint: {len(findings)} finding(s), "
                 f"{suppressed} allowlisted")
    return "\n".join(lines)


def render_json(findings: Sequence[Finding], suppressed: int,
                timings: Optional[Dict[str, float]] = None) -> str:
    """The CTLINT.json report. Everything except ``timings_ms`` is
    deterministic for a given tree (sorted findings, fixed key
    order); ``timings_ms`` is measured wall time per rule module and
    varies run to run — stability tests must compare around it."""
    report = {
        "schema_version": SCHEMA_VERSION,
        "findings": [f.as_dict() for f in findings],
        "count": len(findings),
        "suppressed": suppressed,
        "wall_budget_ms": WALL_BUDGET_MS,
    }
    if timings is None:
        timings = LAST_TIMINGS
    if timings:
        report["timings_ms"] = {k: round(v, 3)
                                for k, v in sorted(timings.items())}
    return json.dumps(report, indent=2)
