"""device-dataflow (ctlint v4): host↔device hazards on the serving
hot path.

Every engine/ring/multichip number so far comes from CPU hosts, where
a host↔device round-trip is free — so the hazards that will wreck
real-v5e latency are invisible to every tier-1 test: an implicit host
sync (``float()`` of a device scalar, ``np.asarray`` per loop
iteration, a Python branch on a device value) serializes the async
dispatch pipeline; a per-iteration ``device_put`` puts a PCIe/ICI
transfer on the critical path; an undonated in-place buffer update
doubles HBM traffic. This family proves the hot path free of them the
same way v3 proved it free of data races.

Mechanically it extends the v2 dataflow core (``dataflow.AbsVal``)
with a **device-residency dimension**: values produced by jitted
dispatches, ``jax.device_put``, ``jnp.*``/``lax.*`` constructors, and
the known device tables (memo table, session row table, ServedPack
lanes) carry ``device=True`` plus a ``dev_chain`` def-site provenance
chain, propagated through ops, subscripts, calls, and containers. Hot
roots are discovered over the callgraph — any in-scope function that
issues a device dispatch (a jitted entry call, a ``self._step``-style
memoized step, a ``_gather_step()(…)`` factory step, or a serve-plane
method like ``serve_ids``/``verdict_chunk``) — plus the named serving
spine (ring pack, session serve, capture chunk, serve-loop cycle,
dnsproxy batch, megakernel step). Four rules consume the resulting
event stream; findings carry the residency chain in schema-v4
CTLINT.json.

False-negative classes are deliberate (miss, don't invent) — see
docs/ANALYSIS.md §v4 for the catalog: residency is lost at
unresolvable method boundaries (``self.ring.pack(...)``), through
dict containers, and through first-class callables (the phase probes'
``_timed(fn)`` indirection); a single terminal batched readback at
the API edge is the *contract*, not a hazard, and is exempt by
construction.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Sequence, Set, Tuple

from cilium_tpu.analysis import dataflow
from cilium_tpu.analysis.callgraph import (ModuleInfo, Project, dotted,
                                           project_for)
from cilium_tpu.analysis.core import Finding, ProjectIndex, checker
from cilium_tpu.analysis.dataflow import AbsVal, EventSink, Interp
from cilium_tpu.analysis.purity import find_entries

RULE_SYNC = "implicit-sync"
RULE_H2D = "hot-loop-h2d"
RULE_DONATE = "missing-donation"
RULE_ORDER = "readback-ordering"

#: the serving hot path lives here; everything else is staging/CLI
#: surface where a sync is fine. dst.py is the simulation harness
#: (its reference lane reads back eagerly BY DESIGN, to compare), and
#: parallel/ is the multi-host compat shim — both out of scope.
_SCOPE_PREFIXES = ("cilium_tpu/engine/", "cilium_tpu/runtime/")
_SCOPE_FILES = ("cilium_tpu/fqdn/dnsproxy.py",)
_SCOPE_EXCLUDE = ("cilium_tpu/runtime/dst.py",)

#: attribute-call names that ARE a device dispatch in this codebase:
#: the serve-plane methods and the ``self._step`` jit-memo idiom. A
#: dispatch is a residency boundary — the walk does not enter it (the
#: callee is analyzed as its own root); its result is device.
DISPATCH_ATTRS = frozenset({
    "serve_ids", "verdict_chunk", "verdict_idx", "verdict_rows",
    "verdict_batch_arrays", "gather", "_step", "_full",
})

#: self-attributes that are device-resident tables, scoped by file
#: suffix so a generic name ("table") marks only the module whose
#: table actually lives on device
DEVICE_ATTRS: Dict[str, Tuple[str, ...]] = {
    "rows_dev": ("engine/session.py",),
    "table": ("engine/memo.py",),
    "verdict": ("engine/attribution.py",),
    "l7_match": ("engine/attribution.py",),
    "match_spec": ("engine/attribution.py",),
}

#: the named serving spine — always roots, even if a refactor hides
#: their dispatch behind an unresolvable boundary
NAMED_ROOTS: Tuple[Tuple[str, Optional[str], str], ...] = (
    ("cilium_tpu/engine/ring.py", "VerdictRing", "pack"),
    ("cilium_tpu/engine/session.py", "IncrementalSession", "serve_ids"),
    ("cilium_tpu/engine/verdict.py", "CaptureReplay", "verdict_chunk"),
    ("cilium_tpu/runtime/serveloop.py", "ServeLoop", "step"),
    ("cilium_tpu/fqdn/dnsproxy.py", "DNSProxy", "check_batch"),
    ("cilium_tpu/engine/megakernel.py", None, "fused_verdict_step"),
    ("cilium_tpu/engine/attribution.py", "ServedPack", "host"),
)

#: sync vocabulary (kept in parity with purity._HOST_SYNC): scalar
#: coercions block the host wherever they appear; bulk readbacks are
#: the legitimate API-edge pattern and only flag inside a loop (or
#: when fragmented — several straight-line readbacks that should be
#: one batched device_get)
_SCALAR_SYNCS = frozenset({"int()", "float()", "bool()", ".item()",
                           ".tolist()", "truthiness"})
_BULK_SYNCS = frozenset({"np.asarray", "np.array", "device_get",
                         "block_until_ready"})


def _in_scope(path: str) -> bool:
    if path in _SCOPE_EXCLUDE:
        return False
    return path.startswith(_SCOPE_PREFIXES) or path in _SCOPE_FILES


# -- dispatch recognition ---------------------------------------------------


def _dispatch_label(node: ast.Call) -> Optional[str]:
    """Syntactic device-dispatch forms: ``obj.serve_ids(…)`` /
    ``self._step(…)`` attribute calls, and the jit-factory idiom
    ``_gather_step()(table, idx)`` / ``self._blob_step(layout)(…)``."""
    f = node.func
    if isinstance(f, ast.Attribute) and f.attr in DISPATCH_ATTRS:
        return f.attr
    if isinstance(f, ast.Call):
        inner = f.func
        name = inner.attr if isinstance(inner, ast.Attribute) else (
            inner.id if isinstance(inner, ast.Name) else None)
        if name is not None and name.endswith("_step"):
            return f"{name}()"
    return None


def _resolve_call(project: Project, mi: ModuleInfo,
                  node: ast.Call) -> Optional[Tuple[ModuleInfo, ast.AST]]:
    """The project-resolution the dataflow core uses for plain calls:
    bare names through all_functions/imports, ``mod.fn`` through an
    imported project module."""
    d = dotted(node.func)
    if d is None:
        return None
    if "." not in d:
        fns = mi.all_functions.get(d)
        if fns:
            return mi, fns[0]
        return project.resolve_function(mi, d)
    root, _, attr = d.rpartition(".")
    target = project.modules.get(mi.imports.get(root, ""))
    if target is not None and "." not in attr \
            and attr in target.functions:
        return target, target.functions[attr]
    return None


def _is_jit_dispatch(project: Project, mi: ModuleInfo, node: ast.Call,
                     jit_ids: Set[int]) -> Optional[str]:
    resolved = _resolve_call(project, mi, node)
    if resolved is not None and id(resolved[1]) in jit_ids:
        return getattr(resolved[1], "name", "<jit>")
    return None


# -- hot-root discovery -----------------------------------------------------


def _module_units(mi: ModuleInfo):
    """(class name or None, ClassDef or None, fn) for every top-level
    function and class-body method. Nested defs are reached
    interprocedurally from their parent, not walked as roots."""
    for node in mi.sf.tree.body:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield None, None, node
        elif isinstance(node, ast.ClassDef):
            for stmt in node.body:
                if isinstance(stmt, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                    yield node.name, node, stmt


def _is_named_root(path: str, cls: Optional[str], fn_name: str) -> bool:
    for p, c, n in NAMED_ROOTS:
        if path.endswith(p) and fn_name == n \
                and (c is None or c == cls):
            return True
    return False


def _has_dispatch(project: Project, mi: ModuleInfo, fn: ast.AST,
                  jit_ids: Set[int]) -> bool:
    for node in ast.walk(fn):
        if not isinstance(node, ast.Call):
            continue
        if _dispatch_label(node) is not None:
            return True
        if _is_jit_dispatch(project, mi, node, jit_ids) is not None:
            return True
    return False


def find_hot_roots(project: Project, jit_ids: Optional[Set[int]] = None
                   ) -> List[Tuple[ModuleInfo, ast.AST,
                                   Optional[ast.ClassDef], str]]:
    """Every in-scope function/method that issues a device dispatch,
    plus the named serving spine. Sorted by label so shared-site
    finding attribution is deterministic."""
    if jit_ids is None:
        jit_ids = {id(fn) for _, fn in find_entries(project)}
    roots = []
    seen: Set[int] = set()
    for modname in sorted(project.modules):
        mi = project.modules[modname]
        path = mi.sf.path
        if not _in_scope(path):
            continue
        for cls_name, cls_node, fn in _module_units(mi):
            if id(fn) in seen:
                continue
            if _is_named_root(path, cls_name, fn.name) \
                    or _has_dispatch(project, mi, fn, jit_ids):
                seen.add(id(fn))
                owner = f"{cls_name}." if cls_name else ""
                roots.append((mi, fn, cls_node,
                              f"{path}::{owner}{fn.name}"))
    roots.sort(key=lambda r: r[3])
    return roots


# -- the residency-aware interpreter state ----------------------------------


class _DevSink(EventSink):
    """Ordered, deduplicated residency event stream for one root.

    The core's loop bodies run twice (widening) and exclusive branch
    arms run serialized, so raw emission both duplicates and
    scrambles; dedup on (kind, site, how) keeps the first occurrence,
    and the ordering rule additionally gates on straight-line events
    (branch_depth 0, not in a loop) where emission order IS program
    order."""

    def __init__(self) -> None:
        self.events: List[tuple] = []
        self._seen: Set[tuple] = set()

    def _emit(self, key: tuple, ev: tuple) -> None:
        if key in self._seen:
            return
        self._seen.add(key)
        self.events.append(ev)

    def host_sync(self, path, line, how, val, in_loop,
                  branch_depth=0):
        self._emit(("sync", path, line, how),
                   ("sync", path, line, how, val, in_loop,
                    branch_depth))

    def h2d(self, path, line, how, val, in_loop, staged,
            branch_depth=0):
        self._emit(("h2d", path, line, how),
                   ("h2d", path, line, how, val, in_loop, staged,
                    branch_depth))

    def device_dispatch(self, path, line, label, arg_chains, out_chain,
                        in_loop, branch_depth=0):
        self._emit(("dispatch", path, line, label),
                   ("dispatch", path, line, label, arg_chains,
                    out_chain, in_loop, branch_depth))


class _DevState(dataflow._State):
    """The core's state plus the codebase's device boundaries: known
    device tables on ``self``, dispatch-attr calls as residency
    sources (not walked — the callee is its own root), jitted-entry
    calls likewise, and ``self.method(…)`` resolution through the
    root's class so residency survives the helper-method hop."""

    def _attribute(self, node: ast.Attribute) -> AbsVal:
        if isinstance(node.value, ast.Name) and node.value.id == "self":
            suffixes = DEVICE_ATTRS.get(node.attr)
            if suffixes and self.mi.sf.path.endswith(suffixes):
                site = (f"{self.mi.sf.path}:{node.lineno} "
                        f"self.{node.attr} (device table)")
                return AbsVal.array(None, None,
                                    origin=f"self.{node.attr}",
                                    device=True, dev_chain=(site,))
        return super()._attribute(node)

    def _call(self, node: ast.Call) -> AbsVal:
        label = _dispatch_label(node)
        if label is not None:
            return self._dispatch(node, label)
        fn = node.func
        if isinstance(fn, ast.Attribute) \
                and isinstance(fn.value, ast.Name) \
                and fn.value.id == "self":
            meth = self.interp.self_methods.get(fn.attr)
            if meth is not None:
                return self._self_call(node, meth)
        return super()._call(node)

    def _project_call(self, node: ast.Call, q: str,
                      argvals: List[AbsVal]) -> AbsVal:
        name = _is_jit_dispatch(self.interp.project, self.mi, node,
                                self.interp.jit_ids)
        if name is not None:
            return self._emit_dispatch(node, f"jit `{name}`", argvals)
        return super()._project_call(node, q, argvals)

    def _dispatch(self, node: ast.Call, label: str) -> AbsVal:
        f = node.func
        if isinstance(f, ast.Attribute):
            self.eval(f.value)
        elif isinstance(f, ast.Call):
            for a in f.args:
                self.eval(a)
        argvals = [self.eval(a) for a in node.args]
        for kw in node.keywords:
            if kw.value is not None:
                argvals.append(self.eval(kw.value))
        return self._emit_dispatch(node, label, argvals)

    def _emit_dispatch(self, node: ast.Call, label: str,
                       argvals: Sequence[AbsVal]) -> AbsVal:
        path, line = self.mi.sf.path, node.lineno
        chains: List[Tuple[str, ...]] = []
        for v in argvals:
            if v.device:
                chains.append(v.dev_chain)
            elif not (v.kind == "const" or v.from_shape):
                # a host value the dispatch consumes — it MAY depend
                # on an earlier readback, so the ordering rule must
                # not call this dispatch independent
                chains.append(("<host>",))
        out_chain = (f"{path}:{line} {label} dispatch",)
        self.sink.device_dispatch(path, line, label, tuple(chains),
                                  out_chain,
                                  self.interp.loop_depth > 0,
                                  self.interp.branch_depth)
        return AbsVal.array(None, None, origin=f"{label} result",
                            device=True, dev_chain=out_chain)

    def _self_call(self, node: ast.Call, meth: ast.AST) -> AbsVal:
        params = [a.arg for a in meth.args.args]
        env: Dict[str, AbsVal] = {}
        if params and params[0] == "self":
            env["self"] = AbsVal.host(origin="self")
            params = params[1:]
        argvals = [self.eval(a) for a in node.args]
        for p, v in zip(params, argvals):
            env[p] = v if v.origin \
                else dataflow._with_origin(v, f"param `{p}`")
        for kw in node.keywords:
            if kw.value is None:
                continue
            v = self.eval(kw.value)
            if kw.arg is not None and kw.arg in params:
                env[kw.arg] = v
        self._default_params(meth, env)
        return self.interp.run_function(self.mi, meth, env,
                                        self.depth + 1)


class _DevInterp(Interp):
    state_cls = _DevState

    def __init__(self, project: Project, sink: EventSink,
                 jit_ids: Set[int],
                 self_methods: Dict[str, ast.AST]):
        super().__init__(project, sink)
        self.jit_ids = jit_ids
        #: the root's class methods, for `self.helper(…)` resolution
        self.self_methods = self_methods


def _walk_root(project: Project, jit_ids: Set[int], mi: ModuleInfo,
               fn: ast.AST,
               cls_node: Optional[ast.ClassDef]) -> _DevSink:
    sink = _DevSink()
    methods: Dict[str, ast.AST] = {}
    if cls_node is not None:
        for stmt in cls_node.body:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                methods[stmt.name] = stmt
    interp = _DevInterp(project, sink, jit_ids, methods)
    env = dataflow.param_shapes(mi, fn)
    # `self` is an object, never an array — param_shapes' array seed
    # would swallow every attribute/method access on it
    env["self"] = AbsVal.host(origin="self")
    interp.run_function(mi, fn, env)
    return sink


# -- rules over the event stream --------------------------------------------


def _residency(val: AbsVal) -> Tuple[str, ...]:
    return tuple(val.dev_chain)


def _findings_for_root(sink: _DevSink, label: str) -> List[Finding]:
    out: List[Finding] = []
    #: straight-line bulk readbacks, for the fragmented face
    frag: List[tuple] = []
    for ev in sink.events:
        if ev[0] == "sync":
            _, path, line, how, val, in_loop, bd = ev
            what = val.origin or "a device value"
            if how in _SCALAR_SYNCS:
                out.append(Finding(
                    path, line, RULE_SYNC,
                    f"implicit host sync: `{how}` coerces "
                    f"device-resident {what} on hot path `{label}` — "
                    f"the host blocks mid-dispatch; read back once, "
                    f"in bulk, at the path's edge",
                    residency=_residency(val)))
            elif how in _BULK_SYNCS and in_loop:
                out.append(Finding(
                    path, line, RULE_SYNC,
                    f"per-iteration host readback `{how}` of "
                    f"device-resident {what} inside a loop on hot "
                    f"path `{label}` — batch one readback outside "
                    f"the loop",
                    residency=_residency(val)))
            elif how in _BULK_SYNCS and bd == 0 and val.dev_chain:
                frag.append((path, line, how, val))
        elif ev[0] == "h2d":
            _, path, line, how, val, in_loop, staged, bd = ev
            if in_loop and not staged and not val.device:
                out.append(Finding(
                    path, line, RULE_H2D,
                    f"per-iteration host→device transfer `{how}` "
                    f"inside a loop on hot path `{label}` — hoist it "
                    f"out of the loop, or stage it ahead into "
                    f"instance state (the capture-prefetch "
                    f"double-buffer idiom)",
                    residency=(f"{path}:{line} {how}",)))
    # fragmented readback: several straight-line bulk readbacks on one
    # hot path — each is a separate blocking transfer where a single
    # batched jax.device_get would do
    if len(frag) >= 2:
        path, line, how, val = frag[0]
        others = ", ".join(f"{p.rsplit('/', 1)[-1]}:{ln}"
                           for p, ln, _h, _v in frag[1:])
        out.append(Finding(
            path, line, RULE_SYNC,
            f"fragmented readback: {len(frag)} separate host "
            f"readbacks on hot path `{label}` (also {others}) — "
            f"batch them into a single jax.device_get",
            residency=_residency(val)))
    out.extend(_ordering_findings(sink, label))
    return out


def _ordering_findings(sink: _DevSink, label: str) -> List[Finding]:
    """A straight-line bulk readback of one dispatch's result issued
    BEFORE a later, provably independent dispatch: the readback
    blocks the host, so the second dispatch misses its pipeline slot.
    Independence is conservative — every dispatch argument must be
    device-resident (chains disjoint from the readback's) or a known
    static; any plain host argument may depend on the readback and
    vetoes the pairing."""
    out: List[Finding] = []
    events = sink.events
    for i, ev in enumerate(events):
        if ev[0] != "sync":
            continue
        _, path, line, how, val, in_loop, bd = ev
        if how not in _BULK_SYNCS or in_loop or bd != 0 \
                or not val.dev_chain:
            continue
        chain = set(val.dev_chain)
        for later in events[i + 1:]:
            if later[0] != "dispatch":
                continue
            (_, dpath, dline, dlabel, arg_chains, _out_chain,
             d_in_loop, d_bd) = later
            if d_in_loop or d_bd != 0:
                continue
            if any(c == ("<host>",) for c in arg_chains):
                continue
            if any(chain & set(c) for c in arg_chains):
                continue
            out.append(Finding(
                path, line, RULE_ORDER,
                f"host readback `{how}` of "
                f"{val.origin or 'a device value'} blocks before the "
                f"independent device dispatch `{dlabel}` at "
                f"{dpath}:{dline} on hot path `{label}` — issue the "
                f"dispatch first (or batch readbacks after all "
                f"dispatches) to keep the device pipeline full",
                residency=_residency(val)))
            break
    return out


# -- missing-donation (syntactic, over the jitted entries) ------------------


def _int_elems(node: ast.expr) -> List[int]:
    if isinstance(node, ast.Constant) and isinstance(node.value, int):
        return [node.value]
    if isinstance(node, (ast.Tuple, ast.List)):
        out = []
        for e in node.elts:
            if isinstance(e, ast.Constant) and isinstance(e.value, int):
                out.append(e.value)
        return out
    return []


def _decorated_donations(mi: ModuleInfo, fn: ast.AST) -> Set[int]:
    donated: Set[int] = set()
    for dec in getattr(fn, "decorator_list", []):
        if not isinstance(dec, ast.Call):
            continue
        q = mi.qualify(dec.func)
        keywords = ()
        if q in ("functools.partial", "partial") and dec.args \
                and mi.qualify(dec.args[0]) in ("jax.jit", "jit",
                                                "jax.pmap"):
            keywords = dec.keywords
        elif q in ("jax.jit", "jit", "jax.pmap"):
            keywords = dec.keywords
        for kw in keywords:
            if kw.arg in ("donate_argnums", "donate_argnames"):
                donated.update(_int_elems(kw.value))
                donated.add(-1)  # marker: donation was declared
    return donated


def _wrap_site_donations(project: Project) -> Dict[int, Set[int]]:
    """``jax.jit(fn, donate_argnums=…)`` wrap-call sites, mapped onto
    the resolved function."""
    out: Dict[int, Set[int]] = {}
    for mi in project.modules.values():
        # wrap sites for in-scope entries live in-scope too (the wrap
        # IS the dispatch the hot path calls) — skip the rest of the
        # tree rather than re-walking it
        if not _in_scope(mi.sf.path):
            continue
        for node in ast.walk(mi.sf.tree):
            if not isinstance(node, ast.Call) or not node.args:
                continue
            if mi.qualify(node.func) not in ("jax.jit", "jit",
                                             "jax.pmap"):
                continue
            donated: Set[int] = set()
            for kw in node.keywords:
                if kw.arg in ("donate_argnums", "donate_argnames"):
                    donated.update(_int_elems(kw.value))
                    donated.add(-1)
            if not donated:
                continue
            arg = node.args[0]
            if isinstance(arg, ast.Name):
                resolved = project.resolve_function(mi, arg.id)
                if resolved is not None:
                    out.setdefault(id(resolved[1]),
                                   set()).update(donated)
    return out


def _updated_params(fn: ast.AST) -> List[Tuple[int, str, int]]:
    """(param index, param name, line) for every in-place functional
    update of a direct parameter: ``param.at[…].set(…)`` or
    ``lax.dynamic_update_slice(param, …)``."""
    params = [a.arg for a in getattr(fn, "args", ast.arguments(
        args=[], posonlyargs=[], kwonlyargs=[], kw_defaults=[],
        defaults=[])).args]
    index = {p: i for i, p in enumerate(params)}
    out: List[Tuple[int, str, int]] = []
    for node in ast.walk(fn):
        if not isinstance(node, ast.Call):
            continue
        f = node.func
        # param.at[...].set(...)
        if isinstance(f, ast.Attribute) and f.attr == "set" \
                and isinstance(f.value, ast.Subscript) \
                and isinstance(f.value.value, ast.Attribute) \
                and f.value.value.attr == "at" \
                and isinstance(f.value.value.value, ast.Name):
            name = f.value.value.value.id
            if name in index:
                out.append((index[name], name, node.lineno))
        # dynamic_update_slice(param, ...)
        d = dotted(f) or ""
        if d.rsplit(".", 1)[-1] == "dynamic_update_slice" \
                and node.args and isinstance(node.args[0], ast.Name):
            name = node.args[0].id
            if name in index:
                out.append((index[name], name, node.lineno))
    return out


def check_donation(index: ProjectIndex,
                   project: Optional[Project] = None) -> List[Finding]:
    project = project or project_for(index)
    wrap_donations = _wrap_site_donations(project)
    findings: List[Finding] = []
    for mi, fn in find_entries(project):
        if not _in_scope(mi.sf.path):
            continue
        donated = _decorated_donations(mi, fn)
        donated |= wrap_donations.get(id(fn), set())
        name = getattr(fn, "name", "<lambda>")
        seen: Set[Tuple[int, int]] = set()
        for idx, pname, line in _updated_params(fn):
            if idx in donated or (idx, line) in seen:
                continue
            seen.add((idx, line))
            findings.append(Finding(
                mi.sf.path, line, RULE_DONATE,
                f"jitted entry `{name}` overwrites its parameter "
                f"`{pname}` in place without donating it — XLA "
                f"allocates a fresh output buffer every call; add "
                f"donate_argnums=({idx},) to the jit wrap",
                residency=(f"{mi.sf.path}:{getattr(fn, 'lineno', line)}"
                           f" jit `{name}` param `{pname}`",)))
    return findings


# -- the checker ------------------------------------------------------------


@checker
def check(index: ProjectIndex) -> List[Finding]:
    project = project_for(index)
    findings = check_donation(index, project)
    jit_ids = {id(fn) for _, fn in find_entries(project)}
    picked: Dict[Tuple[str, int, str], Finding] = {}
    for mi, fn, cls_node, label in find_hot_roots(project, jit_ids):
        sink = _walk_root(project, jit_ids, mi, fn, cls_node)
        for f in _findings_for_root(sink, label):
            # the first (label-sorted) root to reach a shared helper
            # site owns the attribution
            picked.setdefault((f.path, f.line, f.rule), f)
    findings.extend(picked.values())
    return sorted(set(findings))
check.emits = (RULE_SYNC, RULE_H2D, RULE_DONATE, RULE_ORDER)
