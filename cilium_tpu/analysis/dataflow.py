"""ctlint's dataflow core: a forward abstract interpreter for the
jitted kernel surface.

PR 3's rules are syntactic — they can see a call to ``jnp.sum`` but
not what flows *into* it. This module adds the missing half: an
abstract-value lattice for the Python/JAX values that appear in this
codebase (arrays with partially-known shapes and dtypes, const host
scalars, shape-derived scalars, opaque host objects) and a forward
interpreter that propagates them through a function body — across
assignments, tuple unpacking, branches (joined), loops (widened), and
interprocedurally through calls the project index can resolve
(depth-bounded; an unresolvable callee degrades to ⊤, never guesses).

Shape seeding exploits this repo's kernel-comment convention: every
device entry documents its parameters as ``trans: jax.Array,  # [S, K]
int32``. The interpreter parses those trailing comments into symbolic
shapes (``S``/``K`` become symbolic dims, equal symbols compare
equal), which is what lets it prove e.g. that a ``take_along_axis``
rank mismatch is real rather than merely possible. The bias
everywhere is the framework's: **miss, don't invent** — two distinct
symbols are *unknown*-compatible, not incompatible.

Rule families consume the interpreter through an :class:`EventSink`:
the core reports semantic events (a broadcast, a reduction, a
shape-derived branch, a closure scalar reaching a shape position) and
the rule modules (``shapes.py``, ``recompile.py``) turn the ones they
care about into findings. The core itself emits nothing.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, List, Optional, Sequence, Tuple

from cilium_tpu.analysis.callgraph import ModuleInfo, Project, dotted

# -- the value lattice ------------------------------------------------------

#: dimensions are ints (known), Sym (named symbolic — equal name ⇒
#: equal extent), or None (unknown)
class Sym(str):
    """A named symbolic dimension (``B``, ``S``, ``L``…)."""

    __slots__ = ()

    def __repr__(self) -> str:  # pragma: no cover - debug aid
        return str(self)


Dim = object  # int | Sym | None

_INT_DTYPES = ("int8", "uint8", "int16", "uint16", "int32", "uint32",
               "int64", "uint64")
_FLOAT_DTYPES = ("float16", "bfloat16", "float32", "float64")
_DTYPES = ("bool",) + _INT_DTYPES + _FLOAT_DTYPES

#: value range of each integer dtype (for weak-type wrap detection)
_INT_RANGE = {
    "int8": (-128, 127), "uint8": (0, 255),
    "int16": (-32768, 32767), "uint16": (0, 65535),
    "int32": (-2**31, 2**31 - 1), "uint32": (0, 2**32 - 1),
    "int64": (-2**63, 2**63 - 1), "uint64": (0, 2**64 - 1),
}


class AbsVal:
    """One abstract value. ``kind`` ∈ {"const", "tuple", "array",
    "host", "top"}.

    * const — a known host scalar (``.const``); ``from_shape`` marks
      values derived from a traced array's ``.shape`` (a symbolic dim
      is a const whose value is a :class:`Sym`).
    * tuple — a fixed-length sequence of AbsVals (``.items``).
    * array — a (possibly traced) array: ``.shape`` is a tuple of
      dims or None (unknown rank), ``.dtype`` a dtype string or None,
      ``.weak`` marks weak-typed scalars promoted from Python consts.
    * host — a non-array host object (lock, dict, config…).
    * top — unknown.

    ``origin`` is a human-readable provenance ("param `trans`",
    "closure `block`", "cfg.engine.batch_size") carried into findings.

    ``device`` is the v4 residency dimension: True when the value is
    (provably) device-resident — produced by a jitted dispatch,
    ``jax.device_put``, a ``jnp.*``/``lax.*`` constructor, or a staged
    device table. ``dev_chain`` is its def-site provenance — the chain
    of ``path:line what`` sites that made it device-resident — carried
    into device-dataflow findings as the ``residency`` field. The join
    bias is the framework's: residency survives a join only when BOTH
    sides are device (miss, don't invent).
    """

    __slots__ = ("kind", "const", "items", "shape", "dtype", "weak",
                 "from_shape", "origin", "device", "dev_chain")

    def __init__(self, kind: str, const=None, items=None, shape=None,
                 dtype: Optional[str] = None, weak: bool = False,
                 from_shape: bool = False, origin: str = "",
                 device: bool = False,
                 dev_chain: Tuple[str, ...] = ()):
        self.kind = kind
        self.const = const
        self.items = items
        self.shape = shape
        self.dtype = dtype
        self.weak = weak
        self.from_shape = from_shape
        self.origin = origin
        self.device = device
        self.dev_chain = dev_chain

    # constructors
    @staticmethod
    def top(origin: str = "") -> "AbsVal":
        return AbsVal("top", origin=origin)

    @staticmethod
    def host(origin: str = "") -> "AbsVal":
        return AbsVal("host", origin=origin)

    @staticmethod
    def const_(value, from_shape: bool = False,
               origin: str = "") -> "AbsVal":
        return AbsVal("const", const=value, from_shape=from_shape,
                      origin=origin)

    @staticmethod
    def tuple_(items: Sequence["AbsVal"], origin: str = "") -> "AbsVal":
        return AbsVal("tuple", items=list(items), origin=origin)

    @staticmethod
    def array(shape: Optional[Tuple], dtype: Optional[str],
              weak: bool = False, origin: str = "",
              device: bool = False,
              dev_chain: Tuple[str, ...] = ()) -> "AbsVal":
        return AbsVal("array", shape=shape, dtype=dtype, weak=weak,
                      origin=origin, device=device,
                      dev_chain=dev_chain)

    @property
    def is_array(self) -> bool:
        return self.kind == "array"

    @property
    def rank(self) -> Optional[int]:
        return None if self.shape is None else len(self.shape)

    def describe(self) -> str:
        if self.kind == "array":
            dims = "?" if self.shape is None else \
                "[" + ", ".join(str(d) if d is not None else "?"
                                for d in self.shape) + "]"
            return f"{dims} {self.dtype or '?'}"
        if self.kind == "const":
            return f"const {self.const!r}"
        return self.kind

    def __repr__(self) -> str:  # pragma: no cover - debug aid
        return f"<AbsVal {self.describe()}>"


def join(a: AbsVal, b: AbsVal) -> AbsVal:
    """Least upper bound — degrade to the weakest description that
    covers both."""
    if a is b:
        return a
    if a.kind == "top" or b.kind == "top":
        return AbsVal.top(origin=a.origin or b.origin)
    if a.kind != b.kind:
        return AbsVal.top(origin=a.origin or b.origin)
    fs = a.from_shape or b.from_shape
    if a.kind == "const":
        if a.const == b.const and type(a.const) is type(b.const):
            return AbsVal.const_(a.const, from_shape=fs, origin=a.origin)
        return AbsVal("const", const=None, from_shape=fs,
                      origin=a.origin or b.origin)
    if a.kind == "tuple":
        if len(a.items) != len(b.items):
            return AbsVal.host(origin=a.origin)
        return AbsVal.tuple_([join(x, y)
                              for x, y in zip(a.items, b.items)],
                             origin=a.origin)
    if a.kind == "array":
        shape = None
        if a.shape is not None and b.shape is not None \
                and len(a.shape) == len(b.shape):
            shape = tuple(x if _dim_eq(x, y) else None
                          for x, y in zip(a.shape, b.shape))
        dtype = a.dtype if a.dtype == b.dtype else None
        # residency survives only when BOTH arms are device-resident
        dev = a.device and b.device
        return AbsVal.array(shape, dtype, weak=a.weak and b.weak,
                            origin=a.origin or b.origin,
                            device=dev,
                            dev_chain=(a.dev_chain or b.dev_chain)
                            if dev else ())
    return AbsVal.host(origin=a.origin or b.origin)


def widen(old: AbsVal, new: AbsVal) -> AbsVal:
    """Loop widening: any still-changing component jumps straight to
    unknown so the fixpoint terminates in two passes."""
    j = join(old, new)
    if j.kind == "array":
        if old.kind == "array" and old.shape != j.shape:
            j = AbsVal.array(None, j.dtype, weak=j.weak,
                             origin=j.origin, device=j.device,
                             dev_chain=j.dev_chain)
    elif j.kind == "const" and old.kind == "const" \
            and old.const != new.const:
        j = AbsVal("const", const=None, from_shape=j.from_shape,
                   origin=j.origin)
    return j


def _dim_eq(a: Dim, b: Dim) -> bool:
    if a is None or b is None:
        return False
    if isinstance(a, Sym) or isinstance(b, Sym):
        return isinstance(a, Sym) and isinstance(b, Sym) and str(a) == str(b)
    return a == b


def _dim_conflict(a: Dim, b: Dim) -> bool:
    """True only when both extents are KNOWN and provably unequal —
    two distinct symbols are unknown-compatible (miss, don't invent)."""
    if isinstance(a, int) and isinstance(b, int):
        return a != b
    if isinstance(a, Sym) and isinstance(b, Sym):
        return False
    return False


def broadcast_shapes(a: Optional[Tuple], b: Optional[Tuple]
                     ) -> Tuple[Optional[Tuple], Optional[Tuple[Dim, Dim, int]]]:
    """Numpy broadcasting over symbolic shapes. Returns
    ``(result_shape, conflict)`` where conflict is ``(dim_a, dim_b,
    axis_from_end)`` for a provable mismatch, else None."""
    if a is None or b is None:
        return None, None
    out: List[Dim] = []
    for i in range(1, max(len(a), len(b)) + 1):
        da = a[-i] if i <= len(a) else 1
        db = b[-i] if i <= len(b) else 1
        if da == 1:
            out.append(db)
        elif db == 1:
            out.append(da)
        elif _dim_eq(da, db):
            out.append(da)
        elif _dim_conflict(da, db):
            return None, (da, db, i)
        else:
            out.append(None)
    return tuple(reversed(out)), None


def promote(a: AbsVal, b: AbsVal) -> Optional[str]:
    """Result dtype of a binary op (jax default, x64 disabled). A weak
    operand adopts the strong side's dtype; otherwise widest wins,
    floats beat ints."""
    da, db = a.dtype, b.dtype
    if da is None or db is None:
        return None
    if da == db:
        return da
    if a.weak and not b.weak:
        if da in _INT_DTYPES and db in _FLOAT_DTYPES + _INT_DTYPES:
            return db
        if da in _FLOAT_DTYPES and db in _FLOAT_DTYPES:
            return db
    if b.weak and not a.weak:
        if db in _INT_DTYPES and da in _FLOAT_DTYPES + _INT_DTYPES:
            return da
        if db in _FLOAT_DTYPES and da in _FLOAT_DTYPES:
            return da
    for f in ("float64", "float32", "bfloat16", "float16"):
        if f in (da, db):
            return f
    order = list(_INT_DTYPES)
    if da in order and db in order:
        return max(da, db, key=order.index)
    return None


# -- event sink -------------------------------------------------------------

class EventSink:
    """Rule modules subclass this; every hook default is a no-op. The
    interpreter calls hooks with enough context for a finding message
    (entry name threading is the caller's business). ``path`` is the
    repo-relative file the event's ``line`` belongs to — under the
    interprocedural walk that is the CALLEE's module, not the
    entry's."""

    def binop_conflict(self, path: str, line: int, op: str, a: AbsVal,
                       b: AbsVal, conflict) -> None:
        pass

    def rank_mismatch(self, path: str, line: int, what: str, a: AbsVal,
                      b: AbsVal) -> None:
        pass

    def matmul_conflict(self, path: str, line: int, a: AbsVal,
                        b: AbsVal) -> None:
        pass

    def reshape_mismatch(self, path: str, line: int, src: AbsVal,
                         want: Tuple) -> None:
        pass

    def reduction(self, path: str, line: int, fn: str, operand: AbsVal,
                  extent, has_dtype: bool) -> None:
        pass

    def weak_wrap(self, path: str, line: int, op: str, arr: AbsVal,
                  value) -> None:
        pass

    def shape_branch(self, path: str, line: int, kind: str,
                     origin: str) -> None:
        pass

    def shape_position(self, path: str, line: int, fn: str,
                       val: AbsVal) -> None:
        pass

    # -- v4 device-residency events (devicedataflow.py) ----------------

    def host_sync(self, path: str, line: int, how: str, val: AbsVal,
                  in_loop: bool, branch_depth: int = 0) -> None:
        """A device-resident value was coerced to host: ``np.asarray``
        / ``jax.device_get`` / ``float()``/``int()``/``bool()`` /
        ``.item()``/``.tolist()`` / ``.block_until_ready()`` /
        truthiness branching. ``how`` names the coercion."""

    def h2d(self, path: str, line: int, how: str, val: AbsVal,
            in_loop: bool, staged: bool,
            branch_depth: int = 0) -> None:
        """Host data crossed to device (``jax.device_put`` /
        ``jnp.asarray`` of a host value). ``staged`` marks the
        double-buffer idiom: the transferred value is stored into
        instance state (an attribute/container) for a LATER
        iteration rather than consumed by this one."""

    def device_dispatch(self, path: str, line: int, label: str,
                        arg_chains: Tuple[Tuple[str, ...], ...],
                        out_chain: Tuple[str, ...], in_loop: bool,
                        branch_depth: int = 0) -> None:
        """A device dispatch was issued (jitted entry call, or a
        staged-step/memo-serve attribute call the resolver cannot see
        through). ``arg_chains`` are the residency chains of its
        device arguments; ``out_chain`` the chain stamped on its
        result."""


# -- comment-shape seeding --------------------------------------------------

#: ``# [S, K] int32``, ``# [B, L] uint8/int32 …``, ``# scalar int32``,
#: ``# [NB] int32 — …``
_SHAPE_COMMENT = re.compile(
    r"#\s*(?:(scalar)|\[(?P<dims>[^\]]*)\])\s*(?P<dtype>[A-Za-z0-9_/]+)?")


def _parse_dim(tok: str) -> Dim:
    tok = tok.strip()
    if not tok:
        return None
    if tok.isdigit():
        return int(tok)
    if re.fullmatch(r"[A-Za-z_][A-Za-z0-9_]*(/[A-Za-z0-9_]+)?", tok):
        return Sym(tok)
    return None


def _parse_dtype(tok: Optional[str]) -> Optional[str]:
    if not tok:
        return None
    first = tok.split("/")[0].lower()
    return first if first in _DTYPES else None


def param_shapes(mi: ModuleInfo, fn: ast.AST) -> Dict[str, AbsVal]:
    """Seed abstract values for a function's parameters from the
    kernel-comment convention (``name,   # [S, K] int32``). Parameters
    with no comment seed as unknown arrays only when the function
    looks like a kernel (the caller decides); here they seed ⊤-array.
    """
    out: Dict[str, AbsVal] = {}
    args = getattr(fn, "args", None)
    if args is None:
        return out
    lines = mi.sf.lines
    for arg in list(args.args) + list(args.kwonlyargs):
        val = AbsVal.array(None, None, origin=f"param `{arg.arg}`")
        line = lines[arg.lineno - 1] if arg.lineno - 1 < len(lines) else ""
        m = _SHAPE_COMMENT.search(line)
        if m is not None:
            if m.group(1):  # scalar
                shape: Optional[Tuple] = ()
            else:
                dims = m.group("dims")
                shape = tuple(_parse_dim(t) for t in dims.split(",")) \
                    if dims.strip() else ()
            val = AbsVal.array(shape, _parse_dtype(m.group("dtype")),
                               origin=f"param `{arg.arg}`")
        out[arg.arg] = val
    return out


# -- the interpreter --------------------------------------------------------

#: reductions whose accumulator dtype follows the operand (overflow
#: surface when the operand is a narrow int)
_REDUCTIONS = {"sum", "cumsum", "prod", "cumprod", "dot", "matmul",
               "einsum", "mean", "trace"}

#: jnp/np dtype-constructor names usable as casts (jnp.uint32(x))
_DTYPE_CASTS = {d: d for d in _DTYPES}

#: call argument positions that are SHAPE positions (static under jit)
_SHAPE_ARG_FNS = {
    "zeros": 0, "ones": 0, "full": 0, "empty": 0, "arange": 0,
    "broadcast_to": 1, "reshape": 1, "one_hot": 1, "iota": 1,
    "tile": 1, "repeat": 1,
}

_MAX_DEPTH = 4      # interprocedural call depth bound
_MAX_LOOP = 2       # loop body passes before widening


class Interp:
    """Forward abstract interpreter over one function (and, depth-
    bounded, its resolvable callees).

    Subclasses may set :attr:`state_cls` to a ``_State`` subclass —
    the v4 device-dataflow family plugs its residency-aware state in
    this way rather than duplicating the interpreter. ``loop_depth``
    / ``branch_depth`` / ``staged_assign`` live on the Interp (not
    the per-function state) so the context survives interprocedural
    steps: a sync inside a callee reached from a caller's loop still
    reports ``in_loop``."""

    #: _State subclass run_function constructs (None → _State)
    state_cls = None

    def __init__(self, project: Project, sink: EventSink,
                 max_depth: int = _MAX_DEPTH):
        self.project = project
        self.sink = sink
        self.max_depth = max_depth
        #: (id(fn)) currently on the call stack — cycle breaker
        self._active: set = set()
        #: nesting depth of Python loops on the current walk path
        self.loop_depth = 0
        #: nesting depth of joined branches (if/try arms) — events at
        #: depth 0 are straight-line and safely ordered
        self.branch_depth = 0
        #: True while evaluating the RHS of an attribute/container
        #: store (the double-buffer staging idiom)
        self.staged_assign = False

    # -- entry ---------------------------------------------------------

    def run_function(self, mi: ModuleInfo, fn: ast.AST,
                     env: Optional[Dict[str, AbsVal]] = None,
                     depth: int = 0) -> AbsVal:
        """Interpret ``fn``'s body under ``env`` (parameter bindings +
        visible closure values); returns the join of its returns."""
        if id(fn) in self._active or depth > self.max_depth:
            return AbsVal.top()
        self._active.add(id(fn))
        try:
            st = (self.state_cls or _State)(self, mi, dict(env or {}),
                                            depth)
            body = fn.body if isinstance(
                fn, (ast.FunctionDef, ast.AsyncFunctionDef)) else [
                    ast.Return(value=fn.body)]
            if isinstance(fn, ast.Lambda):
                body = [ast.Return(value=fn.body)]
            st.exec_block(body)
            ret = st.ret
            return ret if ret is not None else AbsVal.const_(None)
        finally:
            self._active.discard(id(fn))


class _State:
    """Mutable interpretation state for one function body."""

    def __init__(self, interp: Interp, mi: ModuleInfo,
                 env: Dict[str, AbsVal], depth: int):
        self.interp = interp
        self.mi = mi
        self.env = env
        self.depth = depth
        self.ret: Optional[AbsVal] = None

    @property
    def sink(self) -> EventSink:
        return self.interp.sink

    # -- statements ----------------------------------------------------

    def exec_block(self, body: Sequence[ast.stmt]) -> None:
        for node in body:
            self.exec_stmt(node)

    def exec_stmt(self, node: ast.stmt) -> None:
        if isinstance(node, ast.Assign):
            staged = any(isinstance(t, (ast.Attribute, ast.Subscript))
                         for t in node.targets)
            if staged:
                prev = self.interp.staged_assign
                self.interp.staged_assign = True
                try:
                    val = self.eval(node.value)
                finally:
                    self.interp.staged_assign = prev
            else:
                val = self.eval(node.value)
            for tgt in node.targets:
                self.bind(tgt, val)
        elif isinstance(node, ast.AnnAssign) and node.value is not None:
            self.bind(node.target, self.eval(node.value))
        elif isinstance(node, ast.AugAssign):
            cur = self.eval(node.target)
            val = self._binop(node, cur, self.eval(node.value),
                              type(node.op).__name__)
            self.bind(node.target, val)
        elif isinstance(node, ast.Return):
            val = self.eval(node.value) if node.value is not None \
                else AbsVal.const_(None)
            self.ret = val if self.ret is None else join(self.ret, val)
        elif isinstance(node, ast.If):
            self._branch_event(node)
            self._exec_branches([node.body, node.orelse])
        elif isinstance(node, (ast.For, ast.AsyncFor)):
            it = self.eval(node.iter)
            self._exec_loop(node.body, node.target, _element_of(it))
            self.exec_block(node.orelse)
        elif isinstance(node, ast.While):
            self._branch_event(node)
            self._exec_loop(node.body, None, None)
            self.exec_block(node.orelse)
        elif isinstance(node, (ast.With, ast.AsyncWith)):
            for item in node.items:
                self.eval(item.context_expr)
                if item.optional_vars is not None:
                    self.bind(item.optional_vars, AbsVal.host())
            self.exec_block(node.body)
        elif isinstance(node, ast.Try):
            self._exec_branches([node.body])
            for h in node.handlers:
                if h.name:
                    self.env[h.name] = AbsVal.host()
                self._exec_branches([h.body])
            self.exec_block(node.orelse)
            self.exec_block(node.finalbody)
        elif isinstance(node, ast.Expr):
            self.eval(node.value)
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            # a nested def is a host value; callees resolve through
            # all_functions when invoked by name
            self.env[node.name] = AbsVal.host(origin=f"def {node.name}")
        elif isinstance(node, (ast.Assert, ast.Raise, ast.Pass,
                               ast.Break, ast.Continue, ast.Global,
                               ast.Nonlocal, ast.Import,
                               ast.ImportFrom, ast.Delete,
                               ast.ClassDef)):
            pass
        # anything else: ignore (miss, don't invent)

    def _exec_branches(self, blocks: Sequence[Sequence[ast.stmt]]) -> None:
        """Run each block against a copy of the env; join results."""
        base = dict(self.env)
        merged: Optional[Dict[str, AbsVal]] = None
        ret = self.ret
        self.interp.branch_depth += 1
        try:
            for block in blocks:
                self.env = dict(base)
                self.ret = ret
                self.exec_block(block)
                if merged is None:
                    merged = self.env
                else:
                    merged = _join_envs(merged, self.env)
                ret = self.ret
        finally:
            self.interp.branch_depth -= 1
        self.env = merged if merged is not None else base
        self.ret = ret

    def _exec_loop(self, body, target,
                   elem: Optional[AbsVal]) -> None:
        self.interp.loop_depth += 1
        try:
            self._exec_loop_passes(body, target, elem)
        finally:
            self.interp.loop_depth -= 1

    def _exec_loop_passes(self, body, target,
                          elem: Optional[AbsVal]) -> None:
        for i in range(_MAX_LOOP):
            before = dict(self.env)
            if target is not None:
                self.bind(target, elem or AbsVal.top())
            self.exec_block(body)
            after = self.env
            nxt = {}
            changed = False
            for k in set(before) | set(after):
                b, a = before.get(k), after.get(k)
                if b is None or a is None:
                    nxt[k] = a or b
                    changed = changed or b is None
                    continue
                w = widen(b, a) if i == _MAX_LOOP - 1 else join(b, a)
                nxt[k] = w
                if w.kind != b.kind or w.shape != b.shape \
                        or w.const != b.const:
                    changed = True
            self.env = nxt
            if not changed:
                break

    def _branch_event(self, node) -> None:
        """Report shape-derived / config-derived Python branching."""
        test = getattr(node, "test", None)
        if test is not None:
            try:
                tv = self.eval(test)
            except RecursionError:  # pragma: no cover
                return
            if tv.device:
                # branching on a device value blocks on its readback —
                # the truthiness face of an implicit host sync (and
                # where a len()/shape compare of device data lands)
                self.sink.host_sync(
                    self.mi.sf.path, node.lineno, "truthiness", tv,
                    self.interp.loop_depth > 0,
                    self.interp.branch_depth)
        body = getattr(node, "body", None)
        if isinstance(body, list) and body \
                and all(isinstance(s, (ast.Raise, ast.Assert))
                        for s in body) and not getattr(node, "orelse",
                                                       None):
            return  # a shape guard that only raises is trace-time
            # validation, not cache-key churn
        test = node.test
        for sub in ast.walk(test):
            if isinstance(sub, (ast.Name, ast.Attribute)):
                try:
                    v = self.eval(sub)
                except RecursionError:  # pragma: no cover
                    return
                if v.kind == "const" and v.from_shape:
                    self.sink.shape_branch(self.mi.sf.path, node.lineno, "shape",
                                           v.origin or _src_of(sub))
                    return

    # -- binding -------------------------------------------------------

    def bind(self, target: ast.expr, val: AbsVal) -> None:
        if isinstance(target, ast.Name):
            if not val.origin:
                val = _with_origin(val, f"`{target.id}`")
            self.env[target.id] = val
        elif isinstance(target, (ast.Tuple, ast.List)):
            items = None
            if val.kind == "tuple" and len(val.items) == len(target.elts):
                items = val.items
            elif val.kind == "array" and val.shape is not None \
                    and len(val.shape):
                # unpacking an array's leading axis / a .shape tuple
                items = [_dim_val(d, val) for d in val.shape] \
                    if len(val.shape) == len(target.elts) else None
            # unpacking an UNKNOWN-rank .shape: the dims are unknown
            # consts but still shape-derived — branching on them is
            # still one-compile-per-shape
            fallback = AbsVal("const", const=None, from_shape=True,
                              origin=val.origin) \
                if val.kind == "const" and val.from_shape \
                else AbsVal.top()
            if items is None and val.kind == "array" and val.device:
                # unpacking a device container: the parts are still
                # device-resident even when we can't count them
                fallback = AbsVal.array(None, None, origin=val.origin,
                                        device=True,
                                        dev_chain=val.dev_chain)
            for i, elt in enumerate(target.elts):
                if isinstance(elt, ast.Starred):
                    self.bind(elt.value, AbsVal.host())
                    continue
                self.bind(elt, items[i] if items else fallback)
        elif isinstance(target, (ast.Attribute, ast.Subscript)):
            self.eval(target.value)
        elif isinstance(target, ast.Starred):
            self.bind(target.value, AbsVal.host())

    # -- expressions ---------------------------------------------------

    def eval(self, node: ast.expr) -> AbsVal:
        try:
            return self._eval(node)
        except RecursionError:  # pragma: no cover - pathological input
            return AbsVal.top()

    def _eval(self, node: ast.expr) -> AbsVal:
        if isinstance(node, ast.Constant):
            return AbsVal.const_(node.value)
        if isinstance(node, ast.Name):
            if node.id in self.env:
                return self.env[node.id]
            return self._free_name(node)
        if isinstance(node, (ast.Tuple, ast.List)):
            return AbsVal.tuple_([self.eval(e) for e in node.elts])
        if isinstance(node, ast.Attribute):
            return self._attribute(node)
        if isinstance(node, ast.Subscript):
            return self._subscript(node)
        if isinstance(node, ast.BinOp):
            a = self.eval(node.left)
            b = self.eval(node.right)
            return self._binop(node, a, b, type(node.op).__name__)
        if isinstance(node, ast.UnaryOp):
            v = self.eval(node.operand)
            if v.kind == "const" and isinstance(v.const, (int, float)) \
                    and isinstance(node.op, ast.USub):
                return AbsVal.const_(-v.const, from_shape=v.from_shape,
                                     origin=v.origin)
            return v
        if isinstance(node, ast.Compare):
            a = self.eval(node.left)
            if all(isinstance(o, (ast.Is, ast.IsNot))
                   for o in node.ops):
                # identity tests never touch array contents — no
                # residency, no sync
                for cmp in node.comparators:
                    self.eval(cmp)
                return AbsVal("const", const=None)
            out = a
            for cmp in node.comparators:
                b = self.eval(cmp)
                out = self._binop(node, out, b, "Compare")
            if out.kind == "array":
                return AbsVal.array(out.shape, "bool", origin=out.origin,
                                    device=out.device,
                                    dev_chain=out.dev_chain)
            return AbsVal("const", const=None,
                          from_shape=a.from_shape or out.from_shape)
        if isinstance(node, ast.BoolOp):
            vals = [self.eval(v) for v in node.values]
            out = vals[0]
            for v in vals[1:]:
                out = join(out, v)
            return out
        if isinstance(node, ast.Call):
            return self._call(node)
        if isinstance(node, ast.IfExp):
            self._branch_event(node)
            return join(self.eval(node.body), self.eval(node.orelse))
        if isinstance(node, (ast.ListComp, ast.SetComp, ast.DictComp,
                             ast.GeneratorExp)):
            # a comprehension IS a loop: its body runs per element, so
            # a sync/transfer inside it is per-iteration, and its
            # target carries the iterated value's residency (the
            # `{k: np.asarray(v) for k, v in out.items()}` per-lane
            # readback idiom)
            for gen in node.generators:
                it = self.eval(gen.iter)
                self.bind(gen.target, _element_of(it))
            self.interp.loop_depth += 1
            try:
                for gen in node.generators:
                    for cond in gen.ifs:
                        cv = self.eval(cond)
                        if cv.device:
                            self.sink.host_sync(
                                self.mi.sf.path, cond.lineno,
                                "truthiness", cv, True,
                                self.interp.branch_depth)
                if isinstance(node, ast.DictComp):
                    self.eval(node.key)
                    body = self.eval(node.value)
                else:
                    body = self.eval(node.elt)
            finally:
                self.interp.loop_depth -= 1
            if body.device:
                # a container of device values stays device-resident
                return AbsVal.array(None, None, origin="comprehension",
                                    device=True,
                                    dev_chain=body.dev_chain)
            return AbsVal.host()
        if isinstance(node, ast.Lambda):
            return AbsVal.host(origin="<lambda>")
        if isinstance(node, ast.Starred):
            return self.eval(node.value)
        if isinstance(node, (ast.JoinedStr, ast.FormattedValue)):
            return AbsVal.const_(None)
        if isinstance(node, ast.Dict):
            for v in node.values:
                if v is not None:
                    self.eval(v)
            return AbsVal.host()
        return AbsVal.top()

    # -- name / attribute resolution -----------------------------------

    def _free_name(self, node: ast.Name) -> AbsVal:
        name = node.id
        mi = self.mi
        if name in mi.constants:
            c = mi.constants[name]
            if isinstance(c, ast.Constant):
                return AbsVal.const_(c.value,
                                     origin=f"module const `{name}`")
            return AbsVal.host(origin=f"module global `{name}`")
        if name in mi.imports:
            return AbsVal.host(origin=f"import `{name}`")
        if name in mi.functions or name in mi.all_functions:
            return AbsVal.host(origin=f"def {name}")
        if name in ("True", "False", "None"):  # pragma: no cover
            return AbsVal.const_({"True": True, "False": False,
                                  "None": None}[name])
        if name in ("range", "len", "min", "max", "int", "float",
                    "enumerate", "zip", "sorted", "list", "tuple",
                    "abs", "bool", "str"):
            return AbsVal.host(origin=f"builtin `{name}`")
        return AbsVal.top(origin=f"free `{name}`")

    def _attribute(self, node: ast.Attribute) -> AbsVal:
        base = self.eval(node.value)
        attr = node.attr
        if base.is_array:
            if attr == "shape":
                if base.shape is None:
                    return AbsVal("const", const=None, from_shape=True,
                                  origin=f"{base.origin}.shape")
                return AbsVal.tuple_(
                    [_dim_val(d, base) for d in base.shape],
                    origin=f"{base.origin}.shape")
            if attr == "ndim":
                return AbsVal.const_(base.rank, from_shape=True,
                                     origin=f"{base.origin}.ndim")
            if attr == "size":
                return AbsVal("const", const=_shape_size(base.shape),
                              from_shape=True,
                              origin=f"{base.origin}.size")
            if attr == "T":
                shape = None if base.shape is None \
                    else tuple(reversed(base.shape))
                return AbsVal.array(shape, base.dtype, origin=base.origin,
                                    device=base.device,
                                    dev_chain=base.dev_chain)
            if attr == "dtype":
                return AbsVal.const_(base.dtype)
            # bound array method (astype/reshape/sum/…): handled at
            # the Call site via _method_call
            return AbsVal.host(origin=f"{base.origin}.{attr}")
        if base.kind == "tuple" and attr == "shape":
            return AbsVal.host()
        q = self.mi.qualify(node)
        if q is not None:
            leaf = q.rsplit(".", 1)[-1]
            if leaf in _DTYPE_CASTS and _is_np_root(q):
                return AbsVal.const_(("dtype", leaf))
        return AbsVal.host(origin=_src_of(node))

    # -- subscripts ----------------------------------------------------

    def _subscript(self, node: ast.Subscript) -> AbsVal:
        base = self.eval(node.value)
        out = self._subscript_with(node, base)
        if base.device and out.is_array and not out.device:
            # lazy device slicing: the piece stays on device
            out = AbsVal.array(out.shape, out.dtype, weak=out.weak,
                               origin=out.origin, device=True,
                               dev_chain=base.dev_chain)
        return out

    def _subscript_with(self, node: ast.Subscript,
                        base: AbsVal) -> AbsVal:
        idx = node.slice
        if base.kind == "tuple":
            iv = self.eval(idx)
            if iv.kind == "const" and isinstance(iv.const, int):
                i = iv.const
                if -len(base.items) <= i < len(base.items):
                    return base.items[i]
            if isinstance(idx, ast.Slice):
                lo = _const_int(self.eval(idx.lower)) if idx.lower else 0
                hi = _const_int(self.eval(idx.upper)) if idx.upper \
                    else len(base.items)
                if lo is not None and hi is not None:
                    return AbsVal.tuple_(base.items[lo:hi])
            return AbsVal.top()
        if not base.is_array:
            return AbsVal.top()
        if base.shape is None:
            return AbsVal.array(None, base.dtype, origin=base.origin)
        parts = idx.elts if isinstance(idx, ast.Tuple) else [idx]
        out: List[Dim] = []
        consumed = 0
        adv: List[AbsVal] = []
        ok = True
        for p in parts:
            if isinstance(p, ast.Slice):
                if consumed < len(base.shape):
                    full = p.lower is None and p.upper is None \
                        and p.step is None
                    out.append(base.shape[consumed] if full
                               else _slice_extent(self, p,
                                                  base.shape[consumed]))
                    consumed += 1
                else:
                    ok = False
            elif isinstance(p, ast.Constant) and p.value is None:
                out.append(1)
            elif isinstance(p, ast.Constant) and p.value is Ellipsis:
                ok = False
            else:
                v = self.eval(p)
                if v.kind == "const" and isinstance(v.const, int):
                    consumed += 1          # integer index drops the dim
                elif v.is_array:
                    adv.append(v)
                    consumed += 1
                else:
                    consumed += 1
                    ok = False
        if not ok:
            return AbsVal.array(None, base.dtype, origin=base.origin)
        rest = list(base.shape[consumed:])
        if adv:
            # advanced indexing: index arrays broadcast; their common
            # shape replaces the consumed axes (approximate: single
            # index-array case exact, multi-array joined)
            ishape: Optional[Tuple] = adv[0].shape
            for v in adv[1:]:
                ishape, conflict = broadcast_shapes(ishape, v.shape)
            pre = list(ishape) if ishape is not None else [None]
            return AbsVal.array(tuple(pre + rest) if ishape is not None
                                else None,
                                base.dtype, origin=base.origin)
        return AbsVal.array(tuple(out + rest), base.dtype,
                            origin=base.origin)

    # -- operators -----------------------------------------------------

    def _binop(self, node, a: AbsVal, b: AbsVal, op: str) -> AbsVal:
        line = getattr(node, "lineno", 0)
        if op == "MatMult":
            return self._matmul(line, a, b)
        # const folding (host arithmetic, shape math)
        if a.kind == "const" and b.kind == "const" \
                and isinstance(a.const, (int, float)) \
                and isinstance(b.const, (int, float)):
            folded = _fold(op, a.const, b.const)
            return AbsVal("const", const=folded,
                          from_shape=a.from_shape or b.from_shape,
                          origin=a.origin or b.origin)
        if a.kind == "const" and b.kind == "const":
            return AbsVal("const", const=None,
                          from_shape=a.from_shape or b.from_shape)
        # array ⊗ array
        if a.is_array and b.is_array:
            shape, conflict = broadcast_shapes(a.shape, b.shape)
            if conflict is not None:
                self.sink.binop_conflict(self.mi.sf.path, line, op, a, b, conflict)
            dtype = promote(a, b)
            return AbsVal.array(shape, dtype,
                                weak=a.weak and b.weak,
                                origin=a.origin or b.origin,
                                device=a.device or b.device,
                                dev_chain=a.dev_chain or b.dev_chain)
        # array ⊗ const scalar: weak promotion — the const must fit
        arr, const = (a, b) if a.is_array else \
            (b, a) if b.is_array else (None, None)
        if arr is not None and const.kind == "const":
            if isinstance(const.const, int) and arr.dtype in _INT_RANGE \
                    and op in ("Add", "Sub", "Mult", "BitOr", "BitAnd",
                               "BitXor", "Mod", "FloorDiv"):
                lo, hi = _INT_RANGE[arr.dtype]
                if not (lo <= const.const <= hi):
                    self.sink.weak_wrap(self.mi.sf.path, line, op, arr, const.const)
            dtype = arr.dtype
            if isinstance(const.const, float) \
                    and arr.dtype in _INT_DTYPES:
                dtype = "float32"
            return AbsVal.array(arr.shape, dtype, weak=arr.weak,
                                origin=arr.origin, device=arr.device,
                                dev_chain=arr.dev_chain)
        if arr is not None:
            return AbsVal.array(arr.shape, arr.dtype, origin=arr.origin,
                                device=arr.device,
                                dev_chain=arr.dev_chain)
        return AbsVal.top()

    def _matmul(self, line: int, a: AbsVal, b: AbsVal) -> AbsVal:
        if not (a.is_array and b.is_array):
            return AbsVal.top()
        if a.shape is None or b.shape is None or len(a.shape) < 1 \
                or len(b.shape) < 1:
            return AbsVal.array(None, promote(a, b))
        ka = a.shape[-1]
        kb = b.shape[-2] if len(b.shape) >= 2 else b.shape[-1]
        if _dim_conflict(ka, kb):
            self.sink.matmul_conflict(self.mi.sf.path, line, a, b)
        if len(a.shape) >= 2 and len(b.shape) >= 2:
            batch, _ = broadcast_shapes(a.shape[:-2], b.shape[:-2])
            shape = None if batch is None else \
                tuple(batch) + (a.shape[-2], b.shape[-1])
        else:
            shape = None
        return AbsVal.array(shape, promote(a, b), origin=a.origin,
                            device=a.device or b.device,
                            dev_chain=a.dev_chain or b.dev_chain)

    # -- calls ---------------------------------------------------------

    def _call(self, node: ast.Call) -> AbsVal:
        fn = node.func
        # bound array method: x.astype(...), x.reshape(...), x.sum(...)
        if isinstance(fn, ast.Attribute):
            base = self.eval(fn.value)
            if base.is_array:
                return self._method_call(node, base, fn.attr)
        q = self.mi.qualify(fn) or ""
        leaf = q.rsplit(".", 1)[-1]
        args = node.args
        # device transfer / readback primitives
        if q in ("jax.device_put", "device_put") and args:
            v = self.eval(args[0])
            for extra in args[1:]:
                self.eval(extra)
            self.sink.h2d(self.mi.sf.path, node.lineno, "device_put", v,
                          self.interp.loop_depth > 0,
                          self.interp.staged_assign,
                          self.interp.branch_depth)
            chain = v.dev_chain + (
                f"{self.mi.sf.path}:{node.lineno} device_put",)
            if v.is_array:
                return AbsVal.array(v.shape, v.dtype, weak=v.weak,
                                    origin=v.origin, device=True,
                                    dev_chain=chain)
            return AbsVal.array(None, None, origin=v.origin,
                                device=True, dev_chain=chain)
        if q in ("jax.device_get", "device_get") and args:
            v = self.eval(args[0])
            if v.device or _any_device(v):
                self.sink.host_sync(self.mi.sf.path, node.lineno,
                                    "device_get", v,
                                    self.interp.loop_depth > 0,
                                    self.interp.branch_depth)
            return _undevice(v)
        if q in ("jax.block_until_ready", "block_until_ready") and args:
            v = self.eval(args[0])
            if v.device or _any_device(v):
                self.sink.host_sync(self.mi.sf.path, node.lineno,
                                    "block_until_ready", v,
                                    self.interp.loop_depth > 0,
                                    self.interp.branch_depth)
            return v
        # shape-position event (static under jit)
        if leaf in _SHAPE_ARG_FNS and _is_np_root(q) or \
                leaf in _SHAPE_ARG_FNS and q.startswith("jax.nn"):
            pos = _SHAPE_ARG_FNS[leaf]
            if pos < len(args):
                v = self.eval(args[pos])
                self.sink.shape_position(self.mi.sf.path, node.lineno, leaf, v)
        if _is_np_root(q):
            return self._numpy_call(node, leaf, q)
        if q.startswith(("jax.lax.", "lax.")) or q == "jax.lax":
            return self._lax_call(node, leaf)
        if q == "jax.nn.one_hot" or (leaf == "one_hot"
                                     and "nn" in q.split(".")):
            x = self.eval(args[0]) if args else AbsVal.top()
            n = _const_int(self.eval(args[1])) if len(args) > 1 else None
            dt = self._dtype_kwarg(node) or "float32"
            shape = None if x.shape is None else tuple(x.shape) + (n,)
            return AbsVal.array(shape, dt, origin=x.origin)
        if leaf in _DTYPE_CASTS and _is_np_root(q):
            v = self.eval(args[0]) if args else AbsVal.top()
            if v.is_array:
                return AbsVal.array(v.shape, leaf, origin=v.origin)
            return AbsVal.array((), leaf)
        if leaf in ("len",) and q == "len" and args:
            v = self.eval(args[0])
            if v.kind == "tuple":
                return AbsVal.const_(len(v.items))
            if v.is_array and v.shape is not None and v.shape:
                return AbsVal("const",
                              const=v.shape[0] if isinstance(
                                  v.shape[0], int) else None,
                              from_shape=True, origin=v.origin)
            return AbsVal("const", const=None)
        if q in ("int", "float", "bool", "abs", "min", "max") and args:
            v = self.eval(args[0])
            if v.device and q in ("int", "float", "bool"):
                # scalar coercion of a device value blocks the host on
                # the readback — the canonical implicit sync
                self.sink.host_sync(self.mi.sf.path, node.lineno,
                                    f"{q}()", v,
                                    self.interp.loop_depth > 0,
                                    self.interp.branch_depth)
            if v.kind == "const":
                return AbsVal("const", const=None,
                              from_shape=v.from_shape, origin=v.origin)
            return AbsVal("const", const=None,
                          from_shape=getattr(v, "from_shape", False))
        # project-resolvable call → interprocedural step
        for kw in node.keywords:
            if kw.value is not None:
                self.eval(kw.value)
        argvals = [self.eval(a) for a in args]
        return self._project_call(node, q, argvals)

    def _method_call(self, node: ast.Call, base: AbsVal,
                     meth: str) -> AbsVal:
        path, line = self.mi.sf.path, node.lineno
        if base.device and meth in ("item", "tolist"):
            self.sink.host_sync(path, line, f".{meth}()", base,
                                self.interp.loop_depth > 0,
                                self.interp.branch_depth)
        if meth == "block_until_ready":
            if base.device:
                self.sink.host_sync(path, line, "block_until_ready",
                                    base, self.interp.loop_depth > 0,
                                    self.interp.branch_depth)
            return base
        out = self._method_call_impl(node, base, meth)
        if base.device and out.is_array and not out.device:
            out = AbsVal.array(out.shape, out.dtype, weak=out.weak,
                               origin=out.origin, device=True,
                               dev_chain=base.dev_chain)
        return out

    def _method_call_impl(self, node: ast.Call, base: AbsVal,
                          meth: str) -> AbsVal:
        args = node.args
        if meth == "astype":
            dt = self._dtype_of_expr(args[0]) if args else None
            return AbsVal.array(base.shape, dt, origin=base.origin)
        if meth == "reshape":
            want = args[0] if len(args) == 1 else ast.Tuple(
                elts=list(args), ctx=ast.Load())
            return self._reshape(node, base, want)
        if meth in _REDUCTIONS:
            return self._reduce(node, meth, base)
        if meth in ("transpose",):
            return AbsVal.array(None if base.shape is None
                                else tuple(reversed(base.shape)),
                                base.dtype, origin=base.origin)
        if meth in ("min", "max", "argmax", "argmin", "any", "all"):
            dt = base.dtype if meth in ("min", "max") else (
                "bool" if meth in ("any", "all") else "int32")
            return self._axis_reduce_shape(node, base, dt)
        if meth in ("item", "tolist"):
            return AbsVal("const", const=None)
        if meth == "view":
            return AbsVal.array(None, self._dtype_of_expr(args[0])
                                if args else None, origin=base.origin)
        return AbsVal.array(None, None, origin=base.origin)

    def _numpy_call(self, node: ast.Call, leaf: str,
                    q: str = "") -> AbsVal:
        """np/jnp dispatch: shape/dtype via the impl, residency here.

        jnp.* results are device-resident; strict-numpy results are
        host.  asarray/array is the transfer boundary in both
        directions, so it is handled inline (the input value decides
        whether the call is an H2D stage or a blocking D2H readback).
        """
        path, line = self.mi.sf.path, node.lineno
        jnp = _is_jnp_root(q)
        if leaf in ("asarray", "array") and node.args:
            v = self.eval(node.args[0])
            dt = self._dtype_kwarg(node) or v.dtype
            if jnp:
                if not (v.device or _any_device(v)):
                    self.sink.h2d(path, line, f"jnp.{leaf}", v,
                                  self.interp.loop_depth > 0,
                                  self.interp.staged_assign,
                                  self.interp.branch_depth)
                out = _coerce_array(v, dt)
                return AbsVal.array(out.shape, out.dtype, weak=out.weak,
                                    origin=out.origin, device=True,
                                    dev_chain=v.dev_chain + (
                                        f"{path}:{line} jnp.{leaf}",))
            if v.device or _any_device(v):
                # strict-numpy materialisation of a device value is a
                # blocking D2H readback
                self.sink.host_sync(path, line, f"np.{leaf}", v,
                                    self.interp.loop_depth > 0,
                                    self.interp.branch_depth)
            return _coerce_array(v, dt)
        out = self._numpy_call_impl(node, leaf)
        if jnp and out.is_array and not out.device:
            out = AbsVal.array(out.shape, out.dtype, weak=out.weak,
                               origin=out.origin, device=True,
                               dev_chain=(f"{path}:{line} jnp.{leaf}",))
        return out

    def _numpy_call_impl(self, node: ast.Call, leaf: str) -> AbsVal:
        args = node.args
        ev = self.eval
        if leaf in ("zeros", "ones", "empty", "full"):
            shape = _shape_from_val(ev(args[0])) if args else None
            dt = self._dtype_kwarg(node)
            if dt is None and leaf == "full" and len(args) > 1:
                dt = None
            if dt is None:
                dt = "float32"
            return AbsVal.array(shape, dt)
        if leaf == "zeros_like" or leaf == "ones_like" \
                or leaf == "full_like" or leaf == "empty_like":
            v = ev(args[0]) if args else AbsVal.top()
            dt = self._dtype_kwarg(node) or v.dtype
            return AbsVal.array(v.shape, dt, origin=v.origin)
        if leaf == "arange":
            n = _const_int(ev(args[0])) if args else None
            if len(args) >= 2:
                lo = _const_int(ev(args[0]))
                hi = _const_int(ev(args[1]))
                n = hi - lo if lo is not None and hi is not None else None
            dt = self._dtype_kwarg(node) or "int32"
            return AbsVal.array((n,), dt)
        if leaf == "asarray" or leaf == "array":
            v = ev(args[0]) if args else AbsVal.top()
            dt = self._dtype_kwarg(node) or v.dtype
            if v.is_array:
                return AbsVal.array(v.shape, dt, origin=v.origin)
            if v.kind == "tuple":
                return AbsVal.array((len(v.items),), dt)
            if v.kind == "const":
                return AbsVal.array((), dt, weak=dt is None)
            return AbsVal.array(None, dt)
        if leaf == "reshape" and args:
            base = ev(args[0])
            return self._reshape(node, base,
                                 args[1] if len(args) > 1 else None)
        if leaf == "broadcast_to" and len(args) >= 2:
            base = ev(args[0])
            shape = _shape_from_val(ev(args[1]))
            bshape, conflict = broadcast_shapes(base.shape, shape)
            if conflict is not None:
                self.sink.binop_conflict(self.mi.sf.path, node.lineno, "broadcast_to",
                                         base, AbsVal.array(shape, None),
                                         conflict)
            return AbsVal.array(shape, base.dtype, origin=base.origin)
        if leaf in ("where",) and len(args) >= 3:
            c, x, y = ev(args[0]), ev(args[1]), ev(args[2])
            shape, conflict = broadcast_shapes(c.shape, x.shape)
            if conflict is not None:
                self.sink.binop_conflict(self.mi.sf.path, node.lineno, "where", c, x,
                                         conflict)
            shape2, conflict2 = broadcast_shapes(shape, y.shape)
            if conflict2 is not None:
                self.sink.binop_conflict(self.mi.sf.path, node.lineno, "where", x, y,
                                         conflict2)
            xv = x if x.is_array else y
            return AbsVal.array(shape2, promote(x, y) or xv.dtype,
                                origin=xv.origin)
        if leaf in _REDUCTIONS and args:
            base = ev(args[0])
            if leaf in ("matmul", "dot") and len(args) >= 2:
                return self._matmul(node.lineno, base, ev(args[1]))
            return self._reduce(node, leaf, base)
        if leaf in ("any", "all", "max", "min", "argmax", "argmin") \
                and args:
            base = ev(args[0])
            dt = ("bool" if leaf in ("any", "all")
                  else "int32" if leaf.startswith("arg") else base.dtype)
            return self._axis_reduce_shape(node, base, dt)
        if leaf == "take_along_axis" and len(args) >= 2:
            a, idx = ev(args[0]), ev(args[1])
            if a.rank is not None and idx.rank is not None \
                    and a.rank != idx.rank:
                self.sink.rank_mismatch(self.mi.sf.path, node.lineno, "take_along_axis",
                                        a, idx)
            return AbsVal.array(idx.shape, a.dtype, origin=a.origin)
        if leaf == "transpose" and args:
            base = ev(args[0])
            axes = _const_tuple(ev(args[1])) if len(args) > 1 else None
            if base.shape is not None and axes is not None \
                    and len(axes) == len(base.shape):
                return AbsVal.array(
                    tuple(base.shape[i] for i in axes), base.dtype,
                    origin=base.origin)
            return AbsVal.array(None if base.shape is None else
                                tuple(reversed(base.shape)),
                                base.dtype, origin=base.origin)
        if leaf == "pad" and args:
            base = ev(args[0])
            shape = None if base.shape is None else \
                tuple(None for _ in base.shape)
            return AbsVal.array(shape, base.dtype, origin=base.origin)
        if leaf in ("clip", "abs", "negative", "logical_not",
                    "invert", "exp", "log", "sqrt"):
            base = ev(args[0]) if args else AbsVal.top()
            for extra in args[1:]:
                ev(extra)
            return AbsVal.array(base.shape, base.dtype,
                                origin=base.origin)
        if leaf in ("repeat", "tile", "concatenate", "stack",
                    "searchsorted", "unique", "nonzero", "flip",
                    "sort", "argsort", "cumsum"):
            for a in args:
                ev(a)
            base = ev(args[0]) if args else AbsVal.top()
            if leaf == "cumsum" and base.is_array:
                return self._reduce(node, leaf, base)
            if leaf == "searchsorted":
                probe = ev(args[1]) if len(args) > 1 else AbsVal.top()
                return AbsVal.array(probe.shape, "int32")
            return AbsVal.array(None, base.dtype if base.is_array
                                else None)
        if leaf == "broadcast_shapes":
            return AbsVal.host()
        for a in args:
            ev(a)
        return AbsVal.array(None, None)

    def _lax_call(self, node: ast.Call, leaf: str) -> AbsVal:
        out = self._lax_call_impl(node, leaf)
        if out.is_array and not out.device:
            out = AbsVal.array(
                out.shape, out.dtype, weak=out.weak, origin=out.origin,
                device=True,
                dev_chain=(f"{self.mi.sf.path}:{node.lineno} "
                           f"lax.{leaf}",))
        return out

    def _lax_call_impl(self, node: ast.Call, leaf: str) -> AbsVal:
        args = node.args
        ev = self.eval
        if leaf == "scan" and len(args) >= 2:
            # step(carry, x) — interpret the body once with the seeded
            # carry (exact enough for the checks; the carry type is
            # invariant by lax.scan's contract)
            carry = ev(args[1])
            xs = ev(args[2]) if len(args) > 2 else AbsVal.top()
            self._apply_callable(args[0], [carry, _element_of(xs)])
            return AbsVal.tuple_([carry, AbsVal.array(None, None)])
        if leaf == "fori_loop" and len(args) >= 4:
            init = ev(args[3])
            self._apply_callable(
                args[2], [AbsVal.array((), "int32"), init])
            return init
        if leaf == "while_loop" and len(args) >= 3:
            init = ev(args[2])
            self._apply_callable(args[1], [init])
            return init
        if leaf == "associative_scan" and len(args) >= 2:
            x = ev(args[1])
            self._apply_callable(args[0], [x, x])
            return x
        if leaf in ("psum", "pmax", "pmin", "pmean") and args:
            return ev(args[0])
        if leaf == "ppermute" and args:
            return ev(args[0])
        if leaf == "all_gather" and args:
            v = ev(args[0])
            shape = None if v.shape is None else (None,) + tuple(v.shape)
            return AbsVal.array(shape, v.dtype, origin=v.origin)
        if leaf == "all_to_all" and args:
            v = ev(args[0])
            return AbsVal.array(None, v.dtype, origin=v.origin)
        if leaf == "axis_index":
            return AbsVal.array((), "int32")
        if leaf in ("dynamic_update_slice",) and args:
            return ev(args[0])
        if leaf in ("dynamic_slice",) and args:
            v = ev(args[0])
            return AbsVal.array(None, v.dtype, origin=v.origin)
        if leaf == "bitcast_convert_type" and args:
            v = ev(args[0])
            dt = self._dtype_of_expr(args[1]) if len(args) > 1 else None
            return AbsVal.array(None, dt, origin=v.origin)
        if leaf == "select" and len(args) >= 3:
            return join(ev(args[1]), ev(args[2]))
        for a in args:
            ev(a)
        return AbsVal.top()

    def _apply_callable(self, fnexpr: ast.expr,
                        argvals: List[AbsVal]) -> AbsVal:
        """Call a first-class function expression (lambda or name) with
        abstract arguments — the lax.scan/fori body face."""
        if isinstance(fnexpr, ast.Lambda):
            env = dict(self.env)
            params = [a.arg for a in fnexpr.args.args]
            for p, v in zip(params, argvals):
                env[p] = v
            return self.interp.run_function(self.mi, fnexpr, env,
                                            self.depth + 1)
        if isinstance(fnexpr, ast.Name):
            resolved = self.project_resolve(fnexpr.id)
            if resolved is not None:
                mi, fn = resolved
                env = dict(self.env) if mi is self.mi else {}
                params = [a.arg for a in fn.args.args]
                for p, v in zip(params, argvals):
                    env[p] = v
                self._default_params(fn, env)
                return self.interp.run_function(mi, fn, env,
                                                self.depth + 1)
        return AbsVal.top()

    def project_resolve(self, name: str):
        fns = self.mi.all_functions.get(name)
        if fns:
            return self.mi, fns[0]
        return self.project_fn(name)

    def project_fn(self, name: str):
        return self.interp.project.resolve_function(self.mi, name)

    def _project_call(self, node: ast.Call, q: str,
                      argvals: List[AbsVal]) -> AbsVal:
        d = dotted(node.func)
        if d is None:
            return AbsVal.top()
        resolved = None
        if "." not in d:
            resolved = self.project_resolve(d)
        else:
            root, _, attr = d.rpartition(".")
            target = self.interp.project.modules.get(
                self.mi.imports.get(root, ""))
            if target is not None and "." not in attr \
                    and attr in target.functions:
                resolved = (target, target.functions[attr])
        if resolved is None:
            return AbsVal.top()
        mi, fn = resolved
        if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
            return AbsVal.top()
        env: Dict[str, AbsVal] = {}
        params = [a.arg for a in fn.args.args]
        for p, v in zip(params, argvals):
            env[p] = _with_origin(v, f"param `{p}`") if not v.origin \
                else v
        for kw in node.keywords:
            if kw.arg is not None and kw.arg in params:
                env[kw.arg] = self.eval(kw.value)
        self._default_params(fn, env)
        return self.interp.run_function(mi, fn, env, self.depth + 1)

    def _default_params(self, fn, env: Dict[str, AbsVal]) -> None:
        """Bind unbound params: defaults fold to consts, the rest ⊤."""
        args = fn.args
        defaults = list(args.defaults)
        names = [a.arg for a in args.args]
        for name, d in zip(names[len(names) - len(defaults):], defaults):
            if name not in env:
                env[name] = AbsVal.const_(d.value) \
                    if isinstance(d, ast.Constant) \
                    else AbsVal.top()
        for a in args.args + args.kwonlyargs:
            env.setdefault(a.arg, AbsVal.top(origin=f"param `{a.arg}`"))

    # -- shared op helpers ---------------------------------------------

    def _reshape(self, node, base: AbsVal, want_expr) -> AbsVal:
        want = _shape_from_val(self.eval(want_expr)) \
            if want_expr is not None else None
        if want is not None and base.shape is not None:
            src_n = _shape_size(base.shape)
            dst_n = _shape_size(want)
            has_minus1 = any(isinstance(d, int) and d == -1
                             for d in want)
            if src_n is not None and dst_n is not None \
                    and not has_minus1 and src_n != dst_n:
                self.sink.reshape_mismatch(self.mi.sf.path, node.lineno, base, want)
            if has_minus1:
                want = tuple(None if (isinstance(d, int) and d == -1)
                             else d for d in want)
        return AbsVal.array(want, base.dtype, origin=base.origin)

    def _reduce(self, node: ast.Call, leaf: str,
                base: AbsVal) -> AbsVal:
        has_dtype = any(kw.arg == "dtype" for kw in node.keywords)
        dt = self._dtype_kwarg(node) or base.dtype
        axis = None
        for kw in node.keywords:
            if kw.arg == "axis":
                axis = _const_int(self.eval(kw.value))
        # positional axis for jnp.sum(x, axis) style? rare here; skip
        extent = _reduced_extent(base.shape, axis,
                                 keep=leaf in ("cumsum", "cumprod"))
        if base.is_array:
            self.sink.reduction(self.mi.sf.path, node.lineno, leaf, base, extent,
                                has_dtype)
        if leaf in ("cumsum", "cumprod"):
            return AbsVal.array(base.shape, dt, origin=base.origin)
        shape = _drop_axis(base.shape, axis)
        return AbsVal.array(shape, dt, origin=base.origin)

    def _axis_reduce_shape(self, node: ast.Call, base: AbsVal,
                           dtype: Optional[str]) -> AbsVal:
        axis = None
        for kw in node.keywords:
            if kw.arg == "axis":
                axis = _const_int(self.eval(kw.value))
        if len(node.args) >= 2:
            axis = _const_int(self.eval(node.args[1]))
        return AbsVal.array(_drop_axis(base.shape, axis), dtype,
                            origin=base.origin)

    def _dtype_kwarg(self, node: ast.Call) -> Optional[str]:
        for kw in node.keywords:
            if kw.arg == "dtype":
                return self._dtype_of_expr(kw.value)
        return None

    def _dtype_of_expr(self, expr: ast.expr) -> Optional[str]:
        if isinstance(expr, ast.Constant) and isinstance(expr.value, str):
            return expr.value if expr.value in _DTYPES else None
        q = self.mi.qualify(expr)
        if q is not None:
            leaf = q.rsplit(".", 1)[-1]
            if leaf in _DTYPES:
                return leaf
        v = self.eval(expr)
        if v.kind == "const" and isinstance(v.const, tuple) \
                and len(v.const) == 2 and v.const[0] == "dtype":
            return v.const[1]
        if v.kind == "const" and isinstance(v.const, str) \
                and v.const in _DTYPES:
            return v.const
        return None


# -- small helpers ----------------------------------------------------------

def _is_np_root(q: str) -> bool:
    return q.startswith(("jax.numpy.", "jnp.", "numpy.", "np.")) \
        or q in ("jax.numpy", "numpy")


def _is_jnp_root(q: str) -> bool:
    """jnp-family qualifier — constructors produce *device* arrays."""
    return q.startswith(("jax.numpy.", "jnp.")) or q == "jax.numpy"


def _with_origin(v: AbsVal, origin: str) -> AbsVal:
    out = AbsVal(v.kind, const=v.const, items=v.items, shape=v.shape,
                 dtype=v.dtype, weak=v.weak, from_shape=v.from_shape,
                 origin=origin, device=v.device, dev_chain=v.dev_chain)
    return out


def _any_device(v: AbsVal) -> bool:
    """True if the value or any container element is device-resident."""
    if v.device:
        return True
    if v.kind == "tuple":
        return any(_any_device(i) for i in v.items)
    return False


def _undevice(v: AbsVal) -> AbsVal:
    """The host copy of a value: same shape face, residency cleared."""
    if v.kind == "tuple":
        return AbsVal.tuple_([_undevice(i) for i in v.items],
                             origin=v.origin)
    if v.is_array and v.device:
        return AbsVal.array(v.shape, v.dtype, weak=v.weak,
                            origin=v.origin)
    return v


def _coerce_array(v: AbsVal, dt) -> AbsVal:
    """asarray/array coercion: shape face of the result (host)."""
    if v.is_array:
        return AbsVal.array(v.shape, dt, origin=v.origin)
    if v.kind == "tuple":
        return AbsVal.array((len(v.items),), dt)
    if v.kind == "const":
        return AbsVal.array((), dt, weak=dt is None)
    return AbsVal.array(None, dt)


def _dim_val(d: Dim, base: AbsVal) -> AbsVal:
    name = base.origin or "array"
    if isinstance(d, int):
        return AbsVal.const_(d, from_shape=True,
                             origin=f"dim of {name}")
    if isinstance(d, Sym):
        return AbsVal.const_(d, from_shape=True,
                             origin=f"dim `{d}` of {name}")
    return AbsVal("const", const=None, from_shape=True,
                  origin=f"dim of {name}")


def _shape_size(shape: Optional[Tuple]):
    if shape is None:
        return None
    n = 1
    for d in shape:
        if not isinstance(d, int) or d < 0:
            return None
        n *= d
    return n


def _reduced_extent(shape: Optional[Tuple], axis: Optional[int],
                    keep: bool = False):
    """Number of elements folded into one accumulator lane; None when
    unknown. ``keep`` (cumsum) reduces along one axis regardless."""
    if shape is None:
        return None
    if axis is None and not keep:
        return _shape_size(shape)
    if axis is None:
        axis = 0
    if -len(shape) <= axis < len(shape):
        d = shape[axis]
        return d if isinstance(d, int) else None
    return None


def _drop_axis(shape: Optional[Tuple], axis: Optional[int]):
    if shape is None:
        return None
    if axis is None:
        return ()
    if -len(shape) <= axis < len(shape):
        idx = axis % len(shape)
        return tuple(d for i, d in enumerate(shape) if i != idx)
    return None


def _shape_from_val(v: AbsVal) -> Optional[Tuple]:
    """A shape argument: a const int (1-d), a tuple of dims, or ⊥."""
    if v.kind == "const" and isinstance(v.const, int):
        return (v.const,)
    if v.kind == "const" and isinstance(v.const, Sym):
        return (v.const,)
    if v.kind == "const" and v.const is None:
        return (None,)
    if v.kind == "tuple":
        out = []
        for item in v.items:
            if item.kind == "const" and isinstance(item.const,
                                                   (int, Sym)):
                out.append(item.const)
            else:
                out.append(None)
        return tuple(out)
    return None


def _const_int(v: AbsVal) -> Optional[int]:
    if v.kind == "const" and isinstance(v.const, int) \
            and not isinstance(v.const, bool):
        return v.const
    return None


def _const_tuple(v: AbsVal) -> Optional[Tuple[int, ...]]:
    if v.kind != "tuple":
        return None
    out = []
    for item in v.items:
        i = _const_int(item)
        if i is None:
            return None
        out.append(i)
    return tuple(out)


def _element_of(it: AbsVal) -> AbsVal:
    """Abstract element of an iterated value."""
    if it.kind == "tuple" and it.items:
        out = it.items[0]
        for v in it.items[1:]:
            out = join(out, v)
        return out
    if it.is_array and it.shape is not None and it.shape:
        return AbsVal.array(tuple(it.shape[1:]), it.dtype,
                            origin=it.origin, device=it.device,
                            dev_chain=it.dev_chain)
    if it.is_array and it.device:
        # unknown shape, but residency survives iteration
        return AbsVal.array(None, it.dtype, origin=it.origin,
                            device=True, dev_chain=it.dev_chain)
    return AbsVal.top()


def _fold(op: str, a, b):
    try:
        if op == "Add":
            return a + b
        if op == "Sub":
            return a - b
        if op == "Mult":
            return a * b
        if op == "FloorDiv":
            return a // b
        if op == "Mod":
            return a % b
        if op == "Pow" and abs(b) < 64:
            return a ** b
        if op == "LShift" and 0 <= b < 128:
            return a << b
        if op == "RShift" and 0 <= b < 128:
            return a >> b
        if op == "BitOr":
            return a | b
        if op == "BitAnd":
            return a & b
        if op == "BitXor":
            return a ^ b
        if op == "Div" and b != 0:
            return a / b
    except (TypeError, ZeroDivisionError, OverflowError, ValueError):
        return None
    return None


def _slice_extent(state: _State, sl: ast.Slice, dim: Dim) -> Dim:
    """Extent of a slice over a dim — exact for const bounds over
    const dims, else unknown."""
    if sl.step is not None:
        return None
    lo = _const_int(state.eval(sl.lower)) if sl.lower is not None else 0
    hi = _const_int(state.eval(sl.upper)) if sl.upper is not None \
        else (dim if isinstance(dim, int) else None)
    if lo is not None and hi is not None and isinstance(dim, int):
        lo = lo if lo >= 0 else max(0, dim + lo)
        hi = hi if hi >= 0 else max(0, dim + hi)
        return max(0, min(hi, dim) - lo)
    return None


def _join_envs(a: Dict[str, AbsVal], b: Dict[str, AbsVal]
               ) -> Dict[str, AbsVal]:
    out = {}
    for k in set(a) | set(b):
        if k in a and k in b:
            out[k] = join(a[k], b[k])
        else:
            out[k] = a.get(k) or b.get(k)
    return out


def _src_of(node: ast.expr) -> str:
    d = dotted(node)
    return f"`{d}`" if d else "<expr>"
