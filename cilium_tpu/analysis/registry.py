"""Registry consistency: the string registries producer and consumer
sites must agree on.

Three registries, three failure smells this rule set closes:

* **metric-registry** — a typo'd ``METRICS.inc`` name silently creates
  a dead Prometheus series (and the dashboard keeps reading the old,
  now-frozen one). Every metric family must be declared exactly once
  (``METRICS.describe`` in ``runtime/metrics.py``), be
  Prometheus-legal and ``cilium_tpu_``-prefixed, be written with
  exactly one instrument kind (counter/gauge/histogram — a family
  exposed twice with two TYPEs is invalid exposition), follow the
  counter ``_total`` suffix convention, and never be read
  (``get``/``quantile``/``histo_*``) under a name nothing writes.
* **fault-registry** — a ``faults.maybe_fail`` seam naming an
  unregistered point is unreachable from every FaultPlan (the chaos
  suite thinks it covered an outage it never injected); a registered
  point with no seam is dead coverage.
* **frame-kind** — every ``KIND_*`` stream frame constant must be
  dispatched in both the server worker and the client receive loop,
  or a peer speaking that kind gets its payload misparsed.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, List, Optional, Sequence, Tuple

from cilium_tpu.analysis.callgraph import ModuleInfo, Project, dotted
from cilium_tpu.analysis.core import Finding, ProjectIndex, checker

METRIC_RULE = "metric-registry"
FAULT_RULE = "fault-registry"
FRAME_RULE = "frame-kind"

#: the one module allowed to declare metric families
METRICS_MODULE = "cilium_tpu.runtime.metrics"
FAULTS_MODULE = "cilium_tpu.runtime.faults"
STREAM_MODULE = "cilium_tpu.runtime.stream"

_PROM_NAME = re.compile(r"[a-zA-Z_:][a-zA-Z0-9_:]*\Z")
METRIC_PREFIX = "cilium_tpu_"

_WRITE_KIND = {"inc": "counter", "set_gauge": "gauge",
               "observe": "histogram"}
_READ_METHODS = {"get", "quantile", "histo_sum", "histo_count",
                 "samples_since"}


def _metrics_receiver(project: Project, mi: ModuleInfo,
                      call: ast.Call) -> Optional[str]:
    """The Metrics method name if this call targets the global
    registry (``METRICS.inc`` / ``self.metrics.observe``), else
    None."""
    if not isinstance(call.func, ast.Attribute):
        return None
    recv = dotted(call.func.value)
    if recv is None:
        return None
    if recv in ("METRICS", "self.metrics", "self._metrics"):
        return call.func.attr
    q = mi.qualify(call.func.value)
    if q == f"{METRICS_MODULE}.METRICS":
        return call.func.attr
    return None


def check_metrics(index: ProjectIndex,
                  decl_module: str = METRICS_MODULE) -> List[Finding]:
    project = Project(index)
    declared: Dict[str, Tuple[str, int]] = {}
    findings: List[Finding] = []
    decl = project.modules.get(decl_module)

    # pass 1: declarations (describe calls in the metrics module) +
    # string constants there (the shared-name surface other modules
    # import)
    if decl is not None:
        for node in ast.walk(decl.sf.tree):
            if isinstance(node, ast.Call):
                meth = _metrics_receiver(project, decl, node)
                if meth == "describe" and node.args:
                    name = project.resolve_string(decl, node.args[0])
                    if name is None:
                        continue
                    if name in declared:
                        findings.append(Finding(
                            decl.sf.path, node.lineno, METRIC_RULE,
                            f"metric `{name}` declared more than once "
                            f"(first at line {declared[name][1]})"))
                    else:
                        declared[name] = (decl.sf.path, node.lineno)

    writes: Dict[str, Dict[str, Tuple[str, int]]] = {}
    reads: Dict[str, Tuple[str, int]] = {}
    for mi in project.modules.values():
        # class-level string constants make `self.gauge_name`-style
        # metric names resolvable: call node → enclosing class attrs
        cls_attrs: Dict[int, Dict[str, str]] = {}
        for cls in mi.classes.values():
            attrs = {s.targets[0].id: s.value.value
                     for s in cls.body
                     if isinstance(s, ast.Assign)
                     and len(s.targets) == 1
                     and isinstance(s.targets[0], ast.Name)
                     and isinstance(s.value, ast.Constant)
                     and isinstance(s.value.value, str)}
            if attrs:
                for node in ast.walk(cls):
                    if isinstance(node, ast.Call):
                        cls_attrs[id(node)] = attrs
        for node in ast.walk(mi.sf.tree):
            if not isinstance(node, ast.Call):
                continue
            meth = _metrics_receiver(project, mi, node)
            if meth is None or not node.args:
                continue
            site = (mi.sf.path, node.lineno)
            name = project.resolve_string(mi, node.args[0])
            if name is None:
                arg = node.args[0]
                d = dotted(arg) or ""
                if d.startswith("self.") and d.count(".") == 1:
                    name = cls_attrs.get(id(node), {}).get(
                        d.split(".", 1)[1])
            if meth in _WRITE_KIND or meth in _READ_METHODS:
                if name is None:
                    findings.append(Finding(
                        *site, METRIC_RULE,
                        "metric name is not a resolvable string "
                        "constant — the registry cannot be checked"))
                    continue
            else:
                continue
            if not _PROM_NAME.match(name):
                findings.append(Finding(
                    *site, METRIC_RULE,
                    f"`{name}` is not a legal Prometheus metric name"))
            elif not name.startswith(METRIC_PREFIX):
                findings.append(Finding(
                    *site, METRIC_RULE,
                    f"`{name}` lacks the `{METRIC_PREFIX}` namespace "
                    f"prefix"))
            if meth in _WRITE_KIND:
                writes.setdefault(name, {}).setdefault(
                    _WRITE_KIND[meth], site)
                if meth == "inc" and not name.endswith("_total"):
                    findings.append(Finding(
                        *site, METRIC_RULE,
                        f"counter `{name}` must end in `_total` "
                        f"(Prometheus counter convention)"))
                if meth != "inc" and name.endswith("_total"):
                    findings.append(Finding(
                        *site, METRIC_RULE,
                        f"`{name}` ends in `_total` but is written as "
                        f"a {_WRITE_KIND[meth]}"))
                if name not in declared:
                    findings.append(Finding(
                        *site, METRIC_RULE,
                        f"metric `{name}` written here but never "
                        f"declared — add METRICS.describe(...) in "
                        f"runtime/metrics.py"))
            else:
                reads.setdefault(name, site)

    for name, kinds in writes.items():
        if len(kinds) > 1:
            sites = ", ".join(f"{k} at {p}:{ln}"
                              for k, (p, ln) in sorted(kinds.items()))
            p, ln = sorted(kinds.values())[0]
            findings.append(Finding(
                p, ln, METRIC_RULE,
                f"metric `{name}` written with conflicting instrument "
                f"kinds ({sites}) — one family, one TYPE"))
    for name, (p, ln) in reads.items():
        if name not in writes:
            findings.append(Finding(
                p, ln, METRIC_RULE,
                f"metric `{name}` is read here but nothing in the "
                f"package writes it — dead series or typo"))
    return findings


def check_faults(index: ProjectIndex,
                 faults_module: str = FAULTS_MODULE) -> List[Finding]:
    project = Project(index)
    findings: List[Finding] = []
    registered: Dict[str, Tuple[str, int]] = {}
    seams: Dict[str, Tuple[str, int]] = {}
    for mi in project.modules.values():
        for node in ast.walk(mi.sf.tree):
            if not isinstance(node, ast.Call):
                continue
            q = mi.qualify(node.func) or ""
            if q == f"{faults_module}.register_point" and node.args:
                name = project.resolve_string(mi, node.args[0])
                if name is None:
                    continue
                if name in registered and mi.sf.module != faults_module:
                    findings.append(Finding(
                        mi.sf.path, node.lineno, FAULT_RULE,
                        f"fault point `{name}` registered more than "
                        f"once (first at "
                        f"{registered[name][0]}:{registered[name][1]})"))
                registered.setdefault(name, (mi.sf.path, node.lineno))
            elif q == f"{faults_module}.maybe_fail" and node.args:
                name = project.resolve_string(mi, node.args[0])
                if name is None:
                    findings.append(Finding(
                        mi.sf.path, node.lineno, FAULT_RULE,
                        "maybe_fail point is not a resolvable string "
                        "constant — use `POINT = "
                        "faults.register_point(...)`"))
                    continue
                seams.setdefault(name, (mi.sf.path, node.lineno))
    for name, (p, ln) in seams.items():
        if name not in registered:
            findings.append(Finding(
                p, ln, FAULT_RULE,
                f"maybe_fail(`{name}`) names an unregistered point — "
                f"no FaultPlan can target it by registry"))
    for name, (p, ln) in registered.items():
        if name not in seams and p.endswith(".py") \
                and not p.endswith("faults.py"):
            findings.append(Finding(
                p, ln, FAULT_RULE,
                f"fault point `{name}` is registered but no seam "
                f"calls maybe_fail with it — dead injection point"))
    return findings


#: (module, class, methods) pairs that must each dispatch every frame
#: kind — the stream protocol's two ends
FRAME_DISPATCH_SITES: Tuple[Tuple[str, str, Tuple[str, ...]], ...] = (
    (STREAM_MODULE, "StreamSession", ("_work",)),
    (STREAM_MODULE, "StreamClient", ("_recv_loop",)),
)


def check_frames(index: ProjectIndex,
                 defs_module: str = STREAM_MODULE,
                 sites: Sequence[Tuple[str, str, Tuple[str, ...]]]
                 = FRAME_DISPATCH_SITES) -> List[Finding]:
    project = Project(index)
    findings: List[Finding] = []
    mi = project.modules.get(defs_module)
    if mi is None:
        return findings
    kinds: Dict[str, Tuple[int, int]] = {}   # name → (value, line)
    for name, value in mi.constants.items():
        if name.startswith("KIND_") and isinstance(value, ast.Constant) \
                and isinstance(value.value, int):
            line = next((n.lineno for n in mi.sf.tree.body
                         if isinstance(n, ast.Assign)
                         and isinstance(n.targets[0], ast.Name)
                         and n.targets[0].id == name), 1)
            kinds[name] = (value.value, line)
    by_value: Dict[int, str] = {}
    for name, (value, line) in sorted(kinds.items()):
        if value in by_value:
            findings.append(Finding(
                mi.sf.path, line, FRAME_RULE,
                f"`{name}` reuses wire value {value} of "
                f"`{by_value[value]}`"))
        else:
            by_value[value] = name
    for site_module, cls_name, methods in sites:
        smi = project.modules.get(site_module)
        if smi is None or cls_name not in smi.classes:
            continue
        cls = smi.classes[cls_name]
        names_seen = set()
        for node in cls.body:
            if isinstance(node, ast.FunctionDef) \
                    and node.name in methods:
                for sub in ast.walk(node):
                    if isinstance(sub, ast.Name):
                        names_seen.add(sub.id)
        for name, (_value, line) in sorted(kinds.items()):
            if name not in names_seen:
                findings.append(Finding(
                    mi.sf.path, line, FRAME_RULE,
                    f"frame kind `{name}` is not handled in "
                    f"`{cls_name}.{'/'.join(methods)}` — a peer "
                    f"sending it gets its payload misparsed"))
    return findings


@checker
def check(index: ProjectIndex) -> List[Finding]:
    return (check_metrics(index) + check_faults(index)
            + check_frames(index))
check.emits = (METRIC_RULE, FAULT_RULE, FRAME_RULE)
