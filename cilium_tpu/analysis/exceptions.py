"""swallowed-exception: no silent failure on the serving paths.

A bare ``except:`` catches ``KeyboardInterrupt``/``SystemExit`` and
hides programming errors; an ``except Exception:`` whose body only
``pass``es turns a broken verdict/stream/loader path into silent wrong
behavior (the round-5 outage log's stream stall escaped exactly this
way). Handlers that DO something — log, count a metric, degrade to a
fallback, re-raise — are fine; handlers for narrow exception types
are the caller's business and not flagged.
"""

from __future__ import annotations

import ast
from typing import List

from cilium_tpu.analysis.callgraph import ModuleInfo, dotted
from cilium_tpu.analysis.core import Finding, ProjectIndex, checker

RULE = "swallowed-exception"

_BROAD = {"Exception", "BaseException"}


def _is_broad(mi: ModuleInfo, handler: ast.ExceptHandler) -> bool:
    if handler.type is None:
        return True
    names = [handler.type] if not isinstance(handler.type, ast.Tuple) \
        else list(handler.type.elts)
    return any((dotted(n) or "") in _BROAD for n in names)


def _is_silent(handler: ast.ExceptHandler) -> bool:
    for stmt in handler.body:
        if isinstance(stmt, ast.Pass):
            continue
        if isinstance(stmt, ast.Continue):
            continue
        if isinstance(stmt, ast.Expr) and isinstance(stmt.value,
                                                     ast.Constant):
            continue  # a docstring/ellipsis is not handling
        return False
    return True


@checker
def check(index: ProjectIndex) -> List[Finding]:
    from cilium_tpu.analysis.callgraph import Project

    project = Project(index)
    findings: List[Finding] = []
    for mi in project.modules.values():
        for node in ast.walk(mi.sf.tree):
            if not isinstance(node, ast.ExceptHandler):
                continue
            if node.type is None:
                findings.append(Finding(
                    mi.sf.path, node.lineno, RULE,
                    "bare `except:` — catches KeyboardInterrupt/"
                    "SystemExit and hides programming errors; name "
                    "the exceptions"))
            elif _is_broad(mi, node) and _is_silent(node):
                findings.append(Finding(
                    mi.sf.path, node.lineno, RULE,
                    "`except Exception` with a body that only passes "
                    "— the failure vanishes; log it, count it, or "
                    "narrow the type"))
    return findings
check.emits = (RULE,)
