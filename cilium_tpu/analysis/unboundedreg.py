"""unbounded-registry: every long-lived registry in the serving plane
carries a bound, an eviction path, or a TTL.

The fleet-scale postmortem behind ISSUE 13: the policy plane's
content-addressed stores (bank registry, artifact cache, fingerprint
maps) each started life as a bare dict that only ever grew — fine at
27 banks, a slow memory leak at 5k-CNP churn, where every update
inserts new content keys and nothing ever leaves. The fix is
byte-bounded LRU shards; this rule keeps the property from regressing
anywhere in the long-lived serving modules:

* scope: modules under ``cilium_tpu/runtime/``, ``cilium_tpu/engine/``
  and ``cilium_tpu/policy/`` — the processes that live for the
  daemon's lifetime and take request/event traffic;
* an **instance attribute** initialized to an empty dict/set/
  OrderedDict/defaultdict and **inserted into outside ``__init__``**
  (``self._x[k] = v`` / ``.setdefault`` / ``.add`` / ``.update``) is
  a finding UNLESS the class shows bound/eviction evidence for it:
  ``del self._x[...]``, ``.pop``/``.popitem``/``.clear``, a
  ``len(self._x)`` comparison, or a wholesale rebuild
  (``self._x = ...`` reassignment outside ``__init__`` — the pruning
  idiom);
* a **module-level** dict/set with an insertion inside any function
  is flagged under the same evidence rules (import-time registries
  that only grow with module count are the classic justified
  allowlist).

The heuristic is deliberately syntactic, like ``unbounded-queue``: a
real bound satisfies it, and a registry with no eviction syntax
anywhere cannot be bounded. Provably-bounded growth (keys drawn from
a finite static set, test-only ledgers) carries the standard
justified pragma::

    # ctlint: disable=unbounded-registry  # why growth is bounded
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set

from cilium_tpu.analysis.callgraph import dotted
from cilium_tpu.analysis.core import Finding, ProjectIndex, checker

RULE = "unbounded-registry"

#: path prefixes of the long-lived serving modules in scope
_SCOPED = ("cilium_tpu/runtime/", "cilium_tpu/engine/",
           "cilium_tpu/policy/")

#: ctor calls that build a growable mapping/set registry
_REGISTRY_CTORS = ("dict", "set", "OrderedDict",
                   "collections.OrderedDict", "defaultdict",
                   "collections.defaultdict")

#: method calls that insert into a registry
_INSERT_METHODS = ("setdefault", "add", "update")

#: method calls that evict/bound a registry
_EVICT_METHODS = ("pop", "popitem", "clear", "discard", "remove")


def _is_registry_ctor(node: ast.AST) -> bool:
    if isinstance(node, ast.Dict) and not node.keys:
        return True
    if isinstance(node, ast.Set):
        return False            # literal non-empty set: not a registry
    if isinstance(node, ast.Call):
        d = dotted(node.func)
        return d in _REGISTRY_CTORS
    return False


def _self_attr(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Attribute) \
            and isinstance(node.value, ast.Name) \
            and node.value.id == "self":
        return node.attr
    return None


def _name(node: ast.AST) -> Optional[str]:
    return node.id if isinstance(node, ast.Name) else None


class _ClassScan:
    """One class's registry attrs, insertions, and bound evidence."""

    def __init__(self, cls: ast.ClassDef):
        self.inits: Dict[str, int] = {}       # attr → init lineno
        self.inserts: Dict[str, int] = {}     # attr → insertion lineno
        self.evidence: Set[str] = set()
        for fn in (n for n in cls.body
                   if isinstance(n, (ast.FunctionDef,
                                     ast.AsyncFunctionDef))):
            in_init = fn.name == "__init__"
            for node in ast.walk(fn):
                self._visit(node, in_init)

    def _visit(self, node: ast.AST, in_init: bool) -> None:
        # annotated (`self._x: Dict = {}`) and plain assignments both
        # initialize registries
        if isinstance(node, ast.AnnAssign) and node.value is not None:
            node = ast.Assign(targets=[node.target], value=node.value,
                              lineno=node.lineno)
        if isinstance(node, ast.Assign) and len(node.targets) == 1:
            tgt = node.targets[0]
            attr = _self_attr(tgt)
            if attr is not None:
                if _is_registry_ctor(node.value):
                    if in_init:
                        self.inits.setdefault(attr, node.lineno)
                    else:
                        # re-init outside __init__: the rebuild/prune
                        # idiom — evidence AND a fresh registration
                        self.inits.setdefault(attr, node.lineno)
                        self.evidence.add(attr)
                elif not in_init:
                    # wholesale reassignment (comprehension, filtered
                    # rebuild): eviction evidence
                    self.evidence.add(attr)
            elif isinstance(tgt, ast.Subscript):
                a = _self_attr(tgt.value)
                if a is not None and not in_init:
                    self.inserts.setdefault(a, node.lineno)
        elif isinstance(node, ast.Delete):
            for t in node.targets:
                if isinstance(t, ast.Subscript):
                    a = _self_attr(t.value)
                    if a is not None:
                        self.evidence.add(a)
        elif isinstance(node, ast.Call) \
                and isinstance(node.func, ast.Attribute):
            a = _self_attr(node.func.value)
            if a is not None:
                if node.func.attr in _EVICT_METHODS:
                    self.evidence.add(a)
                elif node.func.attr in _INSERT_METHODS \
                        and not in_init:
                    self.inserts.setdefault(a, node.lineno)
        elif isinstance(node, ast.Compare):
            for sub in ast.walk(node):
                if isinstance(sub, ast.Call) \
                        and isinstance(sub.func, ast.Name) \
                        and sub.func.id == "len" and sub.args:
                    a = _self_attr(sub.args[0])
                    if a is not None:
                        self.evidence.add(a)


def _scan_module_level(tree: ast.Module):
    """(name → init lineno, name → insert lineno, evidence names) for
    module-global registries."""
    inits: Dict[str, int] = {}
    inserts: Dict[str, int] = {}
    evidence: Set[str] = set()
    for node in tree.body:
        if isinstance(node, ast.AnnAssign) and node.value is not None:
            node = ast.Assign(targets=[node.target], value=node.value,
                              lineno=node.lineno)
        if isinstance(node, ast.Assign) and len(node.targets) == 1:
            name = _name(node.targets[0])
            if name is not None and _is_registry_ctor(node.value):
                inits.setdefault(name, node.lineno)
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for sub in ast.walk(node):
                if isinstance(sub, ast.Assign) \
                        and len(sub.targets) == 1:
                    tgt = sub.targets[0]
                    if isinstance(tgt, ast.Subscript):
                        name = _name(tgt.value)
                        if name in inits:
                            inserts.setdefault(name, sub.lineno)
                    elif _name(tgt) in inits:
                        evidence.add(_name(tgt))   # rebuild
                elif isinstance(sub, ast.Delete):
                    for t in sub.targets:
                        if isinstance(t, ast.Subscript):
                            name = _name(t.value)
                            if name in inits:
                                evidence.add(name)
                elif isinstance(sub, ast.Call) \
                        and isinstance(sub.func, ast.Attribute):
                    name = _name(sub.func.value)
                    if name in inits:
                        if sub.func.attr in _EVICT_METHODS:
                            evidence.add(name)
                        elif sub.func.attr in _INSERT_METHODS:
                            inserts.setdefault(name, sub.lineno)
                elif isinstance(sub, ast.Compare):
                    for s2 in ast.walk(sub):
                        if isinstance(s2, ast.Call) \
                                and isinstance(s2.func, ast.Name) \
                                and s2.func.id == "len" and s2.args:
                            name = _name(s2.args[0])
                            if name in inits:
                                evidence.add(name)
    return inits, inserts, evidence


@checker
def check(index: ProjectIndex) -> List[Finding]:
    findings: List[Finding] = []
    for sf in index.files.values():
        path = sf.path.replace("\\", "/")
        if not any(path.startswith(p) or f"/{p}" in path
                   for p in _SCOPED):
            continue
        # instance-level registries
        for node in ast.walk(sf.tree):
            if not isinstance(node, ast.ClassDef):
                continue
            scan = _ClassScan(node)
            for attr in sorted(set(scan.inits) & set(scan.inserts)):
                if attr in scan.evidence:
                    continue
                findings.append(Finding(
                    sf.path, scan.inserts[attr], RULE,
                    f"`self.{attr}` in `{node.name}` is a registry "
                    f"(dict/set) inserted into on an event path with "
                    f"no eviction, bound, or TTL — under sustained "
                    f"churn it grows without limit; add a byte/len "
                    f"bound with eviction, prune it, or justify with "
                    f"a disable pragma"))
        # module-level registries
        inits, inserts, evidence = _scan_module_level(sf.tree)
        for name in sorted(set(inits) & set(inserts)):
            if name in evidence:
                continue
            findings.append(Finding(
                sf.path, inserts[name], RULE,
                f"module-level `{name}` is a registry (dict/set) "
                f"inserted into from function bodies with no "
                f"eviction, bound, or TTL — if growth is provably "
                f"bounded (import-time registration), justify with a "
                f"disable pragma"))
    return findings
check.emits = (RULE,)
