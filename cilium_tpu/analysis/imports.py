"""unused-import: no dead module-level imports.

Dead imports hide real dependencies (and real cycles) and make the
purity/lock analyses resolve names that nothing uses. ``__init__.py``
files are exempt wholesale — their imports ARE the re-export surface.
``from __future__`` and explicit re-exports via ``__all__`` are
recognized as uses.
"""

from __future__ import annotations

import ast
from typing import List

from cilium_tpu.analysis.core import Finding, ProjectIndex, checker

RULE = "unused-import"


@checker
def check(index: ProjectIndex) -> List[Finding]:
    findings: List[Finding] = []
    for sf in index.files.values():
        if sf.path.endswith("__init__.py"):
            continue
        imported = {}  # local name → (line, display)
        import_nodes = []
        for node in sf.tree.body:
            if isinstance(node, ast.Import):
                import_nodes.append(node)
                for alias in node.names:
                    local = alias.asname or alias.name.split(".")[0]
                    imported[local] = (node.lineno, alias.name)
            elif isinstance(node, ast.ImportFrom):
                if node.module == "__future__":
                    continue
                import_nodes.append(node)
                for alias in node.names:
                    if alias.name == "*":
                        continue
                    local = alias.asname or alias.name
                    imported[local] = (
                        node.lineno,
                        f"{node.module or '.'}.{alias.name}")
        if not imported:
            continue
        used = set()
        import_ids = {id(n) for node in import_nodes
                      for n in ast.walk(node)}
        for node in ast.walk(sf.tree):
            if id(node) in import_ids:
                continue
            if isinstance(node, ast.Name):
                used.add(node.id)
            elif isinstance(node, ast.Attribute):
                pass  # the root Name is walked separately
            elif isinstance(node, ast.Constant) \
                    and isinstance(node.value, str):
                continue
        # __all__ re-exports count as uses
        for node in sf.tree.body:
            if isinstance(node, ast.Assign) \
                    and any(isinstance(t, ast.Name)
                            and t.id == "__all__"
                            for t in node.targets):
                for sub in ast.walk(node.value):
                    if isinstance(sub, ast.Constant) \
                            and isinstance(sub.value, str):
                        used.add(sub.value)
        for local, (line, display) in sorted(imported.items()):
            if local not in used:
                findings.append(Finding(
                    sf.path, line, RULE,
                    f"`{display}` imported as `{local}` but never "
                    f"used at module level"))
    return findings
check.emits = (RULE,)
