"""abi-surface: the C↔Python ctypes contract, machine-diffed.

The framework crosses the language boundary twice: the proxylib-ABI
shim (``shim/cilium_shim.cpp`` → ``cshim_*``) and the capture codec
(``native/capture/capture.cpp`` → ``ct_capture_*``). Both are loaded
with raw ``ctypes.CDLL`` — there is no header parser, no stub
generator, nothing that fails at import time when a C signature gains
an argument or changes a width. The failure mode of drift is a
segfault (wrong arity / wrong pointer marshaling) or silent value
truncation (a ``long`` return read through the ``c_int`` default),
neither of which a green unit test on the happy path rules out.

This rule parses every ``extern "C"`` function in the repo's C++
sources and diffs the surface **bidirectionally** against every
Python use — ``argtypes``/``restype`` declarations and raw call
arity — in the package *and* in the test/bench surfaces that bind
the shim directly:

* a Python binding or call of an unknown ``cshim_*``/``ct_capture_*``
  symbol (deleted or typo'd on the C side);
* ``argtypes`` arity or per-position type drift (each C type has a
  small set of legal ctypes spellings);
* a missing/wrong ``restype`` where the ctypes default (``c_int``)
  truncates or misreads the C return (``long``, ``uint32_t``,
  ``double``, ``void``);
* a call through a symbol that takes pointers but was never given
  ``argtypes`` in that file (nothing checks the marshaling);
* call-site arity that disagrees with the C parameter count;
* a C symbol no scanned Python file binds or calls (dead ABI).
"""

from __future__ import annotations

import ast
import os
import re
from typing import Dict, List, Optional, Sequence, Set, Tuple

from cilium_tpu.analysis.core import Finding, ProjectIndex, checker
from cilium_tpu.analysis.callgraph import dotted

RULE = "abi-surface"

#: repo-relative C++ sources carrying the extern "C" surfaces
CPP_SOURCES = ("shim/cilium_shim.cpp", "native/capture/capture.cpp")

#: extra Python surfaces (outside the package) that bind the ABI
EXTRA_PY = ("tests", "bench_service.py")

#: symbol prefixes that mark our ABI (anything else is ignored)
SYMBOL_PREFIXES = ("cshim_", "ct_capture_")

# -- C side -----------------------------------------------------------------

_FN_RE = re.compile(
    r"^\s*(?P<ret>[A-Za-z_][A-Za-z0-9_ ]*?[\*\s])\s*"
    r"(?P<name>(?:%s)[A-Za-z0-9_]*)\s*\("
    % "|".join(SYMBOL_PREFIXES), re.M)


#: C-side allowlist: ``// ctlint: disable=abi-surface  # why`` on the
#: signature line or a comment line directly above exempts the symbol
#: from the dead-ABI (unbound) check — Python pragmas cannot annotate
#: a .cpp file. A justification is still required (bare pragmas are
#: ignored, so the finding stays).
_CPP_DISABLE_RE = re.compile(
    r"//\s*ctlint:\s*disable=abi-surface\s*#\s*\S")


class CSymbol:
    def __init__(self, name: str, ret: str, params: List[str],
                 path: str, line: int, allow_unbound: bool = False):
        self.name = name
        self.ret = ret          # normalized C return type
        self.params = params    # normalized C param types
        self.path = path
        self.line = line
        self.allow_unbound = allow_unbound


def _norm_ctype(t: str) -> str:
    t = t.replace("const", " ").replace("struct", " ")
    t = re.sub(r"\s+", " ", t).strip()
    t = t.replace(" *", "*").replace("* ", "*")
    return t


def _split_params(blob: str) -> List[str]:
    blob = blob.strip()
    if blob in ("", "void"):
        return []
    out = []
    for part in blob.split(","):
        part = _norm_ctype(part)
        # drop the trailing parameter name (last identifier not part
        # of the type); pointer stars belong to the type
        m = re.match(r"^(.*?)([A-Za-z_][A-Za-z0-9_]*)$", part)
        ty = m.group(1).strip() if m else part
        if not ty:            # unnamed param: the whole token is a type
            ty = part
        out.append(_norm_ctype(ty))
    return out


def parse_extern_c(source: str, path: str) -> List[CSymbol]:
    """All ABI-prefixed function definitions/declarations in one C++
    source (regex over the flat text: the shim surface is plain
    C-style signatures, which is the point of ``extern "C"``)."""
    out: List[CSymbol] = []
    for m in _FN_RE.finditer(source):
        start = m.end()
        depth = 1
        i = start
        while i < len(source) and depth:
            if source[i] == "(":
                depth += 1
            elif source[i] == ")":
                depth -= 1
            i += 1
        params = _split_params(source[start:i - 1])
        line = source.count("\n", 0, m.start()) + 1
        lines = source.splitlines()
        context = lines[max(0, line - 2):line]
        allow = any(_CPP_DISABLE_RE.search(t) for t in context)
        out.append(CSymbol(m.group("name"), _norm_ctype(m.group("ret")),
                           params, path, line, allow_unbound=allow))
    return out


#: C type → legal ctypes spellings for argtypes
_ARG_OK: Dict[str, Set[str]] = {
    "char*": {"c_char_p", "c_void_p"},
    "uint8_t*": {"c_void_p", "c_char_p", "POINTER(c_uint8)"},
    "void*": {"c_void_p", "c_char_p", "POINTER(c_uint8)"},
    "uint16_t*": {"POINTER(c_uint16)"},
    "uint32_t*": {"POINTER(c_uint32)"},
    "uint64_t*": {"POINTER(c_uint64)"},
    "int32_t*": {"POINTER(c_int32)"},
    "int64_t*": {"POINTER(c_int64)"},
    "size_t": {"c_size_t", "c_uint64"},
    "uint64_t": {"c_uint64"},
    "uint32_t": {"c_uint32"},
    "uint16_t": {"c_uint16"},
    "uint8_t": {"c_uint8"},
    "int": {"c_int"},
    "long": {"c_long"},
    "double": {"c_double"},
    "float": {"c_float"},
}

#: C return type → (required restype spellings, None-default is safe)
_RET_OK: Dict[str, Tuple[Set[str], bool]] = {
    "int": ({"c_int"}, True),          # ctypes default IS c_int
    "long": ({"c_long"}, False),       # default truncates on LP64
    "void": ({"None"}, False),         # default reads garbage
    "uint32_t": ({"c_uint32"}, False),  # default sign-misreads
    "uint64_t": ({"c_uint64"}, False),
    "double": ({"c_double"}, False),
    "char*": ({"c_char_p"}, False),
}


def _arg_ok(cty: str, spelling: str) -> bool:
    allowed = _ARG_OK.get(cty)
    if allowed is None:
        return True  # unknown C type: miss, don't invent
    return spelling in allowed


# -- Python side ------------------------------------------------------------

class PyUse:
    """Everything one Python file says about one symbol."""

    def __init__(self) -> None:
        self.argtypes: Optional[Tuple[List[str], int]] = None
        self.restype: Optional[Tuple[str, int]] = None
        self.calls: List[Tuple[int, int]] = []   # (arity, line)
        self.hasattr_probe = False


def _ctypes_spelling(node: ast.expr) -> str:
    """`ctypes.c_uint32` → "c_uint32"; `ctypes.POINTER(ctypes.c_int32)`
    → "POINTER(c_int32)"; `None` → "None"."""
    if isinstance(node, ast.Constant) and node.value is None:
        return "None"
    d = dotted(node)
    if d is not None:
        return d.rsplit(".", 1)[-1]
    if isinstance(node, ast.Call):
        f = dotted(node.func) or ""
        leaf = f.rsplit(".", 1)[-1]
        inner = _ctypes_spelling(node.args[0]) if node.args else "?"
        return f"{leaf}({inner})"
    return "?"


def scan_python(tree: ast.AST) -> Dict[str, PyUse]:
    """Collect argtypes/restype/call uses of ABI symbols in one
    module."""
    uses: Dict[str, PyUse] = {}

    def use(sym: str) -> PyUse:
        return uses.setdefault(sym, PyUse())

    for node in ast.walk(tree):
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Attribute):
            tgt = node.targets[0]
            if tgt.attr in ("argtypes", "restype") \
                    and isinstance(tgt.value, ast.Attribute):
                sym = tgt.value.attr
                if sym.startswith(SYMBOL_PREFIXES):
                    if tgt.attr == "argtypes" and isinstance(
                            node.value, (ast.List, ast.Tuple)):
                        use(sym).argtypes = (
                            [_ctypes_spelling(e)
                             for e in node.value.elts],
                            node.lineno)
                    elif tgt.attr == "restype":
                        use(sym).restype = (
                            _ctypes_spelling(node.value), node.lineno)
        elif isinstance(node, ast.Call):
            f = node.func
            if isinstance(f, ast.Attribute) \
                    and f.attr.startswith(SYMBOL_PREFIXES):
                use(f.attr).calls.append((len(node.args), node.lineno))
            elif isinstance(f, ast.Name) and f.id == "hasattr" \
                    and len(node.args) == 2 \
                    and isinstance(node.args[1], ast.Constant) \
                    and isinstance(node.args[1].value, str) \
                    and node.args[1].value.startswith(SYMBOL_PREFIXES):
                use(node.args[1].value).hasattr_probe = True
    return uses


# -- the diff ---------------------------------------------------------------

def diff(c_symbols: Sequence[CSymbol],
         py_files: Dict[str, Dict[str, PyUse]]) -> List[Finding]:
    by_name = {s.name: s for s in c_symbols}
    findings: List[Finding] = []
    bound: Set[str] = set()

    for path, uses in sorted(py_files.items()):
        for sym, use in sorted(uses.items()):
            bound.add(sym)
            c = by_name.get(sym)
            line = (use.argtypes[1] if use.argtypes
                    else use.restype[1] if use.restype
                    else use.calls[0][1] if use.calls else 1)
            if c is None:
                findings.append(Finding(
                    path, line, RULE,
                    f"`{sym}` is bound/called here but no extern "
                    f"\"C\" symbol of that name exists in "
                    f"{', '.join(CPP_SOURCES)}"))
                continue
            if use.argtypes is not None:
                spelt, aline = use.argtypes
                if len(spelt) != len(c.params):
                    findings.append(Finding(
                        path, aline, RULE,
                        f"`{sym}` argtypes declares {len(spelt)} "
                        f"parameter(s) but the C signature has "
                        f"{len(c.params)} ({c.path}:{c.line})"))
                else:
                    for i, (py, cty) in enumerate(zip(spelt, c.params)):
                        if not _arg_ok(cty, py):
                            findings.append(Finding(
                                path, aline, RULE,
                                f"`{sym}` argtypes[{i}] is `{py}` "
                                f"but the C parameter is `{cty}` "
                                f"({c.path}:{c.line})"))
            ret_rule = _RET_OK.get(c.ret)
            if use.restype is not None and ret_rule is not None:
                spelt, rline = use.restype
                if spelt not in ret_rule[0]:
                    findings.append(Finding(
                        path, rline, RULE,
                        f"`{sym}` restype `{spelt}` does not match "
                        f"the C return `{c.ret}` "
                        f"({c.path}:{c.line})"))
            if use.restype is None and use.calls and ret_rule is not None \
                    and not ret_rule[1]:
                findings.append(Finding(
                    path, use.calls[0][1], RULE,
                    f"`{sym}` returns C `{c.ret}` but this file "
                    f"never sets restype — the ctypes default "
                    f"(c_int) misreads it"))
            if use.argtypes is None and use.calls \
                    and any("*" in p for p in c.params):
                findings.append(Finding(
                    path, use.calls[0][1], RULE,
                    f"`{sym}` takes pointer parameters but this "
                    f"file calls it without declaring argtypes — "
                    f"nothing checks the marshaling"))
            for arity, cline in use.calls:
                if arity != len(c.params):
                    findings.append(Finding(
                        path, cline, RULE,
                        f"`{sym}` called with {arity} argument(s) "
                        f"but the C signature has {len(c.params)} "
                        f"({c.path}:{c.line})"))

    for s in c_symbols:
        if s.name not in bound and not s.allow_unbound:
            findings.append(Finding(
                s.path, s.line, RULE,
                f"extern \"C\" `{s.name}` is never bound or called "
                f"from any scanned Python surface — dead ABI or a "
                f"missing binding"))
    return findings


# -- wiring -----------------------------------------------------------------

def _root_of(index: ProjectIndex) -> Optional[str]:
    return getattr(index, "root", None)


def _iter_extra_py(root: str):
    for target in EXTRA_PY:
        full = os.path.join(root, target)
        if os.path.isfile(full):
            yield target, full
        elif os.path.isdir(full):
            for name in sorted(os.listdir(full)):
                if name.endswith(".py"):
                    yield os.path.join(target, name), \
                        os.path.join(full, name)


def check_abi(index: ProjectIndex,
              cpp_sources: Optional[Dict[str, str]] = None,
              extra_py: Optional[Dict[str, str]] = None
              ) -> List[Finding]:
    """``cpp_sources``/``extra_py`` map repo-relative path → text; the
    corpus-test face. Defaults read the real tree off ``index.root``."""
    root = _root_of(index)
    if cpp_sources is None:
        cpp_sources = {}
        if root is not None:
            for rel in CPP_SOURCES:
                full = os.path.join(root, rel)
                if os.path.exists(full):
                    with open(full, encoding="utf-8") as f:
                        cpp_sources[rel] = f.read()
    if not cpp_sources:
        return []   # in-memory corpus with no C side: nothing to diff

    c_symbols: List[CSymbol] = []
    for rel, text in sorted(cpp_sources.items()):
        c_symbols.extend(parse_extern_c(text, rel))

    py_files: Dict[str, Dict[str, PyUse]] = {}
    for sf in index.files.values():
        uses = scan_python(sf.tree)
        if uses:
            py_files[sf.path] = uses
    if extra_py is None:
        extra_py = {}
        if root is not None:
            for rel, full in _iter_extra_py(root):
                try:
                    with open(full, encoding="utf-8") as f:
                        text = f.read()
                except OSError:
                    continue
                if any(p in text for p in SYMBOL_PREFIXES):
                    extra_py[rel] = text
    for rel, text in sorted(extra_py.items()):
        try:
            tree = ast.parse(text, filename=rel)
        except SyntaxError:
            continue  # parse errors in extra surfaces are not ABI drift
        uses = scan_python(tree)
        if uses:
            py_files[rel] = uses

    return diff(c_symbols, py_files)


def symbol_count(index: ProjectIndex) -> int:
    """C symbols visible to the rule — the non-vacuity guard hook."""
    root = _root_of(index)
    n = 0
    if root is None:
        return 0
    for rel in CPP_SOURCES:
        full = os.path.join(root, rel)
        if os.path.exists(full):
            with open(full, encoding="utf-8") as f:
                n += len(parse_extern_c(f.read(), rel))
    return n


@checker
def check(index: ProjectIndex) -> List[Finding]:
    return check_abi(index)
check.emits = (RULE,)
