"""shape-dtype: abstract shape/dtype interpretation of the jitted
kernel surface.

Runs the dataflow interpreter over every jitted/pallas/shard_map entry
point (the same entry discovery as ``jit-purity``) with parameters
seeded from the kernel-comment shape convention, and flags what a CPU
unit test at toy shapes cannot:

* **rank/shape mismatches** — a broadcast whose aligned extents are
  both known and provably unequal (neither 1), a matmul whose
  contraction extents disagree, a ``take_along_axis`` whose index rank
  differs from the operand's (jax requires equal ranks), a ``reshape``
  whose known element counts disagree;
* **overflow-prone integer accumulations** — ``sum``/``cumsum``/
  ``prod`` over a narrow-int operand with no explicit ``dtype=``
  where the reduced extent is unknown or large: the accumulator
  inherits the operand's int32 (x64 is disabled — there is no silent
  promotion to rescue it), so a payload-scale reduction wraps;
* **weak-type wraps** — an int literal folded into a narrow-dtype
  array that cannot represent it (jax keeps the array's dtype for
  weak Python scalars: ``uint8_arr + 1000`` wraps, silently).

Every finding names the jitted entry it is reachable from. The bias
is the framework's: two *symbolic* extents that merely differ by name
are unknown-compatible, not findings.
"""

from __future__ import annotations

import ast
from typing import List, Optional

from cilium_tpu.analysis import dataflow
from cilium_tpu.analysis.callgraph import ModuleInfo, Project
from cilium_tpu.analysis.core import Finding, ProjectIndex, checker
from cilium_tpu.analysis.dataflow import EventSink, Interp
from cilium_tpu.analysis.purity import find_entries

RULE = "shape-dtype"

#: reductions that accumulate in the operand dtype (overflow surface)
_ACC_FNS = {"sum", "cumsum", "prod", "cumprod", "dot", "trace"}

#: narrow integer dtypes whose accumulator can wrap at batch scale
_NARROW_INTS = {"int8", "uint8", "int16", "uint16", "int32", "uint32"}

#: reduced extents below this are treated as structurally small
#: (bit-plane folds, probe grids) rather than batch/payload axes
_SMALL_EXTENT = 4096


def _fmt_dim(d) -> str:
    return "?" if d is None else str(d)


class _Sink(EventSink):
    """Collects shape/dtype events as findings for one entry walk.
    ``path`` per event: under the interprocedural walk an event lands
    in the CALLEE's file, not the entry's."""

    def __init__(self, entry: str):
        self.entry = entry
        self.findings: List[Finding] = []

    def _add(self, path: str, line: int, msg: str) -> None:
        self.findings.append(Finding(
            path, line, RULE,
            f"{msg} (reachable from jitted entry `{self.entry}`)"))

    def binop_conflict(self, path, line, op, a, b, conflict) -> None:
        da, db, axis = conflict
        self._add(path, line,
                  f"shape mismatch in `{op}`: {a.describe()} vs "
                  f"{b.describe()} — axis -{axis} has extents "
                  f"{_fmt_dim(da)} and {_fmt_dim(db)}, neither 1")

    def rank_mismatch(self, path, line, what, a, b) -> None:
        self._add(path, line,
                  f"`{what}` requires equal ranks: operand "
                  f"{a.describe()} (rank {a.rank}) vs indices "
                  f"{b.describe()} (rank {b.rank})")

    def matmul_conflict(self, path, line, a, b) -> None:
        self._add(path, line,
                  f"matmul contraction mismatch: {a.describe()} @ "
                  f"{b.describe()}")

    def reshape_mismatch(self, path, line, src, want) -> None:
        dims = ", ".join(_fmt_dim(d) for d in want)
        self._add(path, line,
                  f"reshape element-count mismatch: {src.describe()} "
                  f"cannot reshape to [{dims}]")

    def reduction(self, path, line, fn, operand, extent,
                  has_dtype) -> None:
        if has_dtype or fn not in _ACC_FNS:
            return
        if operand.dtype not in _NARROW_INTS:
            return
        if isinstance(extent, int) and extent < _SMALL_EXTENT:
            return
        ext = "unknown" if extent is None else str(extent)
        self._add(path, line,
                  f"int32-overflow-prone accumulation: `{fn}` over "
                  f"{operand.describe()} with no explicit dtype= — "
                  f"the accumulator stays {operand.dtype} over an "
                  f"axis of {ext} elements (x64 disabled: no "
                  f"promotion)")

    def weak_wrap(self, path, line, op, arr, value) -> None:
        self._add(path, line,
                  f"weak-type wrap: int literal {value} does not fit "
                  f"{arr.dtype} ({arr.describe()}) — jax keeps the "
                  f"array dtype for Python scalars, so this wraps "
                  f"silently")


def analyze_entry(project: Project, mi: ModuleInfo, fn: ast.AST,
                  entry_name: Optional[str] = None) -> List[Finding]:
    """Interpret one jitted entry; returns its shape-dtype findings."""
    name = entry_name or getattr(fn, "name", "<lambda>")
    sink = _Sink(name)
    interp = Interp(project, sink)
    env = dataflow.param_shapes(mi, fn)
    interp.run_function(mi, fn, env)
    return sink.findings


def entry_count(index: ProjectIndex) -> int:
    """How many jitted entries the analysis walks — the non-vacuity
    guard's hook (``tests/test_ctlint.py``)."""
    return len(find_entries(Project(index)))


@checker
def check(index: ProjectIndex) -> List[Finding]:
    project = Project(index)
    findings: List[Finding] = []
    seen: set = set()
    for mi, fn in find_entries(project):
        if id(fn) in seen:
            continue
        seen.add(id(fn))
        findings.extend(analyze_entry(project, mi, fn))
    # one finding per site: several entries reaching the same helper
    # line collapse to the first entry's attribution
    out = {}
    for f in sorted(set(findings)):
        out.setdefault((f.path, f.line, f.rule), f)
    return sorted(out.values())
check.emits = (RULE,)
