"""pallas-block-shape: TPU tiling hygiene for Pallas kernels.

Two classes of silent Pallas performance/correctness hazards this
codebase has now hit enough times to machine-check (the megakernel
review found both in draft kernels):

* **Misaligned block shapes** — a ``pl.BlockSpec`` whose trailing
  block dims don't land on the (8, 128) TPU tile forces Mosaic into
  padded/strided layouts (or compile failure on real hardware that
  interpret-mode tests never see). Flagged when the LITERAL dims are
  provable: the last block dim must be a multiple of 128 and the
  second-to-last a multiple of 8 (leading size-1 dims — the "one
  bank/tile per grid cell" idiom — are exempt, and dims written as
  variables are not guessed at). Module-level integer constants
  (``TILE = 1024``) resolve like literals.
* **Unpinned accumulators** — a matmul inside a kernel body without
  an explicit ``preferred_element_type``: TPU matmuls default to
  bf16 accumulation, which silently rounds integer-valued lattices
  (state ids, position counts) above 256 — the exactness bugs the
  one-hot automaton kernels depend on avoiding. Every
  ``jnp.dot`` / ``jnp.matmul`` / ``lax.dot_general`` / ``pl.dot``
  reachable inside a function passed to ``pallas_call`` must pin it
  (``precision=HIGHEST`` is NOT the same contract: it constrains the
  multiply, not the accumulator dtype).

Kernel bodies are found structurally: any function passed as the
first argument to a ``pallas_call`` in the same module, including
nested helper defs inside it.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional

from cilium_tpu.analysis.callgraph import dotted
from cilium_tpu.analysis.core import Finding, ProjectIndex, checker

RULE = "pallas-block-shape"

_DOT_CALLS = {"jnp.dot", "jnp.matmul", "jax.numpy.dot",
              "jax.numpy.matmul", "lax.dot_general",
              "jax.lax.dot_general", "pl.dot"}


def _module_int_consts(tree: ast.AST) -> Dict[str, int]:
    """Module-level ``NAME = <int literal>`` bindings (TILE = 1024)."""
    out: Dict[str, int] = {}
    for node in getattr(tree, "body", ()):
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name) \
                and isinstance(node.value, ast.Constant) \
                and isinstance(node.value.value, int):
            out[node.targets[0].id] = node.value.value
    return out


def _dim_value(node: ast.AST, consts: Dict[str, int]) -> Optional[int]:
    if isinstance(node, ast.Constant) and isinstance(node.value, int):
        return node.value
    if isinstance(node, ast.Name):
        return consts.get(node.id)
    return None


def _is_blockspec(call: ast.Call) -> bool:
    d = dotted(call.func) or ""
    return d.split(".")[-1] == "BlockSpec"


def _is_pallas_call(call: ast.Call) -> bool:
    d = dotted(call.func) or ""
    return d.split(".")[-1] == "pallas_call"


def _check_block_shape(call: ast.Call, consts: Dict[str, int],
                       path: str) -> List[Finding]:
    if not call.args or not isinstance(call.args[0], ast.Tuple):
        return []
    dims = [_dim_value(e, consts) for e in call.args[0].elts]
    if len(dims) < 1:
        return []
    findings = []
    last = dims[-1]
    if last is not None and last > 1 and last % 128 != 0:
        findings.append(Finding(
            path, call.lineno, RULE,
            f"BlockSpec last block dim {last} is not a multiple of "
            f"128 — TPU lanes tile at 128; Mosaic pads or rejects "
            f"this layout"))
    if len(dims) >= 2:
        second = dims[-2]
        if second is not None and second > 1 and second % 8 != 0:
            findings.append(Finding(
                path, call.lineno, RULE,
                f"BlockSpec second-to-last block dim {second} is not "
                f"a multiple of 8 — TPU sublanes tile at 8 "
                f"(f32); use an (8, 128)-aligned block"))
    return findings


def _kernel_names(tree: ast.AST) -> Dict[str, int]:
    """Function names passed as the first arg to a pallas_call (the
    kernel bodies), with the call line for context."""
    out: Dict[str, int] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Call) and _is_pallas_call(node) \
                and node.args and isinstance(node.args[0], ast.Name):
            out.setdefault(node.args[0].id, node.lineno)
    return out


def _check_kernel_dots(fn: ast.FunctionDef, path: str) -> List[Finding]:
    findings = []
    for node in ast.walk(fn):
        if not isinstance(node, ast.Call):
            continue
        d = dotted(node.func) or ""
        if d not in _DOT_CALLS and d.split(".")[-1] != "dot_general":
            continue
        if any(kw.arg == "preferred_element_type"
               for kw in node.keywords):
            continue
        findings.append(Finding(
            path, node.lineno, RULE,
            f"`{d}` inside pallas kernel `{fn.name}` without "
            f"`preferred_element_type` — TPU matmuls default to bf16 "
            f"accumulation, silently rounding values above 256; pin "
            f"the accumulator dtype explicitly"))
    return findings


@checker
def check(index: ProjectIndex) -> List[Finding]:
    findings: List[Finding] = []
    for sf in index.files.values():
        src = sf.source
        if "pallas" not in src:
            continue
        consts = _module_int_consts(sf.tree)
        kernels = _kernel_names(sf.tree)
        for node in ast.walk(sf.tree):
            if isinstance(node, ast.Call) and _is_blockspec(node):
                findings.extend(_check_block_shape(node, consts,
                                                   sf.path))
            elif isinstance(node, ast.FunctionDef) \
                    and node.name in kernels:
                findings.extend(_check_kernel_dots(node, sf.path))
    return findings
check.emits = (RULE,)
