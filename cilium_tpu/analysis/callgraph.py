"""Shared symbol resolution: imports, constants, classes, call targets.

Everything here is best-effort and syntactic — when a name cannot be
resolved the rules skip it rather than guess. That bias (miss, don't
invent) keeps the lint lane quiet enough that a finding means
something.
"""

from __future__ import annotations

import ast
import threading
from typing import Dict, List, Optional, Tuple

from cilium_tpu.analysis.core import ProjectIndex, SourceFile

_MEMO_LOCK = threading.Lock()


def project_for(index: ProjectIndex) -> "Project":
    """One shared ``Project`` per index. Several rules (lock-order,
    thread-safety, registries) need the same symbol tables; building
    them once matters now that checkers run on a thread pool."""
    with _MEMO_LOCK:
        project = getattr(index, "_ctlint_project", None)
        if project is None:
            project = Project(index)
            index._ctlint_project = project
        return project


def dotted(node: ast.AST) -> Optional[str]:
    """``a.b.c`` for a Name/Attribute chain, else None."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


class ModuleInfo:
    """Per-module symbol table: imports, top-level constants/functions/
    classes, and every (possibly nested) function definition."""

    def __init__(self, sf: SourceFile):
        self.sf = sf
        #: local name → fully qualified target ("time",
        #: "cilium_tpu.runtime.faults", "....metrics.METRICS")
        self.imports: Dict[str, str] = {}
        #: top-level NAME = <expr> assignments
        self.constants: Dict[str, ast.expr] = {}
        self.functions: Dict[str, ast.AST] = {}
        self.classes: Dict[str, ast.ClassDef] = {}
        #: every FunctionDef in the module by name (nested included);
        #: jitted entry points are often closures, so name-level lookup
        #: must see them
        self.all_functions: Dict[str, List[ast.AST]] = {}
        self._build()

    def _build(self) -> None:
        pkg = self.sf.module.rsplit(".", 1)[0] \
            if "." in self.sf.module else ""
        for node in self.sf.tree.body:
            if isinstance(node, ast.Import):
                for alias in node.names:
                    local = alias.asname or alias.name.split(".")[0]
                    target = alias.name if alias.asname else \
                        alias.name.split(".")[0]
                    self.imports[local] = target
            elif isinstance(node, ast.ImportFrom):
                base = node.module or ""
                if node.level:
                    # relative import: climb from this module's package
                    parts = self.sf.module.split(".")
                    parts = parts[: len(parts) - node.level]
                    base = ".".join(parts + ([node.module]
                                             if node.module else []))
                elif not base:
                    base = pkg
                for alias in node.names:
                    if alias.name == "*":
                        continue
                    local = alias.asname or alias.name
                    self.imports[local] = f"{base}.{alias.name}"
            elif isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name):
                self.constants[node.targets[0].id] = node.value
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self.functions[node.name] = node
            elif isinstance(node, ast.ClassDef):
                self.classes[node.name] = node
        for node in ast.walk(self.sf.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self.all_functions.setdefault(node.name, []).append(node)

    def qualify(self, expr: ast.AST) -> Optional[str]:
        """Resolve a Name/Attribute chain through this module's
        imports: ``_time.monotonic`` → ``time.monotonic``,
        ``_faults.maybe_fail`` → ``cilium_tpu.runtime.faults
        .maybe_fail``. Unresolved roots stay as written."""
        d = dotted(expr)
        if d is None:
            return None
        root, _, rest = d.partition(".")
        target = self.imports.get(root, root)
        return f"{target}.{rest}" if rest else target


class Project:
    """ModuleInfo for every indexed file + cross-module resolution."""

    def __init__(self, index: ProjectIndex):
        self.index = index
        self.modules: Dict[str, ModuleInfo] = {
            name: ModuleInfo(sf) for name, sf in index.files.items()}

    def resolve_string(self, mi: ModuleInfo, expr: ast.AST,
                       _depth: int = 0) -> Optional[str]:
        """Constant-fold ``expr`` to a string: literals, module-level
        NAME constants, and from-imports of such constants in other
        indexed modules. Handles the ``POINT = register_point("x")``
        idiom by unwrapping single-call assignments whose first arg is
        a string."""
        if _depth > 8:
            return None
        if isinstance(expr, ast.Constant) and isinstance(expr.value, str):
            return expr.value
        if isinstance(expr, ast.Call) and expr.args:
            return self.resolve_string(mi, expr.args[0], _depth + 1)
        d = dotted(expr)
        if d is None:
            return None
        # local constant?
        if "." not in d and d in mi.constants:
            return self.resolve_string(mi, mi.constants[d], _depth + 1)
        q = mi.qualify(expr)
        if q is None:
            return None
        owner, _, attr = q.rpartition(".")
        target = self.modules.get(owner)
        if target is not None and attr in target.constants:
            return self.resolve_string(target, target.constants[attr],
                                       _depth + 1)
        return None

    def resolve_function(self, mi: ModuleInfo, name: str
                         ) -> Optional[Tuple[ModuleInfo, ast.AST]]:
        """Find the def behind a (possibly imported) function name."""
        fns = mi.all_functions.get(name)
        if fns:
            return mi, fns[0]
        q = mi.imports.get(name)
        if q is None:
            return None
        owner, _, attr = q.rpartition(".")
        for candidate in (self.modules.get(q.rsplit(".", 1)[0]),
                          self.modules.get(owner)):
            if candidate is not None and attr in candidate.functions:
                return candidate, candidate.functions[attr]
        return None

    def resolve_class(self, mi: ModuleInfo, name: str
                      ) -> Optional[Tuple[ModuleInfo, ast.ClassDef]]:
        if name in mi.classes:
            return mi, mi.classes[name]
        q = mi.imports.get(name)
        if q is None:
            return None
        owner, _, attr = q.rpartition(".")
        target = self.modules.get(owner)
        if target is not None and attr in target.classes:
            return target, target.classes[attr]
        return None
