"""ctlint — codebase-aware static analysis for cilium-tpu.

Zero-dependency (stdlib ``ast``) rule framework plus a rule set
tailored to this codebase's unwritten contracts: jit purity, lock
order, and the string registries (metric families, fault points,
stream frame kinds). ``make lint`` runs it as part of ``make check``;
``cilium-tpu lint`` and ``python -m cilium_tpu.analysis`` are the CLI
faces. Rule catalog and allowlisting: docs/ANALYSIS.md.
"""

from cilium_tpu.analysis.core import (
    Finding,
    ProjectIndex,
    RULES,
    render_json,
    render_text,
    run,
)

__all__ = ["Finding", "ProjectIndex", "RULES", "render_json",
           "render_text", "run", "run_cli"]


def run_cli(argv=None) -> int:
    """The `cilium-tpu lint` / `python -m cilium_tpu.analysis` driver.
    Exit 1 on any non-allowlisted finding."""
    import argparse
    import os
    import sys

    ap = argparse.ArgumentParser(
        prog="cilium-tpu lint",
        description="codebase-aware static analysis "
                    "(docs/ANALYSIS.md)")
    ap.add_argument("targets", nargs="*", default=(),
                    help="repo-relative files/dirs "
                         "(default: cilium_tpu)")
    ap.add_argument("--root", default=None,
                    help="repo root (default: the package's parent)")
    ap.add_argument("--format", choices=["text", "json"],
                    default="text")
    ap.add_argument("--rules", default=None,
                    help="comma-separated rule ids to run "
                         "(default: all)")
    ap.add_argument("--rule", action="append", default=None,
                    metavar="ID",
                    help="run one rule id (repeatable; combines "
                         "with --rules)")
    ap.add_argument("--changed-only", action="store_true",
                    help="report findings only for files git sees "
                         "as changed/untracked (the whole tree is "
                         "still indexed — rules are cross-file); "
                         "the pre-commit face")
    ap.add_argument("--out", default=None,
                    help="also write a JSON report here (the CI "
                         "artifact)")
    ap.add_argument("--wall-budget-ms", type=int, default=None,
                    metavar="MS",
                    help="fail (exit 1) if the whole lint run takes "
                         "longer than this many wall-clock ms — the "
                         "`make lint` latency gate (the committed "
                         "budget lives in CTLINT.json as "
                         "wall_budget_ms)")
    ap.add_argument("--list-rules", action="store_true",
                    help="print the rule catalog and exit")
    args = ap.parse_args(argv)

    if args.list_rules:
        for rule, doc in sorted(RULES.items()):
            print(f"{rule}: {doc}")
        return 0
    root = args.root or os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))
    rules = [r.strip() for r in (args.rules or "").split(",")
             if r.strip()]
    rules.extend(args.rule or ())
    if rules:
        unknown = [r for r in rules if r not in RULES]
        if unknown:
            print(f"error: unknown rule(s) {unknown} "
                  f"(--list-rules)", file=sys.stderr)
            return 2
    only_paths = None
    if args.changed_only:
        only_paths = _git_changed_paths(root)
        if only_paths is None:
            print("error: --changed-only needs a git checkout",
                  file=sys.stderr)
            return 2
        if not only_paths:
            print("ctlint: no changed files")
            return 0
    findings, suppressed = run(
        root, targets=tuple(args.targets) or ("cilium_tpu",),
        rules=rules or None, only_paths=only_paths)
    if args.out:
        with open(args.out, "w") as fp:
            fp.write(render_json(findings, suppressed))
    if args.format == "json":
        print(render_json(findings, suppressed))
    else:
        print(render_text(findings, suppressed))
    if args.wall_budget_ms is not None:
        from cilium_tpu.analysis.core import LAST_TIMINGS

        wall = LAST_TIMINGS.get("wall", 0.0)
        if wall > args.wall_budget_ms:
            print(f"ctlint: wall time {wall:.0f}ms exceeds budget "
                  f"{args.wall_budget_ms}ms", file=sys.stderr)
            return 1
    return 1 if findings else 0


def _git_changed_paths(root):
    """Repo-relative .py paths git reports as modified/added/
    untracked (the ``--changed-only`` filter); None when git is
    unavailable."""
    import subprocess

    try:
        out = subprocess.run(
            ["git", "-C", root, "status", "--porcelain"],
            capture_output=True, text=True, timeout=30)
    except (OSError, subprocess.TimeoutExpired):
        return None
    if out.returncode != 0:
        return None
    paths = []
    for line in out.stdout.splitlines():
        if len(line) < 4:
            continue
        path = line[3:].strip()
        if " -> " in path:  # rename: take the new side
            path = path.split(" -> ", 1)[1]
        path = path.strip('"')
        if path.endswith(".py"):
            paths.append(path)
    return paths
