"""``python -m cilium_tpu.analysis`` — the make-lint entry point."""

import sys

from cilium_tpu.analysis import run_cli

if __name__ == "__main__":
    sys.exit(run_cli())
