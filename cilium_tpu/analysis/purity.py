"""jit-purity: nothing host-effectful reachable from a jitted kernel.

A jitted function runs at TRACE time: a ``time.time()`` inside it
stamps the compile, not the request; a ``random.random()`` bakes one
draw into the compiled artifact; a lock acquisition can deadlock the
trace under the loader's swap lock; ``np.asarray``/``.item()`` force a
blocking device sync in the middle of what must stay an async
dispatch; a Python ``if`` over a traced value either fails to trace or
silently specializes. None of these fail a unit test on CPU — the
verdicts stay right — so the contract is machine-checked here instead.

Entry points (detected, not listed): ``@jax.jit`` /
``functools.partial(jax.jit, ...)`` decorators, ``jax.jit(fn)`` /
``pl.pallas_call(kernel, ...)`` / ``shard_map(fn, ...)`` call forms.
Reachability follows plain calls through the indexed project; an
unresolvable callee is skipped (miss, don't invent).
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from cilium_tpu.analysis.callgraph import ModuleInfo, Project, dotted
from cilium_tpu.analysis.core import Finding, ProjectIndex, checker

RULE = "jit-purity"

#: qualified-name prefixes whose call is a host effect under trace
_IMPURE_PREFIXES: Tuple[Tuple[str, str], ...] = (
    ("time.", "wall-clock/sleep"),
    ("random.", "host RNG"),
    ("numpy.random", "host RNG"),
    ("socket.", "I/O"),
    ("os.", "I/O"),
    ("threading.", "thread/lock construction"),
    ("cilium_tpu.runtime.metrics.", "metrics lock"),
    ("cilium_tpu.runtime.tracing.", "tracer lock"),
    ("cilium_tpu.runtime.faults.", "fault-point lock+RNG"),
    ("cilium_tpu.runtime.logging.", "log I/O"),
)

#: exact qualified names that force a host sync / materialization
_HOST_SYNC = {
    "numpy.asarray": "host materialization of a traced value",
    "numpy.array": "host materialization of a traced value",
    "numpy.frombuffer": "host materialization",
    "jax.device_get": "blocking device→host sync",
}

#: builtins that are host I/O
_IO_BUILTINS = {"open", "print", "input"}

#: attribute calls that block on the device
_SYNC_ATTRS = {"item": "blocking .item() host sync",
               "tolist": "blocking .tolist() host sync",
               "block_until_ready": "blocking device sync"}

#: jit-wrapping call forms whose first Name argument is an entry point
_WRAPPERS = ("jax.jit", "jit", "pl.pallas_call", "pallas_call",
             "jax.pmap", "shard_map", "jax.experimental.shard_map"
             ".shard_map")


def _is_jit_decorator(mi: ModuleInfo, dec: ast.expr) -> bool:
    q = mi.qualify(dec if not isinstance(dec, ast.Call) else dec.func)
    if q in ("jax.jit", "jit", "jax.pmap"):
        return True
    if isinstance(dec, ast.Call) and q in ("functools.partial",
                                           "partial") and dec.args:
        inner = mi.qualify(dec.args[0])
        return inner in ("jax.jit", "jit", "jax.pmap")
    return False


def find_entries(project: Project) -> List[Tuple[ModuleInfo, ast.AST]]:
    # memoized per project: five rule families ask for the jitted
    # entries of the same shared Project, and the discovery is a
    # whole-tree ast.walk — pay for it once per run (same idiom as
    # callgraph.project_for; the attribute rides the Project).
    cached = getattr(project, "_ctlint_jit_entries", None)
    if cached is not None:
        return cached
    entries: List[Tuple[ModuleInfo, ast.AST]] = []
    seen: Set[int] = set()

    def add(mi: ModuleInfo, fn: Optional[ast.AST]) -> None:
        if fn is not None and id(fn) not in seen:
            seen.add(id(fn))
            entries.append((mi, fn))

    for mi in project.modules.values():
        for fns in mi.all_functions.values():
            for fn in fns:
                if any(_is_jit_decorator(mi, d)
                       for d in fn.decorator_list):
                    add(mi, fn)
        for node in ast.walk(mi.sf.tree):
            if not isinstance(node, ast.Call) or not node.args:
                continue
            q = mi.qualify(node.func)
            if q is None or (q not in _WRAPPERS
                             and not q.endswith(".shard_map")):
                continue
            arg = node.args[0]
            # jit(partial(fn, ...)) binds statics around a real entry —
            # unwrap to the inner function (the megakernel's staged
            # step and the impl-threaded staging scans jit this way)
            if isinstance(arg, ast.Call) and arg.args \
                    and mi.qualify(arg.func) in ("functools.partial",
                                                 "partial"):
                arg = arg.args[0]
            if isinstance(arg, ast.Name):
                resolved = project.resolve_function(mi, arg.id)
                if resolved is not None:
                    add(*resolved)
            elif isinstance(arg, ast.Attribute):
                # module-qualified entry (`_mk.fused_verdict_step`)
                q2 = mi.qualify(arg) or dotted(arg) or ""
                owner, _, attr = q2.rpartition(".")
                target = project.modules.get(owner)
                if target is not None and attr in target.functions:
                    add(target, target.functions[attr])
            elif isinstance(arg, (ast.FunctionDef, ast.Lambda)):
                add(mi, arg)
    # benign race: concurrent checkers compute identical lists; the
    # last write wins and both results are correct
    project._ctlint_jit_entries = entries
    return entries


def _callees(project: Project, mi: ModuleInfo, fn: ast.AST
             ) -> List[Tuple[ModuleInfo, ast.AST]]:
    out = []
    for node in ast.walk(fn):
        if not isinstance(node, ast.Call):
            continue
        d = dotted(node.func)
        if d is None:
            continue
        if "." not in d:
            resolved = project.resolve_function(mi, d)
            if resolved is not None:
                out.append(resolved)
            continue
        # mod.fn where mod is an imported project module
        root, _, attr = d.rpartition(".")
        target = project.modules.get(mi.imports.get(root, ""))
        if target is not None and "." not in attr \
                and attr in target.functions:
            out.append((target, target.functions[attr]))
    return out


def _scan_impure(mi: ModuleInfo, fn: ast.AST, entry_name: str,
                 findings: List[Finding]) -> None:
    path = mi.sf.path

    def report(line: int, what: str) -> None:
        findings.append(Finding(
            path, line, RULE,
            f"{what} inside `{getattr(fn, 'name', '<lambda>')}`, "
            f"reachable from jitted entry `{entry_name}`"))

    for node in ast.walk(fn):
        if isinstance(node, ast.Call):
            q = mi.qualify(node.func)
            if q is not None:
                if q in _HOST_SYNC:
                    report(node.lineno, f"{_HOST_SYNC[q]} (`{q}`)")
                    continue
                if q in _IO_BUILTINS:
                    report(node.lineno, f"host I/O call `{q}`")
                    continue
                hit = next((why for p, why in _IMPURE_PREFIXES
                            if q.startswith(p)), None)
                if hit is not None:
                    report(node.lineno, f"{hit} call `{q}`")
                    continue
            if isinstance(node.func, ast.Attribute) \
                    and node.func.attr in _SYNC_ATTRS:
                report(node.lineno, _SYNC_ATTRS[node.func.attr])
        elif isinstance(node, ast.With):
            for item in node.items:
                d = dotted(item.context_expr) or ""
                leaf = d.rsplit(".", 1)[-1].lower()
                if "lock" in leaf or "cond" in leaf:
                    report(node.lineno,
                           f"lock acquisition `with {d}`")
        elif isinstance(node, (ast.If, ast.While)):
            for sub in ast.walk(node.test):
                if isinstance(sub, ast.Call):
                    q = mi.qualify(sub.func) or ""
                    if q.startswith(("jnp.", "jax.numpy", "jax.lax",
                                     "lax.")):
                        report(node.lineno,
                               "Python branch on a traced value "
                               f"(`{dotted(sub.func)}` in the test)")
                        break


@checker
def check(index: ProjectIndex) -> List[Finding]:
    project = Project(index)
    findings: List[Finding] = []
    visited: Dict[int, str] = {}
    stack = [(mi, fn, getattr(fn, "name", "<lambda>"))
             for mi, fn in find_entries(project)]
    while stack:
        mi, fn, entry = stack.pop()
        if id(fn) in visited:
            continue
        visited[id(fn)] = entry
        _scan_impure(mi, fn, entry, findings)
        for cmi, cfn in _callees(project, mi, fn):
            if id(cfn) not in visited:
                stack.append((cmi, cfn, entry))
    return findings
check.emits = (RULE,)
