"""unbounded-queue: every queue in the threaded runtime carries a
bound or a shed path.

The overload postmortem behind ISSUE 5: ``MicroBatcher._pending`` was
a bare list — under saturation every request queued without bound,
callers that timed out still consumed device batch slots, and p99
diverged instead of shedding. The fix (runtime/admission.py) is a
bounded queue with explicit sheds; this rule keeps the property from
regressing anywhere in the threaded runtime:

* ``queue.Queue()`` / ``LifoQueue()`` / ``PriorityQueue()`` built
  WITHOUT a ``maxsize`` in a module that imports ``threading`` is a
  finding — an unbounded stdlib queue between threads is exactly the
  buffer-forever failure mode.
* **List-as-queue**: a class that spawns threads
  (``threading.Thread(...)`` anywhere in its body), initializes an
  attribute to an empty list (``self._x = []``), and ``append``\\ s to
  it is flagged UNLESS the class also compares ``len(self._x)``
  somewhere — the bound/shed evidence. The heuristic is deliberately
  syntactic: a real bound check (``if len(self._pending) >=
  self.max_pending: shed``) satisfies it, and a queue with no length
  test anywhere cannot be bounded.

Intentional unbounded growth (a transition log read only by tests, a
batch accumulated then immediately consumed) carries the standard
justified pragma::

    # ctlint: disable=unbounded-queue  # why growth is bounded elsewhere
"""

from __future__ import annotations

import ast
from typing import List, Optional

from cilium_tpu.analysis.callgraph import dotted
from cilium_tpu.analysis.core import Finding, ProjectIndex, checker

RULE = "unbounded-queue"

#: stdlib queue constructors that accept (and default to no) maxsize
_QUEUE_CLASSES = ("Queue", "LifoQueue", "PriorityQueue")


def _imports_threading(tree: ast.AST) -> bool:
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            if any(a.name.split(".")[0] == "threading"
                   for a in node.names):
                return True
        elif isinstance(node, ast.ImportFrom):
            if (node.module or "").split(".")[0] == "threading":
                return True
    return False


def _queue_ctor(call: ast.Call, mi) -> Optional[str]:
    """The queue class name when ``call`` constructs a stdlib queue
    (``queue.Queue(...)`` or a ``from queue import Queue`` name)."""
    d = dotted(call.func)
    if d is None:
        return None
    qualified = mi.qualify(call.func) or d
    for cls in _QUEUE_CLASSES:
        if qualified == f"queue.{cls}":
            return cls
    return None


def _has_maxsize(call: ast.Call) -> bool:
    if call.args:  # maxsize is the first positional
        return True
    return any(kw.arg == "maxsize" for kw in call.keywords)


def _self_attr(node: ast.AST) -> Optional[str]:
    """``x`` for a ``self.x`` attribute access."""
    if isinstance(node, ast.Attribute) \
            and isinstance(node.value, ast.Name) \
            and node.value.id == "self":
        return node.attr
    return None


def _spawns_threads(cls: ast.ClassDef, mi) -> bool:
    for node in ast.walk(cls):
        if isinstance(node, ast.Call):
            q = mi.qualify(node.func) or (dotted(node.func) or "")
            if q in ("threading.Thread", "Thread"):
                return True
    return False


def _len_compared_attrs(cls: ast.ClassDef) -> set:
    """Attrs whose ``len(self.x)`` appears under a comparison anywhere
    in the class — the bound/shed evidence."""
    out = set()
    for node in ast.walk(cls):
        if not isinstance(node, ast.Compare):
            continue
        for sub in ast.walk(node):
            if isinstance(sub, ast.Call) \
                    and isinstance(sub.func, ast.Name) \
                    and sub.func.id == "len" and sub.args:
                attr = _self_attr(sub.args[0])
                if attr is not None:
                    out.add(attr)
    return out


def _check_class(cls: ast.ClassDef, mi, path: str) -> List[Finding]:
    if not _spawns_threads(cls, mi):
        return []
    # attrs initialized to an empty list anywhere in the class
    empty_list_attrs = {}
    appended = {}
    for node in ast.walk(cls):
        if isinstance(node, ast.Assign) and len(node.targets) == 1:
            attr = _self_attr(node.targets[0])
            if attr is None:
                continue
            val = node.value
            is_empty_list = (
                (isinstance(val, ast.List) and not val.elts)
                or (isinstance(val, ast.Call)
                    and isinstance(val.func, ast.Name)
                    and val.func.id == "list" and not val.args))
            if is_empty_list and attr not in empty_list_attrs:
                empty_list_attrs[attr] = node.lineno
        elif isinstance(node, ast.Call) \
                and isinstance(node.func, ast.Attribute) \
                and node.func.attr == "append":
            attr = _self_attr(node.func.value)
            if attr is not None and attr not in appended:
                appended[attr] = node.lineno
    bounded = _len_compared_attrs(cls)
    findings = []
    for attr in sorted(set(empty_list_attrs) & set(appended)):
        if attr in bounded:
            continue
        findings.append(Finding(
            path, appended[attr], RULE,
            f"`self.{attr}` in threaded class `{cls.name}` is a "
            f"list used as a queue with no bound — under overload it "
            f"grows without limit; enforce a max occupancy with an "
            f"explicit shed (compare `len(self.{attr})`), or justify "
            f"with a disable pragma"))
    return findings


@checker
def check(index: ProjectIndex) -> List[Finding]:
    from cilium_tpu.analysis.callgraph import Project

    project = Project(index)
    findings: List[Finding] = []
    for mi in project.modules.values():
        if not _imports_threading(mi.sf.tree):
            continue
        for node in ast.walk(mi.sf.tree):
            if isinstance(node, ast.Call):
                cls = _queue_ctor(node, mi)
                if cls is not None and not _has_maxsize(node):
                    findings.append(Finding(
                        mi.sf.path, node.lineno, RULE,
                        f"`{cls}()` without `maxsize` in a threaded "
                        f"module — an unbounded inter-thread queue "
                        f"buffers forever under overload; pass a "
                        f"bound (producers block or shed)"))
        for cls_node in ast.walk(mi.sf.tree):
            if isinstance(cls_node, ast.ClassDef):
                findings.extend(_check_class(cls_node, mi, mi.sf.path))
    return findings
check.emits = (RULE,)
