"""lock-order: the static lock-acquisition graph must be cycle-free.

The runtime is threaded end to end — MicroBatcher drain workers, the
stream pipeline's three stages, the loader's swap, kvstore watches —
and nothing but convention orders their lock acquisitions. A cycle
(thread 1 holds A wanting B, thread 2 holds B wanting A) is a
production-only hang: it needs precise interleaving, so no unit test
reproduces it. This rule extracts every lock a class owns
(``self._x = threading.Lock()``; ``Condition(self._x)`` aliases to the
wrapped lock), walks ``with`` nesting plus calls made while holding
(through attribute types and module-level singletons like ``METRICS``),
and reports (a) cycles in the resulting acquired-before graph and
(b) re-acquisition of a held non-reentrant lock (a self-deadlock even
with one thread).
"""

from __future__ import annotations

import ast
import threading
from typing import Dict, List, Optional, Set, Tuple

from cilium_tpu.analysis.callgraph import (ModuleInfo, Project, dotted,
                                           project_for)
from cilium_tpu.analysis.core import Finding, ProjectIndex, checker

_MEMO_LOCK = threading.Lock()


def analyzer_for(project: Project) -> "_Analyzer":
    """One shared lock analyzer per project — thread-safety reuses the
    class models and call summaries built here, and checkers now run
    concurrently, so the memo is lock-guarded."""
    with _MEMO_LOCK:
        a = getattr(project, "_ctlint_lock_analyzer", None)
        if a is None:
            a = _Analyzer(project)
            project._ctlint_lock_analyzer = a
        return a

RULE = "lock-order"

_LOCK_CTORS = {"threading.Lock": "lock", "threading.RLock": "rlock",
               "threading.Condition": "cond"}


class ClassModel:
    def __init__(self, module: str, name: str):
        self.module = module
        self.name = name
        #: attr → "lock" | "rlock"
        self.locks: Dict[str, str] = {}
        #: attr → canonical attr (Condition(self._x) → _x)
        self.alias: Dict[str, str] = {}
        #: attr → (module, class name) of the instance assigned to it
        self.attr_types: Dict[str, Tuple[str, str]] = {}

    def lock_id(self, attr: str) -> Optional[str]:
        attr = self.alias.get(attr, attr)
        if attr in self.locks:
            return f"{self.module}.{self.name}.{attr}"
        return None


class FnSummary:
    """What one callable does with locks, directly."""

    def __init__(self) -> None:
        #: (held lock ids, acquired lock id, kind, line)
        self.acquires: List[Tuple[Tuple[str, ...], str, str, int]] = []
        #: (held lock ids, callee key, line)
        self.calls: List[Tuple[Tuple[str, ...], Tuple, int]] = []


def _build_class(project: Project, mi: ModuleInfo,
                 cls: ast.ClassDef) -> ClassModel:
    cm = ClassModel(mi.sf.module, cls.name)
    for node in ast.walk(cls):
        if not (isinstance(node, ast.Assign) and len(node.targets) == 1):
            continue
        tgt = node.targets[0]
        if not (isinstance(tgt, ast.Attribute)
                and isinstance(tgt.value, ast.Name)
                and tgt.value.id == "self"):
            continue
        if isinstance(node.value, ast.Call):
            q = mi.qualify(node.value.func)
            kind = _LOCK_CTORS.get(q or "")
            if kind == "cond":
                arg = node.value.args[0] if node.value.args else None
                d = dotted(arg) if arg is not None else None
                if d and d.startswith("self."):
                    cm.alias[tgt.attr] = d.split(".", 1)[1]
                else:
                    # Condition() wraps its own RLock — reentrant
                    cm.locks[tgt.attr] = "rlock"
                continue
            if kind is not None:
                cm.locks[tgt.attr] = kind
                continue
            fname = dotted(node.value.func)
            if fname is not None:
                resolved = project.resolve_class(
                    mi, fname.split(".", 1)[0]) \
                    if "." not in fname else None
                if "." not in fname and resolved is not None:
                    tmi, tcls = resolved
                    cm.attr_types[tgt.attr] = (tmi.sf.module, tcls.name)
    return cm


def _singletons(project: Project) -> Dict[str, Tuple[str, str]]:
    """fully-qualified module-level name → (module, class) for
    ``NAME = SomeClass(...)`` instances (METRICS, TRACER, ...)."""
    out: Dict[str, Tuple[str, str]] = {}
    for mi in project.modules.values():
        for name, value in mi.constants.items():
            if not isinstance(value, ast.Call):
                continue
            fname = dotted(value.func)
            if fname is None or "." in fname:
                continue
            resolved = project.resolve_class(mi, fname)
            if resolved is not None:
                tmi, tcls = resolved
                out[f"{mi.sf.module}.{name}"] = (tmi.sf.module,
                                                 tcls.name)
    return out


def _module_locks(mi: ModuleInfo) -> Dict[str, str]:
    """module-level NAME = threading.Lock() → kind."""
    out = {}
    for name, value in mi.constants.items():
        if isinstance(value, ast.Call):
            kind = _LOCK_CTORS.get(mi.qualify(value.func) or "")
            if kind is not None:
                out[name] = "rlock" if kind == "cond" else kind
    return out


class _FnVisitor(ast.NodeVisitor):
    def __init__(self, analyzer: "_Analyzer", mi: ModuleInfo,
                 cm: Optional[ClassModel]):
        self.a = analyzer
        self.mi = mi
        self.cm = cm
        self.held: List[Tuple[str, str]] = []  # (lock id, kind)
        self.summary = FnSummary()

    def _resolve_lock(self, expr: ast.AST) -> Optional[Tuple[str, str]]:
        d = dotted(expr)
        if d is None:
            return None
        if d.startswith("self.") and self.cm is not None:
            attr = d.split(".", 1)[1]
            if "." in attr:
                return None
            canonical = self.cm.alias.get(attr, attr)
            lid = self.cm.lock_id(attr)
            if lid is not None:
                return lid, self.cm.locks[canonical]
            return None
        if "." not in d and d in self.a.module_locks.get(
                self.mi.sf.module, {}):
            kind = self.a.module_locks[self.mi.sf.module][d]
            return f"{self.mi.sf.module}.{d}", kind
        return None

    def visit_With(self, node: ast.With) -> None:
        acquired = []
        for item in node.items:
            expr = item.context_expr
            lock = self._resolve_lock(expr)
            if lock is not None:
                held_ids = tuple(h for h, _ in self.held)
                self.summary.acquires.append(
                    (held_ids, lock[0], lock[1], node.lineno))
                self.held.append(lock)
                acquired.append(lock)
        for stmt in node.body:
            self.visit(stmt)
        for _ in acquired:
            self.held.pop()

    visit_AsyncWith = visit_With

    def _callee_key(self, call: ast.Call) -> Optional[Tuple]:
        d = dotted(call.func)
        if d is None:
            return None
        parts = d.split(".")
        if parts[0] == "self" and self.cm is not None:
            if len(parts) == 2:
                return ("method", self.cm.module, self.cm.name,
                        parts[1])
            if len(parts) == 3:
                # self.attr.m() — calls INTO the lock object itself
                # (notify/wait/acquire on a held cond) are the lock's
                # own protocol, not a foreign acquisition
                if self.cm.lock_id(parts[1]) is not None:
                    return None
                t = self.cm.attr_types.get(parts[1])
                if t is not None:
                    return ("method", t[0], t[1], parts[2])
            return None
        if len(parts) >= 2:
            root_q = self.mi.imports.get(parts[0], None)
            owner = f"{root_q or self.mi.sf.module}.{parts[0]}" \
                if root_q is None else root_q
            inst = self.a.singletons.get(
                f"{self.mi.sf.module}.{parts[0]}") \
                or self.a.singletons.get(owner)
            if inst is not None and len(parts) == 2:
                return ("method", inst[0], inst[1], parts[1])
            target = self.a.project.modules.get(owner or "")
            if target is not None and len(parts) == 2 \
                    and parts[1] in target.functions:
                return ("func", target.sf.module, parts[1])
            return None
        resolved = self.a.project.resolve_function(self.mi, d)
        if resolved is not None:
            return ("func", resolved[0].sf.module,
                    getattr(resolved[1], "name", d))
        return None

    def visit_Call(self, node: ast.Call) -> None:
        key = self._callee_key(node)
        if key is not None:
            self.summary.calls.append(
                (tuple(h for h, _ in self.held), key, node.lineno))
        self.generic_visit(node)

    # don't descend into nested defs: they run when CALLED, not here
    def visit_FunctionDef(self, node):  # noqa: D102
        pass

    visit_AsyncFunctionDef = visit_FunctionDef
    visit_Lambda = visit_FunctionDef


class _Analyzer:
    def __init__(self, project: Project):
        self.project = project
        self.classes: Dict[Tuple[str, str], ClassModel] = {}
        self.module_locks: Dict[str, Dict[str, str]] = {}
        self.summaries: Dict[Tuple, FnSummary] = {}
        self.kinds: Dict[str, str] = {}
        self.singletons = _singletons(project)
        for mi in project.modules.values():
            self.module_locks[mi.sf.module] = _module_locks(mi)
            for name, kind in self.module_locks[mi.sf.module].items():
                self.kinds[f"{mi.sf.module}.{name}"] = kind
            for cls in mi.classes.values():
                cm = _build_class(project, mi, cls)
                self.classes[(mi.sf.module, cls.name)] = cm
                for attr, kind in cm.locks.items():
                    self.kinds[f"{cm.module}.{cm.name}.{attr}"] = kind
        for mi in project.modules.values():
            for cls in mi.classes.values():
                cm = self.classes[(mi.sf.module, cls.name)]
                for node in cls.body:
                    if isinstance(node, (ast.FunctionDef,
                                         ast.AsyncFunctionDef)):
                        self._summarize(mi, cm, node,
                                        ("method", mi.sf.module,
                                         cls.name, node.name))
            for name, fn in mi.functions.items():
                self._summarize(mi, None, fn,
                                ("func", mi.sf.module, name))

    def _summarize(self, mi: ModuleInfo, cm: Optional[ClassModel],
                   fn: ast.AST, key: Tuple) -> None:
        v = _FnVisitor(self, mi, cm)
        for stmt in fn.body:
            v.visit(stmt)
        self.summaries[key] = v.summary

    def transitive_acquires(self, key: Tuple, _seen: Optional[Set] = None
                            ) -> Dict[str, Tuple[Tuple, int]]:
        """lock id → (callable key, line) of one acquisition site
        reachable from ``key`` (including via callees)."""
        if _seen is None:
            _seen = set()
        if key in _seen:
            return {}
        _seen.add(key)
        out: Dict[str, Tuple[Tuple, int]] = {}
        s = self.summaries.get(key)
        if s is None:
            return out
        for _held, lock, _kind, line in s.acquires:
            out.setdefault(lock, (key, line))
        for _held, callee, line in s.calls:
            for lock, site in self.transitive_acquires(
                    callee, _seen).items():
                out.setdefault(lock, site)
        return out


def _fmt_key(key: Tuple) -> str:
    return ".".join(key[1:]) if key[0] == "method" else f"{key[1]}.{key[2]}"


@checker
def check(index: ProjectIndex) -> List[Finding]:
    project = project_for(index)
    a = analyzer_for(project)
    findings: List[Finding] = []
    #: edges: held → acquired → (path, line, note)
    edges: Dict[str, Dict[str, Tuple[str, int, str]]] = {}

    for key, s in a.summaries.items():
        mi = project.modules[key[1]]
        path = mi.sf.path
        for held, lock, kind, line in s.acquires:
            if lock in held and kind != "rlock":
                findings.append(Finding(
                    path, line, RULE,
                    f"re-acquisition of held non-reentrant lock "
                    f"`{lock}` in `{_fmt_key(key)}` — self-deadlock"))
            for h in held:
                if h != lock:
                    edges.setdefault(h, {}).setdefault(
                        lock, (path, line, f"in `{_fmt_key(key)}`"))
        for held, callee, line in s.calls:
            if not held:
                continue
            reached = a.transitive_acquires(callee)
            for lock, (site_key, _site_line) in reached.items():
                for h in held:
                    if lock == h and a.kinds.get(lock) != "rlock":
                        findings.append(Finding(
                            path, line, RULE,
                            f"`{_fmt_key(key)}` holds `{h}` and calls "
                            f"`{_fmt_key(callee)}`, which re-acquires "
                            f"it (via `{_fmt_key(site_key)}`) — "
                            f"self-deadlock"))
                    elif lock != h:
                        edges.setdefault(h, {}).setdefault(
                            lock, (path, line,
                                   f"`{_fmt_key(key)}` → "
                                   f"`{_fmt_key(callee)}`"))

    # cycle detection over the acquired-before graph (DFS, each cycle
    # reported once at its lexicographically-first lock)
    def _find_cycles() -> List[List[str]]:
        cycles, state = [], {}

        def dfs(node: str, stack: List[str]) -> None:
            state[node] = 1
            stack.append(node)
            for nxt in sorted(edges.get(node, ())):
                if state.get(nxt) == 1:
                    cyc = stack[stack.index(nxt):] + [nxt]
                    if min(cyc) == cyc[0]:
                        cycles.append(cyc)
                elif state.get(nxt, 0) == 0:
                    dfs(nxt, stack)
            stack.pop()
            state[node] = 2
        for node in sorted(edges):
            if state.get(node, 0) == 0:
                dfs(node, [])
        return cycles

    for cyc in _find_cycles():
        hops = []
        for src, dst in zip(cyc, cyc[1:]):
            p, line, note = edges[src][dst]
            hops.append(f"{src} → {dst} ({p}:{line}, {note})")
        p0, line0, _ = edges[cyc[0]][cyc[1]]
        findings.append(Finding(
            p0, line0, RULE,
            "lock-order cycle: " + "; ".join(hops)))
    return findings
check.emits = (RULE,)
