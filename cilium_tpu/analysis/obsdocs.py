"""obs-doc-parity: the observability surface ⇄ docs/OBSERVABILITY.md.

The perf ledger (ISSUE 6) made docs/OBSERVABILITY.md the operator's
catalog of every metric family and every phase label the system can
emit — and a catalog that drifts is worse than none: a dashboard built
from stale docs reads dead series, and an undocumented phase label is
attribution output nobody can interpret. This rule closes the drift
both ways:

* every metric family declared in ``runtime/metrics.py``
  (``METRICS.describe``) must be mentioned in the doc;
* every phase label value — the tracing ``PHASE_*`` constants, the
  engine-probe ``ENGINE_PHASES`` / ``CAPTURE_PHASES`` tuples
  (``engine/phases.py``), and every ``_StagePhase("...")`` staging
  phase used anywhere — must be mentioned in the doc;
* every ``cilium_tpu_*``-shaped token the doc mentions must still be a
  declared family (stale docs teach dead series); derived histogram
  suffixes (``_bucket``/``_count``/``_sum``) of declared families are
  fine;
* every **reason-label VALUE** the system can emit — shed reasons
  (the ``SHED_*`` constants in ``runtime/admission.py``), memo
  invalidation reasons (``engine/memo.INVALIDATION_REASONS`` plus any
  literal ``reason=`` at engine/runtime call sites), and every
  literal ``{"reason"/"result": ...}`` metric label value anywhere in
  the package — must appear in the doc's **Reason-label catalog**
  section, and a catalog row whose value is no longer emitted
  anywhere is a stale-doc finding (a dashboard filtering on a dead
  label value silently matches nothing);
* every **fleet journal event KIND** — the ``JOURNAL_KINDS`` tuple in
  ``runtime/fleetserve.py`` plus every literal ``journal.record(...)``
  first argument — must appear in the doc's **Fleet event-journal
  catalog** section, both ways: an undocumented kind is an event an
  operator cannot interpret, a catalog row for a kind the journal
  never records is a stale doc.
"""

from __future__ import annotations

import ast
import os
import re
from typing import Dict, List, Optional, Tuple

from cilium_tpu.analysis.callgraph import Project
from cilium_tpu.analysis.core import Finding, ProjectIndex, checker

RULE = "obs-doc-parity"

METRICS_MODULE = "cilium_tpu.runtime.metrics"
TRACING_MODULE = "cilium_tpu.runtime.tracing"
PHASES_MODULE = "cilium_tpu.engine.phases"
DOC_PATH = os.path.join("docs", "OBSERVABILITY.md")

#: phase-label tuple constants whose VALUES the doc must cover
_PHASE_TUPLES = ("ENGINE_PHASES", "CAPTURE_PHASES")

_DOC_FAMILY_RE = re.compile(r"\bcilium_tpu_[a-z0-9_]*[a-z0-9]\b")

ADMISSION_MODULE = "cilium_tpu.runtime.admission"
MEMO_MODULE = "cilium_tpu.engine.memo"
#: module prefixes whose literal ``reason=`` call kwargs / bare
#: ``invalidate("...")`` args are reason-label values
_REASON_CALL_PREFIXES = ("cilium_tpu.engine", "cilium_tpu.runtime",
                        "cilium_tpu.policy")
#: label keys whose literal values are reason-label values
_LABEL_KEYS = ("reason", "result")
#: the doc section holding the reason-label catalog; rows are
#: ``| `value` | ... |`` table lines
REASON_SECTION = "## Reason-label catalog"
_REASON_ROW_RE = re.compile(r"^\|\s*`([a-z0-9*_-]+)`")

FLEETSERVE_MODULE = "cilium_tpu.runtime.fleetserve"
#: the doc section holding the fleet event-journal catalog; rows are
#: ``| `kind` | ... |`` table lines (same row shape as reasons)
JOURNAL_SECTION = "## Fleet event-journal catalog"


def _declared_families(project: Project) -> Dict[str, Tuple[str, int]]:
    mi = project.modules.get(METRICS_MODULE)
    if mi is None:
        return {}
    out: Dict[str, Tuple[str, int]] = {}
    for node in ast.walk(mi.sf.tree):
        if isinstance(node, ast.Call) \
                and isinstance(node.func, ast.Attribute) \
                and node.func.attr == "describe" and node.args:
            name = project.resolve_string(mi, node.args[0])
            if name is not None:
                out.setdefault(name, (mi.sf.path, node.lineno))
    return out


def _phase_values(project: Project) -> Dict[str, Tuple[str, int]]:
    """Phase label value → declaring (path, line)."""
    out: Dict[str, Tuple[str, int]] = {}
    mi = project.modules.get(TRACING_MODULE)
    if mi is not None:
        for node in mi.sf.tree.body:
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name) \
                    and node.targets[0].id.startswith("PHASE_") \
                    and isinstance(node.value, ast.Constant) \
                    and isinstance(node.value.value, str):
                out.setdefault(node.value.value,
                               (mi.sf.path, node.lineno))
    pm = project.modules.get(PHASES_MODULE)
    if pm is not None:
        for node in pm.sf.tree.body:
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name) \
                    and node.targets[0].id in _PHASE_TUPLES \
                    and isinstance(node.value, ast.Tuple):
                for elt in node.value.elts:
                    if isinstance(elt, ast.Constant) \
                            and isinstance(elt.value, str):
                        out.setdefault(elt.value,
                                       (pm.sf.path, node.lineno))
    # _StagePhase("...") call sites anywhere in the package (the
    # capture-staging phase labels are literals at their seams)
    for mod in project.modules.values():
        for node in ast.walk(mod.sf.tree):
            if isinstance(node, ast.Call) and node.args \
                    and isinstance(node.args[0], ast.Constant) \
                    and isinstance(node.args[0].value, str):
                fn = node.func
                name = fn.attr if isinstance(fn, ast.Attribute) \
                    else fn.id if isinstance(fn, ast.Name) else ""
                if name == "_StagePhase":
                    out.setdefault(node.args[0].value,
                                   (mod.sf.path, node.lineno))
    return out


def _const_strs(node: ast.AST) -> List[str]:
    """String constants of a value expression: a bare constant, or
    both branches of a conditional (``"a" if x else "b"`` — the
    explained/unexplained shape)."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return [node.value]
    if isinstance(node, ast.IfExp):
        return _const_strs(node.body) + _const_strs(node.orelse)
    if isinstance(node, ast.BoolOp):
        # `dynamic or "fallback"` — the fallback is emittable
        out: List[str] = []
        for v in node.values:
            out.extend(_const_strs(v))
        return out
    return []


def _reason_values(project: Project) -> Dict[str, Tuple[str, int]]:
    """Every reason-label VALUE the tree can emit → declaring
    (path, line): shed reasons (``SHED_*``), the memo invalidation
    registry (``INVALIDATION_REASONS``), literal ``reason=`` call
    kwargs / ``invalidate("...")`` args in the serving modules, and
    literal ``{"reason"/"result": ...}`` metric label values
    anywhere."""
    out: Dict[str, Tuple[str, int]] = {}

    def note(value: str, path: str, line: int) -> None:
        if value:
            out.setdefault(value, (path, line))

    mi = project.modules.get(ADMISSION_MODULE)
    if mi is not None:
        for node in mi.sf.tree.body:
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name) \
                    and node.targets[0].id.startswith("SHED_") \
                    and isinstance(node.value, ast.Constant) \
                    and isinstance(node.value.value, str):
                note(node.value.value, mi.sf.path, node.lineno)
    mm = project.modules.get(MEMO_MODULE)
    if mm is not None:
        for node in mm.sf.tree.body:
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name) \
                    and node.targets[0].id == "INVALIDATION_REASONS" \
                    and isinstance(node.value, ast.Tuple):
                for elt in node.value.elts:
                    if isinstance(elt, ast.Constant) \
                            and isinstance(elt.value, str):
                        note(elt.value, mm.sf.path, node.lineno)
    for name, mod in project.modules.items():
        reason_module = name.startswith(_REASON_CALL_PREFIXES)
        for node in ast.walk(mod.sf.tree):
            if not isinstance(node, ast.Call):
                continue
            fn = node.func
            fn_name = fn.attr if isinstance(fn, ast.Attribute) \
                else fn.id if isinstance(fn, ast.Name) else ""
            if reason_module:
                if fn_name == "invalidate" and node.args:
                    for v in _const_strs(node.args[0]):
                        note(v, mod.sf.path, node.lineno)
                for kw in node.keywords:
                    if kw.arg == "reason":
                        for v in _const_strs(kw.value):
                            note(v, mod.sf.path, node.lineno)
            # literal {"reason"/"result": ...} metric label values,
            # tree-wide (the artifact-fetch result shape)
            for kw in node.keywords:
                if kw.arg != "labels" \
                        or not isinstance(kw.value, ast.Dict):
                    continue
                for k, v in zip(kw.value.keys, kw.value.values):
                    if isinstance(k, ast.Constant) \
                            and k.value in _LABEL_KEYS:
                        for s in _const_strs(v):
                            note(s, mod.sf.path, node.lineno)
    return out


def _journal_kinds(project: Project) -> Dict[str, Tuple[str, int]]:
    """Every fleet journal event KIND the tree can record →
    declaring (path, line): the ``JOURNAL_KINDS`` tuple in
    ``runtime/fleetserve.py`` plus literal first args of
    ``journal.record("...")`` call sites (a recorded kind missing
    from the tuple is caught at runtime; here both feed the doc
    diff)."""
    out: Dict[str, Tuple[str, int]] = {}
    mi = project.modules.get(FLEETSERVE_MODULE)
    if mi is None:
        return out
    for node in mi.sf.tree.body:
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name) \
                and node.targets[0].id == "JOURNAL_KINDS" \
                and isinstance(node.value, ast.Tuple):
            for elt in node.value.elts:
                if isinstance(elt, ast.Constant) \
                        and isinstance(elt.value, str):
                    out.setdefault(elt.value, (mi.sf.path,
                                               node.lineno))
    for node in ast.walk(mi.sf.tree):
        if not (isinstance(node, ast.Call) and node.args
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == "record"):
            continue
        owner = node.func.value
        owner_name = owner.attr if isinstance(owner, ast.Attribute) \
            else owner.id if isinstance(owner, ast.Name) else ""
        if owner_name != "journal":
            continue
        for v in _const_strs(node.args[0]):
            out.setdefault(v, (mi.sf.path, node.lineno))
    return out


def _documented_rows(doc_text: str, section: str) -> Dict[str, int]:
    """Value → doc line of every ``| `value` |`` row under one
    ``## ...`` section."""
    out: Dict[str, int] = {}
    in_section = False
    for i, line in enumerate(doc_text.splitlines(), 1):
        if line.strip().startswith("## "):
            in_section = line.strip() == section.strip()
            continue
        if not in_section:
            continue
        m = _REASON_ROW_RE.match(line.strip())
        if m:
            out.setdefault(m.group(1), i)
    return out


def _documented_reasons(doc_text: str) -> Dict[str, int]:
    """Value → doc line of every Reason-label catalog row."""
    return _documented_rows(doc_text, REASON_SECTION)


def check_obs_docs(index: ProjectIndex,
                   doc_text: Optional[str] = None) -> List[Finding]:
    if doc_text is None:
        if index.root is None:
            return []  # in-memory corpus without a doc: nothing to diff
        path = os.path.join(index.root, DOC_PATH)
        try:
            with open(path, encoding="utf-8") as fp:
                doc_text = fp.read()
        except OSError:
            mi = index.get(METRICS_MODULE)
            if mi is None:
                return []
            return [Finding(mi.path, 1, RULE,
                            f"{DOC_PATH} is missing — the metric/phase "
                            f"catalog has no doc to agree with")]

    project = Project(index)
    findings: List[Finding] = []
    families = _declared_families(project)
    for name, (path, line) in sorted(families.items()):
        if name not in doc_text:
            findings.append(Finding(
                path, line, RULE,
                f"metric family `{name}` is not documented in "
                f"{DOC_PATH} (add it to the family catalog)"))
    for value, (path, line) in sorted(_phase_values(project).items()):
        if value not in doc_text:
            findings.append(Finding(
                path, line, RULE,
                f"phase label `{value}` is not documented in "
                f"{DOC_PATH}"))
    # reason-label parity, both directions (only when the tree has a
    # reason surface at all — in-memory rule corpora without the
    # admission module are not judged)
    reasons = _reason_values(project)
    documented = _documented_reasons(doc_text)
    if reasons:
        for value, (path, line) in sorted(reasons.items()):
            if value not in documented:
                findings.append(Finding(
                    path, line, RULE,
                    f"reason-label value `{value}` is not in "
                    f"{DOC_PATH}'s Reason-label catalog (an operator "
                    f"cannot interpret an undocumented reason)"))
        for value, line in sorted(documented.items()):
            if value not in reasons:
                findings.append(Finding(
                    DOC_PATH, line, RULE,
                    f"{DOC_PATH} catalogs reason-label value "
                    f"`{value}` but nothing in the tree emits it — "
                    f"stale doc or typo"))
    # fleet journal-kind parity, both directions (only when the tree
    # has a journal at all — corpora without fleetserve are not
    # judged)
    kinds = _journal_kinds(project)
    if kinds:
        doc_kinds = _documented_rows(doc_text, JOURNAL_SECTION)
        for kind, (path, line) in sorted(kinds.items()):
            if kind not in doc_kinds:
                findings.append(Finding(
                    path, line, RULE,
                    f"fleet journal event kind `{kind}` is not in "
                    f"{DOC_PATH}'s Fleet event-journal catalog (an "
                    f"operator cannot interpret an undocumented "
                    f"event)"))
        for kind, line in sorted(doc_kinds.items()):
            if kind not in kinds:
                findings.append(Finding(
                    DOC_PATH, line, RULE,
                    f"{DOC_PATH} catalogs fleet journal event kind "
                    f"`{kind}` but runtime/fleetserve.py never "
                    f"records it — stale doc or typo"))
    # stale direction: doc tokens that are no longer declared families
    if families:
        derived = set()
        for name in families:
            derived.update((name + "_bucket", name + "_count",
                            name + "_sum"))
        for i, line_text in enumerate(doc_text.splitlines(), 1):
            for tok in _DOC_FAMILY_RE.findall(line_text):
                if tok not in families and tok not in derived:
                    findings.append(Finding(
                        DOC_PATH, i, RULE,
                        f"{DOC_PATH} mentions `{tok}` but "
                        f"runtime/metrics.py declares no such family "
                        f"— stale doc or typo"))
    return findings


@checker
def check(index: ProjectIndex) -> List[Finding]:
    return check_obs_docs(index)
check.emits = (RULE,)
