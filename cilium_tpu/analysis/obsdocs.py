"""obs-doc-parity: the observability surface ⇄ docs/OBSERVABILITY.md.

The perf ledger (ISSUE 6) made docs/OBSERVABILITY.md the operator's
catalog of every metric family and every phase label the system can
emit — and a catalog that drifts is worse than none: a dashboard built
from stale docs reads dead series, and an undocumented phase label is
attribution output nobody can interpret. This rule closes the drift
both ways:

* every metric family declared in ``runtime/metrics.py``
  (``METRICS.describe``) must be mentioned in the doc;
* every phase label value — the tracing ``PHASE_*`` constants, the
  engine-probe ``ENGINE_PHASES`` / ``CAPTURE_PHASES`` tuples
  (``engine/phases.py``), and every ``_StagePhase("...")`` staging
  phase used anywhere — must be mentioned in the doc;
* every ``cilium_tpu_*``-shaped token the doc mentions must still be a
  declared family (stale docs teach dead series); derived histogram
  suffixes (``_bucket``/``_count``/``_sum``) of declared families are
  fine.
"""

from __future__ import annotations

import ast
import os
import re
from typing import Dict, List, Optional, Tuple

from cilium_tpu.analysis.callgraph import Project
from cilium_tpu.analysis.core import Finding, ProjectIndex, checker

RULE = "obs-doc-parity"

METRICS_MODULE = "cilium_tpu.runtime.metrics"
TRACING_MODULE = "cilium_tpu.runtime.tracing"
PHASES_MODULE = "cilium_tpu.engine.phases"
DOC_PATH = os.path.join("docs", "OBSERVABILITY.md")

#: phase-label tuple constants whose VALUES the doc must cover
_PHASE_TUPLES = ("ENGINE_PHASES", "CAPTURE_PHASES")

_DOC_FAMILY_RE = re.compile(r"\bcilium_tpu_[a-z0-9_]*[a-z0-9]\b")


def _declared_families(project: Project) -> Dict[str, Tuple[str, int]]:
    mi = project.modules.get(METRICS_MODULE)
    if mi is None:
        return {}
    out: Dict[str, Tuple[str, int]] = {}
    for node in ast.walk(mi.sf.tree):
        if isinstance(node, ast.Call) \
                and isinstance(node.func, ast.Attribute) \
                and node.func.attr == "describe" and node.args:
            name = project.resolve_string(mi, node.args[0])
            if name is not None:
                out.setdefault(name, (mi.sf.path, node.lineno))
    return out


def _phase_values(project: Project) -> Dict[str, Tuple[str, int]]:
    """Phase label value → declaring (path, line)."""
    out: Dict[str, Tuple[str, int]] = {}
    mi = project.modules.get(TRACING_MODULE)
    if mi is not None:
        for node in mi.sf.tree.body:
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name) \
                    and node.targets[0].id.startswith("PHASE_") \
                    and isinstance(node.value, ast.Constant) \
                    and isinstance(node.value.value, str):
                out.setdefault(node.value.value,
                               (mi.sf.path, node.lineno))
    pm = project.modules.get(PHASES_MODULE)
    if pm is not None:
        for node in pm.sf.tree.body:
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name) \
                    and node.targets[0].id in _PHASE_TUPLES \
                    and isinstance(node.value, ast.Tuple):
                for elt in node.value.elts:
                    if isinstance(elt, ast.Constant) \
                            and isinstance(elt.value, str):
                        out.setdefault(elt.value,
                                       (pm.sf.path, node.lineno))
    # _StagePhase("...") call sites anywhere in the package (the
    # capture-staging phase labels are literals at their seams)
    for mod in project.modules.values():
        for node in ast.walk(mod.sf.tree):
            if isinstance(node, ast.Call) and node.args \
                    and isinstance(node.args[0], ast.Constant) \
                    and isinstance(node.args[0].value, str):
                fn = node.func
                name = fn.attr if isinstance(fn, ast.Attribute) \
                    else fn.id if isinstance(fn, ast.Name) else ""
                if name == "_StagePhase":
                    out.setdefault(node.args[0].value,
                                   (mod.sf.path, node.lineno))
    return out


def check_obs_docs(index: ProjectIndex,
                   doc_text: Optional[str] = None) -> List[Finding]:
    if doc_text is None:
        if index.root is None:
            return []  # in-memory corpus without a doc: nothing to diff
        path = os.path.join(index.root, DOC_PATH)
        try:
            with open(path, encoding="utf-8") as fp:
                doc_text = fp.read()
        except OSError:
            mi = index.get(METRICS_MODULE)
            if mi is None:
                return []
            return [Finding(mi.path, 1, RULE,
                            f"{DOC_PATH} is missing — the metric/phase "
                            f"catalog has no doc to agree with")]

    project = Project(index)
    findings: List[Finding] = []
    families = _declared_families(project)
    for name, (path, line) in sorted(families.items()):
        if name not in doc_text:
            findings.append(Finding(
                path, line, RULE,
                f"metric family `{name}` is not documented in "
                f"{DOC_PATH} (add it to the family catalog)"))
    for value, (path, line) in sorted(_phase_values(project).items()):
        if value not in doc_text:
            findings.append(Finding(
                path, line, RULE,
                f"phase label `{value}` is not documented in "
                f"{DOC_PATH}"))
    # stale direction: doc tokens that are no longer declared families
    if families:
        derived = set()
        for name in families:
            derived.update((name + "_bucket", name + "_count",
                            name + "_sum"))
        for i, line_text in enumerate(doc_text.splitlines(), 1):
            for tok in _DOC_FAMILY_RE.findall(line_text):
                if tok not in families and tok not in derived:
                    findings.append(Finding(
                        DOC_PATH, i, RULE,
                        f"{DOC_PATH} mentions `{tok}` but "
                        f"runtime/metrics.py declares no such family "
                        f"— stale doc or typo"))
    return findings


@checker
def check(index: ProjectIndex) -> List[Finding]:
    return check_obs_docs(index)
